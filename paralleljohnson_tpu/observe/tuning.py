"""Profile-calibrated auto-tuning of the dispatch free parameters
(ISSUE 14 tentpole, second half).

Five knobs used to be hand-tuned constants buried in five different
modules:

====================  =========================  =======================
parameter             hand-tuned fallback        consumed by
====================  =========================  =======================
``fw_tile``           512 (roofline-picked)      ``ops.fw`` closure,
                                                 ``solver.partitioned``
``partition_parts``   ~sqrt(V)/8, clamp [2,32]   ``solver.partitioned``
``delta``             mean|w| x degree heuristic ``ops.bucket`` route
``source_batch``      device-memory budget       solver fan-out batching
``pipeline_depth``    2 (double buffering)       solver pipeline window
====================  =========================  =======================

This module converts them into one calibration loop: every solve whose
dispatch went through the planner registry lands a ``kind: "plan"``
profile record carrying the RESOLVED parameter values plus the
measured wall (``planner.plan_record``). :func:`tuned_value` reads
those records back per ``(platform, shape bucket)`` and picks the
parameter value whose best recorded wall is lowest — so an explicit
``--fw-tile 256`` run that measures faster than the 512 default
becomes the auto default for that platform/shape from then on.

Honesty rules:

- **empty store → hand-tuned constant**, always (the acceptance
  contract): with no records, or records for only ONE observed value,
  there is nothing to compare and the fallback stands — a single
  sample proves nothing about the alternatives;
- values are only compared WITHIN a (platform, V-bucket, E-bucket)
  key — a tile that wins on a dense 2^11 closure says nothing about a
  2^14 one;
- an explicit config value always wins over the tuner (set the knob,
  get the knob), and the resolution source ("config" /
  "profile-tuned" / "default") rides on every plan record and
  why-line so a surprising value is attributable.

Stdlib-only (the ``observe`` discipline).
"""

from __future__ import annotations

import os
from pathlib import Path

# The hand-tuned constants the tuner falls back to (single source of
# truth — config.py and the resolution sites import from here).
DEFAULT_FW_TILE = 512
DEFAULT_PIPELINE_DEPTH = 2

# The tunable-parameter vocabulary plan records carry. ``approx_beta``
# joined in ISSUE 19: the hopset relay cap is a per-shape schedule like
# any other knob (PAPERS.md: approximate-shortest-path parameter
# schedules are regime-dependent).
TUNABLE_PARAMS = (
    "fw_tile", "partition_parts", "delta", "source_batch",
    "pipeline_depth", "approx_beta",
)

# A profile-tuned value must beat a MEASURED fallback (seed) wall by
# more than this fraction to displace it — the same calibrated-
# challenger rule the planner applies to routes
# (``planner.PLANNER_NOISE_BAND``); kept numerically in lock-step by
# test_planner. An unmeasured fallback has nothing to defend with, so
# the min-of-best-walls rule stands (the pre-ISSUE-19 behavior).
TUNE_NOISE_BAND = 0.25

# A value needs at least this many distinct observed alternatives in
# the key before the tuner overrides the hand-tuned constant: one
# observed value has nothing to beat.
MIN_DISTINCT_VALUES = 2

# records cache keyed by (path, mtime_ns, size) — the store is
# append-only and finalize_solve appends AFTER a solve completes, so
# one solve's many batches re-read the file at most once.
_CACHE: dict = {}


def cached_records(store_dir: str | Path | None) -> list[dict]:
    if store_dir is None:
        return []
    from paralleljohnson_tpu.observe.store import PROFILE_FILENAME

    path = Path(store_dir) / PROFILE_FILENAME
    try:
        st = path.stat()
    except OSError:
        return []
    key = (str(path), st.st_mtime_ns, st.st_size)
    hit = _CACHE.get(str(path))
    if hit is not None and hit[0] == key:
        return hit[1]
    from paralleljohnson_tpu.observe.store import ProfileStore

    try:
        records = ProfileStore(store_dir).records()
    except ValueError:
        # A corrupt store must not crash dispatch; the solve record
        # writer will surface the corruption on its own append path.
        records = []
    _CACHE.clear()  # one store per process in practice; stay bounded
    _CACHE[str(path)] = (key, records)
    return records


def _bucket(num_nodes: int, num_edges: int) -> tuple[int, int]:
    from paralleljohnson_tpu.observe.costs import shape_bucket

    return shape_bucket(num_nodes, num_edges, 1)[:2]


def _best_walls(
    name: str,
    records: list,
    *,
    platform: str,
    want: tuple,
    validate=None,
) -> dict:
    """Per-value best recorded walls for one knob in one (platform,
    shape-bucket) key: ``{value: {"wall", "record", "kind",
    "tune_record"}}`` where ``record`` is the backing line index in
    ``profiles.jsonl`` and ``tune_record`` is the index of a
    non-censored ``kind:"tune"`` probe for the value (None when only
    plan records back it — i.e. a human-driven run, not the tuner).

    Two honesty rules beyond the plan-record path:

    - a **censored probe never counts** — a probe killed at its
      wall-clock cap proves the value is SLOWER than the cap, not how
      fast it is; promoting from a censored wall would reward the
      kill, so censored tune records are skipped entirely;
    - a **demotion erases history**: a ``kind:"tune", event:"demote"``
      record (written by ``bench_regress`` when a promoted value
      regresses past the noise band) invalidates every record of that
      value with ``ts`` at or before the demotion — newer probes can
      re-promote, stale wins cannot."""
    demoted: dict = {}
    for r in records:
        if r.get("kind") != "tune" or r.get("event") != "demote":
            continue
        if r.get("knob") != name or r.get("platform") != platform:
            continue
        if _bucket(r.get("nodes") or 0, r.get("edges") or 0) != want:
            continue
        v = r.get("value")
        ts = r.get("ts") or 0
        if v is not None and ts >= demoted.get(v, 0):
            demoted[v] = ts
    best: dict = {}
    for idx, r in enumerate(records):
        kind = r.get("kind")
        if kind == "plan":
            value = (r.get("params") or {}).get(name)
        elif kind == "tune":
            if r.get("event") == "demote" or r.get("censored"):
                continue
            if r.get("knob") != name:
                continue
            value = r.get("value")
        else:
            continue
        if value is None:
            continue
        if r.get("platform") != platform:
            continue
        if _bucket(r.get("nodes") or 0, r.get("edges") or 0) != want:
            continue
        if validate is not None and not validate(value):
            continue
        measured = r.get("measured") or {}
        wall = measured.get("compute_s") or measured.get("wall_s")
        if not isinstance(wall, (int, float)) or wall <= 0:
            continue
        if value in demoted and (r.get("ts") or 0) <= demoted[value]:
            continue
        # Min-of-samples per value: timing noise only inflates (the
        # CostModel rationale), so the best recorded wall is the
        # steady-state cost of running with that value.
        entry = best.get(value)
        if entry is None:
            entry = best[value] = {
                "wall": wall, "record": idx, "kind": kind,
                "tune_record": None,
            }
        elif wall < entry["wall"]:
            entry.update(wall=wall, record=idx, kind=kind)
        if kind == "tune" and entry["tune_record"] is None:
            entry["tune_record"] = idx
    return best


def _winner(best: dict, fallback, band: float):
    """The promotion rule shared by :func:`tuned_value` and
    :func:`param_provenance` (see module docstring)."""
    if len(best) < MIN_DISTINCT_VALUES:
        return None
    winner = min(best, key=lambda v: best[v]["wall"])
    if (
        fallback is not None
        and winner != fallback
        and fallback in best
        and not best[winner]["wall"] < best[fallback]["wall"] * (1.0 - band)
    ):
        # The seed defended itself: the challenger's measured edge is
        # inside the noise band, so the hand-tuned value stands.
        return None
    return winner


def tuned_value(
    name: str,
    *,
    records=None,
    store_dir: str | Path | None = None,
    platform: str,
    num_nodes: int,
    num_edges: int,
    validate=None,
    fallback=None,
    band: float = TUNE_NOISE_BAND,
):
    """The profile-tuned value of ``name`` for this (platform, shape
    bucket), or None when the store holds nothing decisive (see module
    docstring). ``validate`` filters candidate values (e.g. fw tiles
    must be 128-multiples). When ``fallback`` (the hand-tuned seed) has
    a measured wall in the same key, a different winner must beat it by
    more than ``band`` — the planner's calibrated-challenger rule
    applied to parameter values."""
    if name not in TUNABLE_PARAMS:
        raise ValueError(
            f"unknown tunable parameter {name!r}; expected one of "
            f"{TUNABLE_PARAMS}"
        )
    if records is None:
        records = cached_records(store_dir)
    if not records:
        return None
    best = _best_walls(
        name, records, platform=platform,
        want=_bucket(num_nodes, num_edges), validate=validate,
    )
    return _winner(best, fallback, band)


def param_provenance(
    name: str,
    *,
    records=None,
    store_dir: str | Path | None = None,
    platform: str,
    num_nodes: int,
    num_edges: int,
    validate=None,
    fallback=None,
    band: float = TUNE_NOISE_BAND,
) -> dict:
    """Where one knob's effective value comes from, for ``pjtpu info``
    (ISSUE 19 satellite): ``{"value", "source", "record", "wall_s",
    "values_seen"}`` with source one of

    - ``"seed"`` — the hand-tuned constant stands (nothing decisive
      measured, or the challenger lost to the measured seed);
    - ``"cpu-calibrated"`` — a human-driven run (explicit config value)
      measured faster and the store promoted it;
    - ``"tuner-promoted"`` — the winning value is backed by a
      ``kind:"tune"`` probe record, i.e. the self-proposing tuner
      discovered it.

    ``record`` is the backing line index into ``profiles.jsonl`` (the
    record whose wall won), None for seed."""
    if records is None:
        records = cached_records(store_dir)
    best = _best_walls(
        name, records or [], platform=platform,
        want=_bucket(num_nodes, num_edges), validate=validate,
    )
    winner = _winner(best, fallback, band)
    if winner is None:
        return {
            "value": fallback, "source": "seed", "record": None,
            "wall_s": (
                best[fallback]["wall"] if fallback in best else None
            ),
            "values_seen": len(best),
        }
    entry = best[winner]
    tuned = entry["tune_record"] is not None
    return {
        "value": winner,
        "source": "tuner-promoted" if tuned else "cpu-calibrated",
        "record": entry["record"],
        "wall_s": entry["wall"],
        "values_seen": len(best),
    }


def resolve_param(
    name: str,
    explicit,
    fallback,
    *,
    config=None,
    store_dir: str | Path | None = None,
    platform: str,
    num_nodes: int,
    num_edges: int,
    validate=None,
) -> tuple:
    """Resolve one tunable parameter to ``(value, source)`` where
    source is ``"config"`` (explicit value set), ``"profile-tuned"``
    (the store's calibration picked it), or ``"default"`` (the
    hand-tuned constant). ``store_dir`` defaults to the config's
    profile store (+ ``PJ_PROFILE_DIR``)."""
    if explicit is not None:
        return explicit, "config"
    if store_dir is None and config is not None:
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir

        store_dir = resolve_profile_dir(
            getattr(config, "profile_store", None)
        )
    if store_dir is not None and os.environ.get("PJ_NO_TUNE") != "1":
        tuned = tuned_value(
            name, store_dir=store_dir, platform=platform,
            num_nodes=num_nodes, num_edges=num_edges, validate=validate,
            fallback=fallback,
        )
        if tuned is not None:
            return tuned, "profile-tuned"
    return fallback, "default"
