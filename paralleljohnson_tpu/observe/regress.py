"""Bench-regression detection — a slowdown should arrive pre-attributed.

``BenchHistory`` is an append-only JSONL (``bench_history.jsonl``,
next to the profile store) of normalized measurement rows:

    {"bench": ..., "backend": ..., "platform": ..., "preset": ...,
     "wall_s": ..., "ts": ..., "detail": {...}, "source": ...}

``normalize_record`` turns every measurement format this repo already
produces into such rows: ``pjtpu bench`` JSON lines (BenchRecord), the
driver's ``BENCH_r0*.json`` files (both the wrapper and its ``parsed``
payload), and the suite-budget guard's wall-clock. ``detect_regressions``
compares fresh rows against the per-(bench, backend, platform) history
with a noise band, and annotates each flagged row with its roofline
classification (from the row's own detail, or the profile store's
latest matching record) so the flag says *what kind* of slow it is.

Stdlib-only: scripts (``bench_regress.py``, ``check_suite_budget.py``)
load this module standalone, without importing the package (no jax).
"""

from __future__ import annotations

import json
import statistics
import time
from pathlib import Path

HISTORY_FILENAME = "bench_history.jsonl"

# Default noise band: a fresh wall more than 35% over the historical
# median (and more than the absolute floor — micro-benches jitter in
# absolute ms) is a regression. Bench rows on shared CPU containers
# routinely wobble 10-20%; 35% flags real slowdowns without paging on
# scheduler noise.
DEFAULT_BAND = 0.35
DEFAULT_ABS_FLOOR_S = 0.05
DEFAULT_MIN_HISTORY = 2

# Iteration-count band (ISSUE 9): iterations-to-converge is a property
# of the graph + route, not of scheduler noise, so it gets a TIGHTER
# band than walls — a fresh row iterating >25% (and >2 iterations) over
# its history median converged slower, which is a perf bug even when
# the wall stays inside its noise band (the sweeps just got cheaper or
# the machine faster). Rows ingest iterations from detail.iterations —
# written by bench rows whenever the convergence observatory was on.
DEFAULT_ITER_BAND = 0.25
DEFAULT_ITER_ABS_FLOOR = 2

# Re-route lapse band (ISSUE 18): how long a killed replica's sources
# stay dark is the serve fleet's graded axis — a slower failover is a
# robustness regression even when the bench wall looks fine. The band
# is WIDE (50%) and the absolute floor generous (0.5 s) because the
# lapse is quantised by heartbeat/refresh clocks, not compute.
DEFAULT_REROUTE_BAND = 0.50
DEFAULT_REROUTE_ABS_FLOOR_S = 0.5

# Tuned-knob band (ISSUE 19): promoted knob values were measured probes,
# and promotion itself required beating the seed beyond the planner's
# 25% noise band — so a fresh probe of the SAME (knob, value, bucket)
# regressing past that same band means the promotion no longer holds.
# Mirrors observe.tuning.TUNE_NOISE_BAND (kept literal: this module is
# loaded standalone by scripts, without the package).
DEFAULT_TUNE_BAND = 0.25

# Trace-hop band (ISSUE 20): assembled per-hop request-trace p50s
# (``scripts/trace_assemble.py --regress-out`` rows) grade the serving
# path hop by hop — a silently doubled convoy queue-wait flags with the
# hop NAMED even when the end-to-end bench wall absorbs it. Queue waits
# are quantised by the batch-window clock and walls are ms-scale, so
# the band is wide (50%) with small absolute floors.
DEFAULT_TRACE_BAND = 0.50
DEFAULT_TRACE_ABS_FLOOR_S = 0.01
DEFAULT_TRACE_QUEUE_ABS_FLOOR_MS = 2.0

# Hopset size band (ISSUE 17): a hopset's edge count is a DETERMINISTIC
# function of (graph, ε, k, β, seed, picker) — same shape bucket, same
# knobs, fatter hopset means the construction changed, not the weather.
# The band exists only to tolerate intentional small re-tunes riding a
# shape bucket; growth past it flags as a size regression.
DEFAULT_SIZE_BAND = 0.10
DEFAULT_SIZE_ABS_FLOOR = 64


def history_key(row: dict) -> tuple:
    return (
        row.get("bench"),
        row.get("backend"),
        row.get("platform"),
        row.get("preset"),
    )


class BenchHistory:
    """Append-only normalized-row history store.

    ``path`` may be a directory (rows live in
    ``<dir>/bench_history.jsonl``) or a file path directly."""

    def __init__(self, path: str | Path) -> None:
        p = Path(path)
        self.path = p if p.suffix == ".jsonl" else p / HISTORY_FILENAME

    def rows(self) -> list[dict]:
        if not self.path.exists():
            return []
        out = []
        lines = self.path.read_text(encoding="utf-8").splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    continue  # torn trailing line: kill damage, tolerated
                raise ValueError(
                    f"{self.path}: corrupt history row at line {i + 1}"
                )
        return out

    @staticmethod
    def _sig(row: dict) -> str:
        """Row identity for ingestion dedup — everything except ``ts``
        (re-ingesting the same BENCH_r0*.json files must be idempotent;
        the committed files carry no timestamps of their own)."""
        return json.dumps(
            {k: v for k, v in row.items() if k != "ts"}, sort_keys=True
        )

    def append(self, row: dict, *, dedup: bool = True) -> bool:
        """Append one row; with ``dedup`` an exact (ts-ignored)
        duplicate of an existing row is skipped. Returns True iff
        written."""
        if dedup:
            sig = self._sig(row)
            if any(self._sig(r) == sig for r in self.rows()):
                return False
        row = dict(row)
        row.setdefault("ts", time.time())
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
        return True


def _driver_metric_rows(obj: dict, source: str | None) -> list[dict]:
    """Rows from the driver bench format: the parsed payload
    ``{"metric": "edges_relaxed_per_sec_per_chip[tag]", "value": ...,
    "detail": {...}}``. The regression axis is the measured wall
    (``detail.dt``, lower = better) — the headline edges/s rate is kept
    in detail; keying strips the platform suffix from the tag so a
    cpu-fallback row and a TPU row land under different platforms, not
    different benches."""
    metric = obj.get("metric", "")
    detail = dict(obj.get("detail") or {})
    dt = detail.get("dt")
    if not isinstance(dt, (int, float)) or dt <= 0:
        return []
    tag = metric.split("[", 1)[1].rstrip("]") if "[" in metric else metric
    # Drop the trailing platform marker ("...,cpu-fallback" / ",cpu" /
    # ",tpu-rung") — platform is its own key axis.
    bench = "driver:" + tag.split(",", 1)[0]
    detail["value"] = obj.get("value")
    detail["metric"] = metric
    return [{
        "bench": bench,
        "backend": "jax",
        "platform": detail.get("platform", "unknown"),
        "preset": None,
        "wall_s": float(dt),
        "detail": detail,
        "source": source,
    }]


def _pow2_up(n) -> int:
    n = int(n or 0)
    return 1 << max(0, (n - 1).bit_length()) if n > 0 else 0


def _planner_rows(obj: dict, source: str | None) -> list[dict]:
    """Rows from ``kind: "plan"`` profile records (ISSUE 14): the
    planner's per-solve decision + measured wall, keyed by the solve's
    pow2 shape bucket. Re-ingesting the same profiles.jsonl is
    idempotent (the ts-ignored dedup in ``BenchHistory.append``), and a
    planner that starts picking a slower route for a shape it used to
    serve faster flags as an ordinary wall regression against that
    bucket's history — with the chosen plan + why-line in the flag's
    detail, so the regression arrives pre-attributed to a dispatch
    decision, not just a slow kernel."""
    measured = obj.get("measured") or {}
    wall = measured.get("wall_s") or measured.get("compute_s")
    if not isinstance(wall, (int, float)) or wall <= 0:
        return []
    bench = (
        f"planner:V{_pow2_up(obj.get('nodes'))}"
        f":E{_pow2_up(obj.get('edges'))}"
        f":B{_pow2_up(obj.get('batch'))}"
    )
    return [{
        "bench": bench,
        "backend": "jax",
        "platform": obj.get("platform", "unknown"),
        "preset": obj.get("label"),
        "wall_s": float(wall),
        "detail": {
            "route": obj.get("route"),
            "chosen": obj.get("chosen"),
            "reason": obj.get("reason"),
            "params": obj.get("params") or {},
            "degraded": bool(obj.get("degraded")),
        },
        "source": source,
    }]


def _tune_rows(obj: dict, source: str | None) -> list[dict]:
    """Rows from ``kind: "tune"`` probe records (ISSUE 19): one budgeted
    probe measurement keyed by (knob, pow2 shape bucket) with the probed
    value as the preset axis — so each candidate value accumulates its
    own history. Censored probes (budget exceeded / probe error) and
    demotion markers (``event``) are not measurements and are skipped.
    A promoted value whose fresh probes regress past the tuning band
    flags as ``kind: "tune"`` and ``bench_regress.py`` auto-demotes it
    back to the seed (an ``event: "demote"`` record the resolver
    honors)."""
    if obj.get("censored") or obj.get("event"):
        return []
    measured = obj.get("measured") or {}
    wall = measured.get("wall_s")
    if not isinstance(wall, (int, float)) or wall <= 0:
        return []
    bench = (
        f"tune:{obj.get('knob')}"
        f":V{_pow2_up(obj.get('nodes'))}"
        f":E{_pow2_up(obj.get('edges'))}"
    )
    return [{
        "bench": bench,
        "backend": "jax",
        "platform": obj.get("platform", "unknown"),
        "preset": str(obj.get("value")),
        "wall_s": float(wall),
        "detail": {
            "knob": obj.get("knob"),
            "value": obj.get("value"),
            "plan": obj.get("plan"),
            "rung": obj.get("rung"),
            "nodes": obj.get("nodes"),
            "edges": obj.get("edges"),
        },
        "source": source,
    }]


def _hopset_rows(obj: dict, source: str | None) -> list[dict]:
    """Rows from ``kind: "hopset"`` profile records (ISSUE 17): one
    construction measurement keyed by the graph's pow2 shape bucket and
    the ε it was built for. The regression axis is the construction
    wall; β and the hopset edge count ride in detail — the edge count
    is ALSO graded (``kind: "size"`` flags) because a fatter hopset
    slows every query downstream even when construction stayed fast.
    Re-ingesting the same profiles.jsonl is idempotent (ts-ignored
    dedup in ``BenchHistory.append``)."""
    wall = obj.get("construction_s")
    if not isinstance(wall, (int, float)) or wall <= 0:
        return []
    bench = (
        f"hopset:V{_pow2_up(obj.get('nodes'))}"
        f":E{_pow2_up(obj.get('edges'))}"
        f":eps{obj.get('epsilon')}"
    )
    return [{
        "bench": bench,
        "backend": "jax",
        "platform": obj.get("platform", "unknown"),
        "preset": obj.get("picker"),
        "wall_s": float(wall),
        "detail": {
            "beta": obj.get("beta"),
            "k": obj.get("k"),
            "hopset_edges": obj.get("hopset_edges"),
            "converged": bool(obj.get("converged")),
            "edges_examined": obj.get("edges_examined"),
        },
        "source": source,
    }]


def _trace_hop_rows(obj: dict, source: str | None) -> list[dict]:
    """Rows from ``kind: "trace"`` assembler output (ISSUE 20): one
    per-hop p50 from ``scripts/trace_assemble.py --regress-out``. The
    row keys as ``trace:<bench>:<hop>`` so every hop (forward /
    serve_request / convoy_member / query / device_megabatch / ...)
    accumulates its own baseline; the graded axes are the hop's p50
    wall and — where the hop records it — the p50 convoy queue wait."""
    hop = obj.get("hop")
    wall = obj.get("wall_s")
    if not hop or not isinstance(wall, (int, float)) or wall < 0:
        return []
    detail: dict = {
        "hop": str(hop),
        "count": obj.get("count"),
        "open": obj.get("open"),
    }
    qw = obj.get("queue_wait_p50_ms")
    if isinstance(qw, (int, float)):
        detail["queue_wait_p50_ms"] = float(qw)
    return [{
        "bench": f"trace:{obj.get('bench')}:{hop}",
        "backend": obj.get("backend", "unknown"),
        "platform": obj.get("platform", "unknown"),
        "preset": obj.get("preset"),
        "wall_s": float(wall),
        "detail": detail,
        "source": source,
    }]


def normalize_record(obj: dict, *, source: str | None = None) -> list[dict]:
    """Normalize ONE parsed measurement object into history rows.

    Accepted shapes: an already-normalized row (has bench + wall_s);
    a ``pjtpu bench`` BenchRecord line (config/backend/preset/wall_s);
    a driver metric payload (metric/value/detail); the committed
    ``BENCH_r0*.json`` wrapper (its ``parsed`` field is the payload);
    a profile store's ``kind: "plan"`` planner-decision record or
    ``kind: "hopset"`` construction record; the trace assembler's
    ``kind: "trace"`` per-hop p50 rows (ISSUE 20).
    Unrecognized objects yield [] — ingestion skips, never crashes."""
    if not isinstance(obj, dict):
        return []
    if obj.get("kind") == "plan":
        return _planner_rows(obj, source)
    if obj.get("kind") == "tune":
        return _tune_rows(obj, source)
    if obj.get("kind") == "hopset":
        return _hopset_rows(obj, source)
    if obj.get("kind") == "trace":
        return _trace_hop_rows(obj, source)
    if "bench" in obj and "wall_s" in obj:
        row = dict(obj)
        row.setdefault("source", source)
        return [row]
    if "config" in obj and "wall_s" in obj:
        detail = dict(obj.get("detail") or {})
        if "failed" in detail:
            return []  # a partial/failed row is not a measurement
        return [{
            "bench": obj["config"],
            "backend": obj.get("backend", "unknown"),
            "platform": detail.get("platform", "unknown"),
            "preset": obj.get("preset"),
            "wall_s": float(obj["wall_s"]),
            "detail": detail,
            "source": source,
        }]
    if "metric" in obj and "detail" in obj:
        return _driver_metric_rows(obj, source)
    if "parsed" in obj and isinstance(obj["parsed"], dict):
        return normalize_record(obj["parsed"], source=source)
    return []


def load_measurements(path: str | Path) -> list[dict]:
    """Rows from a measurement file: one JSON object (driver format) or
    JSONL (bench rows / normalized rows)."""
    text = Path(path).read_text(encoding="utf-8").strip()
    rows: list[dict] = []
    src = str(path)
    try:
        rows.extend(normalize_record(json.loads(text), source=src))
        return rows
    except json.JSONDecodeError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rows.extend(normalize_record(json.loads(line), source=src))
        except json.JSONDecodeError:
            continue
    return rows


def _roofline_of(row: dict, profile_records: list[dict] | None) -> str:
    """Roofline annotation for a flagged row: the row's own detail
    first, else the profile store's latest record matching the row's
    platform (and route tag, when the row carries one)."""
    detail = row.get("detail") or {}
    if detail.get("roofline_bound"):
        return detail["roofline_bound"]
    roof = detail.get("roofline")
    if isinstance(roof, dict) and roof.get("bound"):
        return roof["bound"]
    if profile_records:
        route = detail.get("route") or ""
        best = None
        for r in profile_records:
            if r.get("platform") != row.get("platform"):
                continue
            r_roof = (r.get("roofline") or {}).get("bound")
            if not r_roof:
                continue
            if route and r.get("route") and r["route"] not in route:
                continue
            if best is None or r.get("ts", 0) >= best.get("ts", 0):
                best = r
        if best is not None:
            return (best.get("roofline") or {}).get("bound", "unknown")
    return "unknown"


def _iterations_of(row: dict):
    """A row's iterations-to-converge, when its measurement carried the
    convergence observatory's count (``detail.iterations``)."""
    it = (row.get("detail") or {}).get("iterations")
    return int(it) if isinstance(it, (int, float)) and it > 0 else None


def _hopset_edges_of(row: dict):
    """A row's hopset edge count (``kind:"hopset"`` ingests)."""
    n = (row.get("detail") or {}).get("hopset_edges")
    return int(n) if isinstance(n, (int, float)) and n > 0 else None


def _reroute_lapse_of(row: dict):
    """A row's kill-to-reroute lapse (``serve_fleet`` rows, ISSUE 18)."""
    s = (row.get("detail") or {}).get("reroute_lapse_s")
    return float(s) if isinstance(s, (int, float)) and s > 0 else None


def detect_regressions(
    fresh: list[dict],
    history: list[dict],
    *,
    band: float = DEFAULT_BAND,
    abs_floor_s: float = DEFAULT_ABS_FLOOR_S,
    min_history: int = DEFAULT_MIN_HISTORY,
    iter_band: float = DEFAULT_ITER_BAND,
    profile_records: list[dict] | None = None,
) -> list[dict]:
    """Flag fresh rows slower than their history.

    Per (bench, backend, platform, preset) key the baseline is the
    MEDIAN of the history walls (robust to the odd wedged run); a fresh
    wall above ``baseline * (1 + band)`` AND more than ``abs_floor_s``
    over it is flagged (``kind: "wall"``). Keys with fewer than
    ``min_history`` rows are skipped — one prior point is not a trend.
    Each flag carries the baseline, the slowdown factor, and its
    roofline classification.

    Rows whose detail carries ``iterations`` (the convergence
    observatory was on) are ALSO graded on iterations-to-converge
    against the key's iteration history under the tighter ``iter_band``
    (``kind: "iterations"``) — a route converging slower is a perf bug
    even when wall noise hides it. Rows carrying ``hopset_edges``
    (``kind:"hopset"`` ingests) are graded on edge count under the
    tighter size band (``kind: "size"``) — a fatter hopset slows every
    downstream query even when construction stayed fast. ``serve_fleet``
    rows carrying ``detail.reroute_lapse_s`` are graded on the
    kill-to-reroute lapse (``kind: "reroute"``) under a wide band with
    a heartbeat-clock absolute floor — a slower failover flags the gate
    even when the bench wall is quiet. Trace-hop rows (``detail.hop``,
    ISSUE 20) grade per hop on p50 wall and p50 convoy queue wait
    (``kind: "trace"``, why-line names the hop)."""
    by_key: dict[tuple, list[float]] = {}
    iters_by_key: dict[tuple, list[int]] = {}
    size_by_key: dict[tuple, list[int]] = {}
    reroute_by_key: dict[tuple, list[float]] = {}
    tune_by_key: dict[tuple, list[float]] = {}
    trace_wall_by_key: dict[tuple, list[float]] = {}
    trace_queue_by_key: dict[tuple, list[float]] = {}
    for row in history:
        w = row.get("wall_s")
        if (row.get("detail") or {}).get("hop"):
            if isinstance(w, (int, float)) and w > 0:
                trace_wall_by_key.setdefault(
                    history_key(row), []
                ).append(float(w))
            qw = (row.get("detail") or {}).get("queue_wait_p50_ms")
            if isinstance(qw, (int, float)) and qw > 0:
                trace_queue_by_key.setdefault(
                    history_key(row), []
                ).append(float(qw))
            continue
        if (row.get("detail") or {}).get("knob"):
            if isinstance(w, (int, float)) and w > 0:
                tune_by_key.setdefault(history_key(row), []).append(float(w))
            continue
        if isinstance(w, (int, float)) and w > 0:
            by_key.setdefault(history_key(row), []).append(float(w))
        it = _iterations_of(row)
        if it is not None:
            iters_by_key.setdefault(history_key(row), []).append(it)
        n = _hopset_edges_of(row)
        if n is not None:
            size_by_key.setdefault(history_key(row), []).append(n)
        lapse = _reroute_lapse_of(row)
        if lapse is not None:
            reroute_by_key.setdefault(history_key(row), []).append(lapse)
    flagged = []
    for row in fresh:
        w = row.get("wall_s")
        detail = row.get("detail") or {}
        if detail.get("hop"):
            # Trace-hop rows (ISSUE 20) grade ONLY under the trace band
            # against their own (trace:<bench>:<hop>) history — on the
            # hop's p50 wall AND, where recorded, the convoy's p50
            # queue wait. The flag names the hop so a silently doubled
            # convoy wait arrives pre-attributed to the hop, not just
            # to a slower end-to-end bench.
            hop = detail["hop"]
            key = history_key(row)
            whist = trace_wall_by_key.get(key)
            if (
                isinstance(w, (int, float)) and w > 0
                and whist and len(whist) >= min_history
            ):
                wbase = statistics.median(whist)
                if (
                    w > wbase * (1.0 + DEFAULT_TRACE_BAND)
                    and (w - wbase) > DEFAULT_TRACE_ABS_FLOOR_S
                ):
                    flagged.append({
                        **row,
                        "kind": "trace",
                        "hop": hop,
                        "axis": "wall",
                        "baseline_s": wbase,
                        "slowdown": w / wbase,
                        "band": DEFAULT_TRACE_BAND,
                        "history_n": len(whist),
                        "why": (
                            f"hop '{hop}' p50 wall {w * 1e3:.2f}ms vs "
                            f"median {wbase * 1e3:.2f}ms"
                        ),
                    })
            qw = detail.get("queue_wait_p50_ms")
            qhist = trace_queue_by_key.get(key)
            if (
                isinstance(qw, (int, float)) and qw > 0
                and qhist and len(qhist) >= min_history
            ):
                qbase = statistics.median(qhist)
                if (
                    qw > qbase * (1.0 + DEFAULT_TRACE_BAND)
                    and (qw - qbase) > DEFAULT_TRACE_QUEUE_ABS_FLOOR_MS
                ):
                    flagged.append({
                        **row,
                        "kind": "trace",
                        "hop": hop,
                        "axis": "queue_wait",
                        "queue_wait_p50_ms": float(qw),
                        "baseline_queue_wait_ms": qbase,
                        "slowdown": qw / qbase,
                        "band": DEFAULT_TRACE_BAND,
                        "history_n": len(qhist),
                        "why": (
                            f"hop '{hop}' p50 convoy queue-wait "
                            f"{qw:.2f}ms vs median {qbase:.2f}ms"
                        ),
                    })
            continue
        if not isinstance(w, (int, float)) or w <= 0:
            continue
        if detail.get("knob"):
            # Tuned-knob probe rows (ISSUE 19) grade ONLY under the
            # tuning band against their own (knob, value, bucket)
            # history: a promoted value whose fresh probes regress past
            # the same band that justified its promotion flags — the
            # consumer (bench_regress.py) auto-demotes it to the seed.
            thist = tune_by_key.get(history_key(row))
            if thist and len(thist) >= min_history:
                tbase = statistics.median(thist)
                if (
                    w > tbase * (1.0 + DEFAULT_TUNE_BAND)
                    and (w - tbase) > abs_floor_s
                ):
                    flagged.append({
                        **row,
                        "kind": "tune",
                        "knob": detail["knob"],
                        "value": detail.get("value"),
                        "baseline_s": tbase,
                        "slowdown": w / tbase,
                        "band": DEFAULT_TUNE_BAND,
                        "history_n": len(thist),
                        "roofline_bound": _roofline_of(
                            row, profile_records
                        ),
                    })
            continue
        hist = by_key.get(history_key(row))
        if not hist or len(hist) < min_history:
            continue
        base = statistics.median(hist)
        if w > base * (1.0 + band) and (w - base) > abs_floor_s:
            flagged.append({
                **row,
                "kind": "wall",
                "baseline_s": base,
                "slowdown": w / base,
                "band": band,
                "history_n": len(hist),
                "roofline_bound": _roofline_of(row, profile_records),
            })
        n = _hopset_edges_of(row)
        shist = size_by_key.get(history_key(row))
        if n is not None and shist and len(shist) >= min_history:
            sbase = statistics.median(shist)
            if (
                n > sbase * (1.0 + DEFAULT_SIZE_BAND)
                and (n - sbase) > DEFAULT_SIZE_ABS_FLOOR
            ):
                flagged.append({
                    **row,
                    "kind": "size",
                    "hopset_edges": n,
                    "baseline_edges": sbase,
                    "slowdown": n / sbase,
                    "band": DEFAULT_SIZE_BAND,
                    "history_n": len(shist),
                    "roofline_bound": _roofline_of(row, profile_records),
                })
        lapse = _reroute_lapse_of(row)
        rhist = reroute_by_key.get(history_key(row))
        if lapse is not None and rhist and len(rhist) >= min_history:
            rbase = statistics.median(rhist)
            if (
                lapse > rbase * (1.0 + DEFAULT_REROUTE_BAND)
                and (lapse - rbase) > DEFAULT_REROUTE_ABS_FLOOR_S
            ):
                flagged.append({
                    **row,
                    "kind": "reroute",
                    "reroute_lapse_s": lapse,
                    "baseline_lapse_s": rbase,
                    "slowdown": lapse / rbase,
                    "band": DEFAULT_REROUTE_BAND,
                    "history_n": len(rhist),
                    "roofline_bound": _roofline_of(row, profile_records),
                })
        it = _iterations_of(row)
        ihist = iters_by_key.get(history_key(row))
        if it is None or not ihist or len(ihist) < min_history:
            continue
        ibase = statistics.median(ihist)
        if (
            it > ibase * (1.0 + iter_band)
            and (it - ibase) > DEFAULT_ITER_ABS_FLOOR
        ):
            flagged.append({
                **row,
                "kind": "iterations",
                "iterations": it,
                "baseline_iterations": ibase,
                "slowdown": it / ibase,
                "band": iter_band,
                "history_n": len(ihist),
                "roofline_bound": _roofline_of(row, profile_records),
            })
    return flagged
