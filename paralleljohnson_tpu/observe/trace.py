"""Fleet-wide request tracing (ISSUE 20 tentpole) — end-to-end causality
from socket accept to device kernel, across process boundaries.

The serve tier is a replicated fleet (router -> replica -> MicroBatcher
convoy -> device/host lookup -> scheduled solve -> repair/tuning lease),
but every observability layer before this one was *process-local*: a
flight recorder can say a replica was slow, none of them can answer
"why was THIS p99 request slow" once the request crossed a socket. This
module is the joining layer, three pieces:

- **Wire context** — :class:`TraceContext`: a ``trace_id`` minted at
  first ingress (router or replica) plus the upstream span's *global
  ref* (``"<proc>:<span_id>"``). It rides the ``pjtpu-serve/1`` request
  JSON (and the HTTP ``/query`` body) under the ``"trace"`` key:
  ``{"id": "<hex>", "parent": "<proc>:<span>"}`` (+ ``"sampled": false``
  when head sampling declined the request — downstream processes then
  must NOT re-mint, so one ingress decision governs the whole chain).
  Each process keeps appending to its own flight JSONL exactly as
  before; the ONLY new cross-process state is this one small dict.
- **Head sampling** — :func:`should_sample` is a pure function of the
  trace id (a sha256 fraction), so every process that computes it for
  the same id at the same rate agrees, deterministically. Rate 0 means
  no context is ever minted: the disabled path stays on
  ``NULL_TELEMETRY`` with bitwise-identical answers.
- **The assembler** — :func:`assemble` joins flight dirs from the
  router + N replicas + fleet workers into per-trace span sets: local
  parent chains (the ``Tracer``'s contextvar parenting) propagate the
  trace id downward, ``wire_parent`` attrs stitch processes together,
  and the result renders as one Perfetto timeline per trace
  (:func:`perfetto_trace`) with every span parented. Spans still open
  at a process's death (SIGKILL mid-request) are flagged ``open`` — the
  ingress span of a killed replica is the diagnosis, not a parse error.

Stdlib-only ON PURPOSE (the ``observe.live`` rule): the offline tools
(``scripts/trace_assemble.py``, ``scripts/trace_summary.py --request``)
load this module standalone via ``spec_from_file_location`` on any
log-analysis box — no numpy, no jax, no package imports.
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import os
from pathlib import Path

# The request-JSON key the wire context rides under. Absent = the
# request was never traced upstream (a replica reached directly may
# mint); present with "sampled": false = an upstream ingress declined
# it (do NOT re-mint — the head decision is made exactly once).
WIRE_KEY = "trace"

# The response-document key a traced request's answer carries, so a
# client (or a drill) can jump from an answer to its assembled
# timeline. Never present when tracing is off — the disabled path's
# responses stay bitwise-identical.
RESPONSE_KEY = "trace_id"

TRACE_ID_BYTES = 8


def mint_trace_id() -> str:
    """A fresh 64-bit hex trace id (the ingress mints exactly one)."""
    return os.urandom(TRACE_ID_BYTES).hex()


def should_sample(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling verdict: a pure function of the
    trace id, so router and replicas computing it independently agree.
    ``rate`` <= 0 never samples, >= 1 always; in between the id's
    sha256 fraction is compared against the rate (stable across
    processes, platforms, and reruns — the sampling-determinism test
    pins this)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = hashlib.sha256(str(trace_id).encode("utf-8")).digest()
    frac = int.from_bytes(h[:8], "big") / float(1 << 64)
    return frac < rate


class TraceContext:
    """One request's trace identity: the minted id, the upstream span's
    global ref (``"<proc>:<span_id>"``, None at first ingress), and the
    head-sampling verdict. Unsampled contexts still travel the wire
    (so downstream never re-mints) but open no spans."""

    __slots__ = ("trace_id", "parent", "sampled")

    def __init__(self, trace_id: str, *, parent: str | None = None,
                 sampled: bool = True) -> None:
        self.trace_id = str(trace_id)
        self.parent = parent
        self.sampled = bool(sampled)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"TraceContext({self.trace_id!r}, parent={self.parent!r}, "
                f"sampled={self.sampled})")

    def child(self, parent_ref: str | None) -> "TraceContext":
        """The context to forward downstream: same id + verdict, the
        forwarding span's global ref as the new wire parent."""
        return TraceContext(self.trace_id, parent=parent_ref,
                            sampled=self.sampled)

    def to_wire(self) -> dict:
        doc: dict = {"id": self.trace_id}
        if self.parent is not None:
            doc["parent"] = self.parent
        if not self.sampled:
            doc["sampled"] = False
        return doc

    @classmethod
    def from_wire(cls, doc) -> "TraceContext | None":
        """Parse a request's ``"trace"`` value; None on anything
        malformed (a garbage wire context must degrade to untraced,
        never crash the serving path)."""
        if not isinstance(doc, dict):
            return None
        tid = doc.get("id")
        if not isinstance(tid, str) or not tid:
            return None
        parent = doc.get("parent")
        if parent is not None and not isinstance(parent, str):
            parent = None
        return cls(tid, parent=parent,
                   sampled=doc.get("sampled", True) is not False)


def ingress(req: dict, *, rate: float) -> TraceContext | None:
    """The one decision point every ingress shares: honor an upstream
    wire context when the request carries one (its head decision is
    final), else mint at ``rate`` (None when rate <= 0 — tracing off
    means no context exists anywhere, the bitwise-identical path)."""
    wire = req.get(WIRE_KEY)
    if wire is not None:
        ctx = TraceContext.from_wire(wire)
        if ctx is not None:
            return ctx
    if rate <= 0.0:
        return None
    tid = mint_trace_id()
    return TraceContext(tid, sampled=should_sample(tid, rate))


# -- the current-trace contextvar --------------------------------------------
# Mirrors telemetry._CURRENT_SPAN: threads start untraced; cross-thread
# hops (the MicroBatcher convoy, the pipeline finalize worker) carry the
# context explicitly rather than inheriting it silently.

_CURRENT_TRACE: contextvars.ContextVar[TraceContext | None] = (
    contextvars.ContextVar("pj_current_trace", default=None)
)


def current_trace() -> TraceContext | None:
    return _CURRENT_TRACE.get()


def current_trace_id() -> str | None:
    """The sampled current trace's id, or None — what deep call sites
    (the solver's batch spans, repair/tuning lease events) tag their
    records with."""
    ctx = _CURRENT_TRACE.get()
    if ctx is not None and ctx.sampled:
        return ctx.trace_id
    return None


def trace_attrs() -> dict:
    """``{"trace": <id>}`` when a sampled trace is current, else ``{}``
    — splice into ``tel.span(...)``/``tel.event(...)`` kwargs at call
    sites that are only reached with telemetry enabled."""
    tid = current_trace_id()
    return {"trace": tid} if tid else {}


class use_trace:
    """Context manager installing ``ctx`` as the thread's current trace
    (tolerates ``ctx=None`` — the untraced path costs one isinstance of
    nothing)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: TraceContext | None) -> None:
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> TraceContext | None:
        if self._ctx is not None:
            self._token = _CURRENT_TRACE.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _CURRENT_TRACE.reset(self._token)
            self._token = None


# -- flight-file loading (torn-tail tolerant, the repo-wide convention) ------


def load_flight(path) -> list[dict]:
    """Parse one flight JSONL. A torn LAST line (the process died
    mid-write) is dropped silently; a corrupt line anywhere else raises
    — that is disk damage, not kill damage."""
    p = Path(path)
    lines = p.read_text(encoding="utf-8").splitlines()
    out: list[dict] = []
    for n, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            if n != len(lines) - 1:
                raise ValueError(
                    f"{p}:{n + 1}: corrupt flight line (not the last "
                    "line — this is not kill damage)"
                ) from None
    return out


def flight_files(sources) -> list[Path]:
    """Expand files/dirs into the flight JSONLs to join: a file is
    taken as-is; a dir contributes its ``flight-*.jsonl`` plus those
    one level down (the per-replica trace-dir layout the fleet drill
    writes)."""
    out: list[Path] = []
    for src in sources:
        p = Path(src)
        if p.is_dir():
            out.extend(sorted(p.glob("flight-*.jsonl")))
            out.extend(sorted(p.glob("*/flight-*.jsonl")))
        elif p.exists():
            out.append(p)
    # De-dup while preserving order (a dir and an explicit file may
    # name the same flight).
    seen: set[Path] = set()
    uniq = []
    for p in out:
        rp = p.resolve()
        if rp not in seen:
            seen.add(rp)
            uniq.append(p)
    return uniq


def _label_of(path: Path, meta: dict) -> str:
    label = meta.get("label")
    if label:
        return str(label)
    name = path.name
    if name.startswith("flight-") and name.endswith(".jsonl"):
        return name[len("flight-"):-len(".jsonl")]
    return path.stem


# -- the assembler ------------------------------------------------------------


def _load_processes(path: Path) -> list[dict]:
    """One flight file -> one process record PER SESSION. Flight files
    open in append mode, so a restarted process pointed at the same
    trace dir (same label -> same filename) keeps appending to the
    same JSONL: a fresh ``meta`` record, span ids restarting at 1.
    Every record binds to the most recent ``meta`` above it — keying
    the whole file to the FIRST meta would mis-attribute the second
    session's spans and break every wire join against them."""
    records = load_flight(path)
    segments: list[list[dict]] = []
    cur: list[dict] = []
    for r in records:
        if r.get("type") == "meta" and cur:
            segments.append(cur)
            cur = []
        cur.append(r)
    if cur:
        segments.append(cur)
    return [_load_segment(path, seg) for seg in segments]


def _load_segment(path: Path, records: list[dict]) -> dict:
    """One session's records -> process record: meta + spans + events,
    with global refs and epoch-anchored times."""
    meta = next((r for r in records if r.get("type") == "meta"), {})
    pid = meta.get("pid")
    proc = meta.get("proc") or f"{_label_of(path, meta)}-{pid or '?'}"
    start_ts = float(meta.get("start_ts", 0.0))
    spans: dict[int, dict] = {}
    events: list[dict] = []
    for r in records:
        kind = r.get("type")
        if kind == "span_begin":
            attrs = dict(r.get("attrs") or {})
            spans[r["id"]] = {
                "id": r["id"],
                "ref": f"{proc}:{r['id']}",
                "name": r.get("name", "?"),
                "local_parent": r.get("parent"),
                "t0": float(r.get("t", 0.0)),
                "t1": None,
                "status": None,
                "error": None,
                "thread": r.get("thread", "?"),
                "attrs": attrs,
                "trace": attrs.get("trace"),
                "wire_parent": attrs.get("wire_parent"),
            }
        elif kind == "span_end":
            s = spans.get(r["id"])
            if s is not None:
                s["t1"] = float(r.get("t", 0.0))
                s["status"] = r.get("status")
                s["error"] = r.get("error")
        elif kind == "event":
            attrs = dict(r.get("attrs") or {})
            events.append({
                "name": r.get("name", "?"),
                "t": float(r.get("t", 0.0)),
                "span": r.get("span"),
                "thread": r.get("thread", "?"),
                "attrs": attrs,
                "trace": attrs.get("trace"),
            })
    return {
        "path": str(path),
        "label": _label_of(path, meta),
        "proc": proc,
        "pid": pid,
        "start_ts": start_ts,
        "spans": spans,
        "events": events,
        "n_records": len(records),
    }


def _propagate_traces(process: dict) -> None:
    """Within one process, a span's trace id flows down the LOCAL
    parent chain: only the ingress span (and explicitly tagged deep
    spans) must carry the attr; everything nested under it inherits.
    An explicit tag always wins over inheritance."""
    spans = process["spans"]
    children: dict[int, list[int]] = {}
    for sid, s in spans.items():
        lp = s["local_parent"]
        if lp is not None:
            children.setdefault(lp, []).append(sid)
    # Seed from explicitly tagged spans, walk down; explicit child tags
    # are respected (a convoy batch span may fan into several traces).
    frontier = [sid for sid, s in spans.items() if s["trace"]]
    while frontier:
        nxt: list[int] = []
        for sid in frontier:
            tid = spans[sid]["trace"]
            for cid in children.get(sid, ()):
                c = spans[cid]
                if not c["trace"]:
                    c["trace"] = tid
                    nxt.append(cid)
        frontier = nxt
    # Events inherit their enclosing span's trace when untagged.
    for ev in process["events"]:
        if not ev["trace"] and ev["span"] in spans:
            ev["trace"] = spans[ev["span"]]["trace"]


def assemble(sources) -> dict:
    """Join flight files/dirs into per-trace span sets.

    Returns ``{"processes": [...], "traces": {trace_id: trace}}`` where
    each trace is::

        {"trace_id", "spans": [...], "events": [...], "roots": [refs],
         "open": [refs], "linked": [refs], "unresolved": [wire refs],
         "processes": [...], "single_rooted": bool}

    Every span carries ``ref`` / ``parent_ref`` (the local parent's
    global ref, or the wire parent for a cross-process hop) and
    epoch-anchored ``start``/``end`` (meta ``start_ts`` + monotonic
    ``t`` — the same anchoring the chrome exporter uses). ``open``
    spans (no end record — the process died inside them) are the
    SIGKILL diagnosis and are flagged, never dropped. A span whose
    parent was recorded but belongs to ANOTHER trace is a cross-trace
    link, not a root — the convoy case: a follower's ``convoy_member``
    span is explicitly parented to the LEADER's ``convoy_batch`` span,
    which lives in the leader's trace. Those land in ``linked``. A
    trace is ``single_rooted`` when exactly one span is a true root
    and every wire parent resolved — the "every span parented"
    acceptance verdict (linked spans ARE parented)."""
    files = flight_files(sources)
    processes = [seg for p in files for seg in _load_processes(p)]
    for proc in processes:
        _propagate_traces(proc)
    all_refs: dict[str, dict] = {}
    for proc in processes:
        for s in proc["spans"].values():
            all_refs[s["ref"]] = s
    traces: dict[str, dict] = {}
    for proc in processes:
        spans = proc["spans"]
        for s in spans.values():
            tid = s["trace"]
            if not tid:
                continue
            tr = traces.setdefault(tid, {
                "trace_id": tid, "spans": [], "events": [],
                "roots": [], "open": [], "linked": [],
                "unresolved": [], "processes": [],
            })
            lp = s["local_parent"]
            local_parent_ref = (
                f"{proc['proc']}:{lp}" if lp is not None and lp in spans
                else None
            )
            parent_ref = s["wire_parent"] or local_parent_ref
            start = proc["start_ts"] + s["t0"]
            end = (proc["start_ts"] + s["t1"]
                   if s["t1"] is not None else None)
            tr["spans"].append({
                "ref": s["ref"],
                "name": s["name"],
                "proc": proc["proc"],
                "label": proc["label"],
                "thread": s["thread"],
                "start": start,
                "end": end,
                "open": s["t1"] is None,
                "status": s["status"],
                "error": s["error"],
                "parent_ref": parent_ref,
                "wire_parent": s["wire_parent"],
                "attrs": s["attrs"],
            })
            if proc["label"] not in tr["processes"]:
                tr["processes"].append(proc["label"])
        for ev in proc["events"]:
            tid = ev["trace"]
            if not tid or tid not in traces:
                continue
            traces[tid]["events"].append({
                "name": ev["name"],
                "t": proc["start_ts"] + ev["t"],
                "proc": proc["proc"],
                "label": proc["label"],
                "attrs": ev["attrs"],
            })
    for tr in traces.values():
        in_trace = {s["ref"] for s in tr["spans"]}
        for s in tr["spans"]:
            pr = s["parent_ref"]
            if pr is None:
                tr["roots"].append(s["ref"])
            elif pr not in in_trace:
                if pr in all_refs:
                    # Parented into a recorded span of ANOTHER trace —
                    # the convoy follower->leader link. Parented, so
                    # not a root; kept visible under "linked".
                    tr["linked"].append(s["ref"])
                else:
                    # A wire parent nothing recorded: the upstream's
                    # flight file is missing from the join.
                    tr["unresolved"].append(pr)
                    tr["roots"].append(s["ref"])
            if s["open"]:
                tr["open"].append(s["ref"])
        tr["spans"].sort(key=lambda s: s["start"])
        tr["events"].sort(key=lambda e: e["t"])
        tr["single_rooted"] = (
            len(tr["roots"]) == 1 and not tr["unresolved"]
        )
    return {
        "processes": [
            {k: p[k] for k in ("path", "label", "proc", "pid",
                               "start_ts", "n_records")}
            for p in processes
        ],
        "traces": traces,
    }


# -- exports ------------------------------------------------------------------


def perfetto_trace(trace: dict) -> dict:
    """One assembled trace -> Perfetto/chrome trace-event JSON: one pid
    per PROCESS (router / replica-0 / worker-...), one tid per OS
    thread within it, ts anchored to the trace's first span. Open
    spans emit begin-only "B" events (the killed-replica death point
    stays visible in the viewer — same convention as
    ``chrome_trace_from_records``)."""
    spans = trace["spans"]
    t_base = min((s["start"] for s in spans), default=0.0)
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    meta: list[dict] = []
    events: list[dict] = []
    for s in spans:
        if s["proc"] not in pids:
            pids[s["proc"]] = len(pids)
            meta.append({"name": "process_name", "ph": "M",
                         "pid": pids[s["proc"]], "tid": 0,
                         "args": {"name": s["label"]}})
        pid = pids[s["proc"]]
        tkey = (s["proc"], s["thread"])
        if tkey not in tids:
            tids[tkey] = len([k for k in tids if k[0] == s["proc"]])
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tids[tkey],
                         "args": {"name": s["thread"]}})
        tid = tids[tkey]
        args = dict(s["attrs"])
        args["span_ref"] = s["ref"]
        if s["parent_ref"] is not None:
            args["parent_ref"] = s["parent_ref"]
        args["trace_id"] = trace["trace_id"]
        if s["error"]:
            args["error"] = s["error"]
        ts = (s["start"] - t_base) * 1e6
        if s["open"]:
            events.append({"name": s["name"], "ph": "B", "pid": pid,
                           "tid": tid, "ts": ts, "args": args})
        else:
            events.append({
                "name": s["name"], "ph": "X", "pid": pid, "tid": tid,
                "ts": ts,
                "dur": max(0.0, (s["end"] - s["start"]) * 1e6),
                "args": args,
            })
    for ev in trace.get("events", ()):
        pid = pids.get(ev["proc"])
        if pid is None:
            continue
        events.append({"name": ev["name"], "ph": "i", "s": "t",
                       "pid": pid, "tid": 0,
                       "ts": (ev["t"] - t_base) * 1e6,
                       "args": dict(ev["attrs"])})
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _median(values: list[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if n == 0:
        return 0.0
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def hop_summary(assembly: dict) -> dict:
    """Per-hop (span name) aggregates over every assembled trace:
    count, open count, p50 wall seconds, and — for spans carrying the
    convoy's ``queue_wait_ms`` attr — the p50 queue wait. The
    ``kind:"trace"`` regression rows (``observe/regress.py``) are these
    numbers, one row per hop."""
    by_hop: dict[str, dict] = {}
    for tr in assembly["traces"].values():
        for s in tr["spans"]:
            h = by_hop.setdefault(s["name"], {
                "count": 0, "open": 0, "walls": [], "queue_waits": [],
            })
            h["count"] += 1
            if s["open"]:
                h["open"] += 1
            else:
                h["walls"].append(s["end"] - s["start"])
            qw = s["attrs"].get("queue_wait_ms")
            if isinstance(qw, (int, float)):
                h["queue_waits"].append(float(qw))
    out = {}
    for name, h in sorted(by_hop.items()):
        row = {
            "count": h["count"],
            "open": h["open"],
            "wall_p50_s": round(_median(h["walls"]), 6),
        }
        if h["queue_waits"]:
            row["queue_wait_p50_ms"] = round(_median(h["queue_waits"]), 4)
        out[name] = row
    return out


def format_request_tree(trace: dict) -> list[str]:
    """One trace's span tree as printable lines: per-hop wall clock,
    the parent->child start delta (the cross-hop queue/network wait),
    and the convoy's explicit ``queue_wait_ms`` where recorded — the
    ``trace_summary.py --request`` rendering."""
    spans = {s["ref"]: s for s in trace["spans"]}
    linked = set(trace.get("linked") or ())
    children: dict[str | None, list[str]] = {}
    for s in trace["spans"]:
        parent = s["parent_ref"] if s["parent_ref"] in spans else None
        children.setdefault(parent, []).append(s["ref"])
    for refs in children.values():
        refs.sort(key=lambda r: spans[r]["start"])
    lines = [f"trace {trace['trace_id']}  "
             f"({len(trace['spans'])} spans, "
             f"{len(trace['processes'])} processes: "
             f"{', '.join(trace['processes'])})"]
    if trace["unresolved"]:
        lines.append(f"  !! {len(trace['unresolved'])} unresolved wire "
                     f"parent(s): {', '.join(trace['unresolved'])}")

    def walk(ref: str, depth: int, parent_start: float | None) -> None:
        s = spans[ref]
        wall = (f"{(s['end'] - s['start']) * 1e3:9.3f} ms"
                if not s["open"] else "     OPEN   ")
        delta = ("" if parent_start is None else
                 f"  +{(s['start'] - parent_start) * 1e3:.3f} ms")
        qw = s["attrs"].get("queue_wait_ms")
        qtxt = (f"  queue_wait {float(qw):.3f} ms"
                if isinstance(qw, (int, float)) else "")
        err = f"  ERROR: {s['error']}" if s["error"] else ""
        hop = f"[{s['label']}] " if depth <= 1 or s["wire_parent"] else ""
        link = (f"  (linked under {s['parent_ref']})"
                if ref in linked else "")
        lines.append(f"  {'  ' * depth}{wall}  {hop}{s['name']}"
                     f"{delta}{qtxt}{err}{link}")
        for cref in children.get(ref, ()):
            walk(cref, depth + 1, s["start"])

    for root in children.get(None, ()):
        walk(root, 0, None)
    for ref in trace["open"]:
        s = spans[ref]
        lines.append(f"  !! span {s['name']} ({ref}) still OPEN — the "
                     f"process died inside it")
    return lines
