"""``pjtpu top`` — the fleet-wide operations console (ISSUE 12).

Before this module every operational surface was its own island:
``serve_stats.json`` per store, ``pjtpu fleet status`` per coordinator,
per-worker heartbeats, ``repair_status.json`` per graph directory. One
incident means four file formats and no joined picture. ``top`` reads
them all — every one an ATOMICALLY-published snapshot, so a reader
never blocks a producer and a SIGKILLed producer's last view stays
readable — and joins them into a single document:

- **serve**: per graph directory, throughput (windowed queries/sec from
  the live snapshot's rate counters), streaming-histogram p50/p99 with
  their error bounds, hit/stale/error counters, and the SLO burn
  verdict;
- **fleet**: the coordinator's lease table (pending/leased/committed,
  requeues, outstanding leases with deadlines), per-worker heartbeats
  (stage, batches done, ETA) and live-metrics snapshots (lease
  claim-to-commit latency, solver batch walls, retry rates);
- **repairs**: each graph's ``repair_status.json`` (state, dirty parts,
  remaining sources).

Every joined snapshot carries its AGE (seconds since its own publish
stamp) and a ``stale`` flag once the age exceeds ``stale_after_s`` —
the heartbeat-freshness idiom: a fresh file is a live process, a stale
one is hung or dead, and the console says which rather than presenting
dead numbers as current.

``gather_ops`` returns the JSON document (``pjtpu top --once --json``
emits it verbatim for scripts/CI); ``render_ops`` formats the ASCII
console view the live-refresh loop repaints.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


def _age(ts: float | None, now: float) -> float | None:
    if ts is None:
        return None
    return round(now - float(ts), 3)


def _flag_stale(entry: dict, ts: float | None, now: float,
                stale_after_s: float) -> None:
    age = _age(ts, now)
    entry["age_s"] = age
    entry["stale"] = age is None or age > stale_after_s


def _read_json(path: Path) -> dict | None:
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def _summarize_live(live: dict | None) -> dict:
    """Compact the registry snapshot embedded in serve_stats.json /
    a worker metrics file: windowed rates, key histograms (estimate +
    error bound), SLO verdicts."""
    if not live:
        return {}
    out: dict = {}
    counters = live.get("counters") or {}
    rates = {}
    for name, c in counters.items():
        rate_keys = sorted(k for k in c if k.startswith("rate_"))
        if rate_keys:
            rates[name] = {k: c[k] for k in rate_keys}
            rates[name]["total"] = c.get("total")
    if rates:
        out["rates"] = rates
    from paralleljohnson_tpu.observe.live import tail_exemplars_from_dict
    hists = {}
    for name, h in (live.get("histograms") or {}).items():
        hists[name] = {
            k: h.get(k)
            for k in ("count", "mean", "p50_ms", "p50_err_ms",
                      "p99_ms", "p99_err_ms", "max")
            if k in h
        }
        # Tail exemplars (ISSUE 20): the trace ids behind the slowest
        # buckets, so the p99 number links straight to a request tree.
        tail = tail_exemplars_from_dict(h.get("hist"))
        if tail:
            hists[name]["tail_exemplars"] = [
                {"trace_id": e, "ms": round(v, 3)} for e, v in tail]
    if hists:
        out["histograms"] = hists
    slos = {}
    for name, s in (live.get("slos") or {}).items():
        slos[name] = {
            "burning": s.get("burning"),
            "burn_rate": s.get("burn_rate"),
            "bad_total": s.get("bad_total"),
            "events_total": s.get("events_total"),
            "latency": s.get("latency"),
        }
    if slos:
        out["slos"] = slos
    if live.get("gauges"):
        out["gauges"] = live["gauges"]
    return out


def _gather_serve(root: Path, now: float, stale_after_s: float) -> list[dict]:
    from paralleljohnson_tpu.incremental.status import read_repair_status
    from paralleljohnson_tpu.serve.engine import SERVE_STATS_FILENAME

    entries = []
    for d in sorted({root, *root.glob("graph_*")}):
        stats = _read_json(d / SERVE_STATS_FILENAME)
        repair = read_repair_status(d)
        if stats is None and repair is None:
            continue
        entry: dict = {"dir": str(d)}
        if stats is not None:
            engine = stats.get("engine") or {}
            store = stats.get("store") or {}
            live_summary = _summarize_live(stats.get("live"))
            shed_rate = ((live_summary.get("rates") or {})
                         .get("pjtpu_shed_answers") or {}).get("rate_60s")
            entry["serve"] = {
                "pid": stats.get("pid"),
                "queries_total": engine.get("queries_total"),
                "errors": engine.get("errors"),
                "stale_answers": engine.get("stale_answers"),
                # Traffic-front-end overload columns (ISSUE 15): how
                # much of the answer stream is certified-degraded, and
                # what admission turned away.
                "shed_answers": engine.get("shed_answers"),
                "shed_rate_60s": shed_rate,
                "rejected": engine.get("rejected"),
                "deadline_drops": engine.get("deadline_drops"),
                "open_connections": engine.get("open_connections"),
                # Lookup-path dispatch (ISSUE 16): which path answered
                # and how wide the aggregated batches ran.
                "device_lookups": engine.get("device_lookups"),
                "host_lookups": engine.get("host_lookups"),
                "batch_width_p50": engine.get("batch_width_p50"),
                "batch_width_p99": engine.get("batch_width_p99"),
                "hits_by_tier": engine.get("hits_by_tier"),
                # Certified approximate tier (ISSUE 17): how much of
                # the answer stream is flagged approximate, how much
                # of that the hopset tier served, and the attached
                # hopset's provenance knobs.
                "approx_answers": engine.get("approx_answers"),
                "hopset_answers": engine.get("hopset_answers"),
                "hopset": stats.get("hopset"),
                "p50_ms": engine.get("p50_ms"),
                "p50_err_ms": engine.get("p50_err_ms"),
                "p99_ms": engine.get("p99_ms"),
                "p99_err_ms": engine.get("p99_err_ms"),
                "hit_rate": store.get("hit_rate"),
                "digest": store.get("digest"),
                "live": live_summary,
            }
            _flag_stale(entry["serve"], stats.get("ts"), now, stale_after_s)
        if repair is not None:
            remaining = repair.get("remaining")
            entry["repair"] = {
                "status": repair.get("status"),
                "new_digest": repair.get("new_digest"),
                "dirty_parts": repair.get("dirty_parts"),
                "parts_total": repair.get("parts_total"),
                "affected": (
                    "all" if repair.get("affected") == "all"
                    else len(repair.get("affected") or [])
                ),
                "remaining": (
                    "all" if remaining == "all"
                    else len(remaining or [])
                ),
                "reason": repair.get("reason"),
            }
            _flag_stale(entry["repair"], repair.get("ts"), now, stale_after_s)
        entries.append(entry)
    return entries


def _gather_fleet(coord_dir: Path, now: float, stale_after_s: float) -> dict:
    from paralleljohnson_tpu.distributed.coordinator import (
        Coordinator,
        CoordinatorError,
    )

    try:
        coord = Coordinator(coord_dir)
    except CoordinatorError as e:
        return {"dir": str(coord_dir), "error": str(e)}
    status = coord.status(now=now)
    workers: dict[str, dict] = {}
    hb_dir = coord_dir / "heartbeats"
    if hb_dir.is_dir():
        for p in sorted(hb_dir.glob("*.json")):
            hb = _read_json(p) or {}
            w: dict = {
                "pid": hb.get("pid"),
                "stage": hb.get("stage"),
                "lease": hb.get("lease"),
                "lease_range": hb.get("lease_range"),
                "batches_done": hb.get("batches_done"),
                "sources_done": hb.get("sources_done"),
                "sources_total": hb.get("sources_total"),
                "eta_s": hb.get("eta_s"),
                "leases_committed": hb.get("leases_committed"),
                "last_event": hb.get("last_event"),
            }
            _flag_stale(w, hb.get("ts"), now, stale_after_s)
            workers[p.stem] = w
    metrics_dir = coord_dir / "metrics"
    if metrics_dir.is_dir():
        for p in sorted(metrics_dir.glob("*.json")):
            snap = _read_json(p)
            if snap is None:
                continue
            w = workers.setdefault(p.stem, {})
            live = {"pid": snap.get("pid"),
                    **_summarize_live(snap)}
            _flag_stale(live, snap.get("ts"), now, stale_after_s)
            w["metrics"] = live
    return {
        "dir": str(coord_dir),
        "graph_spec": status.get("graph_spec"),
        "leases": status.get("leases"),
        "leases_total": status.get("leases_total"),
        "requeues": status.get("requeues"),
        "extensions": status.get("extensions"),
        "outstanding": status.get("outstanding"),
        "committed_by": status.get("committed_by"),
        "done": status.get("done"),
        "workers": workers,
    }


def _gather_serve_fleet(fleet_dir: Path, now: float,
                        stale_after_s: float) -> dict:
    """Merged serve-fleet view (ISSUE 18): membership records under
    ``<fleet>/serve/replicas/`` + the published ``routing.json``, joined
    into one service-level document. Per-replica counters sum; the
    per-replica latency histograms MERGE (same log-bucket geometry by
    construction — round 17's design goal), so the fleet p50/p99 carry
    the same one-bucket error bound as any single replica's. Dead/stale
    replicas stay in the document, flagged, but never contribute to the
    merge. Absent/torn files degrade — never a crash."""
    from paralleljohnson_tpu.observe.live import LogHistogram
    from paralleljohnson_tpu.serve import fleet as fleet_mod

    doc: dict = {"dir": str(fleet_dir), "routing": None,
                 "replicas": {}, "merged": None}
    routing = _read_json(fleet_mod.routing_path(fleet_dir))
    if routing is not None:
        doc["routing"] = {
            "epoch": routing.get("epoch"),
            "vnodes": routing.get("vnodes"),
            "replicas": sorted(routing.get("replicas") or {}),
        }
        _flag_stale(doc["routing"], routing.get("ts"), now, stale_after_s)
    records = fleet_mod.read_replicas(
        fleet_dir, stale_after_s=stale_after_s, now=now
    )
    counter_keys = ("queries_total", "exact_answers", "approx_answers",
                    "hopset_answers", "errors", "stale_answers",
                    "shed_answers", "rejected", "deadline_drops",
                    "client_limited", "open_connections")
    merged_hist = None
    merge_error = None
    counters: dict = {}
    slo_bad = 0.0
    slo_events = 0.0
    burning = False
    objective = None
    alive = 0
    for rec in records:
        rid = rec.get("replica_id") or "?"
        stats = rec.get("stats") or {}
        live = rec.get("live") or {}
        entry = {
            "host": rec.get("host"),
            "port": rec.get("port"),
            "pid": rec.get("pid"),
            "torn": bool(rec.get("torn")),
            "age_s": rec.get("age_s"),
            "stale": bool(rec.get("stale", True)),
            "queries_total": stats.get("queries_total"),
            "p50_ms": stats.get("p50_ms"),
            "p99_ms": stats.get("p99_ms"),
            "p99_err_ms": stats.get("p99_err_ms"),
            "shed_answers": stats.get("shed_answers"),
            "rejected": stats.get("rejected"),
            "client_limited": stats.get("client_limited"),
            "open_connections": stats.get("open_connections"),
        }
        doc["replicas"][rid] = entry
        if entry["stale"]:
            continue  # flagged corpse: shown, never merged
        alive += 1
        for k in counter_keys:
            v = stats.get(k)
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        serve_slo = (live.get("slos") or {}).get("serve") or {}
        if serve_slo:
            slo_bad += float(serve_slo.get("bad_total") or 0.0)
            slo_events += float(serve_slo.get("events_total") or 0.0)
            burning = burning or bool(serve_slo.get("burning"))
            objective = objective or serve_slo.get("objective")
        hist_doc = (((live.get("histograms") or {})
                     .get("pjtpu_query_latency_ms") or {}).get("hist"))
        if hist_doc and merge_error is None:
            try:
                h = LogHistogram.from_dict(hist_doc)
                merged_hist = (h if merged_hist is None
                               else merged_hist.merge(h))
            except (ValueError, TypeError, KeyError) as e:
                # Geometry guard: mismatched bucketings must never
                # silently corrupt the merged percentiles — degrade to
                # per-replica data with the reason on the document.
                merge_error = str(e)
                merged_hist = None
    merged: dict = {"replicas_live": alive,
                    "replicas_total": len(records),
                    "counters": counters}
    if merge_error is not None:
        merged["histogram_merge_error"] = merge_error
    elif merged_hist is not None and merged_hist.count:
        merged.update({
            k: round(v, 4)
            for k, v in merged_hist.percentiles((50, 99)).items()
        })
        tail = merged_hist.tail_exemplars()
        if tail:
            merged["tail_exemplars"] = [
                {"trace_id": e, "ms": round(v, 3)} for e, v in tail]
    slo: dict = {"burning": burning, "bad_total": slo_bad,
                 "events_total": slo_events}
    if slo_events > 0:
        slo["availability"] = round(1.0 - slo_bad / slo_events, 6)
    if objective:
        slo["objective"] = objective
        target = objective.get("latency_ms")
        pct = objective.get("latency_pct", 99.0)
        if (target is not None and merge_error is None
                and merged_hist is not None and merged_hist.count):
            pr = merged_hist.percentile(pct)
            slo["latency"] = {
                "pct": pct,
                "observed_ms": round(pr["value"], 4),
                "max_error_ms": round(pr["max_error"], 4),
                "target_ms": target,
                # The honest tri-state (round 17): a bucket bound that
                # straddles the target says so instead of picking a side.
                "met": (True if pr["upper"] <= target
                        else False if pr["lower"] > target
                        else "within-error-bound"),
            }
    # One service-level verdict: a burning replica or a missed merged
    # latency target degrades the whole fleet's word.
    lat_met = (slo.get("latency") or {}).get("met")
    merged["verdict"] = ("burning" if burning
                         else "degraded" if lat_met is False
                         else "no-replicas" if alive == 0
                         else "ok")
    merged["slo"] = slo
    doc["merged"] = merged
    return doc


def gather_ops(
    *,
    serve_store: str | Path | None = None,
    coordinator_dir: str | Path | None = None,
    serve_fleet: str | Path | None = None,
    stale_after_s: float = 15.0,
    now: float | None = None,
) -> dict:
    """One joined operations document (the ``--once --json`` payload).
    Any source may be absent — the document reports what exists and
    flags by age what stopped publishing."""
    now = time.time() if now is None else now
    doc: dict = {
        "ts": now,
        "stale_after_s": float(stale_after_s),
        "serve": [],
        "serve_fleet": None,
        "fleet": None,
        "repairs": [],
    }
    if serve_store is not None:
        root = Path(serve_store)
        entries = _gather_serve(root, now, stale_after_s)
        doc["serve"] = [e for e in entries if "serve" in e]
        doc["repairs"] = [
            {"dir": e["dir"], **e["repair"]}
            for e in entries if "repair" in e
        ]
    if serve_fleet is not None:
        doc["serve_fleet"] = _gather_serve_fleet(Path(serve_fleet), now,
                                                 stale_after_s)
    if coordinator_dir is not None:
        doc["fleet"] = _gather_fleet(Path(coordinator_dir), now,
                                     stale_after_s)
    return doc


# -- rendering ----------------------------------------------------------------


def _fmt(v, nd: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "y" if v else "n"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _staleness(entry: dict) -> str:
    if entry.get("stale"):
        return (f"STALE ({_fmt(entry.get('age_s'), 1)}s old)"
                if entry.get("age_s") is not None else "STALE (no snapshot)")
    return f"fresh {_fmt(entry.get('age_s'), 1)}s"


def _render_serve(lines: list[str], entries: list[dict]) -> None:
    for e in entries:
        s = e["serve"]
        lines.append(f"SERVE {e['dir']}  [{_staleness(s)}]")
        live = s.get("live") or {}
        qrate = ((live.get("rates") or {}).get("pjtpu_queries") or {})
        rate_60 = qrate.get("rate_60s")
        lines.append(
            f"  queries {_fmt(s.get('queries_total'))} "
            f"({_fmt(rate_60)}/s 1m)   "
            f"p50 {_fmt(s.get('p50_ms'))}±{_fmt(s.get('p50_err_ms'))} ms   "
            f"p99 {_fmt(s.get('p99_ms'))}±{_fmt(s.get('p99_err_ms'))} ms   "
            f"hit {_fmt(s.get('hit_rate'))}   "
            f"stale-answers {_fmt(s.get('stale_answers'))}   "
            f"errors {_fmt(s.get('errors'))}"
        )
        # Overload line only when the front end saw any of it — a plain
        # JSONL-loop serve keeps the old two-line layout.
        if any(s.get(k) for k in ("shed_answers", "rejected",
                                  "deadline_drops", "open_connections")):
            lines.append(
                f"  shed {_fmt(s.get('shed_answers'))} "
                f"({_fmt(s.get('shed_rate_60s'))}/s 1m)   "
                f"rejected {_fmt(s.get('rejected'))}   "
                f"deadline-drops {_fmt(s.get('deadline_drops'))}   "
                f"conns {_fmt(s.get('open_connections'))}"
            )
        # Approximate-tier line only when a hopset is attached or an
        # approximate answer was actually served (ISSUE 17) — exact-
        # only engines keep the compact layout.
        if s.get("hopset") or s.get("approx_answers"):
            h = s.get("hopset") or {}
            lines.append(
                f"  approx {_fmt(s.get('approx_answers'))} "
                f"(hopset {_fmt(s.get('hopset_answers'))})   "
                f"hopset eps {_fmt(h.get('epsilon'))} "
                f"beta {_fmt(h.get('beta'), 0)} "
                f"k {_fmt(h.get('k'), 0)} "
                f"edges {_fmt(h.get('edges'), 0)}"
                + ("" if h.get("converged") is None
                   else f" converged {_fmt(h.get('converged'))}")
            )
        # Lookup-path line only once a path counter moved (older
        # snapshots and idle engines keep the compact layout).
        if s.get("device_lookups") or s.get("host_lookups"):
            lines.append(
                f"  lookups device {_fmt(s.get('device_lookups'))} / "
                f"host {_fmt(s.get('host_lookups'))}   "
                f"batch-width p50 {_fmt(s.get('batch_width_p50'))} "
                f"p99 {_fmt(s.get('batch_width_p99'))}"
            )
        # Tail exemplar line (ISSUE 20): trace ids behind the slowest
        # latency buckets — feed them to `scripts/trace_summary.py
        # --request ID` for the full span tree.
        tail = (((live.get("histograms") or {})
                 .get("pjtpu_query_latency_ms") or {}).get("tail_exemplars"))
        if tail:
            lines.append(
                "  tail traces " + "  ".join(
                    f"{t['trace_id']}@{_fmt(t['ms'])}ms" for t in tail))
        for name, slo in (live.get("slos") or {}).items():
            lat = slo.get("latency") or {}
            verdict = "BURNING" if slo.get("burning") else "ok"
            lines.append(
                f"  SLO {name}: {verdict} (burn {_fmt(slo.get('burn_rate'))}"
                f", bad {_fmt(slo.get('bad_total'), 0)}/"
                f"{_fmt(slo.get('events_total'), 0)})"
                + (f"   p{_fmt(lat.get('pct'), 0)} "
                   f"{_fmt(lat.get('observed_ms'))} ms "
                   f"(±{_fmt(lat.get('max_error_ms'))}) "
                   f"vs target {_fmt(lat.get('target_ms'))} ms -> "
                   f"{lat.get('met')}" if lat else "")
            )


def _render_serve_fleet(lines: list[str], doc: dict) -> None:
    merged = doc.get("merged") or {}
    slo = merged.get("slo") or {}
    lat = slo.get("latency") or {}
    counters = merged.get("counters") or {}
    lines.append(
        f"SERVE-FLEET {doc.get('dir')}  "
        f"[{_fmt(merged.get('replicas_live'), 0)}/"
        f"{_fmt(merged.get('replicas_total'), 0)} live]  "
        f"verdict {merged.get('verdict', '-').upper()}"
    )
    routing = doc.get("routing")
    if routing:
        lines.append(
            f"  routing epoch {_fmt(routing.get('epoch'), 0)} "
            f"vnodes {_fmt(routing.get('vnodes'), 0)} over "
            f"{len(routing.get('replicas') or [])} replicas "
            f"[{_staleness(routing)}]"
        )
    if merged.get("histogram_merge_error"):
        lines.append(
            f"  merged percentiles unavailable "
            f"(geometry guard): {merged['histogram_merge_error']}"
        )
    else:
        lines.append(
            f"  merged queries {_fmt(counters.get('queries_total'))}   "
            f"p50 {_fmt(merged.get('p50_ms'))}"
            f"±{_fmt(merged.get('p50_err_ms'))} ms   "
            f"p99 {_fmt(merged.get('p99_ms'))}"
            f"±{_fmt(merged.get('p99_err_ms'))} ms   "
            f"shed {_fmt(counters.get('shed_answers'))}   "
            f"rejected {_fmt(counters.get('rejected'))}   "
            f"client-limited {_fmt(counters.get('client_limited'))}"
        )
        tail = merged.get("tail_exemplars") or []
        if tail:
            lines.append(
                "  tail traces " + "  ".join(
                    f"{t['trace_id']}@{_fmt(t['ms'])}ms" for t in tail))
    if slo:
        lines.append(
            f"  SLO fleet: {'BURNING' if slo.get('burning') else 'ok'} "
            f"(bad {_fmt(slo.get('bad_total'), 0)}/"
            f"{_fmt(slo.get('events_total'), 0)}"
            + (f", availability {_fmt(slo.get('availability'), 4)}"
               if slo.get("availability") is not None else "")
            + ")"
            + (f"   p{_fmt(lat.get('pct'), 0)} "
               f"{_fmt(lat.get('observed_ms'))} ms "
               f"(±{_fmt(lat.get('max_error_ms'))}) vs target "
               f"{_fmt(lat.get('target_ms'))} ms -> {lat.get('met')}"
               if lat else "")
        )
    for rid, r in (doc.get("replicas") or {}).items():
        addr = f"{r.get('host')}:{r.get('port')}" if r.get("port") else "-"
        flag = ("TORN" if r.get("torn")
                else _staleness(r))
        lines.append(
            f"  {rid:<14} {addr:<22} [{flag}]  "
            f"queries {_fmt(r.get('queries_total'))}   "
            f"p99 {_fmt(r.get('p99_ms'))}"
            f"±{_fmt(r.get('p99_err_ms'))} ms   "
            f"conns {_fmt(r.get('open_connections'))}"
        )


def _render_fleet(lines: list[str], fleet: dict) -> None:
    lines.append(f"FLEET {fleet.get('dir')}")
    if "error" in fleet:
        lines.append(f"  error: {fleet['error']}")
        return
    by_state = fleet.get("leases") or {}
    lines.append(
        f"  {fleet.get('graph_spec')}   leases "
        f"{_fmt(by_state.get('committed'), 0)} committed / "
        f"{_fmt(by_state.get('leased'), 0)} leased / "
        f"{_fmt(by_state.get('pending'), 0)} pending of "
        f"{_fmt(fleet.get('leases_total'), 0)}   "
        f"requeues {_fmt(fleet.get('requeues'), 0)}   "
        f"extensions {_fmt(fleet.get('extensions'), 0)}"
        + ("   DONE" if fleet.get("done") else "")
    )
    for lease in fleet.get("outstanding") or []:
        lines.append(
            f"  lease {lease.get('lease')} {lease.get('range')} "
            f"owner {lease.get('owner')} deadline in "
            f"{_fmt(lease.get('deadline_in_s'), 1)}s"
        )
    workers = fleet.get("workers") or {}
    if workers:
        lines.append(
            f"  {'worker':<12} {'state':<22} {'stage':<12} "
            f"{'done/total':<12} {'eta':<8} {'lease-p50':<12} committed"
        )
    for name, w in workers.items():
        m = (w.get("metrics") or {})
        lease_hist = ((m.get("histograms") or {})
                      .get("pjtpu_lease_wall_ms") or {})
        done = (f"{_fmt(w.get('sources_done'), 0)}/"
                f"{_fmt(w.get('sources_total'), 0)}")
        lines.append(
            f"  {name:<12} {_staleness(w):<22} "
            f"{_fmt(w.get('stage')):<12} {done:<12} "
            f"{_fmt(w.get('eta_s'), 1):<8} "
            f"{_fmt(lease_hist.get('p50_ms'), 1):<12} "
            f"{_fmt(w.get('leases_committed'), 0)}"
        )


def _render_repairs(lines: list[str], repairs: list[dict]) -> None:
    for r in repairs:
        lines.append(
            f"REPAIR {r.get('dir')}  [{_staleness(r)}]\n"
            f"  {r.get('status')} -> {r.get('new_digest')}   dirty parts "
            f"{_fmt(r.get('dirty_parts'), 0)}/{_fmt(r.get('parts_total'), 0)}"
            f"   affected {r.get('affected')}   remaining "
            f"{r.get('remaining')}"
            + (f"   reason: {r.get('reason')}" if r.get("reason") else "")
        )


def render_ops(doc: dict) -> str:
    """ASCII console view of one gathered document."""
    t = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(doc.get("ts")))
    lines = [
        f"pjtpu top — {t} (snapshots stale after "
        f"{_fmt(doc.get('stale_after_s'), 0)}s)"
    ]
    if doc.get("serve"):
        _render_serve(lines, doc["serve"])
    if doc.get("serve_fleet"):
        _render_serve_fleet(lines, doc["serve_fleet"])
    if doc.get("fleet"):
        _render_fleet(lines, doc["fleet"])
    if doc.get("repairs"):
        _render_repairs(lines, doc["repairs"])
    if len(lines) == 1:
        lines.append(
            "nothing to show — point --serve-store at a checkpoint/store "
            "directory, --fleet-dir at a serve-fleet directory, and/or "
            "--coordinator-dir at a fleet directory"
        )
    return "\n".join(lines)
