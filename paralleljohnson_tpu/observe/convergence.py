"""Convergence observatory (ISSUE 9 tentpole) — see *inside* the
iterative relaxation loops.

Every sweep/GS/DIA/bucket solve has been a black box between "stage
started" and "stage converged": the flight recorder (round 10) and the
cost observatory (round 12) see *between* stages, never the
per-iteration trajectory. ROADMAP item 4 (JFR frontier compaction) is
premised on the active frontier collapsing in late iterations, and the
cost model's iterative routes need iterations-to-converge as a
predictable input — both need the trajectory measured, not assumed.

Mechanism: each instrumented ``lax.while_loop`` iteration accumulates
three numbers into device-resident buffers carried through the loop —

  frontier_size        vertices whose distance label strictly decreased
                       this iteration (any batch row counts the vertex
                       once) — the JFR opportunity metric;
  relaxations_applied  distance LABELS improved this iteration (rows x
                       vertices; equals frontier_size at B=1);
  residual_mass        sum of finite distance decreases (an inf -> finite
                       first-reach contributes 0 — its decrease is not a
                       finite number; the mass decays to 0 at fixpoint).

Zero extra host syncs per iteration: the buffers ride the while_loop
carry and cross to the host ONCE after convergence (the same
``np.asarray`` moment the iteration count already pays). Iterations
past the static buffer cap accumulate into the last row (totals stay
exact; per-iteration resolution truncates — ``summarize_trajectory``
flags it).

Exactness contract (the split-int32 idiom of ``ops/bucket.py``): counts
are int32. A single iteration's addend is bounded by batch x V
(relaxations) — callers on shapes where that bound reaches 2^31 must
run the shared :func:`~paralleljohnson_tpu.utils.metrics.
warn_if_traj_counter_wrapped` guard so a wrapped counter is a warned
lower bound, never a silent lie. ``residual_mass`` is f32 and
advisory (a decay shape, not an exact counter).

Disabled path (no telemetry and no profile store configured): the
backend dispatches the ORIGINAL kernels — the instrumented while_loops
are separate compilations, so the disabled jaxpr is bit-for-bit the
pre-observatory one (asserted in tests/test_trajectory.py).

Host-side consumers: :func:`summarize_trajectory` (iterations, frontier
half-life, tail fraction — ``SolverStats.convergence``),
:func:`trajectory_record` (the per-iteration profile-store record),
:func:`frontier_curve` (downsampled curve for flight-recorder events),
and :func:`estimate_eta` (the trajectory-aware completion estimate the
heartbeat publishes for the TPU watchdog).

Top-level imports are stdlib-only (the offline report script loads this
module without jax); the device-side builders import jax lazily.
"""

from __future__ import annotations

from typing import Any, Callable

# Rows of the device trajectory buffer. Iterations beyond the cap
# accumulate into the last row — totals stay exact, per-iteration
# resolution truncates (summarize_trajectory sets "truncated"). 2048
# rows x (2 x int32 + 1 x f32) = 24 KB of HBM — noise next to one
# [B, V] distance block.
DEFAULT_TRAJ_CAP = 2048

# Frontier below this fraction of V marks a "tail" iteration — the
# iterations JFR-style frontier compaction would collapse (ROADMAP
# item 4's opportunity definition).
TAIL_FRONTIER_FRAC = 0.01


# -- device side (lazy jax imports: tracing-time only) -----------------------


def traj_init(cap: int):
    """Fresh trajectory carries: (counts int32 [cap, 2], resid f32 [cap])
    — columns of ``counts`` are (frontier_size, relaxations_applied)."""
    import jax.numpy as jnp

    return (
        jnp.zeros((int(cap), 2), jnp.int32),
        jnp.zeros((int(cap),), jnp.float32),
    )


def traj_record(counts, resid, i, d, nd, *, batch_axis: int | None = None):
    """Accumulate one iteration's (frontier, relaxations, residual mass)
    into row ``min(i, cap-1)`` of the carried buffers.

    ``d``/``nd`` are the distances before/after the iteration's sweep;
    ``batch_axis`` is the batch dimension of ``d`` (None for B=1 [V]
    vectors, 0 for [B, V], 1 for vertex-major [V, B]) — a vertex counts
    toward the frontier once no matter how many batch rows improved it.
    Pure accumulate-into-carry: XLA aliases the while_loop buffers, so
    the per-iteration cost is one O(size(d)) compare + two O(1) row
    writes, no host transfer."""
    import jax.numpy as jnp

    improved = nd < d
    if batch_axis is None:
        vert_changed = improved
    else:
        vert_changed = jnp.any(improved, axis=batch_axis)
    frontier = jnp.sum(vert_changed, dtype=jnp.int32)
    relaxed = jnp.sum(improved, dtype=jnp.int32)
    # First-reach improvements come from d = +inf: their decrease is not
    # a finite number, so they contribute 0 mass (documented above).
    gain = jnp.where(improved & jnp.isfinite(d), d - nd, 0.0)
    mass = jnp.sum(gain).astype(resid.dtype)
    row = jnp.minimum(i, counts.shape[0] - 1)
    counts = counts.at[row].add(jnp.stack([frontier, relaxed]))
    resid = resid.at[row].add(mass)
    return counts, resid


def instrumented_fixpoint(
    step_fn: Callable,
    dist0,
    *,
    max_iter: int,
    cap: int,
    batch_axis: int | None = None,
):
    """Iterate ``step_fn(d) -> nd`` to fixpoint under ``lax.while_loop``
    with trajectory recording — the instrumented twin of the plain
    ``(dist, i, improving)`` fixpoints in ``ops.relax`` / ``ops.dia``
    (same cond/body contract, two extra carries).

    Returns ``(dist, iterations, still_improving, counts, resid)``;
    decode host-side with :func:`decode_trajectory`."""
    import jax.numpy as jnp
    from jax import lax

    counts0, resid0 = traj_init(cap)

    def cond(state):
        _, i, improving, _, _ = state
        return improving & (i < max_iter)

    def body(state):
        d, i, _, counts, resid = state
        nd = step_fn(d)
        counts, resid = traj_record(
            counts, resid, i, d, nd, batch_axis=batch_axis
        )
        return nd, i + 1, jnp.any(nd < d), counts, resid

    improving0 = jnp.any(jnp.isfinite(dist0))
    return lax.while_loop(
        cond, body, (dist0, jnp.int32(0), improving0, counts0, resid0)
    )


# -- host side (stdlib + numpy only) -----------------------------------------


def decode_trajectory(counts, resid, iterations: int):
    """Device buffers -> the ``[n, 3]`` float64 host trajectory
    (columns: frontier_size, relaxations_applied, residual_mass), where
    ``n = min(iterations, cap)`` — THE one D2H of the whole mechanism.
    Counts decode through int64 so the exact int32 device values never
    round through f32."""
    import numpy as np

    counts = np.asarray(counts)
    resid = np.asarray(resid)
    n = max(0, min(int(iterations), counts.shape[0]))
    out = np.empty((n, 3), np.float64)
    out[:, :2] = counts[:n].astype(np.int64)
    out[:, 2] = resid[:n]
    return out


def summarize_trajectory(
    traj,
    *,
    num_nodes: int,
    batch: int = 1,
    num_edges: int | None = None,
    iterations: int | None = None,
    degree_bias: float | None = None,
) -> dict:
    """The ``SolverStats.convergence`` summary of one decoded trajectory.

    iterations           total loop iterations (>= rows when truncated)
    frontier_peak/last   max / final frontier size
    frontier_half_life   first iteration index whose frontier is <= half
                         the peak and never recovers above it — the
                         collapse speed the JFR evidence quantifies
    tail_iterations /    iterations (count / fraction) whose frontier is
      tail_fraction      below ``TAIL_FRONTIER_FRAC`` of V — full sweeps
                         there relax E edges to improve < 1% of vertices
    jfr_skippable_edge_frac
                         estimated fraction of full-sweep examined edges
                         a frontier-compacted schedule would skip. With
                         ``degree_bias`` (the size-biased mean
                         out-degree E[d^2]/E[d], from the caller's
                         degree array): 1 - sum(min(E, frontier_i x
                         degree_bias)) / (iterations x E) — frontier
                         membership correlates with degree on power-law
                         graphs (hubs are reached early and re-improved
                         often), so pricing frontier mass at the
                         UNIFORM mean degree overweighted hub collapse:
                         rmat_s12 measured 60.0% skippable vs 81.6%
                         uniform-estimated (ISSUE 13 satellite; the
                         regression test pins the recorded fixture).
                         Without ``degree_bias`` the uniform estimate
                         1 - sum(frontier_i) / (iterations x V) stands
                         (identical when degrees are uniform; exact
                         counters from the real frontier/bucket/dw
                         kernels remain the ground truth —
                         scripts/convergence_report.py --evidence)
    relaxations_total /  exact totals (Python ints / float)
      residual_mass_total
    truncated            True when iterations > buffer rows (the last
                         row then holds the whole tail's accumulation
                         and per-iteration resolution stops there)
    """
    import numpy as np

    traj = np.asarray(traj, np.float64)
    rows = int(traj.shape[0])
    iters = int(iterations) if iterations is not None else rows
    out: dict = {
        "iterations": iters,
        "rows": rows,
        "batch": int(batch),
        "num_nodes": int(num_nodes),
        "truncated": iters > rows,
    }
    if rows == 0:
        out.update(
            frontier_peak=0, frontier_last=0, frontier_half_life=0,
            tail_iterations=0, tail_fraction=0.0,
            jfr_skippable_edge_frac=0.0, relaxations_total=0,
            residual_mass_total=0.0,
        )
        return out
    frontier = traj[:, 0]
    peak = float(frontier.max())
    out["frontier_peak"] = int(peak)
    out["frontier_last"] = int(frontier[-1])
    # Half-life: first index from which the frontier STAYS at or below
    # half the peak (a one-iteration dip that recovers is not collapse).
    half = peak / 2.0
    above = np.flatnonzero(frontier > half)
    out["frontier_half_life"] = int(above[-1]) + 1 if above.size else 0
    tail_mask = frontier < TAIL_FRONTIER_FRAC * max(int(num_nodes), 1)
    out["tail_iterations"] = int(tail_mask.sum())
    out["tail_fraction"] = float(tail_mask.sum() / rows)
    # JFR-win estimate over full sweeps. The truncated tail accumulates
    # into the last row, so sum(frontier) stays the exact total
    # frontier-visit count even past the cap. With a degree_bias the
    # frontier mass is priced at the size-biased mean degree (capped at
    # E per iteration — a sweep cannot examine more); without one, the
    # uniform-degree estimate (bias = mean degree) stands.
    if degree_bias is not None and num_edges:
        per_iter = np.minimum(
            float(num_edges), frontier * float(degree_bias)
        )
        out["jfr_skippable_edge_frac"] = float(
            max(0.0, 1.0 - per_iter.sum() / (float(iters) * num_edges))
        )
        out["degree_bias"] = float(degree_bias)
    else:
        denom = float(iters) * max(int(num_nodes), 1)
        out["jfr_skippable_edge_frac"] = float(
            max(0.0, 1.0 - frontier.sum() / denom)
        )
    if num_edges:
        out["num_edges"] = int(num_edges)
    out["relaxations_total"] = int(traj[:, 1].sum())
    out["residual_mass_total"] = float(traj[:, 2].sum())
    return out


def merge_summaries(prev: dict | None, summ: dict) -> dict:
    """Fold one more kernel call's summary into a phase entry
    (multi-batch fan-outs land one trajectory per batch): the entry
    keeps the LATEST batch's shape fields and accumulates ``batches`` /
    ``iterations_total`` / ``relaxations_total`` across calls."""
    entry = dict(summ)
    if prev is None:
        entry["batches"] = 1
        entry["iterations_total"] = summ.get("iterations", 0)
    else:
        entry["batches"] = int(prev.get("batches", 1)) + 1
        entry["iterations_total"] = int(
            prev.get("iterations_total", 0)
        ) + int(summ.get("iterations", 0))
        entry["relaxations_total"] = int(
            prev.get("relaxations_total", 0)
        ) + int(summ.get("relaxations_total", 0))
    return entry


def frontier_curve(traj, max_points: int = 64) -> list:
    """Downsampled frontier-size curve (head-biased stride) for flight-
    recorder event attrs — enough shape to render a collapse curve from
    a dead run's JSONL without dragging the full buffer through every
    event line."""
    import numpy as np

    traj = np.asarray(traj)
    if traj.shape[0] <= max_points:
        return [int(x) for x in traj[:, 0]]
    idx = np.unique(
        np.linspace(0, traj.shape[0] - 1, max_points).astype(np.int64)
    )
    return [int(traj[i, 0]) for i in idx]


def estimate_eta(
    elapsed_s: float, done: int, remaining: int
) -> float | None:
    """Remaining-wall estimate from completed work units (batches):
    ``remaining x (elapsed / done)``. None until one unit completes —
    an ETA with no evidence is noise, not telemetry. The heartbeat
    publishes this as ``eta_s`` so the TPU watchdog
    (``tpu_round3_run.sh``) can extend a fresh stage's soft deadline by
    a real completion estimate instead of a blind half-budget step."""
    if done <= 0 or elapsed_s < 0:
        return None
    return float(remaining) * (float(elapsed_s) / float(done))


# -- dirty-window dispatch decision (ISSUE 13) -------------------------------
#
# The first concrete step of the priced dispatch registry (ROADMAP item
# 2): route selection consults MEASURED trajectory evidence instead of a
# static heuristic. Thresholds: the dw schedule's overhead (bitmap
# maintenance, compaction, tile padding) was measured to eat roughly a
# quarter of the skippable fraction at block granularity, so it pays
# when the recorded collapse leaves a comfortable margin.

# Minimum recorded jfr_skippable_edge_frac for dw to engage: the
# scrambled road grid measures 0.963 (engages), rmat_s12 measures 0.600
# (declines) — 0.75 splits the measured workloads with margin both ways.
DW_MIN_SKIPPABLE_FRAC = 0.75

# Below this many iterations a solve has no tail to collect — the fixed
# per-round costs dominate whatever the bitmap skips.
DW_MIN_ITERATIONS = 8


def degree_bias_from_degrees(degrees) -> float | None:
    """Size-biased mean out-degree E[d^2]/E[d] — the expected degree of
    a vertex sampled proportionally to its degree, which is what
    frontier membership approximates on skewed graphs. None for
    edgeless graphs. Uniform-degree graphs return the plain mean, so
    the corrected estimator reduces to the uniform one there."""
    import numpy as np

    d = np.asarray(degrees, np.float64)
    total = d.sum()
    if total <= 0:
        return None
    return float((d * d).sum() / total)


def dw_decision(
    records,
    *,
    num_nodes: int,
    num_edges: int,
    platform: str | None = None,
) -> dict:
    """Should the dirty-window route serve a (num_nodes, num_edges)
    graph? Scans ``kind: "trajectory"`` profile-store records for the
    graph's pow2 shape bucket (the ``observe.costs.shape_bucket``
    keying) and applies the collapse thresholds. Platform-matching
    records are preferred but any-platform evidence counts — frontier
    collapse is a property of the graph and schedule, not the chip.

    Returns ``{"engage": bool, "reason": str, "summary": dict | None}``
    — never engages without evidence (the acceptance contract: a graph
    with no recorded collapse, or a flat trajectory, routes to plain
    vm / vm-blocked)."""
    from paralleljohnson_tpu.observe.costs import shape_bucket

    want = shape_bucket(num_nodes, num_edges, 1)[:2]
    best = None
    best_rank = -1
    for r in records:
        if r.get("kind") != "trajectory":
            continue
        nodes = r.get("nodes") or 0
        edges = r.get("edges") or 0
        if shape_bucket(nodes, edges, 1)[:2] != want:
            continue
        summ = r.get("summary") or {}
        if not summ:
            continue
        # Prefer same-platform, then recency (records are appended in
        # time order, so the last qualifying one wins its rank tier).
        rank = 1 if (platform and r.get("platform") == platform) else 0
        if rank >= best_rank:
            best, best_rank = r, rank
    if best is None:
        return {
            "engage": False,
            "reason": (
                "no trajectory record for shape bucket "
                f"(V~2^{max(want[0], 1).bit_length() - 1}, "
                f"E~2^{max(want[1], 1).bit_length() - 1})"
            ),
            "summary": None,
        }
    summ = best.get("summary") or {}
    iters = int(summ.get("iterations", 0) or 0)
    skippable = float(summ.get("jfr_skippable_edge_frac", 0.0) or 0.0)
    half_life = summ.get("frontier_half_life")
    if iters < DW_MIN_ITERATIONS:
        return {
            "engage": False,
            "reason": f"recorded solve converges in {iters} iterations "
                      f"(< {DW_MIN_ITERATIONS}) — no tail to collect",
            "summary": summ,
        }
    if skippable < DW_MIN_SKIPPABLE_FRAC:
        return {
            "engage": False,
            "reason": (
                f"recorded jfr_skippable_edge_frac {skippable:.3f} < "
                f"{DW_MIN_SKIPPABLE_FRAC} (flat trajectory — the "
                "schedule overhead would eat the skip)"
            ),
            "summary": summ,
        }
    return {
        "engage": True,
        "reason": (
            f"trajectory records {skippable:.1%} skippable over "
            f"{iters} iterations (half-life {half_life})"
        ),
        "summary": summ,
    }


def trajectory_record(
    traj,
    *,
    label: str,
    phase: str,
    index: int,
    route: str | None,
    platform: str,
    num_nodes: int,
    num_edges: int,
    batch: int,
    summary: dict | None = None,
    degree_bias: float | None = None,
) -> dict:
    """The per-solve-stage profile-store record (``kind:
    "trajectory"``): the full per-iteration curve plus its summary,
    keyed like solve records so ``scripts/convergence_report.py`` and
    the cost model join on (route, platform). ``degree_bias`` feeds the
    skew-corrected JFR estimator (see :func:`summarize_trajectory`) —
    the number the dirty-window dispatch decision reads."""
    import time

    import numpy as np

    traj = np.asarray(traj, np.float64)
    return {
        "ts": time.time(),
        "kind": "trajectory",
        "label": label,
        "phase": phase,
        "batch_index": int(index),
        "route": route,
        "platform": platform,
        "nodes": int(num_nodes),
        "edges": int(num_edges),
        "batch": int(batch),
        "summary": summary or summarize_trajectory(
            traj, num_nodes=num_nodes, batch=batch, num_edges=num_edges,
            degree_bias=degree_bias,
        ),
        # Columns: frontier_size, relaxations_applied, residual_mass.
        "trajectory": [
            [int(r[0]), int(r[1]), float(r[2])] for r in traj
        ],
    }
