"""Live SLO observatory (ISSUE 12 tentpole) — *streaming* operational
metrics, where everything the repo emitted before was post-hoc.

The serve tier is judged as a traffic-bearing service (ROADMAP item 4:
"sustained queries/sec under concurrency with p99 SLOs, not one-shot
latency"), yet ``serve_stats.json`` was written once at close and the
latency percentiles buffered every sample in host RAM. This module is
the streaming substrate, four pieces sharing one snapshot artifact:

- :class:`LogHistogram` — log-bucketed latency histogram: bounded
  memory (one int per occupied bucket), EXACT counts, mergeable across
  processes, and percentile estimates whose error is bounded by one
  bucket width — the bound is computed and reported alongside every
  estimate (the repo's never-an-unflagged-approximation rule applied
  to percentiles).
- :class:`RateCounter` — sliding-window event counter (sparse
  per-second bins): exact totals plus windowed rates (queries/sec over
  the last 60 s), bounded by the window length.
- :class:`SLO` + :class:`SLOTracker` — service objectives
  (availability + latency target) evaluated with MULTI-WINDOW
  BURN-RATE rules (SRE-workbook style: alert only when both a long and
  a short window burn error budget faster than threshold — fast
  detection without flapping on one bad batch). Transitions into
  burning emit an ``slo_burn`` flight-recorder event; the current burn
  rate exports as a labeled ``pjtpu_slo_burn_rate`` gauge.
- :class:`MetricsRegistry` — the shared façade the hot paths are wired
  through (``QueryEngine``, the solver's ``_resilient_batches``, fleet
  workers, the incremental repair engine). A daemon thread atomically
  rewrites a snapshot JSON every ``interval_s`` (the
  ``HeartbeatReporter`` idiom: tmp + ``os.replace``, a reader never
  sees a torn file), so a SIGKILLed process leaves a view fresh to
  within one interval. Each snapshot also appends one compact line to
  a ``*_history.jsonl`` beside it — the burn-rate trajectory
  ``scripts/slo_report.py`` renders offline.

Everything here is stdlib-only (no numpy, no jax): the offline readers
(``scripts/slo_report.py``, ``pjtpu top``'s gatherer) load this module
standalone on any log-analysis box, and the disabled path
(:data:`NULL_METRICS`) is near-free like ``telemetry.NULL_TELEMETRY``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from pathlib import Path

SNAPSHOT_VERSION = 1

# Default log-bucket geometry: buckets grow by 2^(1/4) ≈ 18.9% per
# step from 1e-3 (one microsecond, in ms units) to 1e7 ms (~2.8 h) —
# 134 buckets cover ten decades, so a histogram is a few hundred bytes
# of occupied bins no matter how many samples it absorbs. The relative
# percentile error bound is therefore ≤ 18.9% of the estimate — wide
# enough to be cheap, tight enough that p99 regressions of interest
# (2x, 10x) are unmistakable.
DEFAULT_LO = 1e-3
DEFAULT_HI = 1e7
DEFAULT_GROWTH = 2.0 ** 0.25

# Per-bucket exemplar bound (ISSUE 20): each occupied bucket keeps the
# LAST K (trace_id, value) pairs recorded into it, so "p99 = 38 ms"
# comes with concrete request traces to assemble — bounded memory no
# matter how many samples flow through (K * occupied buckets entries).
DEFAULT_EXEMPLAR_K = 4


class LogHistogram:
    """Log-bucketed streaming histogram with bounded-error percentiles.

    Bucket ``i`` (1-based) covers ``(lo * growth**(i-1), lo * growth**i]``;
    bucket 0 is the underflow bin ``[0, lo]`` and the last bucket
    collects overflow ``(hi, +inf)``. Counts are EXACT integers; only
    the position of a sample WITHIN its bucket is forgotten, which is
    what bounds every percentile estimate by one bucket width. Exact
    ``count``/``sum``/``min``/``max`` ride along so means and extremes
    stay approximation-free.

    Thread-safe; :meth:`merge` combines histograms with identical
    geometry (fleet-wide unions of per-worker snapshots).
    """

    __slots__ = ("lo", "hi", "growth", "_log_growth", "n_buckets",
                 "_counts", "count", "sum", "min", "max", "_lock",
                 "exemplar_k", "_exemplars")

    def __init__(self, *, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 growth: float = DEFAULT_GROWTH) -> None:
        if not (lo > 0 and hi > lo and growth > 1.0):
            raise ValueError(
                f"need 0 < lo < hi and growth > 1, got lo={lo} hi={hi} "
                f"growth={growth}"
            )
        self.lo = float(lo)
        self.hi = float(hi)
        self.growth = float(growth)
        self._log_growth = math.log(self.growth)
        # Regular buckets 1..n cover (lo, lo*growth**n] with
        # lo*growth**n >= hi; index 0 underflow, n+1 overflow.
        self.n_buckets = int(
            math.ceil(math.log(self.hi / self.lo) / self._log_growth)
        )
        self._counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        # bucket index -> last-K [(exemplar_id, value), ...] (ISSUE 20).
        self.exemplar_k = DEFAULT_EXEMPLAR_K
        self._exemplars: dict[int, list] = {}
        self._lock = threading.Lock()

    # -- geometry ----------------------------------------------------------

    def _index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        i = int(math.ceil(math.log(v / self.lo) / self._log_growth))
        # Float round-off at an exact edge: nudge so v <= upper(i) holds.
        if self.lo * self.growth ** (i - 1) >= v:
            i -= 1
        return min(max(i, 1), self.n_buckets + 1)

    def bucket_bounds(self, i: int) -> tuple[float, float]:
        """``(lower, upper]`` of bucket ``i`` (underflow lower is 0;
        overflow upper is +inf until a sample narrows it to ``max``)."""
        if i <= 0:
            return 0.0, self.lo
        if i > self.n_buckets:
            return self.lo * self.growth ** self.n_buckets, math.inf
        return (self.lo * self.growth ** (i - 1),
                self.lo * self.growth ** i)

    def same_geometry(self, other: "LogHistogram") -> bool:
        return (self.lo == other.lo and self.hi == other.hi
                and self.growth == other.growth)

    # -- recording ---------------------------------------------------------

    def record(self, v: float, exemplar: str | None = None) -> None:
        """Record one sample; ``exemplar`` (a trace_id) rides into the
        sample's bucket, displacing the oldest of that bucket's last-K
        — how a latency histogram keeps concrete traces per bucket
        without unbounded growth."""
        v = float(v)
        if math.isnan(v):
            return  # a NaN latency is a caller bug, never a bin
        v = max(v, 0.0)
        i = self._index(v)
        with self._lock:
            self._counts[i] = self._counts.get(i, 0) + 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if exemplar is not None:
                ex = self._exemplars.setdefault(i, [])
                ex.append((str(exemplar), v))
                if len(ex) > self.exemplar_k:
                    del ex[0]

    def record_many(self, values) -> None:
        for v in values:
            self.record(v)

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Fold ``other``'s counts into self (identical geometry only —
        merging mismatched bucketings would silently corrupt counts)."""
        if not self.same_geometry(other):
            raise ValueError(
                "cannot merge histograms with different geometry: "
                f"(lo={self.lo}, hi={self.hi}, growth={self.growth}) vs "
                f"(lo={other.lo}, hi={other.hi}, growth={other.growth})"
            )
        with other._lock:
            counts = dict(other._counts)
            o_count, o_sum = other.count, other.sum
            o_min, o_max = other.min, other.max
            o_ex = {i: list(ex) for i, ex in other._exemplars.items()}
        with self._lock:
            for i, c in counts.items():
                self._counts[i] = self._counts.get(i, 0) + c
            self.count += o_count
            self.sum += o_sum
            self.min = min(self.min, o_min)
            self.max = max(self.max, o_max)
            for i, oex in o_ex.items():
                ex = self._exemplars.setdefault(i, [])
                ex.extend(oex)
                if len(ex) > self.exemplar_k:
                    del ex[: len(ex) - self.exemplar_k]
        return self

    # -- percentiles -------------------------------------------------------

    def percentile(self, p: float) -> dict:
        """Bounded-error percentile estimate.

        Returns ``{"value", "lower", "upper", "max_error"}`` where the
        nearest-rank percentile provably lies in ``(lower, upper]``,
        ``value`` is the bucket's geometric midpoint, and ``max_error``
        = ``max(value - lower, upper - value)`` < one bucket width —
        the flagged bound the estimate always travels with. Zeros when
        the histogram is empty (a server that served nothing)."""
        if not 0 <= p <= 100:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        with self._lock:
            count = self.count
            counts = sorted(self._counts.items())
            vmin, vmax = self.min, self.max
        if count == 0:
            return {"value": 0.0, "lower": 0.0, "upper": 0.0,
                    "max_error": 0.0}
        rank = max(1, int(math.ceil(p / 100.0 * count)))
        seen = 0
        idx = counts[-1][0]
        for i, c in counts:
            seen += c
            if seen >= rank:
                idx = i
                break
        lower, upper = self.bucket_bounds(idx)
        # Exact extremes narrow the open-ended bins (and every bin: no
        # estimate may leave the observed range).
        lower = max(lower, 0.0 if vmin is math.inf else min(vmin, upper))
        upper = min(upper, vmax) if vmax > -math.inf else upper
        upper = max(upper, lower)
        if lower <= 0.0:
            value = upper / 2.0
        else:
            value = math.sqrt(lower * upper)
        return {
            "value": value,
            "lower": lower,
            "upper": upper,
            "max_error": max(value - lower, upper - value),
        }

    def percentiles(self, pcts=(50, 99), *, key: str = "p{p}_ms") -> dict:
        """``{"p50_ms": est, "p50_err_ms": bound, ...}`` — the estimate
        never travels without its error bound."""
        out = {}
        for p in pcts:
            r = self.percentile(p)
            label = key.format(p=p)
            out[label] = r["value"]
            out[label.replace("_ms", "_err_ms")
                if label.endswith("_ms") else label + "_err"] = (
                r["max_error"]
            )
        return out

    # -- exports -----------------------------------------------------------

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """Prometheus-convention cumulative buckets: ``(le, cum_count)``
        per occupied prefix (upper edges strictly increasing, counts
        non-decreasing), ending with ``(inf, count)``."""
        with self._lock:
            counts = sorted(self._counts.items())
            total = self.count
        out: list[tuple[float, int]] = []
        cum = 0
        for i, c in counts:
            cum += c
            _, upper = self.bucket_bounds(i)
            if math.isinf(upper):
                break
            out.append((upper, cum))
        out.append((math.inf, total))
        return out

    def bucket_exemplars(self) -> dict:
        """Latest exemplar per occupied bucket, keyed by the bucket's
        Prometheus ``le`` edge (the same edges
        :meth:`cumulative_buckets` emits; the overflow bucket maps to
        the ``+Inf`` edge): ``{le: (exemplar_id, value)}``. Feeds the
        OpenMetrics exemplar suffix in
        ``telemetry.write_prom_metrics(..., exemplars=True)``."""
        with self._lock:
            ex = {i: list(v) for i, v in self._exemplars.items()}
        out = {}
        for i, pairs in ex.items():
            if not pairs:
                continue
            _, upper = self.bucket_bounds(i)
            out[upper] = pairs[-1]
        return out

    def tail_exemplars(self, limit: int = DEFAULT_EXEMPLAR_K) -> list:
        """``[(exemplar_id, value), ...]`` from the slowest occupied
        buckets downward (newest first within a bucket) — the "show me
        the traces behind the p99" accessor ``pjtpu top`` and
        ``slo_report.py`` render."""
        with self._lock:
            ex = sorted(self._exemplars.items(), reverse=True)
        out: list = []
        for _i, pairs in ex:
            for pair in reversed(pairs):
                out.append(tuple(pair))
                if len(out) >= limit:
                    return out
        return out

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "lo": self.lo,
                "hi": self.hi,
                "growth": self.growth,
                "buckets": {str(i): c for i, c in
                            sorted(self._counts.items())},
                "count": self.count,
                "sum": self.sum,
                "min": None if self.min is math.inf else self.min,
                "max": None if self.max == -math.inf else self.max,
                **({"exemplars": {str(i): [[e, v] for e, v in ex]
                                  for i, ex in
                                  sorted(self._exemplars.items())}}
                   if self._exemplars else {}),
            }

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls(lo=float(d["lo"]), hi=float(d["hi"]),
                growth=float(d["growth"]))
        h._counts = {int(i): int(c) for i, c in (d.get("buckets") or
                                                 {}).items()}
        h.count = int(d.get("count", sum(h._counts.values())))
        h.sum = float(d.get("sum", 0.0))
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        h._exemplars = {
            int(i): [(str(e), float(v)) for e, v in ex][-h.exemplar_k:]
            for i, ex in (d.get("exemplars") or {}).items()
        }
        return h

    def summary(self, pcts=(50, 99)) -> dict:
        """Compact snapshot payload: count/sum/min/max + bounded
        percentiles + the full sparse dict (so snapshots stay mergeable
        offline)."""
        out = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / self.count, 6) if self.count else 0.0,
            "min": None if self.min is math.inf else round(self.min, 6),
            "max": None if self.max == -math.inf else round(self.max, 6),
            **{k: round(v, 6) for k, v in self.percentiles(pcts).items()},
            "hist": self.as_dict(),
        }
        return out


def tail_exemplars_from_dict(hist_dict: dict | None,
                             limit: int = DEFAULT_EXEMPLAR_K) -> list:
    """:meth:`LogHistogram.tail_exemplars` over the serialized
    ``as_dict`` form — what ``pjtpu top`` / ``slo_report.py`` render
    straight from a snapshot JSON without rebuilding the histogram."""
    ex = (hist_dict or {}).get("exemplars") or {}
    out: list = []
    for i in sorted((int(k) for k in ex), reverse=True):
        for pair in reversed(ex[str(i)]):
            out.append((str(pair[0]), float(pair[1])))
            if len(out) >= limit:
                return out
    return out


class RateCounter:
    """Sliding-window event counter: exact monotone ``total`` plus
    windowed rates from sparse per-``resolution_s`` bins (memory bounded
    by ``window_s / resolution_s`` occupied bins). Thread-safe; ``now``
    is injectable everywhere so tests and replayers control the clock."""

    __slots__ = ("window_s", "resolution_s", "_bins", "total", "_lock")

    def __init__(self, *, window_s: float = 3600.0,
                 resolution_s: float = 1.0) -> None:
        if not (window_s > 0 and resolution_s > 0):
            raise ValueError("window_s and resolution_s must be > 0")
        self.window_s = float(window_s)
        self.resolution_s = float(resolution_s)
        self._bins: dict[int, float] = {}
        self.total = 0.0
        self._lock = threading.Lock()

    def _bin(self, now: float) -> int:
        return int(now // self.resolution_s)

    def _prune(self, now: float) -> None:
        horizon = self._bin(now - self.window_s)
        if len(self._bins) > 2 * int(self.window_s / self.resolution_s):
            for b in [b for b in self._bins if b < horizon]:
                del self._bins[b]

    def add(self, n: float = 1.0, *, now: float | None = None) -> None:
        now = time.time() if now is None else now
        b = self._bin(now)
        with self._lock:
            self._bins[b] = self._bins.get(b, 0.0) + n
            self.total += n
            self._prune(now)

    def count_in(self, window_s: float, *, now: float | None = None) -> float:
        """Events in the trailing ``window_s`` (clamped to the counter's
        own window — it cannot answer for longer than it remembers)."""
        now = time.time() if now is None else now
        window_s = min(float(window_s), self.window_s)
        horizon = self._bin(now - window_s)
        with self._lock:
            return sum(c for b, c in self._bins.items()
                       if horizon < b <= self._bin(now))

    def rate(self, window_s: float = 60.0, *,
             now: float | None = None) -> float:
        """Events/second over the trailing window."""
        window_s = min(float(window_s), self.window_s)
        if window_s <= 0:
            return 0.0
        return self.count_in(window_s, now=now) / window_s


# -- SLOs and burn rates ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLO:
    """One service-level objective over a request stream.

    An event is BAD when it errored or exceeded ``latency_ms`` (the
    combined formulation: latency violations spend the same error
    budget as failures, so one burn-rate number covers both targets).
    ``availability`` is the good-fraction target; the error budget is
    ``1 - availability``. ``rules`` are multi-window burn-rate alerts
    ``(long_window_s, short_window_s, burn_threshold)``: the SLO is
    *burning* when ANY rule sees burn-rate >= threshold over BOTH its
    windows (the short window arms fast detection, the long window
    stops one bad batch from flapping the alert). Defaults are the
    SRE-workbook pair scaled to process lifetimes this repo runs
    (minutes-hours, not 30-day pages)."""

    name: str
    latency_ms: float
    latency_pct: float = 99.0
    availability: float = 0.999
    rules: tuple = ((300.0, 60.0, 14.4), (3600.0, 300.0, 6.0))

    def __post_init__(self):
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {self.availability}"
            )
        if not self.latency_ms > 0:
            raise ValueError(f"latency_ms must be > 0, got {self.latency_ms}")
        for rule in self.rules:
            long_w, short_w, thr = rule
            if not (long_w >= short_w > 0 and thr > 0):
                raise ValueError(f"bad burn rule {rule!r}: need "
                                 "long >= short > 0 and threshold > 0")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "latency_ms": self.latency_ms,
            "latency_pct": self.latency_pct,
            "availability": self.availability,
            "rules": [list(r) for r in self.rules],
        }


class SLOTracker:
    """Evaluates one :class:`SLO` against a live stream of observations.

    ``observe(latency_ms, ok)`` files the event good/bad;
    ``evaluate(now)`` computes per-rule burn rates (bad-fraction over
    the window divided by the error budget) and the burning verdict.
    The owning registry emits the ``slo_burn`` telemetry event on the
    not-burning -> burning transition."""

    def __init__(self, slo: SLO, *, histogram: LogHistogram | None = None):
        self.slo = slo
        self.histogram = histogram
        window = max(long_w for long_w, _, _ in slo.rules)
        self.good = RateCounter(window_s=window)
        self.bad = RateCounter(window_s=window)
        self.burning = False

    def observe(self, latency_ms: float | None, *, ok: bool = True,
                now: float | None = None) -> None:
        is_bad = (not ok) or (
            latency_ms is not None and latency_ms > self.slo.latency_ms
        )
        (self.bad if is_bad else self.good).add(1.0, now=now)

    def burn_rate(self, window_s: float, *, now: float | None = None) -> float:
        """Error-budget burn over one window: bad-fraction / budget.
        1.0 = burning exactly at budget (sustainable); >> 1 = the
        budget is being spent that many times too fast; 0 with no
        traffic (an idle service is not failing)."""
        bad = self.bad.count_in(window_s, now=now)
        total = bad + self.good.count_in(window_s, now=now)
        if total <= 0:
            return 0.0
        return (bad / total) / self.slo.error_budget

    def evaluate(self, *, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        rules = []
        burning = False
        worst = 0.0
        for long_w, short_w, threshold in self.slo.rules:
            b_long = self.burn_rate(long_w, now=now)
            b_short = self.burn_rate(short_w, now=now)
            firing = b_long >= threshold and b_short >= threshold
            burning = burning or firing
            worst = max(worst, min(b_long, b_short))
            rules.append({
                "long_window_s": long_w, "short_window_s": short_w,
                "threshold": threshold,
                "burn_long": round(b_long, 4),
                "burn_short": round(b_short, 4),
                "firing": firing,
            })
        out = {
            "objective": self.slo.as_dict(),
            "events_total": self.good.total + self.bad.total,
            "bad_total": self.bad.total,
            "burn_rate": round(worst, 4),
            "burning": burning,
            "rules": rules,
        }
        if self.histogram is not None and self.histogram.count:
            pr = self.histogram.percentile(self.slo.latency_pct)
            out["latency"] = {
                "pct": self.slo.latency_pct,
                "observed_ms": round(pr["value"], 4),
                "max_error_ms": round(pr["max_error"], 4),
                "target_ms": self.slo.latency_ms,
                # The honest tri-state: the bucket bound may straddle
                # the target, in which case the verdict says so rather
                # than picking a side.
                "met": (True if pr["upper"] <= self.slo.latency_ms
                        else False if pr["lower"] > self.slo.latency_ms
                        else "within-error-bound"),
            }
        return out


# -- the registry -------------------------------------------------------------


class MetricsRegistry:
    """Shared live-metrics façade: named histograms, rate counters,
    gauges, and SLO trackers, with periodic atomic snapshots.

    The snapshotter is the ``HeartbeatReporter`` idiom: a daemon thread
    serializes :meth:`snapshot` every ``interval_s`` and publishes via
    tmp-write + ``os.replace`` — a concurrent reader (``pjtpu top``)
    never sees a torn file, and a SIGKILLed process leaves a view
    fresh to within one interval. Every publish also appends one
    compact history line (ts, totals, burn rates) to
    ``<name>_history.jsonl`` beside the snapshot — the burn-rate
    trajectory the offline reader renders."""

    def __init__(self, *, label: str = "metrics", telemetry=None) -> None:
        self.label = label
        self.telemetry = telemetry
        self._hists: dict[str, LogHistogram] = {}
        self._counters: dict[str, RateCounter] = {}
        self._gauges: dict[str, float] = {}
        self._slos: dict[str, SLOTracker] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._seq = 0
        self.write_errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._snapshot_path: Path | None = None
        self._history = True

    enabled = True

    def __bool__(self) -> bool:
        return True

    # -- instruments -------------------------------------------------------

    def histogram(self, name: str, **kwargs) -> LogHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LogHistogram(**kwargs)
            return h

    def counter(self, name: str, **kwargs) -> RateCounter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = RateCounter(**kwargs)
            return c

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def slo(self, objective: SLO, *,
            histogram: str | None = None) -> SLOTracker:
        """Register (or fetch) the tracker for ``objective``;
        ``histogram`` names the registry histogram its latency verdict
        reads (usually the one the same events are recorded into)."""
        with self._lock:
            t = self._slos.get(objective.name)
            if t is None:
                hist = self._hists.get(histogram) if histogram else None
                t = self._slos[objective.name] = SLOTracker(
                    objective, histogram=hist
                )
            return t

    def observe_slo(self, name: str, latency_ms: float | None, *,
                    ok: bool = True, now: float | None = None) -> None:
        """File one event against a registered SLO and fire the
        ``slo_burn`` transition event when it tips into burning."""
        t = self._slos.get(name)
        if t is None:
            return
        t.observe(latency_ms, ok=ok, now=now)
        verdict = t.evaluate(now=now)
        if verdict["burning"] and not t.burning:
            t.burning = True
            if self.telemetry is not None:
                self.telemetry.event(
                    "slo_burn", slo=name,
                    burn_rate=verdict["burn_rate"],
                    bad_total=verdict["bad_total"],
                )
        elif not verdict["burning"]:
            t.burning = False

    def slo_burn_gauge(self) -> dict:
        """``{slo_name: worst burn rate}`` — the labeled
        ``pjtpu_slo_burn_rate`` prometheus gauge's samples."""
        with self._lock:
            trackers = dict(self._slos)
        return {name: t.evaluate()["burn_rate"]
                for name, t in trackers.items()}

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, *, now: float | None = None,
                 rate_windows=(60.0, 300.0)) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            hists = dict(self._hists)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            slos = dict(self._slos)
            self._seq += 1
            seq = self._seq
        return {
            "version": SNAPSHOT_VERSION,
            "kind": "live_metrics",
            "label": self.label,
            "ts": now,
            "seq": seq,
            "pid": os.getpid(),
            "uptime_s": round(time.perf_counter() - self._t0, 3),
            "counters": {
                name: {
                    "total": c.total,
                    **{f"rate_{int(w)}s": round(c.rate(w, now=now), 6)
                       for w in rate_windows},
                }
                for name, c in sorted(counters.items())
            },
            "gauges": {k: v for k, v in sorted(gauges.items())},
            "histograms": {
                name: h.summary() for name, h in sorted(hists.items())
            },
            "slos": {
                name: t.evaluate(now=now)
                for name, t in sorted(slos.items())
            },
        }

    def write_snapshot(self, path: str | Path, *,
                       now: float | None = None) -> Path:
        """One atomic publish (+ a compact history append)."""
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        snap = self.snapshot(now=now)
        tmp = p.with_name(p.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(snap), encoding="utf-8")
        os.replace(tmp, p)
        if self._history:
            try:
                line = {
                    "ts": snap["ts"],
                    "seq": snap["seq"],
                    "label": snap["label"],
                    "counters": {n: c["total"]
                                 for n, c in snap["counters"].items()},
                    "slos": {
                        n: {"burn_rate": s["burn_rate"],
                            "burning": s["burning"],
                            "bad_total": s["bad_total"]}
                        for n, s in snap["slos"].items()
                    },
                }
                hist_path = p.with_name(p.stem + "_history.jsonl")
                with open(hist_path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(line) + "\n")
            except OSError:
                self.write_errors += 1
        return p

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.write_snapshot(self._snapshot_path)
            except Exception:  # noqa: BLE001 — metrics must never kill work
                self.write_errors += 1

    def start_snapshotter(self, path: str | Path,
                          interval_s: float = 5.0, *,
                          history: bool = True) -> "MetricsRegistry":
        """Publish to ``path`` every ``interval_s`` on a daemon thread
        (first write immediately, so liveness is visible before the
        first interval elapses)."""
        if not interval_s > 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if self._thread is None:
            self._snapshot_path = Path(path)
            self._history = history
            self._stop.clear()
            try:
                self.write_snapshot(self._snapshot_path)
            except Exception:  # noqa: BLE001
                self.write_errors += 1
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name=f"pj-metrics-{self.label}", daemon=True,
            )
            self._thread.start()
        return self

    def stop_snapshotter(self, *, final_write: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        if final_write and self._snapshot_path is not None:
            try:
                self.write_snapshot(self._snapshot_path)
            except Exception:  # noqa: BLE001
                self.write_errors += 1


class _NullMetrics:
    """The disabled path: all hot-path call sites are wired
    unconditionally; this object makes ``metrics=None`` near-free (no
    allocation, no locking, no IO) — the ``NULL_TELEMETRY`` pattern."""

    __slots__ = ()
    enabled = False

    def __bool__(self) -> bool:
        return False

    def histogram(self, name, **kwargs):
        return _NULL_HIST

    def counter(self, name, **kwargs):
        return _NULL_COUNTER

    def gauge(self, name, value):
        return None

    def slo(self, objective, *, histogram=None):
        return None

    def observe_slo(self, name, latency_ms, *, ok=True, now=None):
        return None

    def slo_burn_gauge(self):
        return {}

    def snapshot(self, *, now=None, rate_windows=(60.0, 300.0)):
        return {}

    def write_snapshot(self, path, *, now=None):
        return None

    def start_snapshotter(self, path, interval_s=5.0, *, history=True):
        return self

    def stop_snapshotter(self, *, final_write=True):
        return None


class _NullHistogram:
    __slots__ = ()
    count = 0
    sum = 0.0

    def record(self, v, exemplar=None):
        return None

    def record_many(self, values):
        return None

    def bucket_exemplars(self):
        return {}

    def tail_exemplars(self, limit=4):
        return []

    def percentile(self, p):
        return {"value": 0.0, "lower": 0.0, "upper": 0.0, "max_error": 0.0}

    def percentiles(self, pcts=(50, 99), *, key="p{p}_ms"):
        return {}

    def summary(self, pcts=(50, 99)):
        return {}


class _NullCounter:
    __slots__ = ()
    total = 0.0

    def add(self, n=1.0, *, now=None):
        return None

    def count_in(self, window_s, *, now=None):
        return 0.0

    def rate(self, window_s=60.0, *, now=None):
        return 0.0


_NULL_HIST = _NullHistogram()
_NULL_COUNTER = _NullCounter()
NULL_METRICS = _NullMetrics()


def resolve_metrics(metrics) -> "MetricsRegistry | _NullMetrics":
    """``config.metrics`` (or None) -> the object hot paths call."""
    return metrics if metrics is not None else NULL_METRICS


# -- snapshot readers (pjtpu top / slo_report) --------------------------------


def read_snapshot(path: str | Path) -> dict | None:
    """Parse one snapshot file; None when absent or torn (atomic
    publish means torn never legitimately happens — but a reader tool
    must degrade to "no information", not crash)."""
    p = Path(path)
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def snapshot_age_s(snap: dict | None, *, now: float | None = None) -> float | None:
    """Seconds since the snapshot's own publish stamp (its ``ts``) —
    the staleness clock ``pjtpu top`` flags dead processes by."""
    if snap is None or "ts" not in snap:
        return None
    return (time.time() if now is None else now) - float(snap["ts"])


def read_history(path: str | Path, *, limit: int | None = None) -> list[dict]:
    """Parse a ``*_history.jsonl`` (torn trailing line tolerated, the
    flight-recorder convention). ``limit`` keeps the newest N lines."""
    p = Path(path)
    try:
        lines = p.read_text(encoding="utf-8").splitlines()
    except OSError:
        return []
    out = []
    for n, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            if n != len(lines) - 1:
                raise ValueError(
                    f"{p}:{n + 1}: corrupt history line (not the last "
                    "line — this is not kill damage)"
                ) from None
    return out[-limit:] if limit else out
