"""Cost observatory (ISSUE 7 tentpole) — *why a solve costs what it
costs, and whether it is getting slower*.

The flight recorder (``utils.telemetry``) says what happened; this
package prices it. Four pieces sharing one persisted artifact:

- :mod:`~paralleljohnson_tpu.observe.costs` — compiled-cost capture:
  at jit-compile time, harvest XLA's ``cost_analysis()`` (FLOPs, bytes
  accessed, transcendentals) and ``memory_analysis()`` (argument /
  output / temp HBM) for every instrumented route's executable, keyed
  by ``(route, platform, shape-bucket)``; graceful no-op markers on
  backends/JAX versions (or routes) that don't expose them.
- :mod:`~paralleljohnson_tpu.observe.store` — the persisted profile
  store: append-only JSONL of per-solve records (analytic costs +
  measured wall + exact counters + SolverStats phases), written per
  solve when ``SolverConfig.profile_store`` / ``PJ_PROFILE_DIR`` is
  set, and :class:`~paralleljohnson_tpu.observe.store.CostModel` — the
  per-key calibration (measured seconds per analytic byte / FLOP /
  edge-row) ROADMAP item 7's dispatch registry will consume.
- :mod:`~paralleljohnson_tpu.observe.roofline` — roofline attribution:
  analytic bytes/FLOPs + measured span times + a small per-platform
  peak table classify each solve as HBM-bound / MXU-bound /
  host-IO-bound, surfaced in ``SolverStats``, ``cli info``, bench row
  ``detail``, the heartbeat JSON, and ``scripts/cost_report.py``.
- :mod:`~paralleljohnson_tpu.observe.regress` — bench-regression
  detection: a history store ingesting the ``BENCH_r0*.json``
  trajectory plus fresh rows, and ``scripts/bench_regress.py``
  comparing new measurements against per-(bench, backend, platform)
  history with a noise band — each flagged row arrives pre-attributed
  with its roofline classification.
- :mod:`~paralleljohnson_tpu.observe.live` — the live SLO observatory
  (ISSUE 12): streaming log-bucketed latency histograms, sliding-window
  rates, multi-window burn-rate SLO alerts, and the
  :class:`~paralleljohnson_tpu.observe.live.MetricsRegistry` whose
  atomic periodic snapshots ``pjtpu top``
  (:mod:`~paralleljohnson_tpu.observe.top`) and
  ``scripts/slo_report.py`` read.

Everything here except :mod:`costs` is stdlib-only (no numpy, no jax),
so the offline readers and the suite-budget guard can import it
without initializing a device client.
"""

from __future__ import annotations

import sys

from paralleljohnson_tpu.observe.convergence import (  # noqa: F401
    DEFAULT_TRAJ_CAP,
    degree_bias_from_degrees,
    dw_decision,
    estimate_eta,
    frontier_curve,
    summarize_trajectory,
    trajectory_record,
)
from paralleljohnson_tpu.observe.costs import (  # noqa: F401
    CostCapture,
    resolve_profile_dir,
    shape_bucket,
)
from paralleljohnson_tpu.observe.live import (  # noqa: F401
    NULL_METRICS,
    SLO,
    LogHistogram,
    MetricsRegistry,
    RateCounter,
    SLOTracker,
    read_snapshot,
    resolve_metrics,
    snapshot_age_s,
)
from paralleljohnson_tpu.observe.regress import (  # noqa: F401
    BenchHistory,
    detect_regressions,
    normalize_record,
)
from paralleljohnson_tpu.observe.roofline import (  # noqa: F401
    PLATFORM_PEAKS,
    attribute_stats,
    classify,
)
from paralleljohnson_tpu.observe.store import (  # noqa: F401
    PROFILE_FILENAME,
    CostModel,
    ProfileStore,
    solve_record,
)
from paralleljohnson_tpu.observe.tuning import (  # noqa: F401
    DEFAULT_FW_TILE,
    DEFAULT_PIPELINE_DEPTH,
    TUNABLE_PARAMS,
    TUNE_NOISE_BAND,
    cached_records,
    param_provenance,
    resolve_param,
    tuned_value,
)


def current_platform() -> str:
    """The platform profiles are keyed by. Never imports jax itself —
    the observatory must not initialize a device client behind a host
    backend's back (same contract as the heartbeat's device sampler)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return "cpu"
    try:
        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — a dead device must not kill a record
        return "unknown"


def primary_route(stats) -> str | None:
    """The route tag a solve's profile record is calibrated under: the
    fan-out's (the dominant phase), else the B=1 / batch route."""
    routes = getattr(stats, "routes_by_phase", None) or {}
    for phase in ("fanout", "bellman_ford", "batch_apsp"):
        if routes.get(phase):
            return routes[phase]
    return None


def finalize_solve(
    stats,
    *,
    config,
    telemetry=None,
    label: str = "solve",
    num_nodes: int = 0,
    num_edges: int = 0,
    batch: int = 1,
    degree_bias: float | None = None,
) -> dict | None:
    """Post-solve observatory hook (called by the solver for every
    completed solve): roofline-attribute ``stats``, publish the bound
    classification to the heartbeat, and — when a profile store is
    configured — predict this solve from the store's calibration and
    append its record. Returns the roofline dict (also left on
    ``stats.roofline``)."""
    platform = current_platform()
    roof = attribute_stats(stats, platform=platform)
    stats.roofline = roof
    if telemetry is not None and roof:
        telemetry.progress(roofline_bound=roof.get("bound"))
    store_dir = resolve_profile_dir(getattr(config, "profile_store", None))
    if not store_dir:
        return roof
    store = ProfileStore(store_dir)
    route = primary_route(stats)
    if route is not None:
        # Prediction from the PRE-existing calibration, before this
        # run's own record lands — prediction vs measurement stays an
        # honest out-of-sample comparison.
        pred = CostModel.fit(store).predict(
            route, num_edges=num_edges, batch=batch, platform=platform
        )
        if pred is not None:
            stats.predicted_s = pred["predicted_s"]
    # Planner decision record (ISSUE 14): one ``kind: "plan"`` line per
    # solve whose dispatch went through the registry — carries the
    # chosen plan + why-line + candidate table + the RESOLVED
    # auto-tuned parameters, with the measured wall beside them so
    # ``bench_regress.py`` can flag a planner that starts picking
    # slower routes and ``observe.tuning`` can compare parameter
    # alternatives.
    decision = getattr(stats, "plan", None)
    if decision:
        from paralleljohnson_tpu.planner import plan_record

        decision = dict(decision)
        params = dict(decision.get("params") or {})
        if getattr(stats, "final_batch", None):
            params.setdefault("source_batch", int(stats.final_batch))
        if getattr(stats, "final_pipeline_depth", None):
            params.setdefault(
                "pipeline_depth", int(stats.final_pipeline_depth)
            )
        decision["params"] = params
        stats.plan = decision
        phase_seconds = dict(getattr(stats, "phase_seconds", {}) or {})
        store.append(
            plan_record(
                decision,
                label=label,
                platform=platform,
                num_nodes=num_nodes,
                num_edges=num_edges,
                batch=batch,
                wall_s=float(sum(phase_seconds.values())),
                compute_s=float(
                    sum(
                        s for k, s in phase_seconds.items()
                        if k in ("bellman_ford", "fanout", "batch_apsp")
                    )
                ),
            )
        )
    store.append(
        solve_record(
            stats,
            label=label,
            platform=platform,
            route=route,
            num_nodes=num_nodes,
            num_edges=num_edges,
            batch=batch,
        )
    )
    # Convergence-observatory records (ISSUE 9): one ``kind:
    # "trajectory"`` record per instrumented kernel call (a multi-batch
    # fan-out lands one per batch), keyed by the phase's resolved route
    # so convergence_report.py and the cost model's per-iteration
    # calibration join on (route, platform) like every other record.
    routes = getattr(stats, "routes_by_phase", None) or {}
    for phase, trajs in (getattr(stats, "trajectories", None) or {}).items():
        for idx, traj in enumerate(trajs):
            store.append(
                trajectory_record(
                    traj,
                    label=label,
                    phase=phase,
                    index=idx,
                    route=routes.get(phase) or route,
                    platform=platform,
                    num_nodes=num_nodes,
                    num_edges=num_edges,
                    batch=batch,
                    degree_bias=degree_bias,
                )
            )
    return roof
