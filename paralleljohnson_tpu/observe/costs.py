"""Compiled-cost capture — XLA's own price tag for every route.

At jit-compile time every XLA executable knows its analytic cost
(``Compiled.cost_analysis()``: FLOPs, bytes accessed, transcendentals)
and memory footprint (``Compiled.memory_analysis()``: argument / output
/ temp bytes). The solver has never looked: we measure wall-clocks but
cannot say whether a route is moving bytes or doing math. This module
harvests both, once per ``(route, platform, shape-bucket)`` key, via
the jitted kernel's AOT path (``jitfn.lower(*args).compile()``).

Cost of capture: one extra trace + compile per key (NOT per call —
keys are cached for the life of the :class:`CostCapture`, and the
persistent jax compilation cache makes the XLA part a hit on the TPU
passes). Capture is therefore gated: a backend only enables it when a
profile store is configured (``SolverConfig.profile_store`` /
``PJ_PROFILE_DIR``), so ordinary solves pay nothing.

Graceful no-op everywhere: a backend/JAX version that does not expose
``cost_analysis`` (or a route with no single AOT-lowerable executable
— the sharded collectives, the Pallas sweep) yields a record carrying
an explicit ``cost_analysis_unavailable`` marker instead of numbers,
so downstream consumers can always tell "cheap" from "unmeasured".
"""

from __future__ import annotations

import os
import threading

# (our key, XLA cost_analysis key) — XLA spells "bytes accessed" with a
# space; absent keys read as 0.0 (a kernel genuinely can have zero
# transcendentals).
_COST_KEYS = (
    ("flops", "flops"),
    ("bytes_accessed", "bytes accessed"),
    ("transcendentals", "transcendentals"),
)


def resolve_profile_dir(explicit: str | None = None) -> str | None:
    """Profile-store directory resolution (mirrors the compile-cache
    pattern): an explicit ``SolverConfig.profile_store`` wins, else the
    ``PJ_PROFILE_DIR`` env var; neither set disables capture + store."""
    return explicit or os.environ.get("PJ_PROFILE_DIR") or None


def _pow2_up(n: int) -> int:
    n = int(n)
    if n <= 0:
        return 0
    return 1 << max(0, (n - 1).bit_length())


def shape_bucket(num_nodes: int, num_edges: int, batch: int) -> tuple[int, int, int]:
    """Shape key for cost records: each dimension rounded UP to a power
    of two, so e.g. ragged final batches (104 of 128) and padded edge
    lists share their canonical bucket instead of exploding the key
    space (the same bucketing the layout-chunk sizing uses)."""
    return (_pow2_up(num_nodes), _pow2_up(num_edges), _pow2_up(batch))


class CostCapture:
    """Once-per-key harvest of XLA cost/memory analysis.

    ``capture()`` returns the analytic-cost dict for the key (computed
    on first sight, cached after); ``unavailable()`` records the
    explicit marker for routes that cannot be AOT-lowered. Both return
    None when the capture is disabled, so call sites stay one-liners.
    Thread-safe: the pipelined fan-out's background worker never calls
    in, but the sharded entry points may race the main thread.
    """

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._cache: dict = {}
        self._lock = threading.Lock()

    # -- internals --------------------------------------------------------

    @staticmethod
    def _platform() -> str:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return "cpu"
        try:
            return str(jax.default_backend())
        except Exception:  # noqa: BLE001 — a dead device must not crash capture
            return "unknown"

    def _base(self, route, platform, bucket, num_nodes, num_edges, batch):
        return {
            "route": route,
            "platform": platform,
            "shape_bucket": list(bucket),
            "nodes": int(num_nodes),
            "edges": int(num_edges),
            "batch": int(batch),
        }

    # -- public -----------------------------------------------------------

    def capture(
        self,
        route: str,
        jitfn,
        args: tuple,
        kwargs: dict | None = None,
        *,
        num_nodes: int,
        num_edges: int,
        batch: int = 1,
    ) -> dict | None:
        """Analytic costs of ``jitfn``'s executable at these shapes.

        The WHOLE body is failure-proof: any error (no ``lower`` on
        this jax, a backend whose compiled object lacks the analyses,
        an analysis call that raises) degrades to the explicit
        ``cost_analysis_unavailable`` marker — capture must never fail
        a solve that already computed correct distances."""
        if not self.enabled:
            return None
        platform = self._platform()
        bucket = shape_bucket(num_nodes, num_edges, batch)
        key = (route, platform, bucket)
        with self._lock:
            cached = self._cache.get(key)
        if cached is not None:
            return cached
        rec = self._base(route, platform, bucket, num_nodes, num_edges, batch)
        compiled = None
        try:
            compiled = jitfn.lower(*args, **(kwargs or {})).compile()
        except Exception as e:  # noqa: BLE001 — graceful no-op contract
            rec["cost_analysis_unavailable"] = (
                f"lower/compile failed: {type(e).__name__}: {e}"
            )
        if compiled is not None:
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else None
                if not ca:
                    rec["cost_analysis_unavailable"] = (
                        "cost_analysis returned no properties on "
                        f"platform {platform!r}"
                    )
                else:
                    for ours, theirs in _COST_KEYS:
                        rec[ours] = float(ca.get(theirs, 0.0))
            except Exception as e:  # noqa: BLE001
                rec["cost_analysis_unavailable"] = (
                    f"cost_analysis unavailable: {type(e).__name__}: {e}"
                )
            try:
                ma = compiled.memory_analysis()
                if ma is not None:
                    mem = {
                        "argument_bytes": int(
                            getattr(ma, "argument_size_in_bytes", 0)
                        ),
                        "output_bytes": int(
                            getattr(ma, "output_size_in_bytes", 0)
                        ),
                        "temp_bytes": int(
                            getattr(ma, "temp_size_in_bytes", 0)
                        ),
                        "generated_code_bytes": int(
                            getattr(ma, "generated_code_size_in_bytes", 0)
                        ),
                    }
                    # The executable's peak device footprint: everything
                    # resident at once (args stay alive through temps).
                    mem["peak_bytes"] = (
                        mem["argument_bytes"]
                        + mem["output_bytes"]
                        + mem["temp_bytes"]
                    )
                    rec["memory"] = mem
            except Exception:  # noqa: BLE001 — memory stats are best-effort
                pass
        with self._lock:
            self._cache[key] = rec
        return rec

    def analytic(
        self,
        route: str,
        cost: dict,
        *,
        num_nodes: int,
        num_edges: int,
        batch: int = 1,
    ) -> dict | None:
        """Model-priced cost record for a route whose semiring math XLA
        cannot price representatively (the blocked min-plus FW routes:
        XLA's per-op table charges a tropical product's broadcast
        intermediate as if every candidate hit HBM, which misstates the
        fused kernel's actual tile traffic — ``ops.fw.fw_analytic_cost``
        is the honest price). ``cost`` supplies ``flops`` /
        ``bytes_accessed`` (+ optional ``transcendentals``); the record
        carries ``cost_source: "analytic-model"`` so consumers can
        always tell XLA-priced from model-priced numbers, while the
        values land in the same keys the roofline reads."""
        if not self.enabled:
            return None
        platform = self._platform()
        bucket = shape_bucket(num_nodes, num_edges, batch)
        key = (route, platform, bucket)
        with self._lock:
            cached = self._cache.get(key)
            if cached is not None:
                return cached
        rec = self._base(route, platform, bucket, num_nodes, num_edges, batch)
        rec["flops"] = float(cost.get("flops", 0.0))
        rec["bytes_accessed"] = float(cost.get("bytes_accessed", 0.0))
        rec["transcendentals"] = float(cost.get("transcendentals", 0.0))
        rec["cost_source"] = "analytic-model"
        with self._lock:
            self._cache[key] = rec
        return rec

    def unavailable(
        self,
        route: str,
        reason: str,
        *,
        num_nodes: int,
        num_edges: int,
        batch: int = 1,
    ) -> dict | None:
        """Explicit marker for a route with no single AOT-lowerable
        executable (sharded collectives, Pallas) — "unmeasured", stated,
        never silently zero."""
        if not self.enabled:
            return None
        platform = self._platform()
        bucket = shape_bucket(num_nodes, num_edges, batch)
        key = (route, platform, bucket)
        with self._lock:
            cached = self._cache.get(key)
            if cached is not None:
                return cached
        rec = self._base(route, platform, bucket, num_nodes, num_edges, batch)
        rec["cost_analysis_unavailable"] = reason
        with self._lock:
            self._cache[key] = rec
        return rec

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._cache.values())
