"""Roofline attribution: is a stage HBM-bound, MXU-bound, or host-IO-bound?

Combines the analytic bytes/FLOPs captured at compile time
(``observe.costs``) with the measured phase/pipeline times
(``SolverStats``) and a small per-platform peak table to classify each
solve — the answer ROADMAP item 1 needs ("attribute any residual s22
gap to bandwidth vs compute") and the gate the MXU min-plus direction
(ROADMAP item 3) pays off against: a route whose roofline is HBM gather
traffic cannot be saved by more FLOPs.

The peak table is ORDER-OF-MAGNITUDE pricing, not vendor specs — the
classification compares two derived times against each other, so a 2x
error in both peaks cancels; what matters is the ratio (the ridge
point). Platforms not listed fall back to the cpu row.
"""

from __future__ import annotations

# Per-platform peaks: sustained memory bandwidth (GB/s) and f32 compute
# (GFLOP/s). tpu ~ a v4-class core (HBM ~1.2 TB/s, MXU ~70 TF f32-ish
# via bf16 passes); cpu ~ one container core; gpu ~ an A100-class part.
PLATFORM_PEAKS: dict[str, dict] = {
    "tpu": {"mem_gbps": 1200.0, "flops_gflops": 70000.0},
    "gpu": {"mem_gbps": 1500.0, "flops_gflops": 19000.0},
    "cpu": {"mem_gbps": 20.0, "flops_gflops": 100.0},
}

# A solve whose host-side IO (downloads + pipeline waits, net of what
# the overlap hid) exceeds this fraction of the wall is host-IO-bound
# regardless of what the kernels' analytic costs say.
HOST_IO_DOMINANCE = 0.5

BOUND_KINDS = ("hbm", "mxu", "host-io", "unknown")


def peaks_for(platform: str) -> dict:
    return PLATFORM_PEAKS.get(platform, PLATFORM_PEAKS["cpu"])


def classify(
    *,
    flops: float | None = None,
    bytes_accessed: float | None = None,
    compute_s: float | None = None,
    host_io_s: float = 0.0,
    wall_s: float | None = None,
    platform: str = "cpu",
) -> dict:
    """One roofline classification.

    Returns ``{"bound": "hbm"|"mxu"|"host-io"|"unknown", ...}`` with the
    derived times (``t_hbm_s``, ``t_mxu_s``), the arithmetic intensity
    vs the platform's ridge point, the roofline-predicted floor, and a
    one-line ``why`` a human can read off a bench row."""
    peaks = peaks_for(platform)
    out: dict = {"platform": platform, "bound": "unknown", "peaks": peaks}
    if wall_s and host_io_s and host_io_s >= HOST_IO_DOMINANCE * wall_s:
        out["bound"] = "host-io"
        out["host_io_s"] = host_io_s
        out["why"] = (
            f"host IO {host_io_s:.3f}s is "
            f"{host_io_s / wall_s:.0%} of the {wall_s:.3f}s wall "
            "(downloads / checkpoint waits dominate the kernels)"
        )
        return out
    if not flops or not bytes_accessed or flops <= 0 or bytes_accessed <= 0:
        out["why"] = (
            "no analytic cost captured for this solve "
            "(cost_analysis unavailable or capture disabled)"
        )
        return out
    t_hbm = bytes_accessed / (peaks["mem_gbps"] * 1e9)
    t_mxu = flops / (peaks["flops_gflops"] * 1e9)
    intensity = flops / bytes_accessed
    ridge = peaks["flops_gflops"] / peaks["mem_gbps"]  # FLOP per byte
    bound = "hbm" if t_hbm >= t_mxu else "mxu"
    out.update(
        bound=bound,
        t_hbm_s=t_hbm,
        t_mxu_s=t_mxu,
        intensity_flop_per_byte=intensity,
        ridge_flop_per_byte=ridge,
        roofline_floor_s=max(t_hbm, t_mxu),
    )
    if compute_s and compute_s > 0:
        # Fraction of the roofline the measured kernels achieved; tiny
        # values mean overheads (dispatch, gathers the model under-
        # prices) dominate, not that the roofline is wrong.
        out["roofline_frac"] = max(t_hbm, t_mxu) / compute_s
    out["why"] = (
        f"intensity {intensity:.2f} flop/byte vs ridge {ridge:.1f} -> "
        + (
            f"bandwidth floor {t_hbm * 1e3:.3f} ms >= compute floor "
            f"{t_mxu * 1e3:.3f} ms"
            if bound == "hbm"
            else f"compute floor {t_mxu * 1e3:.3f} ms > bandwidth floor "
            f"{t_hbm * 1e3:.3f} ms"
        )
    )
    return out


def attribute_stats(stats, *, platform: str) -> dict:
    """Roofline-classify one completed solve from its SolverStats: the
    accumulated analytic cost (``stats.analytic_cost``, folded from
    every captured KernelResult) against the measured compute phases,
    with the pipeline's residual host-IO time competing for the bound."""
    g = lambda k, d=None: getattr(stats, k, d)  # noqa: E731
    phase_seconds = dict(g("phase_seconds", {}) or {})
    compute_s = sum(
        s for k, s in phase_seconds.items()
        if k in ("bellman_ford", "fanout", "batch_apsp")
    )
    wall_s = sum(phase_seconds.values())
    # Host IO that actually sat on the critical path: downloads +
    # pipeline waits minus what the overlap provably hid.
    host_io_s = max(
        0.0,
        float(g("download_s", 0.0) or 0.0)
        + float(g("ckpt_wait_s", 0.0) or 0.0)
        - float(g("overlap_saved_s", 0.0) or 0.0),
    )
    cost = g("analytic_cost") or {}
    return classify(
        flops=cost.get("flops"),
        bytes_accessed=cost.get("bytes_accessed"),
        compute_s=compute_s,
        host_io_s=host_io_s,
        wall_s=wall_s or None,
        platform=platform,
    )
