"""The persisted profile store + the calibrated cost model.

``ProfileStore`` is an append-only JSONL (``profiles.jsonl``) of
per-solve records: ``{key, analytic costs, measured wall, exact
counters, SolverStats phases, roofline}``. One record per completed
solve (the solver appends when ``SolverConfig.profile_store`` /
``PJ_PROFILE_DIR`` is set), plus whatever the off-chip validation
scripts and bench passes append. Append-only + flushed per record for
the same reason the flight recorder is: a killed pass keeps every
record it earned.

``CostModel`` is the calibration ROADMAP item 7's dispatch registry
consumes: per ``(route, platform)`` it fits *measured seconds per unit
of analytic work* — per byte accessed, per FLOP, and per edge-row
(``batch x edges``, the unit every sweep route's work scales with) —
and ``predict(route, graph, B)`` prices a prospective solve from it.
Records whose capture was unavailable still calibrate the edge-row
term (the honest fallback), so a CPU store with no ``cost_analysis``
still predicts.

Stdlib-only on purpose: the suite-budget guard and the offline readers
load this module without importing jax/numpy.
"""

from __future__ import annotations

import json
import statistics
import time
from pathlib import Path

PROFILE_FILENAME = "profiles.jsonl"


class ProfileStore:
    """Append-only JSONL profile store rooted at a directory."""

    def __init__(self, root: str | Path) -> None:
        self.root = Path(root)
        self.path = self.root / PROFILE_FILENAME

    def append(self, record: dict) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()

    def records(self) -> list[dict]:
        """All records; [] when the store has never been written. A torn
        TRAILING line (killed mid-append) is tolerated like the flight
        recorder's; anything torn earlier raises — that is corruption,
        not kill damage."""
        if not self.path.exists():
            return []
        lines = self.path.read_text(encoding="utf-8").splitlines()
        out: list[dict] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    continue
                raise ValueError(
                    f"{self.path}: corrupt record at line {i + 1} "
                    "(not the last line — this is not kill damage)"
                )
        return out

    def __len__(self) -> int:
        return len(self.records())


def solve_record(
    stats,
    *,
    label: str,
    platform: str,
    route: str | None,
    num_nodes: int,
    num_edges: int,
    batch: int,
) -> dict:
    """The canonical per-solve profile record (what the solver appends).

    ``stats`` is a SolverStats; everything is read via getattr so
    stats-shaped objects from offline scripts work too."""
    g = lambda k, d=None: getattr(stats, k, d)  # noqa: E731
    phase_seconds = dict(g("phase_seconds", {}) or {})
    compute_s = sum(
        s for k, s in phase_seconds.items()
        if k in ("bellman_ford", "fanout", "batch_apsp")
    )
    cost = g("analytic_cost")
    if not cost:
        cost = {
            "cost_analysis_unavailable":
                "no compiled-cost capture ran for this solve "
                "(host backend, or capture disabled)"
        }
    return {
        "ts": time.time(),
        "kind": "solve",
        "label": label,
        "route": route,
        "platform": platform,
        "nodes": int(num_nodes),
        "edges": int(num_edges),
        "batch": int(batch),
        "routes_by_phase": dict(g("routes_by_phase", {}) or {}),
        "measured": {
            "wall_s": float(sum(phase_seconds.values())),
            "compute_s": float(compute_s),
            "phase_seconds": phase_seconds,
            "download_s": float(g("download_s", 0.0) or 0.0),
            "ckpt_wait_s": float(g("ckpt_wait_s", 0.0) or 0.0),
            "overlap_saved_s": float(g("overlap_saved_s", 0.0) or 0.0),
        },
        "edges_relaxed": int(g("edges_relaxed", 0) or 0),
        # Iterations-to-converge across the compute phases (ISSUE 9):
        # the input the CostModel's per-iteration calibration fits, so
        # high-diameter graphs price by how long they actually iterate
        # instead of a single solve-level wall.
        "iterations": int(
            sum((g("iterations_by_phase", {}) or {}).values())
        ),
        "convergence": g("convergence"),
        "cost": cost,
        "roofline": g("roofline"),
        "predicted_s": g("predicted_s"),
    }


def _median(xs: list[float]) -> float | None:
    return statistics.median(xs) if xs else None


class CostModel:
    """Per-(route, platform) calibration fitted from a profile store.

    Entry fields:
      s_per_edge_row — measured compute seconds per (batch x edges)
        unit; always available (the fallback calibration).
      s_per_byte / s_per_flop — measured seconds per analytic byte /
        FLOP, only from records whose capture succeeded.
      bytes_per_edge_row / flops_per_edge_row — analytic density
        (median), used to extrapolate analytic costs to a prospective
        shape.
      s_per_edge_row_iter / median_iterations — the ITERATIONS term
        (ISSUE 9): seconds per (batch x edges x iteration) unit, fitted
        from records that carry ``iterations`` (solve records written
        with the convergence observatory on; ``kind: "trajectory"``
        records contribute iteration samples). An iterative route's
        wall scales with iterations-to-converge — pure edge-row pricing
        silently assumed every graph converges like the calibration
        graph, which lies on high-diameter inputs. ``predict`` prefers
        this basis whenever it is fitted.

    The per-unit seconds are the MINIMUM over the key's samples, not
    the median: timing noise is one-sided (compile time in a key's
    first record, scheduler contention) and only ever inflates, so the
    min is the steady-state cost — the same reason ``bench.py`` reports
    min-of-repeats. Densities are shape ratios, not timings, so they
    take the median (iterations too — a count, not a timing)."""

    def __init__(self, entries: dict) -> None:
        self.entries = entries

    @classmethod
    def fit(cls, source) -> "CostModel":
        """``source`` is a ProfileStore or a record list."""
        records = source.records() if hasattr(source, "records") else source
        samples: dict[tuple, dict] = {}

        def bucket(route, platform):
            return samples.setdefault(
                (route, platform),
                {"s_edge_row": [], "s_byte": [], "s_flop": [],
                 "bytes_er": [], "flops_er": [], "compute": [],
                 "s_er_iter": [], "iterations": []},
            )

        for r in records:
            route = r.get("route")
            platform = r.get("platform")
            if r.get("kind") == "trajectory":
                # Per-iteration trajectory records carry no measured
                # wall of their own — they contribute iteration
                # samples to the key's median_iterations only.
                iters = (r.get("summary") or {}).get("iterations")
                if route and platform and iters:
                    bucket(route, platform)["iterations"].append(
                        int(iters)
                    )
                continue
            if r.get("kind") not in (None, "solve", "bench", "offchip",
                                     "repair"):
                # "repair" records (ISSUE 11) calibrate like solves:
                # route "incremental-repair" lands in the same priced
                # table, so dispatch can compare repair-vs-resolve.
                continue
            measured = r.get("measured") or {}
            compute = measured.get("compute_s") or measured.get("wall_s")
            edges = r.get("edges") or 0
            batch = r.get("batch") or 1
            if not route or not platform or not compute or compute <= 0:
                continue
            edge_rows = float(batch) * float(edges)
            if edge_rows <= 0:
                continue
            s = bucket(route, platform)
            s["s_edge_row"].append(compute / edge_rows)
            s["compute"].append(compute)
            iters = r.get("iterations")
            if iters and iters > 0:
                s["iterations"].append(int(iters))
                s["s_er_iter"].append(compute / (edge_rows * iters))
            cost = r.get("cost") or {}
            by = cost.get("bytes_accessed")
            fl = cost.get("flops")
            if by and by > 0:
                s["s_byte"].append(compute / by)
                s["bytes_er"].append(by / edge_rows)
            if fl and fl > 0:
                s["s_flop"].append(compute / fl)
                s["flops_er"].append(fl / edge_rows)
        entries = {}
        for key, s in samples.items():
            if not s["s_edge_row"]:
                continue  # iteration-only samples cannot price a route
            entries[key] = {
                "route": key[0],
                "platform": key[1],
                "n": len(s["s_edge_row"]),
                "s_per_edge_row": min(s["s_edge_row"]),
                "s_per_byte": min(s["s_byte"]) if s["s_byte"] else None,
                "s_per_flop": min(s["s_flop"]) if s["s_flop"] else None,
                "bytes_per_edge_row": _median(s["bytes_er"]),
                "flops_per_edge_row": _median(s["flops_er"]),
                "median_compute_s": _median(s["compute"]),
                "s_per_edge_row_iter": (
                    min(s["s_er_iter"]) if s["s_er_iter"] else None
                ),
                "median_iterations": _median(s["iterations"]),
            }
        return cls(entries)

    def _entry(self, route: str, platform: str | None):
        if platform is not None:
            return self.entries.get((route, platform))
        matches = [e for (r, _), e in self.entries.items() if r == route]
        return matches[0] if len(matches) == 1 else None

    def predict(
        self,
        route: str,
        graph=None,
        batch: int = 1,
        *,
        num_edges: int | None = None,
        platform: str | None = None,
        iterations: int | None = None,
    ) -> dict | None:
        """Price a prospective ``(route, graph, B)`` solve from the
        calibration. ``graph`` may be a CSRGraph (its
        ``num_real_edges`` is used) or omitted in favor of
        ``num_edges``. None when the model has no data for the key —
        an unpriced route must read as unpriced, not free.

        ``iterations``: expected iterations-to-converge (a diameter
        estimate, or a measured trajectory's count). When the key has a
        fitted per-iteration calibration the prediction becomes
        ``s_per_edge_row_iter x edge_rows x iterations`` (basis
        ``"s_per_edge_row_iter"``) — with ``iterations=None`` the key's
        observed ``median_iterations`` stands in, so iterative routes
        are priced by how long they iterate, not by one solve-level
        wall (ISSUE 9 satellite; keeps the dispatch registry honest on
        high-diameter graphs)."""
        if num_edges is None and graph is not None:
            num_edges = int(
                getattr(graph, "num_real_edges", 0)
                or getattr(graph, "num_edges", 0)
            )
        if not num_edges or num_edges <= 0:
            return None
        e = self._entry(route, platform)
        if e is None or not e.get("s_per_edge_row"):
            return None
        edge_rows = float(batch) * float(num_edges)
        predicted = e["s_per_edge_row"] * edge_rows
        basis = "s_per_edge_row"
        iters = (
            iterations if iterations is not None
            else e.get("median_iterations")
        )
        if e.get("s_per_edge_row_iter") and iters:
            predicted = e["s_per_edge_row_iter"] * edge_rows * float(iters)
            basis = "s_per_edge_row_iter"
        # Analytic pricing when the key's capture succeeded: extrapolate
        # bytes by density, then apply the measured seconds-per-byte —
        # the same number by construction on in-sample shapes, but it
        # carries the bytes/FLOPs breakdown the roofline preview wants.
        analytic = {}
        if e.get("bytes_per_edge_row") and e.get("s_per_byte"):
            analytic["bytes_accessed"] = e["bytes_per_edge_row"] * edge_rows
            analytic["hbm_s"] = analytic["bytes_accessed"] * e["s_per_byte"]
        if e.get("flops_per_edge_row") and e.get("s_per_flop"):
            analytic["flops"] = e["flops_per_edge_row"] * edge_rows
            analytic["flop_s"] = analytic["flops"] * e["s_per_flop"]
        out = {
            "route": route,
            "platform": e["platform"],
            "predicted_s": predicted,
            "basis": basis,
            "n": e["n"],
            **analytic,
        }
        if basis == "s_per_edge_row_iter":
            out["iterations"] = float(iters)
        return out

    def table(self) -> list[dict]:
        """The priced route table (``cli info`` / cost_report): one row
        per (route, platform) with the fitted calibration."""
        return [
            self.entries[k] for k in sorted(self.entries)
        ]
