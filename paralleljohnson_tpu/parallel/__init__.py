"""Mesh/sharding layer: source parallelism + ICI/DCN collectives."""

from paralleljohnson_tpu.parallel import multihost
from paralleljohnson_tpu.parallel.mesh import (
    edge_sharded_bellman_ford,
    make_edge_mesh,
    make_mesh,
    sharded_fanout,
)

__all__ = [
    "edge_sharded_bellman_ford",
    "make_edge_mesh",
    "make_mesh",
    "multihost",
    "sharded_fanout",
]
