"""Mesh/sharding layer: source parallelism + ICI/DCN collectives."""

from paralleljohnson_tpu.parallel import multihost
from paralleljohnson_tpu.parallel.mesh import make_mesh, sharded_fanout

__all__ = ["make_mesh", "multihost", "sharded_fanout"]
