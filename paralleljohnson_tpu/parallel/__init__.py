"""Mesh/sharding layer: source parallelism + ICI/DCN collectives."""

from paralleljohnson_tpu.parallel import multihost
from paralleljohnson_tpu.parallel.mesh import (
    edge_sharded_bellman_ford,
    make_edge_mesh,
    make_mesh,
    make_mesh_2d,
    sharded_fanout,
    sharded_fanout_2d,
    sharded_dia_fanout,
    sharded_gs_fanout,
    sharded_tight_pred,
)

__all__ = [
    "edge_sharded_bellman_ford",
    "make_edge_mesh",
    "make_mesh",
    "make_mesh_2d",
    "multihost",
    "sharded_fanout",
    "sharded_fanout_2d",
    "sharded_dia_fanout",
    "sharded_gs_fanout",
    "sharded_tight_pred",
]
