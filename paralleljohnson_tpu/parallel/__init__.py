"""Mesh/sharding layer: source parallelism + ICI collectives."""

from paralleljohnson_tpu.parallel.mesh import make_mesh, sharded_fanout

__all__ = ["make_mesh", "sharded_fanout"]
