"""Multi-host execution (SURVEY.md §5 "Distributed communication backend").

The single-host design scales to a multi-host TPU pod without code changes
to the kernels: the same ``shard_map`` fan-out runs over a GLOBAL mesh, XLA
routes the final row all-gather over ICI within a pod slice and DCN across
slices, and the replicated CSR in-specs mean the sweeps themselves stay
collective-free. What multi-host adds is process bootstrap + building the
global sources array from per-process shards — this module owns both.

Usage on each host (standard JAX SPMD launch):

    from paralleljohnson_tpu.parallel import multihost
    multihost.initialize()          # jax.distributed, env-driven
    mesh = multihost.global_mesh()  # 1-D "sources" mesh over ALL devices
    ...

No NCCL/MPI equivalent is needed: XLA's collectives are the communication
backend (the reference's OpenMP path has no cross-host story at all —
SURVEY.md §5 attests shared-memory only).
"""

from __future__ import annotations

import os

import numpy as np


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize ``jax.distributed`` for multi-host runs.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``
    / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``); on TPU pods JAX can also
    autodetect all three. No-op (returns False) when neither arguments nor
    environment indicate a multi-process run, so single-host code can call
    this unconditionally.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if not coordinator_address and not num_processes:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh():
    """1-D ``("sources",)`` mesh over every device of every process.

    After :func:`initialize`, ``jax.devices()`` is the global device list;
    the mesh (and the shard_map fan-out built on it) is then a multi-host
    SPMD program — each process executes the same code on its addressable
    shard, collectives cross hosts via ICI/DCN.
    """
    from paralleljohnson_tpu.parallel.mesh import make_mesh

    return make_mesh(None)


def global_sources(mesh, sources: np.ndarray):
    """Build the global, "sources"-sharded device array from a host copy.

    Every process passes the SAME full ``sources`` array (cheap — it is
    int32[B]); each process materializes only its addressable shards. This
    is the multi-host-safe way to feed ``shard_map``: passing a numpy array
    directly would require process 0 to own all shards.

    Off-multiple batches are padded HERE, on the host copy, to a multiple
    of the global device count (duplicating ``sources[0]``, the same
    convention as ``sharded_fanout``): padding a non-fully-addressable
    global array later with eager ops would fail in a real multi-process
    run. Callers slice result rows back to their own batch length, and
    should pass ``n_real_rows=<their B>`` to ``sharded_fanout`` so the
    duplicate tail rows stay out of the row-sweep accounting.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sources = np.asarray(sources, np.int32)
    n = mesh.devices.size
    pad = (-sources.shape[0]) % n
    if pad and sources.shape[0]:
        sources = np.concatenate(
            [sources, np.full(pad, sources[0], np.int32)]
        )
    sharding = NamedSharding(mesh, P("sources"))
    return jax.make_array_from_callback(
        sources.shape, sharding, lambda idx: sources[idx]
    )


def process_info() -> dict:
    """Process/topology summary for logs and debugging."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
