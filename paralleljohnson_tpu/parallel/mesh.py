"""Source-parallelism across the TPU mesh (SURVEY.md §2 parallelism table).

The attested multi-chip design (BASELINE.json:5): source batches sharded
across the device mesh, CSR replicated per chip, and one ICI ``all_gather``
of per-source distance rows assembling the distance matrix. Implemented as
a 1-D ``Mesh`` over a ``"sources"`` axis + ``shard_map``:

  - in_specs: distance-row sources split on "sources"; CSR buffers
    replicated (P(None)) — each chip relaxes its own rows against the whole
    edge list, so the sweep needs NO cross-chip traffic at all.
  - The single collective is the final tiled ``all_gather`` of rows over
    ICI, plus scalar ``pmax`` reductions for the iteration count and the
    still-improving flag.

The same code runs on a real TPU mesh and on the CPU-simulated 8-device
mesh used in CI (``--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect

# ``check_vma`` was called ``check_rep`` before jax 0.6; passing the
# wrong name is a TypeError, so translate by signature at import time
# (the CPU-mesh CI and the TPU fleet run different jax generations).
_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    kwargs = {}
    if "check_vma" in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in _SHARD_MAP_PARAMS:  # pragma: no cover - jax<0.6
        # The pre-vma checker has no replication rule for while_loop —
        # every kernel here is a fixpoint loop, so it must be off.
        kwargs["check_rep"] = False
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )

from paralleljohnson_tpu.ops import relax
# Gives every sharded entry point a keyword-only ``telemetry=`` argument
# wrapping the call in a flight-recorder span (utils.telemetry) — the
# host-side wall of each collective dispatch lands on the solve's trace.
from paralleljohnson_tpu.utils.telemetry import traced


def make_mesh(
    mesh_shape: tuple[int, ...] | None = None, axis_name: str = "sources"
) -> Mesh:
    """1-D device mesh over ``axis_name`` ("sources" for the fan-out,
    "edges" for edge-sharded Bellman-Ford).

    ``mesh_shape=None`` uses every visible device; ``(n,)`` uses the first
    n. Johnson's kernels each have a single parallel dimension, so the
    mesh is 1-D by design — no model/pipeline axis exists in this domain
    (SURVEY.md §2: TP/PP/EP are N/A).
    """
    devices = np.asarray(jax.devices())
    if mesh_shape is not None:
        n = int(np.prod(mesh_shape))
        if n > devices.size:
            raise ValueError(
                f"mesh_shape {mesh_shape} needs {n} devices; "
                f"only {devices.size} visible"
            )
        devices = devices[:n]
    return Mesh(devices, axis_names=(axis_name,))


def _fire_fault_hook(fault_hook) -> None:
    """Run the caller's fault-injection hook (``utils.faults`` via
    ``JaxBackend._shard_fault_hook``) at the top of a sharded entry
    point — inside the sharded path, so an injected collective/tunnel
    failure propagates through the same except blocks a real one would
    (the sharded→single-device fallback). No-op when None (production
    solves carry no plan)."""
    if fault_hook is not None:
        fault_hook()


def _pad_sources(sources, n: int):
    """Pad a source batch to a multiple of ``n`` mesh shards, duplicating
    ``sources[0]``: padding rows participate in the pmax'd still-improving
    flag, and an arbitrary vertex-0 row could need more sweeps than every
    requested source, turning a converged fan-out into a spurious
    ConvergenceError. Guards the multi-process footgun of eager-padding a
    non-fully-addressable global array. Returns (padded, pad)."""
    b = sources.shape[0]
    pad = (-b) % n
    if pad:
        if isinstance(sources, jax.Array) and not sources.is_fully_addressable:
            raise ValueError(
                "off-multiple source batch arrived as a non-fully-"
                "addressable global array; pad on the host before building "
                "it (multihost.global_sources does this automatically)"
            )
        sources = jnp.concatenate(
            [sources, jnp.full(pad, sources[0], jnp.int32)]
        )
    return sources, pad


def _fetch_shard_vec(iters_vec) -> np.ndarray:
    """Host copy of the tiny per-shard sweep-count vector, multi-host-safe
    (shards of a mesh-sharded output live on other hosts in a
    multi-process run)."""
    if iters_vec.is_fully_addressable:
        return np.asarray(iters_vec)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(iters_vec, tiled=True)
    )


def _row_sweeps_exact(vec: np.ndarray, stride: int, n_groups: int,
                      per_group: int, b_real: int) -> int:
    """Exact, overflow-free accounting in Python ints: each source group's
    sweep count x its REAL row count (an int32 product on device could
    wrap). ``vec`` holds one entry per mesh shard; source group g reads
    entry g*stride (on a 2-D mesh every edges shard of a group reports
    the same lockstep count). Padding rows sit at the TAIL and may span
    several groups (11 rows on 8 groups -> per_group 2, pad 5 across
    groups 5-7), so clip per group."""
    return sum(
        int(vec[g * stride])
        * max(0, min(per_group, b_real - g * per_group))
        for g in range(n_groups)
    )


@functools.lru_cache(maxsize=32)
def _sharded_fanout_fn(mesh: Mesh, num_nodes: int, max_iter: int,
                       edge_chunk: int, replicate: bool,
                       with_pred: bool = False,
                       layout: str = "source_major"):
    """Build + cache the jitted sharded fan-out for one (mesh, graph-shape)
    combo. Cached on function identity so jit's own trace cache works.

    ``replicate=False`` (default): rows come back as a global array sharded
    on "sources" — shard_map stitches shards, nothing is duplicated in HBM,
    and the gather to assemble the full matrix happens wherever the result
    is next consumed (host fetch or downstream op).
    ``replicate=True``: issues the explicit tiled ``all_gather`` over ICI
    inside the kernel so every chip holds the whole matrix (the literal
    attested design). Needs check_vma=False: the vma type system cannot
    infer that a tiled all_gather output is replicated.
    """

    def shard_body(srcs, s, t, wt):
        d0 = relax.multi_source_init(srcs, num_nodes, dtype=wt.dtype)
        if with_pred:
            d, pred, iters, improving = relax.bellman_ford_sweeps_pred(
                d0, s, t, wt, max_iter=max_iter, edge_chunk=edge_chunk
            )
        elif layout == "vertex_major":
            # Caller passes dst-sorted edges for this layout; each shard
            # sweeps its own [V, B_shard] block, transposed back so the
            # out_specs stay layout-independent.
            d, iters, improving = relax.bellman_ford_sweeps_vm(
                d0.T, s, t, wt, max_iter=max_iter, edge_chunk=edge_chunk
            )
            d = d.T
        else:
            d, iters, improving = relax.bellman_ford_sweeps(
                d0, s, t, wt, max_iter=max_iter, edge_chunk=edge_chunk
            )
        if replicate:
            d = jax.lax.all_gather(d, "sources", axis=0, tiled=True)
        # Exact work accounting (not pmax(iters) x B, which overcounts
        # shards that converged early): each shard reports its own sweep
        # count; the host multiplies by that shard's REAL row count in
        # Python ints (an int32 iters x rows product on device could wrap
        # past 2^31 on high-diameter graphs with wide batches).
        iters_vec = iters[None]  # [1] per shard -> [n_shards] global
        iters = jax.lax.pmax(iters, "sources")
        improving = jax.lax.pmax(improving.astype(jnp.int32), "sources")
        if with_pred:
            return d, iters, improving, iters_vec, pred
        return d, iters, improving, iters_vec

    dist_spec = P(None) if replicate else P("sources")
    out_specs = (
        (dist_spec, P(), P(), P("sources"), P("sources")) if with_pred
        else (dist_spec, P(), P(), P("sources"))
    )
    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("sources"), P(None), P(None), P(None)),
        out_specs=out_specs,
        check_vma=not replicate,
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def _edge_sharded_bf_fn(mesh: Mesh, num_nodes: int, max_iter: int,
                        edge_chunk: int):
    """Edge-sharded Bellman-Ford: the scale-out axis for graphs whose
    EDGE LIST exceeds one chip's HBM (beyond the attested replicated-CSR
    design — SURVEY.md §7 notes this as the stretch direction; e.g.
    rmat-26 is ~1 G edges = 12 GB of COO buffers).

    Layout: edges split on the 1-D mesh axis, dist [B, V] (or [V])
    replicated. Each sweep relaxes the local edge shard, then a ``pmin``
    all-reduce merges the per-shard relaxations — one [B, V] collective
    per sweep over ICI. Monotone relaxation makes the merge exact: the
    pmin of per-shard relaxed copies equals a full-edge-list sweep with
    Jacobi (not chunk-Gauss-Seidel) visibility, so convergence needs the
    same <= |V| rounds and the negative-cycle bound holds unchanged.
    """

    def shard_body(dist0, s, t, wt):
        def cond(state):
            _, i, improving = state
            return improving & (i < max_iter)

        def body(state):
            d, i, _ = state
            nd = relax.relax_sweep(d, s, t, wt, edge_chunk=edge_chunk)
            nd = jax.lax.pmin(nd, "edges")
            return nd, i + 1, jnp.any(nd < d)

        improving0 = jnp.any(jnp.isfinite(dist0))
        dist, iters, improving = jax.lax.while_loop(
            cond, body, (dist0, jnp.int32(0), improving0)
        )
        improving = jax.lax.pmax(improving.astype(jnp.int32), "edges")
        return dist, iters, improving

    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P("edges"), P("edges"), P("edges")),
        out_specs=(P(), P(), P()),
        check_vma=False,  # the pmin result is replicated; vma can't infer it
    )
    return jax.jit(mapped)


@traced("edge_sharded_bellman_ford")
def edge_sharded_bellman_ford(
    mesh: Mesh,
    dist0,
    src,
    dst,
    w,
    *,
    max_iter: int,
    edge_chunk: int = 1 << 20,
    fault_hook=None,
):
    """Bellman-Ford with the EDGE LIST sharded across ``mesh`` (axis name
    "edges" — pass a mesh from :func:`make_edge_mesh`). ``dist0`` is
    replicated ([V] or [B, V]); edges are padded to a mesh multiple with
    (0, 0, +inf) no-ops. Returns (dist, iterations, still_improving).
    """
    _fire_fault_hook(fault_hook)
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    e = src.shape[0]
    pad = (-e) % n
    if pad:
        src = jnp.concatenate([src, jnp.zeros(pad, src.dtype)])
        dst = jnp.concatenate([dst, jnp.zeros(pad, dst.dtype)])
        w = jnp.concatenate([w, jnp.full(pad, jnp.inf, w.dtype)])
    fn = _edge_sharded_bf_fn(mesh, int(dist0.shape[-1]), int(max_iter),
                             int(edge_chunk))
    dist, iters, improving = fn(dist0, src, dst, w)
    return dist, iters, improving.astype(bool)


def make_edge_mesh(mesh_shape: tuple[int, ...] | None = None) -> Mesh:
    """1-D device mesh over an ``"edges"`` axis (edge-sharded kernels)."""
    return make_mesh(mesh_shape, axis_name="edges")


@functools.lru_cache(maxsize=32)
def _sharded_gs_fanout_fn(mesh: Mesh, v_pad: int, vb: int, halo: int,
                          max_outer: int, inner_cap: int):
    """Blocked Gauss-Seidel fan-out sharded over the "sources" axis: the
    sequential block schedule (the algorithm) runs PER DEVICE on that
    device's batch slice; the layout + rank are replicated; there are NO
    per-round collectives — rows are independent, so the only cross-chip
    step is the output assembly (exactly the attested all-gather shape).
    Composes the road-graph kernel with pod-scale source parallelism
    (round-3 verdict weak #5)."""

    def shard_body(srcs, src_blk, dstl_blk, w_blk, rank):
        from paralleljohnson_tpu.ops.gauss_seidel import fanout_gs_body

        dist, rounds, improving, iters_blk = fanout_gs_body(
            srcs, src_blk, dstl_blk, w_blk, rank,
            v_pad=v_pad, vb=vb, halo=halo, max_outer=max_outer,
            inner_cap=inner_cap,
        )
        iters_vec = iters_blk[None]                 # [1, NB] per shard
        rounds = jax.lax.pmax(rounds, "sources")
        improving = jax.lax.pmax(improving.astype(jnp.int32), "sources")
        return dist, rounds, improving, iters_vec

    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("sources"), P(None), P(None), P(None), P(None)),
        out_specs=(P("sources"), P(), P(), P("sources")),
        check_vma=False,  # pmax results are replicated
    )
    return jax.jit(mapped)


@traced("sharded_gs_fanout")
def sharded_gs_fanout(
    mesh: Mesh,
    sources,
    src_blk,
    dstl_blk,
    w_blk,
    rank,
    *,
    v_pad: int,
    vb: int,
    halo: int,
    max_outer: int,
    inner_cap: int,
    real_edges_host: np.ndarray,
    fault_hook=None,
):
    """N-source blocked-GS fan-out with sources sharded over ``mesh``
    (1-D "sources" axis). Pads the batch to a mesh multiple (duplicating
    ``sources[0]``; rows dropped from output AND work accounting).

    Returns (dist[B, V], rounds, still_improving, examined) —
    ``examined`` the exact Python-int candidate count: per shard,
    sum(iters_blk x real edges) x that shard's REAL row count."""
    _fire_fault_hook(fault_hook)
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    sources = jnp.asarray(sources, jnp.int32)
    b = sources.shape[0]
    sources, pad = _pad_sources(sources, n)
    fn = _sharded_gs_fanout_fn(mesh, int(v_pad), int(vb), int(halo),
                               int(max_outer), int(inner_cap))
    dist, rounds, improving, iters_vec = fn(
        sources, src_blk, dstl_blk, w_blk, rank
    )
    per = (b + pad) // n
    iters_mat = np.asarray(_fetch_shard_vec(iters_vec), np.int64)  # [n, NB]
    # Same achievable-bound wrap guard as the single-device accounting
    # (jax_backend._gs_examined_exact): the per-block int32 counters are
    # exact only below 2 x rounds x inner_cap < 2^31 (round-5 verdict
    # weak #5 — this path used to skip the check the B=1 route ran).
    from paralleljohnson_tpu.utils.metrics import warn_if_counter_wrapped

    warn_if_counter_wrapped(int(rounds), inner_cap, where="gs-sharded")
    edges = real_edges_host.astype(np.int64)
    examined = sum(
        int(np.dot(iters_mat[g], edges))
        * max(0, min(per, b - g * per))
        for g in range(n)
    )
    return dist[:b], rounds, improving.astype(bool), examined


@functools.lru_cache(maxsize=32)
def _sharded_dia_fanout_fn(mesh: Mesh, num_nodes: int, offsets: tuple,
                           max_iter: int):
    """DIA stencil fan-out sharded over the "sources" axis: the chained
    roll sweeps (ops.dia) run PER DEVICE on that device's [b/n, V] row
    slice with the [K, V] diagonal weights replicated — rows are
    independent, so like the GS composition there are NO per-round
    collectives, only the output assembly."""

    def shard_body(srcs, w_diag):
        from paralleljohnson_tpu.ops.dia import dia_fixpoint

        b_loc = srcs.shape[0]
        dist0 = jnp.full((b_loc, num_nodes), jnp.inf, w_diag.dtype)
        dist0 = dist0.at[jnp.arange(b_loc), srcs].set(0.0)
        dist, iters, improving = dia_fixpoint(
            dist0, w_diag, offsets=offsets, max_iter=max_iter
        )
        iters_vec = iters[None]                     # [1] per shard
        iters = jax.lax.pmax(iters, "sources")
        improving = jax.lax.pmax(improving.astype(jnp.int32), "sources")
        return dist, iters, improving, iters_vec

    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("sources"), P(None)),
        out_specs=(P("sources"), P(), P(), P("sources")),
        check_vma=False,  # pmax results are replicated
    )
    return jax.jit(mapped)


@traced("sharded_dia_fanout")
def sharded_dia_fanout(
    mesh: Mesh,
    sources,
    w_diag,
    *,
    num_nodes: int,
    offsets: tuple,
    max_iter: int,
    num_entries: int,
    fault_hook=None,
):
    """N-source DIA fan-out with sources sharded over ``mesh`` (1-D
    "sources" axis). Pads the batch to a mesh multiple (duplicating
    ``sources[0]``; rows dropped from output AND work accounting).

    Returns (dist[B, V], iterations, still_improving, examined) —
    ``examined`` the exact Python-int candidate count: per shard,
    sweeps x stored diagonal entries x that shard's REAL row count."""
    _fire_fault_hook(fault_hook)
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    sources = jnp.asarray(sources, jnp.int32)
    b = sources.shape[0]
    sources, pad = _pad_sources(sources, n)
    fn = _sharded_dia_fanout_fn(
        mesh, int(num_nodes), tuple(offsets), int(max_iter)
    )
    dist, iters, improving, iters_vec = fn(sources, w_diag)
    per = (b + pad) // n
    iters_arr = np.asarray(_fetch_shard_vec(iters_vec), np.int64).ravel()
    examined = int(num_entries) * _row_sweeps_exact(
        iters_arr, stride=1, n_groups=n, per_group=per, b_real=b
    )
    return dist[:b], iters, improving.astype(bool), examined


@functools.lru_cache(maxsize=32)
def _sharded_tight_pred_fn(mesh: Mesh, num_nodes: int, edge_chunk: int):
    """Tight-edge predecessor extraction (``ops.pred``) sharded over the
    "sources" axis: rows are independent, so each device extracts trees
    for its own [B/n, V] distance block against the REPLICATED edge list
    — zero collectives, exactly the sharded-fanout data layout (CSR
    replicated per chip). Valid on the 1-D sources mesh AND the 2-D
    ("sources", "edges") mesh: ``P("sources")`` leaves rows replicated
    over the edges axis, and the body is deterministic in replicated
    inputs, so each edges shard computes the identical tree."""

    def shard_body(dist, srcs, s, t, wt):
        from paralleljohnson_tpu.ops.pred import extract_pred

        pred, ok = extract_pred(
            dist, srcs, s, t, wt, edge_chunk=edge_chunk
        )
        return pred, ok[None].astype(jnp.int32)  # [1] per shard

    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("sources"), P("sources"), P(None), P(None), P(None)),
        out_specs=(P("sources"), P("sources")),
    )
    return jax.jit(mapped)


@traced("sharded_tight_pred")
def sharded_tight_pred(
    mesh: Mesh,
    dist,
    sources,
    src,
    dst,
    w,
    *,
    num_nodes: int,
    edge_chunk: int = 1 << 20,
):
    """Post-fixpoint predecessor extraction with the distance rows
    sharded over ``mesh``'s "sources" axis (the mesh the fan-out ran
    on). Pads ``dist``/``sources`` to a mesh multiple by duplicating row
    0 (dropped from the output), mirroring :func:`sharded_fanout`.

    Returns (pred[B, V] int32 sharded on "sources", ok bool) — ``ok``
    is the host-reduced all-shards tree-validity certificate
    (``ops.pred.extract_pred`` contract): False means a zero-weight
    tight cycle defeated the one-pass rule and the caller must fall
    back to the legacy argmin sweep."""
    ns = int(mesh.shape.get("sources", mesh.devices.size))
    sources = jnp.asarray(sources, jnp.int32)
    b = sources.shape[0]
    sources, pad = _pad_sources(sources, ns)
    if pad:
        dist = jnp.concatenate(
            [dist, jnp.repeat(dist[:1], pad, axis=0)]
        )
    fn = _sharded_tight_pred_fn(mesh, int(num_nodes), int(edge_chunk))
    pred, ok_vec = fn(dist, sources, src, dst, w)
    ok = bool(np.all(_fetch_shard_vec(ok_vec)))
    return pred[:b], ok


def make_mesh_2d(mesh_shape: tuple[int, int]) -> Mesh:
    """2-D ``("sources", "edges")`` mesh: sources axis for fan-out
    throughput, edges axis for edge lists beyond one chip's HBM — the two
    scale-out dimensions of this domain, composed."""
    ns, ne = int(mesh_shape[0]), int(mesh_shape[1])
    devices = np.asarray(jax.devices())
    if ns * ne > devices.size:
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {ns * ne} devices; "
            f"only {devices.size} visible"
        )
    return Mesh(devices[: ns * ne].reshape(ns, ne),
                axis_names=("sources", "edges"))


@functools.lru_cache(maxsize=32)
def _sharded_fanout_2d_fn(mesh: Mesh, num_nodes: int, max_iter: int,
                          edge_chunk: int, layout: str = "source_major"):
    """Fan-out over a 2-D ("sources", "edges") mesh: each shard holds a
    [B/n_s, V] row block and an E/n_e edge slice. Per sweep: relax the
    local edges, then pmin over the "edges" axis merges the partial
    relaxations (exact — monotone relaxation, Jacobi visibility). Source
    groups run the fixpoint loop independently (no cross-"sources"
    collective inside the loop); within a group the pmin keeps edge
    shards lockstep, so the data-dependent trip count is well defined.
    Rows come back sharded on "sources", replicated over "edges".
    """

    vm = layout == "vertex_major"

    def shard_body(srcs, s, t, wt):
        d0 = relax.multi_source_init(srcs, num_nodes, dtype=wt.dtype)
        if vm:
            d0 = d0.T  # [V, B_shard]; shard slices of a globally
            # dst-sorted edge list stay dst-sorted, so the sorted segment
            # reduction is valid per shard.

        def cond(state):
            _, i, improving = state
            return improving & (i < max_iter)

        def body(state):
            d, i, _ = state
            if vm:
                nd = relax.relax_sweep_vm(d, s, t, wt, edge_chunk=edge_chunk)
            else:
                nd = relax.relax_sweep(d, s, t, wt, edge_chunk=edge_chunk)
            nd = jax.lax.pmin(nd, "edges")
            return nd, i + 1, jnp.any(nd < d)

        improving0 = jnp.any(jnp.isfinite(d0))
        d, iters, improving = jax.lax.while_loop(
            cond, body, (d0, jnp.int32(0), improving0)
        )
        if vm:
            d = d.T
        iters_vec = iters[None]  # [1] per shard -> [n_s * n_e] global
        iters = jax.lax.pmax(iters, ("sources", "edges"))
        improving = jax.lax.pmax(
            improving.astype(jnp.int32), ("sources", "edges")
        )
        return d, iters, improving, iters_vec

    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P("sources"), P("edges"), P("edges"), P("edges")),
        out_specs=(P("sources", None), P(), P(), P(("sources", "edges"))),
        check_vma=False,  # pmin/pmax results are replicated over "edges"
    )
    return jax.jit(mapped)


@traced("sharded_fanout_2d")
def sharded_fanout_2d(
    mesh: Mesh,
    sources,
    src,
    dst,
    w,
    *,
    num_nodes: int,
    max_iter: int,
    edge_chunk: int = 1 << 20,
    layout: str = "source_major",
    with_row_sweeps: bool = False,
    fault_hook=None,
):
    """N-source fan-out with sources AND edges sharded over a 2-D mesh
    (from :func:`make_mesh_2d`). Pads sources to a multiple of the
    "sources" axis (duplicating ``sources[0]``) and edges to a multiple
    of the "edges" axis ((0, 0, +inf) no-ops).

    ``layout="vertex_major"``: the caller MUST pass globally dst-sorted
    edges (``JaxDeviceGraph.by_dst``) — contiguous shard slices of a
    sorted list stay sorted, so each shard runs the sorted segment
    reduction on its slice. Tail pad edges are (0, V-1, +inf) for this
    layout: ``indices_are_sorted=True`` makes an out-of-order index
    undefined behavior, so the pad must preserve monotone dst.

    Returns (dist[B, V], iterations, still_improving[, row_sweeps])."""
    _fire_fault_hook(fault_hook)
    ns = mesh.shape["sources"]
    ne = mesh.shape["edges"]
    sources = jnp.asarray(sources, jnp.int32)
    b = sources.shape[0]
    sources, spad = _pad_sources(sources, ns)
    epad = (-src.shape[0]) % ne
    if epad:
        pad_dst = num_nodes - 1 if layout == "vertex_major" else 0
        src = jnp.concatenate([src, jnp.zeros(epad, src.dtype)])
        dst = jnp.concatenate(
            [dst, jnp.full(epad, pad_dst, dst.dtype)]
        )
        w = jnp.concatenate([w, jnp.full(epad, jnp.inf, w.dtype)])
    fn = _sharded_fanout_2d_fn(mesh, int(num_nodes), int(max_iter),
                               int(edge_chunk), str(layout))
    d, iters, improving, iters_vec = fn(sources, src, dst, w)
    out = (d[:b], iters, improving.astype(bool))
    if with_row_sweeps:
        # Per source group g, every edges shard reports the same sweep
        # count (lockstep) — read entry g*ne.
        row_sweeps = _row_sweeps_exact(
            _fetch_shard_vec(iters_vec), stride=ne, n_groups=ns,
            per_group=(b + spad) // ns, b_real=b,
        )
        out = out + (row_sweeps,)
    return out


@traced("sharded_fanout")
def sharded_fanout(
    mesh: Mesh,
    sources,
    src,
    dst,
    w,
    *,
    num_nodes: int,
    max_iter: int,
    edge_chunk: int = 1 << 20,
    replicate: bool = False,
    with_pred: bool = False,
    layout: str = "source_major",
    with_row_sweeps: bool = False,
    n_real_rows: int | None = None,
    fault_hook=None,
):
    """N-source fan-out with sources sharded over ``mesh``.

    Pads the source batch to a multiple of the mesh size (padding rows
    duplicate ``sources[0]`` and are dropped), runs the per-shard sweep, and
    gathers rows (explicit ICI all_gather when ``replicate=True``, output-
    sharding assembly otherwise). Returns (dist[B, V], iterations,
    still_improving), plus pred[B, V] appended when ``with_pred=True``
    (predecessor rows stay sharded on "sources" like the distance rows),
    plus the exact row-sweep total (sum over shards of sweeps x real rows,
    for edges-relaxed accounting) appended when ``with_row_sweeps=True``.

    ``n_real_rows``: when the caller already padded the batch (e.g.
    :func:`multihost.global_sources`), the number of genuine rows at the
    front — the duplicate tail rows are then excluded from the row-sweep
    accounting exactly like locally-added padding.

    ``layout="vertex_major"`` runs the per-shard sweep on a [V, B_shard]
    block with a sorted segment reduction — the caller MUST then pass
    dst-sorted ``src``/``dst``/``w`` (``JaxDeviceGraph.by_dst``). Not
    compatible with ``with_pred`` (predecessor tracking is source-major).
    """
    if with_pred and layout == "vertex_major":
        raise ValueError("with_pred requires the source_major layout")
    _fire_fault_hook(fault_hook)
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    sources = jnp.asarray(sources, jnp.int32)
    b = sources.shape[0]
    sources, pad = _pad_sources(sources, n)
    acct_pad = pad + (b - n_real_rows if n_real_rows is not None else 0)
    fn = _sharded_fanout_fn(mesh, num_nodes, max_iter, int(edge_chunk),
                            bool(replicate), bool(with_pred), str(layout))
    if with_pred:
        d, iters, improving, iters_vec, pred = fn(sources, src, dst, w)
        out = (d[:b], iters, improving.astype(bool), pred[:b])
    else:
        d, iters, improving, iters_vec = fn(sources, src, dst, w)
        out = (d[:b], iters, improving.astype(bool))
    if with_row_sweeps:
        # acct_pad covers locally-added padding and/or the caller's
        # pre-padded tail (n_real_rows).
        row_sweeps = _row_sweeps_exact(
            _fetch_shard_vec(iters_vec), stride=1, n_groups=n,
            per_group=(b + pad) // n, b_real=b + pad - acct_pad,
        )
        out = out + (row_sweeps,)
    return out
