"""Build + load the native C++/OpenMP kernel library (SURVEY.md §2 #6).

The library is compiled on first use with the system ``g++`` (no pip/apt
dependencies) into ``_build/`` next to the source, keyed by a hash of the
source text and compile flags so edits rebuild and repeat imports reuse the
cached ``.so``. A file lock serializes concurrent builds (pytest-xdist).

Env knobs:
  PJ_NATIVE_CXX       compiler (default g++)
  PJ_NATIVE_TSAN=1    ThreadSanitizer build (-fsanitize=thread -O1 -g) —
                      the race-detection CI mode (SURVEY.md §5)
  PJ_NATIVE_FLAGS     extra compile flags
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path

_SRC = Path(__file__).parent / "pj_native.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"

_lib: ctypes.CDLL | None = None


def _flags() -> list[str]:
    flags = ["-std=c++17", "-shared", "-fPIC", "-fopenmp"]
    if os.environ.get("PJ_NATIVE_TSAN") == "1":
        flags += ["-fsanitize=thread", "-O1", "-g"]
    else:
        flags += ["-O3", "-funroll-loops"]
    extra = os.environ.get("PJ_NATIVE_FLAGS")
    if extra:
        flags += extra.split()
    return flags


def library_path() -> Path:
    """Compile (if needed) and return the shared-library path."""
    cxx = os.environ.get("PJ_NATIVE_CXX", "g++")
    flags = _flags()
    key = hashlib.sha256(
        (_SRC.read_text() + cxx + " ".join(flags)).encode()
    ).hexdigest()[:16]
    out = _BUILD_DIR / f"pj_native_{key}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    lock = _BUILD_DIR / f".{key}.lock"
    import fcntl

    with open(lock, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        if not out.exists():
            tmp = out.with_suffix(".so.tmp")
            subprocess.run(
                [cxx, *flags, str(_SRC), "-o", str(tmp)],
                check=True,
                capture_output=True,
                text=True,
            )
            tmp.replace(out)  # atomic: readers never see a partial .so
    return out


def load_library() -> ctypes.CDLL:
    """Load (building if necessary) and type the native library."""
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(library_path()))

    i32, i64 = ctypes.c_int32, ctypes.c_int64
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_f32 = ctypes.POINTER(ctypes.c_float)
    p_f64 = ctypes.POINTER(ctypes.c_double)

    lib.pj_version.restype = i32
    lib.pj_num_threads.restype = i32
    for suffix, p_t in (("f32", p_f32), ("f64", p_f64)):
        bf = getattr(lib, f"pj_bellman_ford_{suffix}")
        bf.restype = i32
        bf.argtypes = [i32, i64, p_i32, p_i32, p_t, p_t, i32, p_i32, p_i64]
        dj = getattr(lib, f"pj_dijkstra_fanout_{suffix}")
        dj.restype = None
        dj.argtypes = [i32, p_i32, p_i32, p_t, i32, p_i32, p_t, p_i64]
        djp = getattr(lib, f"pj_dijkstra_fanout_pred_{suffix}")
        djp.restype = None
        djp.argtypes = [i32, p_i32, p_i32, p_t, i32, p_i32, p_t, p_i32, p_i64]
        ex = getattr(lib, f"pj_extract_predecessors_{suffix}")
        ex.restype = None
        ex.argtypes = [i32, p_i32, p_i32, p_t, p_t, i32, p_i32]
        bj = getattr(lib, f"pj_batch_johnson_{suffix}")
        bj.restype = i64
        bj.argtypes = [i32, i64, p_i32, i32, p_i32, p_i32, p_t, p_t, p_i32]
    _lib = lib
    return lib
