// Native CPU/OpenMP kernels — the rebuild of the reference's attested
// native component (SURVEY.md §2 #6: "C/C++ + OpenMP shared-memory parallel
// kernels: parallel-for over edges (Bellman-Ford iterations) and over
// sources (Dijkstra fan-out)", BASELINE.json:5 "CPU/OpenMP path").
//
// This is the comparison baseline the TPU backend's >=10x target is
// measured against, not a stand-in: edge relaxation is a lock-free
// atomic-min sweep parallel over edges, and the fan-out is heap Dijkstra
// parallel over sources. Both count edge relaxations for the attested
// edges-relaxed/sec/chip metric (BASELINE.json:2).
//
// Memory-model notes (the part TSan cares about):
//   - dist[] updates go through __atomic_compare_exchange with relaxed
//     ordering. Distances only ever decrease, and the fixpoint of a
//     monotone min-relaxation is unique, so a stale read can only delay
//     convergence by a sweep, never corrupt the result.
//   - The per-sweep "improved" flag is an OpenMP || reduction.
//   - Dijkstra threads share nothing but read-only CSR arrays and disjoint
//     output rows.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <queue>
#include <utility>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

template <typename T>
inline bool atomic_fetch_min(T *addr, T val) {
  // Lock-free min via CAS on the value's object representation. Returns
  // true iff this call lowered *addr. NaN never occurs (weights are
  // finite or +inf and +inf + finite stays +inf).
  T cur;
  __atomic_load(addr, &cur, __ATOMIC_RELAXED);
  while (val < cur) {
    if (__atomic_compare_exchange(addr, &cur, &val, /*weak=*/true,
                                  __ATOMIC_RELAXED, __ATOMIC_RELAXED))
      return true;
  }
  return false;
}

// One Bellman-Ford relaxation sweep over the COO edge list, parallel over
// edges. Returns whether any distance improved.
template <typename T>
bool relax_sweep(int64_t num_edges, const int32_t *src, const int32_t *dst,
                 const T *w, T *dist) {
  bool improved = false;
#pragma omp parallel for schedule(static) reduction(|| : improved)
  for (int64_t i = 0; i < num_edges; ++i) {
    T du;
    __atomic_load(&dist[src[i]], &du, __ATOMIC_RELAXED);
    if (!std::isfinite(du)) continue;  // inf + w never relaxes anything
    const T cand = du + w[i];
    T dv;
    __atomic_load(&dist[dst[i]], &dv, __ATOMIC_RELAXED);
    if (cand < dv) improved |= atomic_fetch_min(&dist[dst[i]], cand);
  }
  return improved;
}

// Binary-heap Dijkstra from one source on non-negative CSR weights.
// Writes the full distance row (and the predecessor row when `pred` is
// non-null; -1 = source/unreachable); returns edges scanned (the
// edges-relaxed count convention for heap Dijkstra: out-edges of settled
// vertices).
template <typename T>
int64_t dijkstra_row(int32_t num_nodes, const int32_t *indptr,
                     const int32_t *indices, const T *w, int32_t source,
                     T *dist, int32_t *pred = nullptr) {
  const T inf = std::numeric_limits<T>::infinity();
  for (int32_t v = 0; v < num_nodes; ++v) dist[v] = inf;
  dist[source] = T(0);
  if (pred)
    for (int32_t v = 0; v < num_nodes; ++v) pred[v] = -1;

  using Item = std::pair<T, int32_t>;  // (distance, vertex), min-heap
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> heap;
  heap.emplace(T(0), source);
  int64_t scanned = 0;
  while (!heap.empty()) {
    const auto [d, u] = heap.top();
    heap.pop();
    if (d > dist[u]) continue;  // lazy deletion: stale entry
    for (int32_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      ++scanned;
      const T nd = d + w[e];
      const int32_t v = indices[e];
      if (nd < dist[v]) {
        dist[v] = nd;
        if (pred) pred[v] = u;
        heap.emplace(nd, v);
      }
    }
  }
  return scanned;
}

// Post-fixpoint predecessor extraction for Bellman-Ford: BFS from the
// source over "tight" edges (dist[u] + w == dist[v] — exact: dist[v] was
// stored as that very sum for its winning edge). Every shortest path
// consists of tight edges, so the BFS reaches every finite-distance vertex,
// and first-discovery assignment makes the result a proper tree — a
// parallel per-edge equality scan could instead pick edges of a zero-weight
// cycle and loop path reconstruction. Runs AFTER the sweeps, so it needs no
// racy paired atomics on (dist, pred); CSR order makes it deterministic.
// O(V + E) sequential — noise next to the O(V * E) sweep phase.
template <typename T>
void extract_predecessors(int32_t num_nodes, const int32_t *indptr,
                          const int32_t *indices, const T *w, const T *dist,
                          int32_t source, int32_t *pred) {
  for (int32_t v = 0; v < num_nodes; ++v) pred[v] = -1;
  std::vector<int32_t> queue;
  std::vector<uint8_t> seen(num_nodes, 0);
  queue.reserve(num_nodes);
  queue.push_back(source);
  seen[source] = 1;
  for (size_t qi = 0; qi < queue.size(); ++qi) {
    const int32_t u = queue[qi];
    const T du = dist[u];
    for (int32_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      const int32_t v = indices[e];
      if (seen[v]) continue;
      if (du + w[e] == dist[v]) {
        pred[v] = u;
        seen[v] = 1;
        queue.push_back(v);
      }
    }
  }
}

template <typename T>
int32_t bellman_ford_impl(int32_t num_nodes, int64_t num_edges,
                          const int32_t *src, const int32_t *dst, const T *w,
                          T *dist, int32_t max_iter, int32_t *iterations,
                          int64_t *edges_relaxed) {
  int32_t iters = 0;
  bool improving = num_nodes > 0;
  while (improving && iters < max_iter) {
    improving = relax_sweep(num_edges, src, dst, w, dist);
    ++iters;
  }
  *iterations = iters;
  // Sweep convention (matches every other backend): each sweep scans all E.
  *edges_relaxed = static_cast<int64_t>(iters) * num_edges;
  return improving ? 1 : 0;  // still improving at cap = caller's flag
}

template <typename T>
void dijkstra_fanout_impl(int32_t num_nodes, const int32_t *indptr,
                          const int32_t *indices, const T *w,
                          int32_t num_sources, const int32_t *sources,
                          T *dist_out, int64_t *edges_relaxed,
                          int32_t *pred_out = nullptr) {
  int64_t total = 0;
#pragma omp parallel for schedule(dynamic, 1) reduction(+ : total)
  for (int32_t b = 0; b < num_sources; ++b) {
    const int64_t off = static_cast<int64_t>(b) * num_nodes;
    total += dijkstra_row(num_nodes, indptr, indices, w, sources[b],
                          dist_out + off,
                          pred_out ? pred_out + off : nullptr);
  }
  *edges_relaxed = total;
}

// One graph of the many-small-graphs batch (SURVEY.md §3.4): full Johnson —
// virtual-source Bellman-Ford -> reweight -> per-source heap Dijkstra ->
// un-reweight. Runs serially; the batch loop parallelizes across graphs
// (the reference-shaped thread-pool decomposition: graphs are independent).
// Edges are COO (CSR-ordered by src) with +inf padding; indptr is rebuilt
// locally. Returns 1 on a negative cycle (dist rows left +inf).
template <typename T>
int32_t johnson_one_graph(int32_t v, int64_t e_pad, const int32_t *src,
                          const int32_t *dst, const T *w, int32_t v_max,
                          T *dist_rows, int64_t *edges_relaxed) {
  const T inf = std::numeric_limits<T>::infinity();
  // Trim +inf padding (stacked graphs pad the edge tail).
  int64_t e = e_pad;
  while (e > 0 && !std::isfinite(w[e - 1])) --e;

  // Phase 1: virtual-source Bellman-Ford (dist0 = 0 everywhere).
  std::vector<T> h(v, T(0));
  int32_t iters = 0;
  bool improving = v > 0;
  while (improving && iters < v) {  // v sweeps max: v-1 suffice cycle-free
    improving = false;
    for (int64_t i = 0; i < e; ++i) {
      const T du = h[src[i]];
      if (!std::isfinite(du)) continue;
      const T cand = du + w[i];
      if (cand < h[dst[i]]) {
        h[dst[i]] = cand;
        improving = true;
      }
    }
    ++iters;
  }
  *edges_relaxed += static_cast<int64_t>(iters) * e;
  if (improving) {  // v-th sweep still improved: negative cycle
    for (int64_t i = 0; i < static_cast<int64_t>(v_max) * v_max; ++i)
      dist_rows[i] = inf;  // honor the contract: rows are +inf, not garbage
    return 1;
  }

  // Reweight + rebuild CSR structure (COO is already src-sorted).
  std::vector<T> wp(e);
  std::vector<int32_t> indptr(v + 1, 0);
  for (int64_t i = 0; i < e; ++i) {
    T x = w[i] + h[src[i]] - h[dst[i]];
    wp[i] = x < T(0) ? T(0) : x;  // clamp float residue
    ++indptr[src[i] + 1];
  }
  for (int32_t u = 0; u < v; ++u) indptr[u + 1] += indptr[u];

  // Phase 2+3: per-source Dijkstra on w', un-reweighted in place.
  for (int32_t s = 0; s < v; ++s) {
    T *row = dist_rows + static_cast<int64_t>(s) * v_max;
    *edges_relaxed +=
        dijkstra_row(v, indptr.data(), dst, wp.data(), s, row);
    for (int32_t t = 0; t < v; ++t)
      if (std::isfinite(row[t])) row[t] += h[t] - h[s];
    for (int32_t t = v; t < v_max; ++t) row[t] = inf;
  }
  // Padded source rows: unreachable except the 0 diagonal (mirrors the
  // vmapped jax batch kernel; callers slice to the true V anyway).
  for (int32_t s = v; s < v_max; ++s) {
    T *row = dist_rows + static_cast<int64_t>(s) * v_max;
    for (int32_t t = 0; t < v_max; ++t) row[t] = (t == s) ? T(0) : inf;
  }
  return 0;
}

}  // namespace

extern "C" {

int32_t pj_version() { return 1; }

int32_t pj_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// Bellman-Ford over a COO edge list. `dist` is in-out: the caller seeds it
// (all-zero for the Johnson virtual source, +inf except source otherwise).
// Returns 1 if a sweep at the iteration cap was still improving (negative
// cycle when max_iter >= V), else 0.
int32_t pj_bellman_ford_f32(int32_t num_nodes, int64_t num_edges,
                            const int32_t *src, const int32_t *dst,
                            const float *w, float *dist, int32_t max_iter,
                            int32_t *iterations, int64_t *edges_relaxed) {
  return bellman_ford_impl(num_nodes, num_edges, src, dst, w, dist, max_iter,
                           iterations, edges_relaxed);
}

int32_t pj_bellman_ford_f64(int32_t num_nodes, int64_t num_edges,
                            const int32_t *src, const int32_t *dst,
                            const double *w, double *dist, int32_t max_iter,
                            int32_t *iterations, int64_t *edges_relaxed) {
  return bellman_ford_impl(num_nodes, num_edges, src, dst, w, dist, max_iter,
                           iterations, edges_relaxed);
}

// N-source heap-Dijkstra fan-out on non-negative CSR weights, parallel over
// sources. dist_out is [num_sources, num_nodes] row-major.
void pj_dijkstra_fanout_f32(int32_t num_nodes, const int32_t *indptr,
                            const int32_t *indices, const float *w,
                            int32_t num_sources, const int32_t *sources,
                            float *dist_out, int64_t *edges_relaxed) {
  dijkstra_fanout_impl(num_nodes, indptr, indices, w, num_sources, sources,
                       dist_out, edges_relaxed);
}

void pj_dijkstra_fanout_f64(int32_t num_nodes, const int32_t *indptr,
                            const int32_t *indices, const double *w,
                            int32_t num_sources, const int32_t *sources,
                            double *dist_out, int64_t *edges_relaxed) {
  dijkstra_fanout_impl(num_nodes, indptr, indices, w, num_sources, sources,
                       dist_out, edges_relaxed);
}

// Predecessor-tracking fan-out: pred_out is [num_sources, num_nodes]
// row-major, -1 = source/unreachable.
void pj_dijkstra_fanout_pred_f32(int32_t num_nodes, const int32_t *indptr,
                                 const int32_t *indices, const float *w,
                                 int32_t num_sources, const int32_t *sources,
                                 float *dist_out, int32_t *pred_out,
                                 int64_t *edges_relaxed) {
  dijkstra_fanout_impl(num_nodes, indptr, indices, w, num_sources, sources,
                       dist_out, edges_relaxed, pred_out);
}

void pj_dijkstra_fanout_pred_f64(int32_t num_nodes, const int32_t *indptr,
                                 const int32_t *indices, const double *w,
                                 int32_t num_sources, const int32_t *sources,
                                 double *dist_out, int32_t *pred_out,
                                 int64_t *edges_relaxed) {
  dijkstra_fanout_impl(num_nodes, indptr, indices, w, num_sources, sources,
                       dist_out, edges_relaxed, pred_out);
}

// Shortest-path-tree extraction after a converged Bellman-Ford: BFS over
// tight edges of the CSR graph (see extract_predecessors).
void pj_extract_predecessors_f32(int32_t num_nodes, const int32_t *indptr,
                                 const int32_t *indices, const float *w,
                                 const float *dist, int32_t source,
                                 int32_t *pred) {
  extract_predecessors(num_nodes, indptr, indices, w, dist, source, pred);
}

void pj_extract_predecessors_f64(int32_t num_nodes, const int32_t *indptr,
                                 const int32_t *indices, const double *w,
                                 const double *dist, int32_t source,
                                 int32_t *pred) {
  extract_predecessors(num_nodes, indptr, indices, w, dist, source, pred);
}

// Many-small-graphs batch Johnson APSP (BASELINE.json:11), parallel over
// graphs. Inputs are the stacked COO arrays [num_graphs, e_pad] with +inf
// edge padding; dist_out is [num_graphs, v_max, v_max]; num_nodes[g] is the
// true vertex count of graph g; neg_out[g] is set to 1 on a negative cycle.
// Returns total edges relaxed across the batch.
#define PJ_BATCH_JOHNSON(SUFFIX, T)                                          \
  int64_t pj_batch_johnson_##SUFFIX(                                         \
      int32_t num_graphs, int64_t e_pad, const int32_t *num_nodes,           \
      int32_t v_max, const int32_t *src, const int32_t *dst, const T *w,     \
      T *dist_out, int32_t *neg_out) {                                       \
    int64_t total = 0;                                                       \
    _Pragma("omp parallel for schedule(dynamic, 1) reduction(+ : total)")    \
    for (int32_t g = 0; g < num_graphs; ++g) {                               \
      int64_t relaxed = 0;                                                   \
      neg_out[g] = johnson_one_graph(                                        \
          num_nodes[g], e_pad, src + g * e_pad, dst + g * e_pad,             \
          w + g * e_pad,                                                     \
          v_max, dist_out + static_cast<int64_t>(g) * v_max * v_max,         \
          &relaxed);                                                         \
      total += relaxed;                                                      \
    }                                                                        \
    return total;                                                            \
  }

PJ_BATCH_JOHNSON(f32, float)
PJ_BATCH_JOHNSON(f64, double)
#undef PJ_BATCH_JOHNSON

}  // extern "C"
