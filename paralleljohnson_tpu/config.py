"""Solver configuration (SURVEY.md §5 "Config / flag system").

The attested reference surface is a ``backend=`` switch (BASELINE.json:5);
the rebuild widens it to a small dataclass mirrored by CLI flags.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SolverConfig:
    """Knobs for :class:`~paralleljohnson_tpu.solver.ParallelJohnsonSolver`.

    Attributes:
      backend: execution engine name — ``"jax"`` (TPU/XLA path), ``"numpy"``
        (scipy oracle-backed), ``"cpp"`` (native C++/OpenMP), as registered
        in :mod:`paralleljohnson_tpu.backends`.
      precision: ``"f32"`` or ``"f64"`` (f64 only meaningful off-TPU).
      source_batch_size: sources solved per device batch in the N-source
        phase; ``None`` picks a batch that fits VMEM/HBM heuristically.
      mesh_shape: ``None`` or ``(n,)``: n devices along a 1-D
        ``("sources",)`` mesh (fan-out rows sharded, CSR replicated).
        ``(n_s, n_e)``: a 2-D ``("sources", "edges")`` mesh — rows shard
        over n_s devices AND the edge list shards over n_e, for graphs
        whose edges exceed one chip's HBM while still fanning out wide.
        Consumed by :mod:`paralleljohnson_tpu.parallel`.
      max_iterations: cap on relaxation sweeps; ``None`` = |V| (the
        Bellman-Ford bound).
      dense_threshold: graphs with V <= threshold are ELIGIBLE for the
        dense min-plus path instead of the sparse CSR sweep; the graph
        must also actually be dense (see ``dense_min_density``).
        Precedence: a multi-device mesh routes the fan-out to the sharded
        sparse path regardless — the dense path is single-chip; set
        mesh_shape=(1,) to force it on a multi-device host.
      dense_min_density: minimum E/V^2 for the dense path (default 1/16:
        per sweep dense does B x V^2 work vs sparse B x E, and dense's
        regularity advantage measures ~an order of magnitude, so below
        V^2/16 edges the sparse path wins even on small graphs). 0 makes
        ``dense_threshold`` alone decide (tests).
      edge_pad_multiple: pad E to this multiple for stable jit shapes.
      use_pallas: ``"auto"`` (the measured winner — currently the XLA
        paths everywhere: the dense Pallas tile kernel measured slower
        on-chip, see ``ops/pallas_kernels.py``, and the VMEM-resident
        fan-out sweep, ``ops/pallas_sweep.py``, awaits on-chip numbers),
        ``True`` (force Pallas for the dense min-plus AND the
        single-device vertex-major fan-out: compiled on TPU,
        interpret-mode off-TPU — tests), or ``False``.
      fanout_layout: sparse fan-out data layout — ``"vertex_major"``
        (dist [V, B], dst-sorted edges, sorted segment reduction: no
        scatter on TPU), ``"source_major"`` (dist [B, V], flattened-id
        scatter-min), or ``"auto"`` (vertex_major — the measured winner,
        ~3x on the CPU mesh; see BASELINE.md "fan-out layout" rows).
        Applies to the sparse single-chip and sharded paths; the dense
        min-plus path has no layout choice.
      frontier: frontier-compacted Bellman-Ford (SSSP): relax only the
        out-edges of vertices improved last round instead of all E every
        sweep — the high-diameter (road/grid) mitigation of SURVEY.md §7.
        ``"auto"`` enables it for low-max-degree non-tiny graphs; True
        forces, False disables (always full sweeps).
      frontier_capacity: static frontier-id buffer size (rounds whose
        active set exceeds it fall back to one full sweep); ``None``
        sizes it from V (see ``JaxBackend._frontier_capacity``).
      gauss_seidel: blocked Gauss-Seidel SSSP over an RCM-relabeled,
        destination-block-bucketed edge layout — the high-diameter
        round-COUNT mitigation (outer rounds ~ path direction changes,
        not diameter; see ``ops.gauss_seidel``). ``"auto"`` enables it on
        TPU for the same low-max-degree graphs the frontier path targets
        (on CPU the frontier path measures faster; on TPU the frontier's
        per-round scatter+nonzero cost dominates). True forces (given the
        host graph is available). The layout is weight-independent, so
        the route survives Johnson reweighting; the fan-out composes
        with a 1-D sources mesh (batch sharded, block schedule per
        device) but NOT with an "edges" mesh axis (raises when forced).
        An explicit ``frontier=True`` beats gauss_seidel="auto".
        False disables.
      dia: gather-free DIA (diagonal/stencil) route for B=1 solves on
        graphs whose GIVEN labeling puts every edge on few index
        diagonals (lattices, banded meshes — ``ops.dia``). ``"auto"``
        prefers it on TPU whenever the labeling qualifies: it sidesteps
        the XLA row-gather floor that lower-bounds every gather-based
        sweep (bench_artifacts/gs_offchip_validation.md). An explicit
        ``frontier=True`` or ``gauss_seidel=True`` beats dia="auto".
      bucket: bucketed (delta-stepping-style) relaxation for B=1 solves
        on irregular high-diameter graphs — the road-family route when
        the labeling is NOT diagonal (``ops.bucket``): tentative
        distances are binned into width-``delta`` buckets, the lowest
        nonempty bucket is settled with light-edge inner steps before
        its heavy edges relax once, so each vertex settles ~once and
        the examined-candidate count collapses vs the GS re-relaxation
        (bench_artifacts/bucket_offchip_validation.md prices the full
        dimacs-scale solve under 1 s vs GS's 4.5-8 s). ``"auto"``
        prefers it on TPU for explicit-source solves on the low-degree
        family whenever DIA disqualifies; an explicitly forced
        frontier/gauss_seidel/dia route beats bucket="auto". True
        forces (including the virtual-source pass, which degrades to
        full sweeps via the overflow fallback); False disables.
      delta: bucket width of the ``bucket`` route; ``None`` = auto:
        the profile-tuned width for this (platform, shape bucket)
        when the store has measured alternatives (``observe.tuning``),
        else the mean |edge weight| x average-degree heuristic
        (``ops.bucket.auto_delta``). Any value > 0 is correct — the
        width only trades inner re-relaxation against bucket count.
      dia_max_offsets: max distinct (dst - src) diagonals the DIA
        layout accepts before disqualifying the graph.
      gs_block_size: vertices per Gauss-Seidel block (the inner-fixpoint
        unit; bigger blocks = fewer, larger device ops but more inner
        iterations per block). Default 8192: at full dimacs scale it
        halves the sequential device steps of vb=4096 (11,224 vs
        20,830) for +7% candidate work — dominant on both terms of the
        on-chip cost model (bench_artifacts/gs_offchip_validation.md);
        the staged on-chip vb sweep (scripts/tpu_gs_micro.py) settles
        the final value.
      gs_inner_cap: max inner iterations per block visit. Bounds EXTRA
        per-visit propagation, never correctness; lower caps cut
        candidate work (CPU evidence: cap=64 examines ~2.3x Jacobi's
        candidates at road scale) at the price of more outer rounds.
      fw: blocked min-plus Floyd-Warshall dense-APSP route (``ops.fw``,
        route tags ``fw`` single-tile / ``fw-tile`` blocked): R-Kleene
        tile schedule — diagonal-block Kleene closure, row/column panel
        updates, min-plus "matmul" trailing update — serving the
        squaring regime of the dense family (most rows wanted, 2B >= V)
        in O(V^3) tropical MACs instead of squaring's O(V^3 log V).
        ``"auto"``: engages when the graph is dense enough (the same
        ``dense_min_density`` gate as the dense path), V is within
        ``fw_threshold``, and the exact analytic MAC counters say FW
        beats squaring (both are host ints — the regime pick and its
        accounting share one source of truth). Single-chip like the
        dense path (a >1-device mesh routes to the sharded sweeps;
        ``fw=True`` on such a mesh fails loud). True forces; False
        disables. Handles negative edges natively where forced.
      fw_threshold: max V the FW route accepts (default 2^14 — a
        [V, V] f32 closure is 1 GB there; beyond it the partitioned
        condensed route is the dense-core escape hatch).
      fw_tile: FW tile edge, a multiple of 128. ``None`` (the default)
        = auto: the profile-tuned value for this (platform, shape
        bucket) when the store has measured alternatives
        (``observe.tuning``), else the hand-tuned 512 — the first
        128-multiple whose trailing-update arithmetic intensity, t/8
        flop/byte, clears the v4-class roofline ridge (``ops.fw``).
        Graphs smaller than the tile shrink it to their own 128-padded
        size instead of padding up. An explicit value always wins.
      partitioned: condense-solve-expand partitioned APSP route
        (``solver.partitioned``, route tag ``condensed+fw``): partition
        the vertices around seeded pivots (the ``serve.landmarks`` pivot
        draw), close each part's dense submatrix with blocked FW,
        condense boundary vertices + cross edges into a dense core,
        close the core with blocked FW on-chip, and expand back to full
        distances with one batched min-plus fan-out per partition —
        EXACT end to end (every shortest path decomposes into
        within-part runs joined at boundary vertices), so large sparse
        graphs get a dense MXU core instead of a pure gather-bound
        sweep. ``"auto"``: on TPU only, for full-APSP-scale source sets
        (2B >= V) on sparse graphs (below ``dense_min_density``) with
        1024 <= V <= ``fw_threshold``; True forces (any backend — the
        route's math is its own); False disables. Negative edges are
        handled natively (no Johnson phases); negative cycles are
        detected exactly (local and core closures jointly cover every
        cycle).
      partition_parts: partition count of the ``partitioned`` route;
        None = auto: profile-tuned per (platform, shape bucket) when
        the store has measured alternatives (``observe.tuning``),
        else ~sqrt(V)/8 clamped to [2, 32].
      dirty_window: dirty-window compacted relaxation (ISSUE 13, route
        tag ``vm-blocked+dw``; README "Dirty-window compaction"): the
        fan-out carries per-destination-block activity bitmaps in the
        while_loop carry, compacts the dirty-block index every round,
        and relaxes ONLY the dirty blocks' out-edge tiles — examined
        work tracks the measured collapsing frontier instead of
        rounds x E, with a full-sweep fallback on overflow rounds, and
        distances stay BITWISE-identical to the plain batched routes.
        Also gates the Gauss-Seidel outer rounds onto the exact
        block-to-block in-adjacency mask (route ``gs+dw``) and the
        partitioned route's sparse expansion onto reachable part pairs.
        ``"auto"`` engages ONLY from evidence: a configured profile
        store must hold a ``kind: "trajectory"`` record for this
        graph's shape bucket whose frontier collapse clears the
        ``observe.convergence.dw_decision`` thresholds (refined by the
        CostModel when it prices both routes) — no record, or a flat
        trajectory, stays on plain vm / vm-blocked. True forces; False
        disables everywhere.
      dw_block: vertices per dirty-window activity bit (block height).
        None = the measured default (``ops.relax.DW_BLOCK`` = 1):
        coarse blocks were measured to collect only 35-80% of the
        skippable work on the scrambled road grid (the active
        wavefront is a thin ring that crosses many coarse blocks — see
        the ``ops/relax.py`` dead-end note), while per-vertex bits
        approach the exact JFR bound.
      pred_extraction: post-fixpoint tight-edge predecessor extraction
        (``ops.pred``): ``--predecessors`` solves run the SAME auto route
        as plain solves (vm-blocked / gs / dia / bucket / dense /
        sharded) and append one vectorized extraction pass over the
        edges, instead of pinning the whole solve to the legacy
        source-major argmin sweep (iterations x B x E work vs the
        extraction's single O(E x B) pass). ``"auto"``: extraction, with
        an automatic fallback to the legacy sweep when the on-device
        tree check detects a zero-weight tight cycle the one-pass rule
        cannot resolve (rare; warns). True forces extraction (the cycle
        fallback becomes an error); False keeps the legacy argmin sweep
        (route tag ``pred-sweep``).
      edge_shard: shard the EDGE LIST across the mesh for single-source
        Bellman-Ford (dist replicated, one pmin all-reduce per sweep) —
        the scale-out axis when the edge list exceeds one chip's HBM,
        and the only way a multi-chip mesh helps a B=1 solve. ``"auto"``
        enables it whenever the mesh has >1 device and the frontier path
        is not active (frontier is work-optimal on low-degree graphs);
        True forces (given >1 device), False keeps single-chip sweeps.
      hopset: the certified (1+ε) approximate tier's dispatch switch
        (ISSUE 17, ``solver.approx`` route tag ``hopset+bf``; ROADMAP
        item 5). ``"auto"`` (the default): ``solve_with_budget``
        qualifies the hopset route exactly when the caller's
        ``error_budget`` is > 0 and the graph is negative-free — a zero
        budget ALWAYS solves exactly. True forces the hopset plan
        (budget still must be > 0 — forcing an approximation under a
        zero budget is a contract violation and fails loud); False
        disqualifies it everywhere.
      approx_epsilon: the hopset tier's target relative error ε (> 0).
        Drives the hop budget β = ``ops.hopset.auto_beta(V, ε)`` and is
        recorded with every certificate; the per-answer bound served is
        always the MEASURED interval, never this target.
      approx_beta: explicit hop budget for hopset construction and
        queries (>= 2); None = auto from (V, ε). More hops = tighter
        rows and later cap, at β sweeps of cost.
      error_budget: per-solve relative error budget (>= 0) for
        ``solver.approx.solve_with_budget``: the planner may pick
        ``hopset+bf`` only when its certified bound can fit the budget;
        0 (the default) pins exact. This is the serving tier's knob —
        plain ``solve()`` never consults it.
      checkpoint_dir: if set, per-source-batch distance rows are saved here
        and resumed after preemption (SURVEY.md §5 checkpoint/resume).
      pipeline_depth: max fan-out batches in flight in the double-buffered
        pipeline — batch k's D2H row download + checkpoint serialization
        run on a background stage while batch k+1's device compute
        proceeds, so the multi-GB transfers and fsyncs of RMAT-22-class
        solves leave the critical path. ``None`` (the default) = auto:
        the profile-tuned depth for this (platform, shape bucket) when
        the store has measured alternatives (``observe.tuning``), else
        the hand-tuned 2. Each extra slot carries one more
        computed-but-unmaterialized [B, V] block in device memory
        (``suggested_source_batch`` budgets the carry); on device OOM the
        window collapses to 1 BEFORE the batch is halved. 1 = the
        pre-pipeline strictly serial loop (bitwise-identical results
        either way — the pipeline changes scheduling, never arithmetic).
      compilation_cache_dir: persistent JAX compilation cache directory
        (``jax_compilation_cache_dir``), so re-runs — and especially the
        3x-retry TPU measurement passes — stop re-paying Mosaic/XLA
        compiles. None falls back to the PJ_COMPILE_CACHE env var; both
        unset leaves the cache off.
      validate: cross-check results against the scipy oracle (slow; tests).
      retry_attempts: max attempts per solve stage before the failure
        propagates (``utils.resilience.RetryPolicy``); 1 disables
        retries. OOM batch degradations do NOT consume these — each
        degraded size gets fresh attempts (the resource changed).
      retry_backoff_s: base backoff before the 2nd attempt of a stage
        (exponential x2 per further attempt, deterministic jitter).
      stage_deadline_s: per-attempt wall-clock cap, enforced by a
        watchdog thread that logs-and-abandons a hung device call (the
        wedged-tunnel mitigation, ROADMAP item 1); None = no watchdog.
      min_source_batch: floor of the OOM degradation schedule — the
        fan-out batch is halved on RESOURCE_EXHAUSTED down to this size,
        then the OOM propagates (``utils.resilience.OOMDegrader``).
      fault_plan: a ``utils.faults.FaultPlan`` (or None) injecting
        deterministic failures into solve stages — the harness tier-1
        CPU tests use to exercise every retry/degrade/resume path
        without a TPU. Production solves leave it None.
      planner: the priced dispatch registry's promotion switch
        (ISSUE 14, ``paralleljohnson_tpu.planner``). ``"auto"`` (the
        default): when a profile store is configured AND its CostModel
        prices both the ladder-priority incumbent and a cheaper
        qualified challenger (beyond the planner noise band), dispatch
        promotes the cheaper plan; with no store, or nothing priced,
        the declared plan priorities reproduce the pre-registry ladder
        exactly. ``False`` disables priced promotion entirely (pure
        declared priority). ``True`` behaves like "auto" (the flag
        exists so scripts can pin semantics against future default
        changes). Forced route flags (``fw=True``, ``dia=True``, ...)
        override the pricing either way — a forced plan is pinned
        first and its contract failures stay loud.
      profile_store: cost-observatory profile-store directory (ISSUE 7,
        ``paralleljohnson_tpu/observe``). When set (or via the
        ``PJ_PROFILE_DIR`` env var), the jax backend harvests XLA's
        compiled-cost analysis (FLOPs / bytes accessed /
        transcendentals + memory analysis) once per (route, platform,
        shape-bucket), the solver roofline-classifies every solve
        (HBM- / MXU- / host-IO-bound) and appends one record per solve
        to ``<dir>/profiles.jsonl`` — the calibration artifact
        ``CostModel.predict`` and the planned dispatch registry
        (ROADMAP item 7) consume. None (and no env var) disables
        capture entirely; roofline attribution of measured phases still
        runs (it is free). Capture pays one extra AOT lower+compile per
        key. CLI: ``--profile-store``.
      convergence: per-iteration convergence trajectory recording
        (ISSUE 9, ``paralleljohnson_tpu/observe/convergence``): the
        iterative kernel routes (sweep / sweep-sm / vm / vm-blocked /
        gs / dia / bucket — incl. the BF-potentials pass) carry
        on-device ``[cap, 3]``-shaped counters of per-iteration
        frontier size, relaxations applied, and residual mass through
        their while_loops — zero extra host syncs per iteration, one
        D2H after convergence — surfacing ``SolverStats.convergence``
        (iterations, frontier half-life, tail fraction, JFR-skippable
        estimate), per-stage ``trajectory`` flight events, heartbeat
        ``iter``/``frontier_size``/``eta_s``, and per-iteration
        profile-store records. ``"auto"``: enabled exactly when a
        consumer exists (telemetry configured or a profile store set);
        with neither, dispatch compiles the ORIGINAL uninstrumented
        kernels — identical jaxpr, asserted in tests. True forces
        recording (tests / ad-hoc introspection); False disables even
        with sinks. Distances are bitwise-identical either way — the
        counters ride the carry, never the arithmetic.
      telemetry: a ``utils.telemetry.Telemetry`` (or None, the default)
        — the flight-recorder subsystem: nested spans + events appended
        to a JSONL that survives a killed worker, a heartbeat JSON
        atomically rewritten every few seconds (stage/batch progress,
        host RSS, device HBM in-use), and a Chrome-trace export. Off by
        default and near-free when off (all call sites route through
        ``telemetry.NULL_TELEMETRY``). CLI: ``--trace-dir`` /
        ``--heartbeat-file`` / ``--heartbeat-interval``.
      metrics: an ``observe.live.MetricsRegistry`` (or None, the
        default) — the live SLO observatory (ISSUE 12): the batch loop
        streams per-batch wall-clock into a log-bucketed histogram and
        retry/OOM counts into sliding-window rate counters, and the
        registry's snapshotter atomically publishes the view every few
        seconds (what ``pjtpu top`` and fleet workers read). Near-free
        when None (all call sites route through
        ``observe.live.NULL_METRICS``).
    """

    backend: str = "jax"
    precision: str = "f32"
    source_batch_size: int | None = None
    mesh_shape: tuple[int, ...] | None = None
    max_iterations: int | None = None
    dense_threshold: int = 1024
    dense_min_density: float = 1.0 / 16.0
    edge_pad_multiple: int = 512
    use_pallas: bool | str = "auto"
    fanout_layout: str = "auto"
    frontier: bool | str = "auto"
    frontier_capacity: int | None = None
    dia: bool | str = "auto"
    dia_max_offsets: int = 16
    bucket: bool | str = "auto"
    delta: float | None = None
    gauss_seidel: bool | str = "auto"
    gs_block_size: int = 8192
    gs_inner_cap: int = 64
    fw: bool | str = "auto"
    fw_threshold: int = 1 << 14
    fw_tile: int | None = None
    partitioned: bool | str = "auto"
    partition_parts: int | None = None
    dirty_window: bool | str = "auto"
    dw_block: int | None = None
    pred_extraction: bool | str = "auto"
    edge_shard: bool | str = "auto"
    hopset: bool | str = "auto"
    approx_epsilon: float = 0.1
    approx_beta: int | None = None
    error_budget: float = 0.0
    checkpoint_dir: str | None = None
    pipeline_depth: int | None = None
    compilation_cache_dir: str | None = None
    validate: bool = False
    retry_attempts: int = 3
    retry_backoff_s: float = 0.05
    stage_deadline_s: float | None = None
    min_source_batch: int = 8
    fault_plan: object | None = None
    planner: bool | str = "auto"
    profile_store: str | None = None
    convergence: bool | str = "auto"
    telemetry: object | None = None
    metrics: object | None = None

    @property
    def np_dtype(self):
        return {"f32": np.float32, "f64": np.float64}[self.precision]

    def __post_init__(self) -> None:
        if self.precision not in ("f32", "f64"):
            raise ValueError(f"precision must be f32/f64, got {self.precision!r}")
        if self.use_pallas not in (True, False, "auto"):
            raise ValueError(
                f"use_pallas must be True/False/'auto', got {self.use_pallas!r}"
            )
        if self.fanout_layout not in ("auto", "source_major", "vertex_major"):
            raise ValueError(
                "fanout_layout must be auto/source_major/vertex_major, "
                f"got {self.fanout_layout!r}"
            )
        if self.frontier not in (True, False, "auto"):
            raise ValueError(
                f"frontier must be True/False/'auto', got {self.frontier!r}"
            )
        if self.gauss_seidel not in (True, False, "auto"):
            raise ValueError(
                "gauss_seidel must be True/False/'auto', "
                f"got {self.gauss_seidel!r}"
            )
        if self.dia not in (True, False, "auto"):
            raise ValueError(
                f"dia must be True/False/'auto', got {self.dia!r}"
            )
        if self.bucket not in (True, False, "auto"):
            raise ValueError(
                f"bucket must be True/False/'auto', got {self.bucket!r}"
            )
        if self.delta is not None and not self.delta > 0:
            raise ValueError(
                f"delta must be > 0 (or None = auto), got {self.delta!r}"
            )
        if self.fw not in (True, False, "auto"):
            raise ValueError(
                f"fw must be True/False/'auto', got {self.fw!r}"
            )
        if self.fw_threshold < 0:
            raise ValueError(
                f"fw_threshold must be >= 0, got {self.fw_threshold}"
            )
        if self.fw_tile is not None and (
            self.fw_tile < 128 or self.fw_tile % 128
        ):
            raise ValueError(
                "fw_tile must be a multiple of 128 (the TPU lane width), "
                f"got {self.fw_tile}"
            )
        if self.partitioned not in (True, False, "auto"):
            raise ValueError(
                f"partitioned must be True/False/'auto', got "
                f"{self.partitioned!r}"
            )
        if self.partition_parts is not None and self.partition_parts < 1:
            raise ValueError(
                "partition_parts must be >= 1 (or None = auto), got "
                f"{self.partition_parts}"
            )
        # The forced kernel routes are mutually exclusive; forcing two
        # at once used to resolve silently by dispatch order (ADVICE
        # round 5) — reject it here so "True forces" can never lie.
        # fw joins the list: a forced dia/gs fan-out and a forced FW
        # closure claim the same dispatch slot.
        forced = [
            name
            for name in ("frontier", "gauss_seidel", "dia", "bucket", "fw")
            if getattr(self, name) is True
        ]
        if len(forced) > 1:
            raise ValueError(
                "mutually-exclusive route flags forced together: "
                + " and ".join(f"{n}=True" for n in forced)
                + "; force at most one (the others dispatch by 'auto')"
            )
        if self.dia_max_offsets < 1:
            raise ValueError(
                f"dia_max_offsets must be >= 1, got {self.dia_max_offsets}"
            )
        if self.gs_block_size < 1:
            raise ValueError(
                f"gs_block_size must be >= 1, got {self.gs_block_size}"
            )
        if self.gs_inner_cap < 1:
            raise ValueError(
                f"gs_inner_cap must be >= 1, got {self.gs_inner_cap}"
            )
        if self.dirty_window not in (True, False, "auto"):
            raise ValueError(
                "dirty_window must be True/False/'auto', "
                f"got {self.dirty_window!r}"
            )
        if self.dw_block is not None and self.dw_block < 1:
            raise ValueError(
                f"dw_block must be >= 1 (or None = auto), got {self.dw_block}"
            )
        if self.pred_extraction not in (True, False, "auto"):
            raise ValueError(
                "pred_extraction must be True/False/'auto', "
                f"got {self.pred_extraction!r}"
            )
        if self.edge_shard not in (True, False, "auto"):
            raise ValueError(
                f"edge_shard must be True/False/'auto', got {self.edge_shard!r}"
            )
        if self.hopset not in (True, False, "auto"):
            raise ValueError(
                f"hopset must be True/False/'auto', got {self.hopset!r}"
            )
        if not self.approx_epsilon > 0:
            raise ValueError(
                f"approx_epsilon must be > 0, got {self.approx_epsilon!r}"
            )
        if self.approx_beta is not None and self.approx_beta < 2:
            raise ValueError(
                "approx_beta must be >= 2 (or None = auto), got "
                f"{self.approx_beta!r}"
            )
        if not self.error_budget >= 0:
            raise ValueError(
                f"error_budget must be >= 0, got {self.error_budget!r}"
            )
        if self.retry_attempts < 1:
            raise ValueError(
                f"retry_attempts must be >= 1, got {self.retry_attempts}"
            )
        if self.retry_backoff_s < 0:
            raise ValueError(
                f"retry_backoff_s must be >= 0, got {self.retry_backoff_s}"
            )
        if self.stage_deadline_s is not None and not self.stage_deadline_s > 0:
            raise ValueError(
                "stage_deadline_s must be > 0 (or None), "
                f"got {self.stage_deadline_s}"
            )
        if self.min_source_batch < 1:
            raise ValueError(
                f"min_source_batch must be >= 1, got {self.min_source_batch}"
            )
        if self.pipeline_depth is not None and self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}"
            )
        if self.planner not in (True, False, "auto"):
            raise ValueError(
                f"planner must be True/False/'auto', got {self.planner!r}"
            )
        if self.convergence not in (True, False, "auto"):
            raise ValueError(
                f"convergence must be True/False/'auto', "
                f"got {self.convergence!r}"
            )

    def retry_policy(self):
        """The :class:`~paralleljohnson_tpu.utils.resilience.RetryPolicy`
        these knobs describe (one construction point for solver/backend)."""
        from paralleljohnson_tpu.utils.resilience import RetryPolicy

        return RetryPolicy(
            max_attempts=self.retry_attempts,
            backoff_s=self.retry_backoff_s,
            deadline_s=self.stage_deadline_s,
        )
