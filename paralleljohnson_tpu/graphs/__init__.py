"""Graph representation, loaders, and generators (SURVEY.md §2 #5, #7-#11)."""

from paralleljohnson_tpu.graphs.csr import (
    CSRGraph,
    EdgeUpdateReport,
    PAD_WEIGHT,
    stack_graphs,
)
from paralleljohnson_tpu.graphs.generators import (
    erdos_renyi,
    grid2d,
    permute_labels,
    random_dag,
    random_graph_batch,
    rmat,
)
from paralleljohnson_tpu.graphs.loaders import (
    GraphFormatError,
    load_dimacs,
    load_snap,
    save_dimacs,
)
from paralleljohnson_tpu.graphs.registry import (
    available_loaders,
    load_graph,
    register_loader,
)

__all__ = [
    "CSRGraph",
    "EdgeUpdateReport",
    "GraphFormatError",
    "PAD_WEIGHT",
    "available_loaders",
    "erdos_renyi",
    "grid2d",
    "load_dimacs",
    "load_graph",
    "load_snap",
    "permute_labels",
    "random_dag",
    "random_graph_batch",
    "register_loader",
    "rmat",
    "save_dimacs",
    "stack_graphs",
]
