"""The ``GraphLoader`` plugin boundary (SURVEY.md §2 #7, BASELINE.json:5).

A loader is any callable ``spec -> CSRGraph``. Loaders register under a
scheme name; :func:`load_graph` dispatches on ``scheme:rest`` specs or on
file extension. Built-in schemes:

  - ``dimacs:<path>`` / ``*.gr`` / ``*.gr.gz``   — DIMACS shortest-path
  - ``snap:<path>``   / ``*.txt`` / ``*.edges``  — SNAP edge list
  - ``er:n=1000,p=0.01[,neg=0.2][,seed=0]``      — Erdős–Rényi
  - ``dag:n=1000,p=0.01[,neg=0.3][,seed=0]``     — acyclic ER (safe negatives)
  - ``rmat:scale=20[,ef=16][,seed=0]``           — R-MAT
  - ``grid:rows=512,cols=512[,neg=0.2][,seed=0]`` — road-like 2-D lattice
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable

from paralleljohnson_tpu.graphs.csr import CSRGraph
from paralleljohnson_tpu.graphs import generators, loaders

GraphLoaderFn = Callable[[str], CSRGraph]

_LOADERS: dict[str, GraphLoaderFn] = {}
_EXTENSIONS: dict[str, str] = {
    ".gr": "dimacs",
    ".edges": "snap",
    ".txt": "snap",
}


def register_loader(scheme: str, fn: GraphLoaderFn) -> None:
    """Register a loader plugin under ``scheme`` (overwrites existing)."""
    _LOADERS[scheme] = fn


def available_loaders() -> list[str]:
    return sorted(_LOADERS)


def _parse_kwargs(rest: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for item in filter(None, rest.split(",")):
        if "=" not in item:
            raise ValueError(f"bad spec item {item!r} (want key=value)")
        k, v = item.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def _er_loader(rest: str) -> CSRGraph:
    kw = _parse_kwargs(rest)
    return generators.erdos_renyi(
        int(kw["n"]), float(kw["p"]),
        negative_fraction=float(kw.get("neg", 0.0)),
        seed=int(kw.get("seed", 0)),
    )


def _dag_loader(rest: str) -> CSRGraph:
    kw = _parse_kwargs(rest)
    return generators.random_dag(
        int(kw["n"]), float(kw["p"]),
        negative_fraction=float(kw.get("neg", 0.3)),
        seed=int(kw.get("seed", 0)),
    )


def _rmat_loader(rest: str) -> CSRGraph:
    kw = _parse_kwargs(rest)
    return generators.rmat(
        int(kw["scale"]), int(kw.get("ef", 16)), seed=int(kw.get("seed", 0)),
    )


def _grid_loader(rest: str) -> CSRGraph:
    kw = _parse_kwargs(rest)
    return generators.grid2d(
        int(kw["rows"]), int(kw["cols"]),
        negative_fraction=float(kw.get("neg", 0.0)),
        seed=int(kw.get("seed", 0)),
    )


register_loader("dimacs", loaders.load_dimacs)
register_loader("snap", loaders.load_snap)
register_loader("er", _er_loader)
register_loader("dag", _dag_loader)
register_loader("rmat", _rmat_loader)
register_loader("grid", _grid_loader)


def load_graph(spec: str | Path) -> CSRGraph:
    """Load a graph from a ``scheme:rest`` spec or a path (by extension)."""
    spec = str(spec)
    if ":" in spec:
        scheme, rest = spec.split(":", 1)
        if scheme in _LOADERS:
            return _LOADERS[scheme](rest)
    path = Path(spec)
    suffix = path.suffix if path.suffix != ".gz" else Path(path.stem).suffix
    if suffix in _EXTENSIONS:
        return _LOADERS[_EXTENSIONS[suffix]](spec)
    raise ValueError(
        f"cannot infer loader for {spec!r}; known schemes: {available_loaders()}"
    )
