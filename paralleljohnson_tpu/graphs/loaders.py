"""File-format loaders: DIMACS shortest-path (.gr) and SNAP edge lists.

Rebuild of the reference's attested loaders (SURVEY.md §2 #8-#9; attested via
the DIMACS-NY and SNAP ego-Facebook benchmark configs, BASELINE.json:8-9).
Both return :class:`CSRGraph`; parsing is host-side numpy.
"""

from __future__ import annotations

import gzip
import io
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.graphs.csr import CSRGraph


class GraphFormatError(ValueError):
    """Malformed graph-file input (truncated record, out-of-range vertex
    id, non-numeric weight, missing problem line). Always names the file
    and 1-based line number, so a bad byte in a multi-GB road-graph file
    is diagnosable from the message — instead of an index crash deep in
    the CSR build minutes later."""


def _format_error(path, lineno, what, line=None) -> GraphFormatError:
    loc = f"{path}:{lineno}" if lineno else f"{path}"
    detail = f" in {line!r}" if line is not None else ""
    return GraphFormatError(f"{loc}: {what}{detail}")


def _open_text(path: str | Path):
    path = Path(path)
    if path.suffix == ".gz":
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    return open(path, "r", encoding="utf-8")


def load_dimacs(path: str | Path, *, dtype=np.float32) -> CSRGraph:
    """Parse the 9th DIMACS Implementation Challenge ``.gr`` format.

    Grammar (one record per line):
      - ``c <comment>``        — ignored
      - ``p sp <V> <E>``       — problem line, exactly one
      - ``a <u> <v> <w>``      — directed arc u->v, 1-indexed, w may be
                                 negative (the DIMACS-NY negative-weight
                                 config is attested, BASELINE.json:8)

    Malformed input (truncated arc line, out-of-range vertex id,
    non-numeric weight, arcs before/without the problem line) raises
    :class:`GraphFormatError` naming file + line number.
    """
    num_nodes = None
    srcs: list[int] = []
    dsts: list[int] = []
    wts: list[float] = []
    with _open_text(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("c"):
                continue
            parts = line.split()
            if parts[0] == "p":
                if len(parts) != 4 or parts[1] != "sp":
                    raise _format_error(path, lineno, "bad problem line", line)
                try:
                    num_nodes = int(parts[2])
                except ValueError:
                    raise _format_error(
                        path, lineno, "non-numeric node count", line
                    ) from None
                if num_nodes < 0:
                    raise _format_error(
                        path, lineno, "negative node count", line
                    )
            elif parts[0] == "a":
                if len(parts) != 4:
                    raise _format_error(
                        path, lineno, "truncated arc line", line
                    )
                if num_nodes is None:
                    raise _format_error(
                        path, lineno, "arc before 'p sp' problem line", line
                    )
                try:
                    u = int(parts[1]) - 1
                    v = int(parts[2]) - 1
                except ValueError:
                    raise _format_error(
                        path, lineno, "non-numeric vertex id", line
                    ) from None
                try:
                    w = float(parts[3])
                except ValueError:
                    raise _format_error(
                        path, lineno, "non-numeric weight", line
                    ) from None
                if not (0 <= u < num_nodes and 0 <= v < num_nodes):
                    raise _format_error(
                        path, lineno,
                        f"vertex id out of range 1..{num_nodes}", line,
                    )
                srcs.append(u)
                dsts.append(v)
                wts.append(w)
            else:
                raise _format_error(
                    path, lineno, f"unknown record {parts[0]!r}", line
                )
    if num_nodes is None:
        raise _format_error(path, None, "missing 'p sp' problem line")
    return CSRGraph.from_edges(srcs, dsts, wts, num_nodes, dtype=dtype)


def load_snap(
    path: str | Path,
    *,
    directed: bool = False,
    default_weight: float = 1.0,
    dtype=np.float32,
) -> CSRGraph:
    """Parse a SNAP plain edge list (``# comment`` lines, then ``u v [w]``).

    SNAP datasets (e.g. ego-Facebook, BASELINE.json:9) are undirected and
    unweighted by default: each line yields both arcs with weight
    ``default_weight`` unless a third column supplies one. Vertex ids are
    remapped to a dense [0, V) in sorted order; the mapping is stored on the
    returned graph as ``node_ids`` (original id of each dense vertex).
    """
    srcs: list[int] = []
    dsts: list[int] = []
    wts: list[float] = []
    with _open_text(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("%"):
                continue
            parts = line.split()
            if len(parts) < 2:
                raise _format_error(path, lineno, "truncated edge line", line)
            try:
                u = int(parts[0])
                v = int(parts[1])
            except ValueError:
                raise _format_error(
                    path, lineno, "non-numeric vertex id", line
                ) from None
            if u < 0 or v < 0:
                raise _format_error(
                    path, lineno, "negative vertex id", line
                )
            try:
                w = float(parts[2]) if len(parts) > 2 else default_weight
            except ValueError:
                raise _format_error(
                    path, lineno, "non-numeric weight", line
                ) from None
            srcs.append(u)
            dsts.append(v)
            wts.append(w)
    src = np.asarray(srcs, np.int64)
    dst = np.asarray(dsts, np.int64)
    w = np.asarray(wts, dtype)
    node_ids = np.unique(np.concatenate([src, dst])) if len(src) else np.array([], np.int64)
    dense = {int(v): i for i, v in enumerate(node_ids)}
    src = np.fromiter((dense[int(v)] for v in src), np.int64, len(src))
    dst = np.fromiter((dense[int(v)] for v in dst), np.int64, len(dst))
    if not directed:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        w = np.concatenate([w, w])
    g = CSRGraph.from_edges(src, dst, w, len(node_ids), dtype=dtype)
    g.__dict__["node_ids"] = node_ids
    return g


def save_dimacs(graph: CSRGraph, path: str | Path, comment: str = "") -> None:
    """Write a graph back out as DIMACS ``.gr`` (round-trip/test helper)."""
    with open(path, "w", encoding="utf-8") as fh:
        if comment:
            fh.write(f"c {comment}\n")
        fh.write(f"p sp {graph.num_nodes} {graph.num_edges}\n")
        for u, v, w in zip(graph.src, graph.indices, graph.weights):
            w = int(w) if float(w).is_integer() else float(w)
            fh.write(f"a {u + 1} {v + 1} {w}\n")
