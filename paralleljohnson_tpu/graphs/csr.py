"""CSR graph representation — the buffer format every backend consumes.

Rebuild of the reference's attested CSR edge list (SURVEY.md §2 #5,
BASELINE.json:5 "a vmapped edge-relaxation scan over a CSR edge list").
Host-side arrays are numpy; backends move them to device memory at upload.

Layout:
  - ``indptr``  : int32[V+1]  — row pointers (out-edges of vertex u are
                  ``indices[indptr[u]:indptr[u+1]]``)
  - ``indices`` : int32[E]    — destination vertex of each edge
  - ``weights`` : f32/f64[E]  — edge weights (negative allowed)
  - ``src``     : int32[E]    — cached COO source column (derived from
                  indptr); the relaxation sweep is a gather on ``src`` and a
                  scatter-min on ``indices``, so both columns are kept hot.

Padding convention: padded edges are ``(src=0, dst=0, w=+inf)`` self-loops —
``dist[0] + inf == inf`` never wins a min, so padded edges are relaxation
no-ops with no masking needed inside kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

PAD_WEIGHT = np.inf


@dataclasses.dataclass(frozen=True)
class EdgeUpdateReport:
    """What :meth:`CSRGraph.apply_edge_updates` actually changed.

    ``changed_edges`` lists every EFFECTIVE change as ``(u, v, old_w,
    new_w)`` with ``None`` for "edge absent" on the respective side —
    no-op updates (removing a missing edge, re-setting the current
    weight) are counted in ``unchanged`` and never listed. Digests are
    the ``utils.checkpoint.graph_digest`` content hashes before/after:
    a no-op batch reports ``new_digest == old_digest`` (the graph
    object itself is returned unchanged), so digest equality IS the
    "did anything happen" test the incremental subsystem keys on.
    """

    added: int
    removed: int
    reweighted: int
    unchanged: int
    changed_edges: tuple
    old_digest: str
    new_digest: str

    @property
    def num_changed(self) -> int:
        return len(self.changed_edges)

    def as_dict(self) -> dict:
        return {
            "added": self.added,
            "removed": self.removed,
            "reweighted": self.reweighted,
            "unchanged": self.unchanged,
            "num_changed": self.num_changed,
            "old_digest": self.old_digest,
            "new_digest": self.new_digest,
        }


@dataclasses.dataclass(frozen=True)
class CSRGraph:
    """An immutable directed weighted graph in CSR form."""

    indptr: np.ndarray   # int32[V+1]
    indices: np.ndarray  # int32[E]
    weights: np.ndarray  # float32/float64[E]

    def __post_init__(self) -> None:
        indptr = np.ascontiguousarray(self.indptr, dtype=np.int32)
        indices = np.ascontiguousarray(self.indices, dtype=np.int32)
        weights = np.ascontiguousarray(self.weights)
        if weights.dtype not in (np.float32, np.float64):
            weights = weights.astype(np.float32)
        object.__setattr__(self, "indptr", indptr)
        object.__setattr__(self, "indices", indices)
        object.__setattr__(self, "weights", weights)
        if indptr.ndim != 1 or indices.ndim != 1 or weights.ndim != 1:
            raise ValueError("CSR arrays must be 1-D")
        if len(indices) != len(weights):
            raise ValueError(
                f"indices ({len(indices)}) and weights ({len(weights)}) disagree"
            )
        # indptr[-1] may be < len(indices): the tail is edge padding
        # (no-op edges that belong to no CSR row — see pad_edges).
        if len(indptr) == 0 or indptr[0] != 0 or indptr[-1] > len(indices):
            raise ValueError("indptr must start at 0 and end at <= num_edges")
        if np.any(np.diff(indptr) < 0):
            raise ValueError("indptr must be non-decreasing")
        if len(indices) and (indices.min() < 0 or indices.max() >= self.num_nodes):
            raise ValueError("edge destination out of range")

    # -- basic properties ---------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return len(self.indptr) - 1

    @property
    def num_edges(self) -> int:
        return len(self.indices)

    @property
    def dtype(self) -> np.dtype:
        return self.weights.dtype

    @property
    def src(self) -> np.ndarray:
        """COO source column, cached after first use."""
        cached = self.__dict__.get("_src")
        if cached is None:
            cached = np.repeat(
                np.arange(self.num_nodes, dtype=np.int32), np.diff(self.indptr)
            )
            pad = self.num_edges - len(cached)  # edge-padding tail -> vertex 0
            if pad:
                cached = np.concatenate([cached, np.zeros(pad, np.int32)])
            self.__dict__["_src"] = cached
        return cached

    @property
    def dst(self) -> np.ndarray:
        """Alias for ``indices`` to pair with :attr:`src`."""
        return self.indices

    @property
    def has_negative_weights(self) -> bool:
        return bool(self.num_edges) and bool((self.weights < 0).any())

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_edges(
        src: Sequence[int] | np.ndarray,
        dst: Sequence[int] | np.ndarray,
        weights: Sequence[float] | np.ndarray,
        num_nodes: int | None = None,
        *,
        dedupe: bool = True,
        dtype: np.dtype | type = np.float32,
    ) -> "CSRGraph":
        """Build CSR from a COO edge list.

        Canonicalizes: sorts by (src, dst); with ``dedupe`` keeps the minimum
        weight among parallel edges (the shortest-path-relevant one).
        """
        src = np.asarray(src, dtype=np.int64)
        dst = np.asarray(dst, dtype=np.int64)
        weights = np.asarray(weights, dtype=dtype)
        if not (len(src) == len(dst) == len(weights)):
            raise ValueError("src/dst/weights length mismatch")
        if num_nodes is None:
            num_nodes = int(max(src.max(), dst.max())) + 1 if len(src) else 0
        if len(src) and (src.min() < 0 or dst.min() < 0):
            raise ValueError("negative vertex id")
        if len(src) and (src.max() >= num_nodes or dst.max() >= num_nodes):
            raise ValueError("vertex id out of range")

        if len(src):
            # Sort by (src, dst, weight) so dedupe-keep-first keeps min weight.
            order = np.lexsort((weights, dst, src))
            src, dst, weights = src[order], dst[order], weights[order]
            if dedupe:
                keep = np.ones(len(src), dtype=bool)
                keep[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
                src, dst, weights = src[keep], dst[keep], weights[keep]

        indptr = np.zeros(num_nodes + 1, dtype=np.int64)
        np.add.at(indptr, src + 1, 1)
        np.cumsum(indptr, out=indptr)
        return CSRGraph(
            indptr=indptr.astype(np.int32),
            indices=dst.astype(np.int32),
            weights=weights,
        )

    @staticmethod
    def from_scipy(mat) -> "CSRGraph":
        """From a scipy sparse matrix (any format); explicit zeros are kept."""
        csr = mat.tocsr()
        return CSRGraph(
            indptr=csr.indptr.astype(np.int32),
            indices=csr.indices.astype(np.int32),
            weights=np.asarray(csr.data),
        )

    # -- conversions --------------------------------------------------------

    def to_scipy(self):
        """To ``scipy.sparse.csr_matrix`` for oracle comparisons.

        Zero-weight edges stay explicitly stored; scipy's csgraph routines
        treat explicitly-stored sparse zeros as true zero-weight edges.
        """
        import scipy.sparse as sp

        return sp.csr_matrix(
            (self.weights, self.indices, self.indptr),
            shape=(self.num_nodes, self.num_nodes),
        )

    def to_dense(
        self, fill: float = np.inf, *, pad_to: int | None = None
    ) -> np.ndarray:
        """Dense adjacency with ``fill`` for absent edges and 0 diagonal kept
        only if a self-loop exists (absent self-edges stay ``fill``).

        ``pad_to``: pad V up to a multiple of ``pad_to`` (the FW tile
        bucketing — one static shape bucket per tile multiple instead of
        a recompile per odd V): padded rows/columns are ``fill`` except
        the padded diagonal entries, which are 0 (a pad vertex is an
        isolated no-op at distance 0 from itself, so min-plus kernels
        need no masks); every real entry — including the real diagonal —
        is preserved exactly, so ``out[:V, :V]`` round-trips to the
        unpadded matrix. Only real edges are written: a ``pad_edges``
        tail (+inf no-op COO slots at (0, 0)) must not clobber a real
        (0, 0) edge."""
        v = self.num_nodes
        vp = v if not pad_to else pad_to * max(1, -(-v // pad_to))
        out = np.full((vp, vp), fill, dtype=self.dtype)
        e = self.num_real_edges
        out[self.src[:e], self.indices[:e]] = self.weights[:e]
        if vp > v:
            pad_idx = np.arange(v, vp)
            out[pad_idx, pad_idx] = 0.0
        return out

    def with_weights(self, weights: np.ndarray) -> "CSRGraph":
        """Same structure, new weights (used for reweighting)."""
        return CSRGraph(indptr=self.indptr, indices=self.indices, weights=weights)

    def astype(self, dtype) -> "CSRGraph":
        return self.with_weights(self.weights.astype(dtype))

    def reverse(self) -> "CSRGraph":
        """Edge-reversed graph on the same vertex set (d_rev(u, v) =
        d(v, u)) — what a landmark index solves to get distances TO each
        pivot (``serve.landmarks``). Padding no-op edges are dropped:
        the reverse is a fresh canonical CSR."""
        e = self.num_real_edges
        return CSRGraph.from_edges(
            self.indices[:e], self.src[:e], self.weights[:e],
            self.num_nodes, dtype=self.dtype,
        )

    def apply_edge_updates(
        self, updates
    ) -> "tuple[CSRGraph, EdgeUpdateReport]":
        """Apply a batch of edge updates, returning ``(new_graph,
        report)`` — the standalone entry of the incremental subsystem
        (``paralleljohnson_tpu.incremental``), usable on its own.

        ``updates``: iterable of ``(u, v, w)`` triples. A finite ``w``
        sets (inserts or reweights) the directed edge ``u -> v``;
        ``w`` of ``None`` or ``+inf`` removes it. The last update to a
        given ``(u, v)`` within the batch wins. Weights are cast to the
        graph's dtype BEFORE comparison, so an update that rounds to
        the stored weight is honestly a no-op. Vertex ids outside
        ``[0, V)`` (the vertex set is fixed), NaN, and ``-inf`` weights
        raise ``ValueError``.

        The new graph is rebuilt canonically through :meth:`from_edges`
        (padding no-op edges dropped, parallel edges impossible by
        construction), and the report carries the before/after content
        digests — identical digests mean the batch was a no-op and
        ``new_graph is self``. Host-side cost is O(E log E + k log E),
        fully vectorized over the edge arrays — a k-edge update batch
        against an RMAT-22-scale graph stays seconds, not a Python loop
        over 67M edges.
        """
        from paralleljohnson_tpu.utils.checkpoint import graph_digest

        v = self.num_nodes
        e = self.num_real_edges
        wtype = np.dtype(self.dtype).type

        # Current edge set as sorted flat (u*V + v) keys; parallel edges
        # in a non-canonical CSR resolve to the min, matching what
        # from_edges(dedupe=True) would have kept.
        keys = self.src[:e].astype(np.int64) * max(v, 1) + self.indices[:e]
        uniq, inv = np.unique(keys, return_inverse=True)
        cur_w = np.full(uniq.size, np.inf, np.float64)
        np.minimum.at(cur_w, inv, self.weights[:e].astype(np.float64))

        final: dict[int, float | None] = {}  # flat key -> new w / remove
        for item in updates:
            try:
                u, d, w = item
            except (TypeError, ValueError):
                raise ValueError(
                    f"edge update must be a (u, v, w) triple, got {item!r}"
                ) from None
            u, d = int(u), int(d)
            if not (0 <= u < v and 0 <= d < v):
                raise ValueError(
                    f"edge update ({u}, {d}) out of vertex range [0, {v})"
                )
            if w is None or (isinstance(w, float) and np.isposinf(w)):
                final[u * v + d] = None
            else:
                w = float(wtype(w))
                if np.isnan(w) or np.isneginf(w):
                    raise ValueError(
                        f"edge update ({u}, {d}) has invalid weight {w!r}"
                    )
                final[u * v + d] = w

        old_digest = graph_digest(self)
        added = removed = reweighted = unchanged = 0
        changed: list[tuple[int, int, float | None, float | None]] = []
        keep = np.ones(uniq.size, bool)
        new_w = cur_w.copy()
        extra_keys: list[int] = []
        extra_w: list[float] = []
        for key, w_new in sorted(final.items()):
            idx = int(np.searchsorted(uniq, key))
            present = idx < uniq.size and uniq[idx] == key
            w_old = float(cur_w[idx]) if present else None
            u, d = divmod(key, v)
            if w_new is None:
                if not present:
                    unchanged += 1
                else:
                    removed += 1
                    changed.append((u, d, w_old, None))
                    keep[idx] = False
            elif not present:
                added += 1
                changed.append((u, d, None, w_new))
                extra_keys.append(key)
                extra_w.append(w_new)
            elif w_old == w_new:
                unchanged += 1
            else:
                reweighted += 1
                changed.append((u, d, w_old, w_new))
                new_w[idx] = w_new

        if not changed:
            return self, EdgeUpdateReport(
                added=0, removed=0, reweighted=0, unchanged=unchanged,
                changed_edges=(), old_digest=old_digest,
                new_digest=old_digest,
            )
        all_keys = np.concatenate(
            [uniq[keep], np.asarray(extra_keys, np.int64)]
        )
        all_w = np.concatenate(
            [new_w[keep], np.asarray(extra_w, np.float64)]
        ).astype(self.dtype)
        g2 = CSRGraph.from_edges(
            all_keys // max(v, 1), all_keys % max(v, 1), all_w, v,
            dtype=self.dtype,
        )
        return g2, EdgeUpdateReport(
            added=added, removed=removed, reweighted=reweighted,
            unchanged=unchanged, changed_edges=tuple(changed),
            old_digest=old_digest, new_digest=graph_digest(g2),
        )

    # -- padding ------------------------------------------------------------

    def pad_edges(self, multiple: int = 128) -> "CSRGraph":
        """Pad the edge arrays to a multiple of ``multiple`` with no-op edges.

        Padded edges are (0 -> 0, +inf): they never change a distance, so
        kernels need no masks. ``indptr`` is NOT updated — padded edges
        belong to no CSR row; they only exist in the COO view. Kernels that
        operate on the COO columns (src/dst/weights) see them; row-wise CSR
        consumers use ``indptr`` and never touch them.
        """
        e = self.num_edges
        target = ((e + multiple - 1) // multiple) * multiple if e else multiple
        if target == e:
            return self
        pad = target - e
        return CSRGraph(
            indptr=self.indptr,
            indices=np.concatenate([self.indices, np.zeros(pad, np.int32)]),
            weights=np.concatenate(
                [self.weights, np.full(pad, PAD_WEIGHT, self.dtype)]
            ),
        )

    @property
    def num_real_edges(self) -> int:
        """Edge count before padding (== num_edges if unpadded); the CSR row
        structure only ever covers real edges, so this is ``indptr[-1]``."""
        return int(self.indptr[-1])

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"CSRGraph(V={self.num_nodes}, E={self.num_edges}, "
            f"dtype={self.dtype}, neg={self.has_negative_weights})"
        )


def stack_graphs(
    graphs: Sequence[CSRGraph],
    *,
    num_nodes: int | None = None,
    num_edges: int | None = None,
) -> dict[str, np.ndarray]:
    """Pad a batch of graphs to uniform (V, E) and stack the COO columns.

    Returns a dict of batched arrays for the vmapped solver path
    (SURVEY.md §3.4): ``src``/``dst`` int32[B, E_max], ``weights`` [B, E_max],
    ``num_nodes`` int32[B] (true sizes), with padding edges (0, 0, +inf).
    Vertices are NOT remapped; each graph keeps ids in [0, V_i). Distance
    rows for padded vertices of smaller graphs come out +inf (unreachable),
    d(v,v)=0 excepted — callers slice to the true V_i.
    """
    if not graphs:
        raise ValueError("empty batch")
    v_max = num_nodes or max(g.num_nodes for g in graphs)
    e_max = num_edges or max(g.num_edges for g in graphs)
    if any(g.num_nodes > v_max or g.num_edges > e_max for g in graphs):
        raise ValueError("explicit num_nodes/num_edges smaller than a graph")
    b = len(graphs)
    dtype = np.result_type(*[g.dtype for g in graphs])
    src = np.zeros((b, e_max), np.int32)
    dst = np.zeros((b, e_max), np.int32)
    wts = np.full((b, e_max), PAD_WEIGHT, dtype)
    sizes = np.zeros(b, np.int32)
    for i, g in enumerate(graphs):
        e = g.num_edges
        src[i, :e] = g.src
        dst[i, :e] = g.indices
        wts[i, :e] = g.weights
        sizes[i] = g.num_nodes
    return {"src": src, "dst": dst, "weights": wts, "num_nodes": sizes,
            "v_max": v_max}
