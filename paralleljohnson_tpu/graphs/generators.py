"""Synthetic graph generators: Erdős–Rényi G(n,p) and R-MAT.

Rebuild of the reference's attested generators (SURVEY.md §2 #10-#11; ER
1k/p=0.01 and RMAT-20/22 configs, BASELINE.json:7,10). Fully vectorized
numpy; R-MAT uses per-bit quadrant sampling so scale-22 (4.2M vertices,
~67M edges at edge_factor=16) generates in seconds.
"""

from __future__ import annotations

import numpy as np

from paralleljohnson_tpu.graphs.csr import CSRGraph


def erdos_renyi(
    num_nodes: int,
    p: float,
    *,
    weight_range: tuple[float, float] = (1.0, 10.0),
    negative_fraction: float = 0.0,
    seed: int = 0,
    dtype=np.float32,
) -> CSRGraph:
    """Directed G(n, p) with uniform weights.

    ``negative_fraction`` of edges get their weight negated (uniformly at
    random) — used to exercise the Bellman-Ford path. Note negated weights
    can create negative cycles; tests that need cycle-free graphs use
    :func:`random_dag` or keep the fraction at 0.
    """
    rng = np.random.default_rng(seed)
    # Sample edge count then distinct pairs — O(E) memory, not O(V^2).
    max_pairs = num_nodes * (num_nodes - 1)
    num_edges = rng.binomial(max_pairs, p) if max_pairs else 0
    # Sample linear indices over the V*(V-1) off-diagonal slots without
    # replacement via a float-key argsort trick on oversampled candidates.
    flat = rng.choice(max_pairs, size=num_edges, replace=False) if num_edges else np.array([], np.int64)
    src = flat // (num_nodes - 1) if num_nodes > 1 else flat
    rem = flat % (num_nodes - 1) if num_nodes > 1 else flat
    dst = rem + (rem >= src)  # skip the diagonal slot
    w = rng.uniform(*weight_range, size=num_edges).astype(dtype)
    if negative_fraction > 0:
        neg = rng.random(num_edges) < negative_fraction
        w = np.where(neg, -w, w)
    return CSRGraph.from_edges(src, dst, w, num_nodes, dtype=dtype)


def random_dag(
    num_nodes: int,
    p: float,
    *,
    weight_range: tuple[float, float] = (1.0, 10.0),
    negative_fraction: float = 0.3,
    seed: int = 0,
    dtype=np.float32,
) -> CSRGraph:
    """ER graph restricted to forward edges (u < v) under a random vertex
    permutation: guaranteed acyclic, so any negative_fraction is safe for
    Johnson (negative weights, never a negative cycle)."""
    g = erdos_renyi(
        num_nodes, p, weight_range=weight_range,
        negative_fraction=negative_fraction, seed=seed, dtype=dtype,
    )
    rng = np.random.default_rng(seed + 1)
    perm = rng.permutation(num_nodes).astype(np.int64)
    src, dst = perm[g.src], perm[g.indices]
    keep = src < dst
    return CSRGraph.from_edges(src[keep], dst[keep], g.weights[keep],
                               num_nodes, dtype=dtype)


def rmat(
    scale: int,
    edge_factor: int = 16,
    *,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    weight_range: tuple[float, float] = (1.0, 10.0),
    seed: int = 0,
    dedupe: bool = True,
    dtype=np.float32,
) -> CSRGraph:
    """R-MAT (Graph500-style) power-law generator: V = 2**scale,
    E = edge_factor * V before dedupe. Quadrant probabilities (a, b, c, d)
    with d = 1-a-b-c; each of the ``scale`` address bits of (src, dst) is
    sampled independently per edge (vectorized over all edges at once)."""
    d = 1.0 - a - b - c
    if d < 0:
        raise ValueError("a + b + c must be <= 1")
    rng = np.random.default_rng(seed)
    num_nodes = 1 << scale
    num_edges = edge_factor * num_nodes
    src = np.zeros(num_edges, np.int64)
    dst = np.zeros(num_edges, np.int64)
    for _ in range(scale):
        r = rng.random(num_edges)
        src_bit = r >= a + b          # quadrants c, d set the src bit
        dst_bit = (r >= a) & (r < a + b) | (r >= a + b + c)  # b or d
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    # Permute vertex labels to break the high-degree-at-0 artifact.
    perm = rng.permutation(num_nodes)
    src, dst = perm[src], perm[dst]
    keep = src != dst  # drop self-loops
    src, dst = src[keep], dst[keep]
    w = rng.uniform(*weight_range, size=len(src)).astype(dtype)
    return CSRGraph.from_edges(src, dst, w, num_nodes, dedupe=dedupe, dtype=dtype)


def permute_labels(graph: CSRGraph, *, seed: int = 0) -> CSRGraph:
    """The same graph under a uniformly random vertex relabeling
    (weights carried per edge, structure otherwise identical).

    Why this exists (round-5 verdict next #3): the benchmark stand-ins'
    NATURAL labelings carry structure the real datasets do not — a
    ``grid2d`` in row-major order puts every edge on 4 index diagonals,
    which is exactly what qualifies the DIA route, while a real DIMACS
    file's labeling is effectively arbitrary. Scrambling the labels
    produces the honest proxy: same distances (up to the relabeling),
    same degree profile and diameter, no labeling gift."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(graph.num_nodes).astype(np.int64)
    return CSRGraph.from_edges(
        perm[graph.src], perm[graph.indices], graph.weights,
        graph.num_nodes, dtype=graph.weights.dtype,
    )


def random_graph_batch(
    batch: int,
    num_nodes: int,
    p: float,
    *,
    weight_range: tuple[float, float] = (1.0, 10.0),
    seed: int = 0,
    dtype=np.float32,
) -> list[CSRGraph]:
    """The many-small-graphs config (BASELINE.json:11): ``batch`` independent
    ER graphs. Returned as a list; :func:`stack_graphs` pads them."""
    return [
        erdos_renyi(num_nodes, p, weight_range=weight_range, seed=seed + i,
                    dtype=dtype)
        for i in range(batch)
    ]


def grid2d(
    rows: int,
    cols: int,
    *,
    weight_range: tuple[float, float] = (1.0, 10.0),
    negative_fraction: float = 0.0,
    seed: int = 0,
    dtype=np.float32,
) -> CSRGraph:
    """Road-network-like graph: a 2-D lattice with bidirectional edges and
    O(rows+cols) diameter — the high-diameter stress profile of the DIMACS
    road graphs (BASELINE.json:8 "DIMACS-NY"), which cannot be downloaded
    in this zero-egress environment; benchmarks use this as the documented
    stand-in (DIMACS-NY: 264k nodes / 733k arcs / diameter ~700; a 515x515
    grid matches the node count and stresses the same sweep-count regime).

    ``negative_fraction`` of the *forward* edges (right/down, u < v) get a
    negative weight drawn from (−0.99·w_min, 0). Any lattice cycle takes
    equally many forward and backward steps, and every backward edge costs
    at least w_min, so a cycle's weight is ≥ k·(w_min − 0.99·w_min) > 0 —
    strictly no negative cycles for any fraction and any weight_range.
    """
    rng = np.random.default_rng(seed)
    n = rows * cols
    idx = np.arange(n, dtype=np.int64).reshape(rows, cols)
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()])
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()])
    fwd = np.concatenate([right, down], axis=1)
    src = np.concatenate([fwd[0], fwd[1]])
    dst = np.concatenate([fwd[1], fwd[0]])
    w = rng.uniform(*weight_range, size=src.shape[0]).astype(dtype)
    if negative_fraction > 0:
        forward = src < dst
        neg = (rng.random(src.shape[0]) < negative_fraction) & forward
        neg_w = -0.99 * weight_range[0] * rng.random(src.shape[0])
        w = np.where(neg, neg_w, w).astype(dtype)
    return CSRGraph.from_edges(src, dst, w, n, dtype=dtype)
