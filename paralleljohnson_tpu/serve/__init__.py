"""Query-serving layer (ROADMAP item 6): the first subsystem where the
solver is a component rather than the product. A :class:`TileStore`
tiers solved distance rows (device-hot / host-RAM-LRU-warm /
checkpoint-cold), a :class:`QueryEngine` aggregates client queries into
source-batched lookups and schedules exact solves for misses, and a
:class:`LandmarkIndex` answers unsolved sources immediately with a
certified ``(estimate, max_error)`` bound. ``pjtpu serve`` is the CLI
front end: a JSONL request loop by default, or — with ``--listen`` —
the :class:`ServeFrontend` threaded socket server with admission
control, per-request deadlines, burn-rate-triggered certified load
shedding, and a SIGTERM drain (ISSUE 15). Concurrent socket clients
are micro-batched through a :class:`MicroBatcher` into device-width
``query_batch`` calls, and a :class:`DeviceQueryPath` answers them in
megabatched kernel launches over the resident hot tier when the
planner prices the device route cheaper (ISSUE 16)."""

from paralleljohnson_tpu.serve.device_query import DeviceQueryPath
from paralleljohnson_tpu.serve.engine import (
    DEFAULT_SLO,
    QueryEngine,
    QueryError,
    SERVE_PROM_METRICS,
    SERVE_STATS_FILENAME,
    ServeStats,
)
from paralleljohnson_tpu.serve.fleet import (
    ReplicaRegistration,
    RoutingTable,
    live_replicas,
    publish_routing,
    read_replicas,
    read_routing,
)
from paralleljohnson_tpu.serve.frontend import (
    DEFAULT_BATCH_WAIT_MS,
    DEFAULT_BATCH_WINDOW,
    MicroBatcher,
    PROTOCOL,
    SHED_POLICIES,
    ServeFrontend,
    parse_listen,
)
from paralleljohnson_tpu.serve.router import FleetRouter
from paralleljohnson_tpu.serve.landmarks import (
    Bounds,
    LandmarkIndex,
    PIVOT_PICKERS,
    finish_estimates,
    pick_pivots,
    widen_bounds,
)
from paralleljohnson_tpu.serve.store import (
    DEFAULT_HOT_ROWS,
    DEFAULT_WARM_ROWS,
    TileStore,
)

__all__ = [
    "Bounds",
    "DEFAULT_BATCH_WAIT_MS",
    "DEFAULT_BATCH_WINDOW",
    "DEFAULT_HOT_ROWS",
    "DEFAULT_SLO",
    "DEFAULT_WARM_ROWS",
    "DeviceQueryPath",
    "FleetRouter",
    "LandmarkIndex",
    "MicroBatcher",
    "PIVOT_PICKERS",
    "PROTOCOL",
    "QueryEngine",
    "QueryError",
    "ReplicaRegistration",
    "RoutingTable",
    "SERVE_PROM_METRICS",
    "SERVE_STATS_FILENAME",
    "SHED_POLICIES",
    "ServeFrontend",
    "ServeStats",
    "TileStore",
    "finish_estimates",
    "live_replicas",
    "parse_listen",
    "pick_pivots",
    "publish_routing",
    "read_replicas",
    "read_routing",
    "widen_bounds",
]
