"""Device-resident query path (ISSUE 16 tentpole — ROADMAP item 3).

The hot tier of :class:`~paralleljohnson_tpu.serve.store.TileStore`
already keeps device-resident ``[V]`` rows, but the host lookup path
(`QueryEngine._answer`) indexes them one source at a time — one device
gather plus one D2H round-trip PER QUERY. This module answers a whole
aggregated batch in one kernel launch instead: the engine flattens its
concurrent clients' lookups into index vectors, the kernels below
megabatch them over a stacked ``[B, V]`` tile (exact hits) and over the
landmark row blocks (certified bounds for misses and shed answers), and
ONE transfer returns everything. The 3D-tensor Floyd-Warshall paper's
point — the hardware wants batched dense tensor ops — applied to the
serving tier.

Bitwise identity with the host path is a DESIGN INVARIANT, not a
tolerance:

- **Exact hits** gather f32 row entries; a gather moves bits, and the
  f32 -> f64 conversion both paths end with is exact.
- **Landmark bounds** are computed on-device in f64 (under
  ``jax.experimental.enable_x64``) but ONLY the raw part — elementwise
  add/sub plus min/max reductions, which are correctly rounded and
  order-independent over never-NaN inputs, so they match numpy bit for
  bit. The multiply-carrying f32-slack widening (where FMA contraction
  could diverge) and the estimate/err derivation always run on host
  through the SAME helpers the host path uses
  (:func:`~paralleljohnson_tpu.serve.landmarks.widen_bounds` /
  :func:`finish_estimates`).

Platforms without native f64 (TPU) fail the one-time probe and the
landmark sub-path falls back to host — recorded in the planner
why-line; the exact-gather sub-path (f32) rides the device everywhere.

The tile is a cached ``jnp.stack`` of the store's non-stale hot rows,
keyed by :meth:`TileStore.hot_token` — any put/evict/stale transition
invalidates it (stable row -> tile-slot mapping in between). Stale rows
are excluded at build: the kernel can never gather a row the host path
would flag. All operand batches are padded to power-of-two lengths so
the jit cache stays bounded under arbitrary client mixes.

Request tracing (ISSUE 20): the engine wraps the whole launch group in
one ``device_megabatch`` span tagged with every sampled ``trace`` that
rides the launch — the kernels here stay trace-agnostic (pure jitted
functions; threading ids through them would poison the jit cache), so
per-request attribution of device time is the span's job, not the
kernel's.
"""

from __future__ import annotations

import functools

import numpy as np

# Pads below this floor round up to it — tiny batches share one
# compiled shape instead of minting one per width.
_MIN_PAD = 8

# Full-row landmark queries materialize a [k, chunk, V] f64 temp;
# chunking bounds it (k is small, V can be large).
_LM_ROW_CHUNK = 8


def available() -> tuple[bool, str]:
    """Whether the device path can exist in this process at all."""
    try:
        import jax  # noqa: F401
    except Exception as e:  # noqa: BLE001 — absence is a reason, not a crash
        return False, f"jax unavailable ({type(e).__name__})"
    return True, "jax importable"


def _pad_len(n: int) -> int:
    return max(_MIN_PAD, 1 << (max(1, int(n)) - 1).bit_length())


@functools.lru_cache(maxsize=1)
def _kernels():
    """The four jitted megabatch kernels, built once per process."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gather_pairs(tile, slots, dsts):
        # [P] entries tile[slots[i], dsts[i]] — the flattened one-to-
        # many megagather (heterogeneous per-query dst lists flatten
        # into one index vector; the engine re-segments on host).
        return tile[slots, dsts]

    @jax.jit
    def gather_rows(tile, slots):
        return tile[slots]  # [Q, V] full rows

    @jax.jit
    def lm_pairs(fwd, rev, s, t):
        # Raw triangle-inequality bounds per flattened (s, t) pair —
        # the f64 twin of LandmarkIndex.raw_bounds_row (see its
        # docstring for why only THIS part may run on device).
        d_s_L = rev[:, s]           # [k, P]  d(s, L)
        d_L_s = fwd[:, s]           # [k, P]  d(L, s)
        fwd_t = fwd[:, t]           # [k, P]  d(L, t)
        rev_t = rev[:, t]           # [k, P]  d(t, L)
        upper = jnp.min(d_s_L + fwd_t, axis=0)
        a = jnp.where(jnp.isfinite(d_L_s), fwd_t - d_L_s, -jnp.inf)
        b = jnp.where(jnp.isfinite(rev_t), d_s_L - rev_t, -jnp.inf)
        lower = jnp.maximum(jnp.max(a, axis=0), jnp.max(b, axis=0))
        return lower, upper

    @jax.jit
    def lm_rows(fwd, rev, s):
        # Raw bounds for Q full-row queries at once: [Q, V] outputs.
        d_s_L = rev[:, s]           # [k, Q]
        d_L_s = fwd[:, s]           # [k, Q]
        upper = jnp.min(d_s_L[:, :, None] + fwd[:, None, :], axis=0)
        a = jnp.where(jnp.isfinite(d_L_s)[:, :, None],
                      fwd[:, None, :] - d_L_s[:, :, None], -jnp.inf)
        b = jnp.where(jnp.isfinite(rev)[:, None, :],
                      d_s_L[:, :, None] - rev[:, None, :], -jnp.inf)
        lower = jnp.maximum(jnp.max(a, axis=0), jnp.max(b, axis=0))
        return lower, upper

    return gather_pairs, gather_rows, lm_pairs, lm_rows


class DeviceQueryPath:
    """Megabatched device lookups over a store's hot tier (+ landmark
    index). One instance per engine; NOT thread-safe on its own — the
    engine's batch lock already serializes every caller."""

    def __init__(self, store, landmarks=None) -> None:
        self.store = store
        self.landmarks = landmarks
        self._token: object = object()  # never equal to a store token
        self._slots: dict[int, int] = {}
        self._tile = None
        self._lm_fwd = None
        self._lm_rev = None
        self._f64_ok: bool | None = None
        self.tile_rebuilds = 0

    # -- qualification --------------------------------------------------------

    def platform(self) -> str:
        import jax

        return jax.default_backend()

    def f64_supported(self) -> bool:
        """One-time probe: can this backend hold and add REAL f64?
        (TPU demotes or refuses — the landmark sub-path then stays on
        host; a silent f32 demotion would break bitwise parity, which
        is exactly what the dtype check catches.)"""
        if self._f64_ok is None:
            try:
                import jax.numpy as jnp
                from jax.experimental import enable_x64

                with enable_x64():
                    x = jnp.asarray(np.array([1.5, 2.5], np.float64))
                    ok = x.dtype == jnp.float64
                    ok = ok and float(np.asarray(x + x)[0]) == 3.0
                self._f64_ok = bool(ok)
            except Exception:  # noqa: BLE001 — no f64 is a route fact
                self._f64_ok = False
        return self._f64_ok

    def landmark_device_ok(self) -> bool:
        return (self.landmarks is not None and self.landmarks.k > 0
                and self.f64_supported())

    # -- the cached device tile ----------------------------------------------

    def refresh(self) -> dict[int, int]:
        """Validate/rebuild the ``[B, V]`` tile against the store's
        token; returns the stable source -> tile-slot mapping (empty
        when nothing hot / everything stale). The common case is one
        integer-tuple compare."""
        token = self.store.hot_token()
        if token == self._token:
            return self._slots
        import jax.numpy as jnp

        token, items = self.store.hot_view()
        if items:
            # Device-resident rows stack device-to-device; host rows
            # (host backends) upload once and then serve from HBM.
            self._tile = jnp.stack([jnp.asarray(r) for _, r in items])
            self._slots = {int(s): i for i, (s, _) in enumerate(items)}
        else:
            self._tile = None
            self._slots = {}
        self._token = token
        self.tile_rebuilds += 1
        return self._slots

    def _lm_dev(self):
        if self._lm_fwd is None:
            import jax.numpy as jnp
            from jax.experimental import enable_x64

            with enable_x64():
                self._lm_fwd = jnp.asarray(self.landmarks.fwd)
                self._lm_rev = jnp.asarray(self.landmarks.rev)
        return self._lm_fwd, self._lm_rev

    # -- megabatched lookups --------------------------------------------------

    def exact_pairs(self, slot_idx, dst_idx) -> np.ndarray:
        """f32 ``[P]`` tile entries for flattened (slot, dst) pairs —
        one launch, one D2H, padded to a power of two."""
        gather_pairs, _, _, _ = _kernels()
        import jax.numpy as jnp

        p = len(slot_idx)
        pad = _pad_len(p)
        s = np.zeros(pad, np.int32)
        s[:p] = slot_idx
        d = np.zeros(pad, np.int32)
        d[:p] = dst_idx
        out = gather_pairs(self._tile, jnp.asarray(s), jnp.asarray(d))
        return np.asarray(out)[:p]

    def exact_rows(self, slot_idx) -> np.ndarray:
        """f32 ``[Q, V]`` full rows for the given tile slots."""
        _, gather_rows, _, _ = _kernels()
        import jax.numpy as jnp

        q = len(slot_idx)
        pad = _pad_len(q)
        s = np.zeros(pad, np.int32)
        s[:q] = slot_idx
        out = gather_rows(self._tile, jnp.asarray(s))
        return np.asarray(out)[:q]

    def landmark_pairs(self, s_idx, t_idx):
        """RAW f64 ``(lower[P], upper[P])`` bounds for flattened (s, t)
        pairs — finish through ``widen_bounds``/``finish_estimates``."""
        _, _, lm_pairs, _ = _kernels()
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        p = len(s_idx)
        pad = _pad_len(p)
        s = np.zeros(pad, np.int32)
        s[:p] = s_idx
        t = np.zeros(pad, np.int32)
        t[:p] = t_idx
        with enable_x64():
            fwd, rev = self._lm_dev()
            lo, up = lm_pairs(fwd, rev, jnp.asarray(s), jnp.asarray(t))
            return np.asarray(lo)[:p], np.asarray(up)[:p]

    def landmark_rows(self, s_idx):
        """RAW f64 ``(lower[Q, V], upper[Q, V])`` bounds for full-row
        landmark queries, chunked to bound the [k, chunk, V] temp."""
        _, _, _, lm_rows = _kernels()
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        lows, ups = [], []
        with enable_x64():
            fwd, rev = self._lm_dev()
            for i in range(0, len(s_idx), _LM_ROW_CHUNK):
                chunk = s_idx[i:i + _LM_ROW_CHUNK]
                s = np.zeros(_LM_ROW_CHUNK, np.int32)
                s[:len(chunk)] = chunk
                lo, up = lm_rows(fwd, rev, jnp.asarray(s))
                lows.append(np.asarray(lo)[:len(chunk)])
                ups.append(np.asarray(up)[:len(chunk)])
        return np.concatenate(lows), np.concatenate(ups)

    # -- introspection --------------------------------------------------------

    def describe(self) -> dict:
        ok, reason = available()
        out = {"available": ok, "reason": reason}
        if ok:
            out.update(
                platform=self.platform(),
                f64_device_bounds=self.landmark_device_ok(),
                tile_slots=len(self._slots),
                tile_rebuilds=self.tile_rebuilds,
            )
        return out
