"""Tiered distance-tile store (ROADMAP item 6 — the serving tentpole).

The artifact of a solve is distance ROWS: ``dist[source] -> [V]``. The
store keeps them in three tiers, hottest first, and every lookup walks
them in order:

- **hot** — rows exactly as the backend returned them, which for device
  backends means device-resident (HBM) arrays that were never forced to
  host; for host backends the tiers differ only in capacity. Newly
  solved batches land here.
- **warm** — a host-RAM LRU of materialized numpy rows. Hot evictions
  demote here (one ``np.asarray`` per row — the D2H download happens at
  demotion, off the solve path); warm evictions are dropped (the cold
  tier still has them when the store is checkpoint-backed).
- **cold** — checkpoint-backed batch files loaded through
  :meth:`BatchCheckpointer.load` (same corruption checks as resume),
  indexed O(1) by the persisted manifest (source -> batch file). A cold
  hit promotes the WHOLE loaded batch into warm — the ``.npz`` decode
  was the expensive part, and query locality across a batch's sources
  is the common case.

The store is keyed by graph content digest (``checkpoint.graph_digest``)
through the checkpointer's per-graph subdirectory, so it can attach to
any finished or in-progress solve directory: rows of a different or
modified graph are invisible by construction, and a solver writing new
batches into the same directory (the engine's exact-miss path) just
grows the cold tier — call :meth:`invalidate_cold_index` after a
scheduled solve so the manifest is re-read.

**Staleness (ISSUE 11).** When the incremental repair engine runs
against this store's graph, it publishes ``repair_status.json`` into
the per-graph subdirectory; :meth:`is_stale` reads it (mtime-cached)
and reports whether a source's row reflects pre-update distances. The
query engine flags every such answer ``stale: true`` — rows outside
the affected set are PROVABLY bitwise identical on the updated graph
(the repair engine's dependency argument), so they stay unflagged.
``mark_stale`` exists for in-memory stores and tests.
"""

from __future__ import annotations

import collections
import threading
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.utils.checkpoint import (
    MANIFEST_NAME,
    BatchCheckpointer,
    graph_digest,
)

# Tier capacities (rows). Hot is device memory — keep it a small working
# set; warm is host RAM (a [V] f32 row at V=2^20 is 4 MB, so the default
# warm tier tops out around 16 GB at that scale — size down via the CLI
# flags for bigger graphs).
DEFAULT_HOT_ROWS = 128
DEFAULT_WARM_ROWS = 4096


class TileStore:
    """Tiered distance-row cache over an optional checkpoint directory.

    ``directory=None`` runs hot+warm only (pure in-memory serving).
    Thread-safe for the in-process request loop (one lock — lookups are
    dict operations plus, on a cold hit, one npz load).
    """

    def __init__(
        self,
        directory: str | Path | None,
        graph,
        *,
        hot_rows: int = DEFAULT_HOT_ROWS,
        warm_rows: int = DEFAULT_WARM_ROWS,
    ) -> None:
        if hot_rows < 0 or warm_rows < 0:
            raise ValueError("tier capacities must be >= 0")
        self.graph = graph
        self.digest = graph_digest(graph)
        self.root = Path(directory) if directory is not None else None
        if directory is None:
            self.ckpt = None
        elif (Path(directory) / "fleet_manifest.json").exists():
            # A distributed-fleet dir (ISSUE 10): the cold tier reads
            # through the merged shard manifest — rows solved by any
            # worker of the fleet — via the same checkpointer read
            # protocol; scheduled exact-miss solves still persist into
            # this root and overlay the fleet map on re-index.
            from paralleljohnson_tpu.distributed.manifest import (
                ShardedCheckpointer,
            )

            self.ckpt = ShardedCheckpointer(directory, graph_key=self.digest)
        else:
            self.ckpt = BatchCheckpointer(directory, graph_key=self.digest)
        self.hot_rows = int(hot_rows)
        self.warm_rows = int(warm_rows)
        self._hot: collections.OrderedDict = collections.OrderedDict()
        self._warm: collections.OrderedDict = collections.OrderedDict()
        self._cold_index: dict[int, tuple[int, str]] | None = None
        self._lock = threading.Lock()
        # Mutation counter for the device-tile view (ISSUE 16): bumped
        # whenever hot-tier MEMBERSHIP or staleness can change, so a
        # cached [B, V] device tile can validate itself with one integer
        # compare instead of re-reading the tier.
        self._version = 0
        self.hits_hot = 0
        self.hits_warm = 0
        self.hits_cold = 0
        self.misses = 0
        self.demotions = 0
        self.evictions = 0
        self.cold_loads = 0
        # Staleness: manual marking (in-memory stores / tests) plus the
        # repair-status marker cache: (mtime_ns, size) -> parsed set.
        self._manual_stale: "set[int] | str | None" = None
        self._stale_cache_key = None
        self._stale_cached: "set[int] | str | None" = None
        # Live-fleet manifest watch (ISSUE 18): (path, mtime_ns, size)
        # per backing manifest, captured at attach.
        # refresh_cold_if_changed() compares against it with one stat()
        # per manifest — called from the miss path only, so the hot
        # path never touches the disk.
        self._manifest_watch_key = self._manifest_key()

    # -- lookup --------------------------------------------------------------

    def get(self, source: int):
        """``(row, tier)`` for one source's distance row, or
        ``(None, None)`` on a full miss. ``tier`` is ``"hot"`` /
        ``"warm"`` / ``"cold"``; the row is host numpy for warm/cold and
        whatever the backend returned (possibly device-resident) for hot.
        Counts exactly one hit or miss per call."""
        source = int(source)
        with self._lock:
            if source in self._hot:
                self._hot.move_to_end(source)
                self.hits_hot += 1
                return self._hot[source], "hot"
            if source in self._warm:
                self._warm.move_to_end(source)
                self.hits_warm += 1
                return self._warm[source], "warm"
            row = self._cold_lookup(source)
            if row is not None:
                self.hits_cold += 1
                return row, "cold"
            self.misses += 1
            return None, None

    def __contains__(self, source: int) -> bool:
        s = int(source)
        with self._lock:
            return (
                s in self._hot
                or s in self._warm
                or s in self._cold_sources()
            )

    # -- insertion -----------------------------------------------------------

    def put(self, sources: np.ndarray, rows, *, tier: str = "hot") -> None:
        """Insert one solved batch's rows (``rows[i]`` is the distance
        row of ``sources[i]``). ``tier="hot"`` keeps rows as given
        (device-resident for device backends); ``tier="warm"``
        materializes to host numpy. Capacity overflow demotes
        hot -> warm (materializing) and drops from warm (LRU order)."""
        if tier not in ("hot", "warm"):
            raise ValueError(f"tier must be hot/warm, got {tier!r}")
        sources = np.asarray(sources, np.int64)
        with self._lock:
            self._version += 1
            for i, s in enumerate(sources):
                s = int(s)
                row = rows[i]
                if tier == "hot" and self.hot_rows > 0:
                    self._hot.pop(s, None)
                    self._hot[s] = row
                else:
                    self._warm.pop(s, None)
                    self._warm[s] = np.asarray(row)
                self._evict()

    def _evict(self) -> None:
        while len(self._hot) > self.hot_rows:
            s, row = self._hot.popitem(last=False)
            self.demotions += 1
            if self.warm_rows > 0:
                self._warm.pop(s, None)
                self._warm[s] = np.asarray(row)  # the D2H happens here
        while len(self._warm) > self.warm_rows:
            self._warm.popitem(last=False)
            self.evictions += 1

    # -- cold tier -----------------------------------------------------------

    def _cold_sources(self) -> dict[int, tuple[int, str]]:
        if self.ckpt is None:
            return {}
        if self._cold_index is None:
            self._cold_index = self.ckpt.manifest()
        return self._cold_index

    def _cold_lookup(self, source: int):
        entry = self._cold_sources().get(source)
        if entry is None:
            return None
        batch_idx, filename = entry
        batch_sources = self.ckpt.batch_sources(filename)
        if batch_sources is None:
            return None
        self.cold_loads += 1
        loaded = self.ckpt.load(batch_idx, batch_sources)
        if loaded is None:  # corrupt/absent batch: a miss, never garbage
            return None
        rows, _ = loaded
        # Promote the whole decoded batch: the npz decode dominated, and
        # neighbors in a batch are the likeliest next queries.
        for i, s in enumerate(batch_sources):
            s = int(s)
            if s not in self._hot and self.warm_rows > 0:
                self._warm.pop(s, None)
                self._warm[s] = rows[i]
        self._evict()
        pos = int(np.flatnonzero(batch_sources == source)[0])
        return rows[pos]

    def invalidate_cold_index(self) -> None:
        """Re-read the manifest on next cold lookup — call after a solver
        appended new batches to the backing directory."""
        key = self._manifest_key()
        with self._lock:
            self._cold_index = None
            # Our own commit is not "news": fold it into the watch key
            # so the next refresh_cold_if_changed() only fires on a
            # manifest some OTHER process has grown since.
            self._manifest_watch_key = key

    def _manifest_key(self):
        """(path, mtime_ns, size) per backing manifest — the fleet
        manifest AND the growth dir's batch manifest for sharded dirs,
        just the batch manifest for plain checkpoint dirs."""
        if self.ckpt is None:
            return None
        paths = {Path(self.ckpt.dir) / MANIFEST_NAME}
        fleet_manifest = getattr(self.ckpt, "manifest_path", None)
        if fleet_manifest is not None:
            paths.add(Path(fleet_manifest))
        key = []
        for p in sorted(paths):
            try:
                st = p.stat()
                key.append((str(p), st.st_mtime_ns, st.st_size))
            except OSError:
                key.append((str(p), None, None))
        return tuple(key)

    def refresh_cold_if_changed(self) -> bool:
        """Live-fleet awareness (ISSUE 18): re-scan the backing
        directory's manifests and drop the cold index iff some OTHER
        process committed batches since attach (or since our own last
        invalidate). One ``stat`` per manifest file — call from the
        miss path, where a changed manifest can turn a scheduled solve
        into a cold hit. Returns whether the cold tier GAINED sources —
        a stat change alone is not news (the first cold lookup lazily
        creates an empty manifest, and our own commits fold into the
        watch key via :meth:`invalidate_cold_index`)."""
        if self.ckpt is None:
            return False
        key = self._manifest_key()
        with self._lock:
            if key == self._manifest_watch_key:
                return False
            self._manifest_watch_key = key
            old = set(self._cold_sources())
            self._cold_index = None
            new = set(self._cold_sources())
            return bool(new - old)

    # -- device-tile view (ISSUE 16: the device-resident query path) ---------

    def hot_token(self):
        """Opaque freshness token for :meth:`hot_view` snapshots: changes
        whenever hot membership OR staleness may have changed (covers
        both manual marks and the on-disk repair marker's mtime key).
        Compare tokens with ``==`` only."""
        with self._lock:
            self._repair_stale()  # refresh the marker's mtime cache key
            return (self._version, self._stale_cache_key)

    def hot_view(self):
        """``(token, [(source, row), ...])`` — a snapshot of the hot
        tier EXCLUDING stale sources, in LRU order (coldest first), with
        rows exactly as the backend returned them (device-resident for
        device backends). The token is :meth:`hot_token` at snapshot
        time: a device tile stacked from this view is valid while the
        store keeps returning the same token. Stale rows are excluded by
        construction — a megabatched kernel must never gather a row the
        host path would have flagged (the host path still serves them,
        with ``stale: true``)."""
        with self._lock:
            stale = self.stale_info()
            token = (self._version, self._stale_cache_key)
            if stale == "all":
                return token, []
            if stale is None:
                items = list(self._hot.items())
            else:
                items = [(s, r) for s, r in self._hot.items()
                         if s not in stale]
            return token, items

    def note_hot_hits(self, sources) -> int:
        """Account device-path lookups that bypassed :meth:`get`: counts
        one hot hit and refreshes LRU position per source still in the
        hot tier (so hit counters and eviction order are identical
        whichever lookup path served the batch). Returns how many
        sources were actually hot."""
        n = 0
        with self._lock:
            for s in sources:
                s = int(s)
                if s in self._hot:
                    self._hot.move_to_end(s)
                    self.hits_hot += 1
                    n += 1
        return n

    # -- staleness (ISSUE 11: stale-but-servable during repair) --------------

    def mark_stale(self, sources) -> None:
        """Manually flag sources (or ``"all"``) stale — the in-memory
        twin of the repair-status marker; union'd with it."""
        if isinstance(sources, str):
            if sources != "all":
                raise ValueError(f"mark_stale takes source ids or 'all', "
                                 f"got {sources!r}")
            self._manual_stale = "all"
        elif self._manual_stale != "all":
            fresh = {int(s) for s in sources}
            self._manual_stale = (
                fresh if self._manual_stale is None
                else self._manual_stale | fresh
            )
        with self._lock:
            self._version += 1

    def clear_stale(self) -> None:
        """Drop the MANUAL stale marks (the repair-status marker, if
        present on disk, still applies — it records durable fact)."""
        self._manual_stale = None
        with self._lock:
            self._version += 1

    def _repair_stale(self) -> "set[int] | str | None":
        """The repair-status marker's affected set, mtime-cached so the
        hot path pays one ``stat`` per lookup batch, not a JSON parse."""
        if self.ckpt is None:
            return None
        from paralleljohnson_tpu.incremental.status import (
            REPAIR_STATUS_FILENAME,
            read_repair_status,
            stale_sources,
        )

        marker = Path(self.ckpt.dir) / REPAIR_STATUS_FILENAME
        try:
            st = marker.stat()
            key = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._stale_cache_key = None
            self._stale_cached = None
            return None
        if key != self._stale_cache_key:
            self._stale_cached = stale_sources(
                read_repair_status(self.ckpt.dir)
            )
            self._stale_cache_key = key
        return self._stale_cached

    def stale_info(self) -> "set[int] | str | None":
        """``None`` (nothing stale), ``"all"``, or the set of stale
        sources — manual marks union'd with the repair marker."""
        repair = self._repair_stale()
        manual = self._manual_stale
        if repair == "all" or manual == "all":
            return "all"
        if repair is None and manual is None:
            return None
        return (repair or set()) | (manual or set())

    def is_stale(self, source: int) -> bool:
        """Whether this source's row reflects pre-update distances (a
        repair ran or is running and this source is in its affected
        set). Sources outside the affected set are provably current."""
        info = self.stale_info()
        if info is None:
            return False
        return True if info == "all" else int(source) in info

    # -- introspection -------------------------------------------------------

    @property
    def hits(self) -> int:
        return self.hits_hot + self.hits_warm + self.hits_cold

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {
                "digest": self.digest,
                "hot_rows": len(self._hot),
                "warm_rows": len(self._warm),
                "cold_rows": len(self._cold_sources()),
                "hot_capacity": self.hot_rows,
                "warm_capacity": self.warm_rows,
                "hits_hot": self.hits_hot,
                "hits_warm": self.hits_warm,
                "hits_cold": self.hits_cold,
                "misses": self.misses,
                "hit_rate": round(self.hit_rate(), 6),
                "demotions": self.demotions,
                "evictions": self.evictions,
                "cold_loads": self.cold_loads,
            }

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"TileStore(digest={self.digest}, hot={len(self._hot)}/"
            f"{self.hot_rows}, warm={len(self._warm)}/{self.warm_rows}, "
            f"cold={'on' if self.ckpt else 'off'})"
        )
