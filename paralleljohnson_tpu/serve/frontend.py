"""Traffic front end (ISSUE 15 tentpole): socket serving with admission
control, certified load shedding, and a drainable lifecycle — the
serving stack's PR-3 moment, where overload behavior is DESIGNED rather
than emergent.

``pjtpu serve --listen HOST:PORT`` runs a stdlib-only threaded TCP
server: newline-delimited JSON both ways, one protocol header line per
connection, one worker thread per connection over ONE shared
:class:`~paralleljohnson_tpu.serve.engine.QueryEngine`. What makes it a
traffic front end rather than a socket wrapper:

- **Admission control** — a connection bound (``max_connections``) and
  an in-flight query semaphore (``max_inflight``). Past either bound,
  new work gets an explicit ``{"error": "overloaded",
  "retry_after_ms": ...}`` instead of an unbounded queue; the client
  decides whether to back off or go elsewhere, and the server's memory
  stays bounded by construction.
- **Per-request deadlines** — a query may carry ``deadline_ms`` (its
  total patience, measured from arrival). A request that cannot START
  before its deadline — the in-flight slot never freed in time — is
  dropped without touching the engine (``deadline_drops``): work the
  client has already abandoned must not spend engine time.
- **Burn-rate-triggered certified shedding** — when the engine's
  :class:`SLOTracker` fires its multi-window burn alert, exact-MISS
  queries are downgraded to landmark answers flagged ``{"shed": true,
  "exact": false, "max_error": ...}`` (the repo's honesty rule: never an
  unflagged approximation; hot/warm/cold HITS still answer exactly —
  they cost nothing to serve right). Shedding disengages automatically
  when the burn clears; both transitions emit an ``slo_shed`` flight
  event. ``shed_policy``: ``"landmark"`` (certified degrade, the
  default when an index exists), ``"reject"`` (exact misses get the
  overloaded rejection instead), ``"off"``.
- **Graceful drain** — SIGTERM stops accepting, lets in-flight requests
  finish under ``drain_timeout_s``, force-closes stragglers, flushes
  ``serve_stats.json`` + the live-metrics snapshot, exits 0. SIGKILL
  mid-traffic leaves the atomic snapshots readable (the engine's
  periodic writers — the heartbeat idiom, now tested through the
  socket path).
- **Fault injection** — the serving path is inside the
  :class:`~paralleljohnson_tpu.utils.faults.FaultPlan` schedule:
  ``serve_accept`` fires per accepted connection (here), and the engine
  fires ``serve_lookup`` / ``serve_solve`` per batch / per scheduled
  solve. ``scripts/serve_chaos_drill.py`` drives them to prove that
  store stalls and solver failures produce shed/rejected/error answers
  and burn events — never hung connections, never wrong exact answers.

Protocol (version ``pjtpu-serve/1``): on connect the server sends one
header line ``{"protocol": "pjtpu-serve/1", "graph_digest": ...,
"shed_policy": ...}``. Each request line is a query object (the engine's
JSONL shape: ``id`` / ``source`` / ``dst`` / ``mode``) plus the optional
``deadline_ms``, or ``{"op": "health"}`` for the liveness document
(admission gauges, shedding state, and the solve heartbeat's freshness
via ``read_heartbeat``/``heartbeat_fresh`` — torn files degrade to
``fresh: false``, never a crash). Every request gets exactly one
response line, in order, on the connection that sent it.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import types

from paralleljohnson_tpu import planner as _planner
from paralleljohnson_tpu.observe import trace as _trace
from paralleljohnson_tpu.serve.engine import (
    SERVE_LIVE_FILENAME,
    QueryError,
)

PROTOCOL = "pjtpu-serve/1"

# Shedding tiers (ISSUE 15, extended by ISSUE 17's hopset tier):
# "landmark" / "hopset" degrade exact misses to that certified tier,
# "priced" orders the two certified tiers by predicted per-query
# serving cost (the priced-shedding clause — reject only when neither
# tier exists), "reject" answers overloaded, "off" disables shedding.
SHED_POLICIES = ("landmark", "hopset", "priced", "reject", "off")

DEFAULT_MAX_CONNECTIONS = 64
DEFAULT_MAX_INFLIGHT = 8
DEFAULT_DRAIN_TIMEOUT_S = 10.0
DEFAULT_RETRY_AFTER_MS = 100

# Micro-batching defaults (ISSUE 16): at most this many queries combine
# into one engine batch; 0/1 disables combining. The wait window
# defaults to ZERO — batching emerges from convoy combining (followers
# enqueue while the leader executes the previous batch), so an idle
# server never trades latency for width.
DEFAULT_BATCH_WINDOW = 32
DEFAULT_BATCH_WAIT_MS = 0.0

# Shedding tiers as a planner registry (ISSUE 19 satellite): what an
# exact-miss degrades to under overload is the same kind of decision as
# which kernel route serves a solve, so it goes through the same
# ``planner.select`` walk — declared priority unpriced, CostModel-priced
# promotion past the 25% band under ``shed_policy="priced"``, forced
# pins for the explicit policies, and a decision record with the full
# candidate table (including honest disqualification reasons) in
# ``health()``. The "stale" tier is declared but self-disqualifying:
# staleness is an answer PROPERTY of the repair contract (ISSUE 11),
# not a servable degrade target, and the candidate table says so
# instead of silently omitting it.
SHED_PLANS = [
    _planner.Plan(
        name="hopset", entry="shed", priority=10,
        qualify=lambda ctx: (
            (True, "certified (1+eps) hopset tier attached")
            if getattr(ctx.engine, "hopset", None) is not None
            else (False, "no hopset attached to the engine")
        ),
        price_routes=("hopset+bf",),
        forced=lambda cfg: getattr(cfg, "shed_policy", None) == "hopset",
    ),
    _planner.Plan(
        name="landmark", entry="shed", priority=20,
        qualify=lambda ctx: (
            (True, "landmark index attached (certified bounds)")
            if ctx.engine.landmarks is not None
            else (False, "no landmark index attached to the engine")
        ),
        price_routes=("lookup-host",),
        forced=lambda cfg: getattr(cfg, "shed_policy", None) == "landmark",
    ),
    _planner.Plan(
        name="stale", entry="shed", priority=25,
        qualify=lambda ctx: (
            False,
            "stale pre-update rows are a property the repair staleness "
            "contract stamps on answers, not a tier a shed exact-miss "
            "can degrade to — nothing independent to serve",
        ),
    ),
    _planner.Plan(
        name="reject", entry="shed", priority=90,
        qualify=lambda ctx: (
            True, "unconditional: the overloaded rejection always exists"
        ),
        forced=lambda cfg: getattr(cfg, "shed_policy", None) == "reject",
    ),
]

# Chosen shed plan -> the query mode an exact-miss is rewritten to
# ("reject" short-circuits to the overloaded answer instead).
_SHED_MODES = {"hopset": "hopset", "landmark": "approx", "reject": "reject"}

# The low-traffic guard on the shed decision (the SRE-workbook caveat:
# burn-rate math over a handful of events is dominated by any single
# failure). Shedding engages only when the burning verdict is backed by
# at least this many observations inside the burn rule's long window —
# one rejected connection on a near-idle server must not degrade the
# next answer. The verdict itself (slo_burn events, `pjtpu top`) is
# untouched; only the DEGRADE action is volume-gated, because acting on
# a statistically empty alert has a real cost here.
DEFAULT_SHED_MIN_EVENTS = 20


def parse_listen(spec: str) -> tuple[str, int]:
    """``"HOST:PORT"`` -> ``(host, port)`` (port 0 = ephemeral; the
    bound port is in :attr:`ServeFrontend.address` / the CLI's
    ``listening`` line)."""
    host, sep, port = str(spec).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"--listen wants HOST:PORT (e.g. 127.0.0.1:7070), got {spec!r}"
        )
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(f"bad --listen port {port!r}") from None


class _BatchSlot:
    """One request's place in a :class:`MicroBatcher` convoy. Captures
    the submitter's trace context and enqueue time at construction —
    the leader executes on ANOTHER thread, so follower→leader span
    linkage (ISSUE 20) must travel with the slot, not a contextvar."""

    __slots__ = ("req", "resp", "exc", "done", "ctx", "t_submit")

    def __init__(self, req: dict, ctx=None) -> None:
        self.req = req
        self.resp: dict | None = None
        self.exc: BaseException | None = None
        self.done = False
        self.ctx = ctx
        self.t_submit = time.perf_counter()


class MicroBatcher:
    """Leader-follower request combining over one shared engine
    (ISSUE 16 tentpole: the frontend-side aggregation that gives the
    device megabatch its width).

    Every submitting thread enqueues a slot, then contends for the TURN
    lock. The holder (the leader) drains up to ``max_width`` pending
    slots — its own included — into ONE ``engine.query_batch`` call and
    marks them done; threads whose slot was served by someone else's
    batch find it completed the moment they get the turn and leave
    immediately. The bounded-latency argument: with ``wait_ms=0`` (the
    default) a lone request takes the turn instantly and runs a
    width-1 batch — combining costs an idle server NOTHING; under load,
    width emerges from exactly the time the previous batch was already
    going to take (the convoy), which is the ISSUE's "bounded
    micro-batching window, never adding unbounded latency". A nonzero
    ``wait_ms`` additionally lets the leader sit out one fixed window
    to accumulate followers — still bounded by construction.

    Exceptions from the engine are stored per slot and re-raised in
    each submitter's own thread (a poisoned batch fails its members,
    not the batcher)."""

    def __init__(self, engine, *, max_width: int = DEFAULT_BATCH_WINDOW,
                 wait_ms: float = DEFAULT_BATCH_WAIT_MS) -> None:
        if max_width < 1:
            raise ValueError(f"max_width must be >= 1, got {max_width}")
        if wait_ms < 0:
            raise ValueError(f"wait_ms must be >= 0, got {wait_ms}")
        self.engine = engine
        self.max_width = int(max_width)
        self.wait_s = float(wait_ms) / 1e3
        self._pending: list[_BatchSlot] = []
        self._lock = threading.Lock()   # guards _pending
        self._turn = threading.Lock()   # one leader at a time
        self.batches = 0
        self.combined = 0  # requests that rode a batch of width > 1

    def submit(self, req: dict) -> dict:
        """Answer one request through the combining pipeline. Blocks
        until the request's batch completes; raises whatever the engine
        raised for that batch."""
        slot = _BatchSlot(req, _trace.current_trace())
        with self._lock:
            self._pending.append(slot)
        while not slot.done:
            with self._turn:
                if slot.done:
                    break  # a previous leader's batch served us
                if self.wait_s:
                    time.sleep(self.wait_s)
                with self._lock:
                    batch = self._pending[:self.max_width]
                    del self._pending[:len(batch)]
                if batch:
                    self._execute(batch, leader_slot=slot)
                # FIFO take: our slot is served within ceil(pos/width)
                # turns, every one of which does real work — no
                # spinning, no starvation.
        if slot.exc is not None:
            raise slot.exc
        return slot.resp  # type: ignore[return-value]

    def _execute(self, batch: list[_BatchSlot],
                 leader_slot: "_BatchSlot | None" = None) -> None:
        tel = getattr(self.engine, "_tel", None)
        traced = ([s for s in batch if s.ctx is not None and s.ctx.sampled]
                  if tel else [])
        if not traced:
            self._run_batch(batch)
            return
        # The convoy made visible (ISSUE 20): one ``convoy_batch`` span
        # on the leader's thread (so the engine's serve_batch nests
        # under it), plus one ``convoy_member`` span per traced slot,
        # explicitly ``parent=``-linked to the batch span — a follower
        # whose request rode someone else's batch still joins its own
        # trace via the ``trace`` attr, and its queue wait (submit ->
        # execution start) stops being invisible.
        t_exec = time.perf_counter()
        with tel.span("convoy_batch", width=len(batch),
                      traced=len(traced)) as bs:
            members = [
                (s, tel.begin_span(
                    "convoy_member", parent=bs.id, trace=s.ctx.trace_id,
                    queue_wait_ms=round((t_exec - s.t_submit) * 1e3, 3),
                    leader=(s is leader_slot),
                ))
                for s in traced
            ]
            try:
                self._run_batch(batch)
            finally:
                for s, sid in members:
                    if s.exc is not None:
                        tel.finish_span(sid, "error", repr(s.exc))
                    else:
                        tel.finish_span(sid)

    def _run_batch(self, batch: list[_BatchSlot]) -> None:
        try:
            responses = self.engine.query_batch([s.req for s in batch])
            for s, resp in zip(batch, responses):
                s.resp = resp
        except BaseException as e:  # noqa: BLE001 — fail the members, not us
            for s in batch:
                s.exc = e
        finally:
            self.batches += 1
            if len(batch) > 1:
                self.combined += len(batch)
            for s in batch:
                s.done = True


class ServeFrontend:
    """Threaded socket front end over one shared engine (module doc).

    The engine's :class:`ServeStats` is the single counter surface:
    ``shed_answers`` / ``rejected`` / ``deadline_drops`` /
    ``open_connections`` land there (and in the live metrics registry),
    so ``serve_stats.json``, the prom export, and ``pjtpu top`` all see
    the frontend's admission behavior without a second bookkeeping
    path."""

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 shed_policy: str = "landmark",
                 drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
                 retry_after_ms: int = DEFAULT_RETRY_AFTER_MS,
                 shed_min_events: int = DEFAULT_SHED_MIN_EVENTS,
                 fault_plan=None, heartbeat_file=None,
                 heartbeat_stale_s: float = 30.0,
                 batch_window: int = DEFAULT_BATCH_WINDOW,
                 batch_wait_ms: float = DEFAULT_BATCH_WAIT_MS,
                 max_inflight_per_client: int | None = None,
                 http: bool = False,
                 fleet_dir=None, replica_id: str | None = None,
                 fleet_heartbeat_s: float = 1.0,
                 tune_dir=None, tune_idle_s: float = 2.0,
                 trace_sample: float | None = None) -> None:
        if shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy must be one of {SHED_POLICIES}, "
                f"got {shed_policy!r}"
            )
        if shed_policy == "landmark" and engine.landmarks is None:
            raise ValueError(
                "shed_policy='landmark' needs a LandmarkIndex on the "
                "engine (build one, or pick shed_policy='reject'/'off')"
            )
        if shed_policy == "hopset" and getattr(engine, "hopset", None) is None:
            raise ValueError(
                "shed_policy='hopset' needs a Hopset on the engine "
                "(build one, or pick shed_policy='reject'/'off')"
            )
        if (shed_policy == "priced" and engine.landmarks is None
                and getattr(engine, "hopset", None) is None):
            raise ValueError(
                "shed_policy='priced' needs at least one certified tier "
                "on the engine (a LandmarkIndex or a Hopset)"
            )
        if max_connections < 1 or max_inflight < 1:
            raise ValueError("max_connections and max_inflight must be >= 1")
        if max_inflight_per_client is not None and max_inflight_per_client < 1:
            raise ValueError("max_inflight_per_client must be >= 1 (or None)")
        self.engine = engine
        self.host, self.port = host, int(port)
        self.max_connections = int(max_connections)
        self.max_inflight = int(max_inflight)
        self.shed_policy = shed_policy
        self.drain_timeout_s = float(drain_timeout_s)
        self.retry_after_ms = int(retry_after_ms)
        self.shed_min_events = int(shed_min_events)
        self.fault_plan = fault_plan
        self.heartbeat_file = heartbeat_file
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        # Micro-batching (ISSUE 16): concurrent connections' requests
        # combine into device-width engine batches; 0/1 = the old
        # one-request-one-batch path.
        self.batch_window = int(batch_window)
        self.batch_wait_ms = float(batch_wait_ms)
        self.batcher = (
            MicroBatcher(engine, max_width=self.batch_window,
                         wait_ms=self.batch_wait_ms)
            if self.batch_window > 1 else None
        )
        # Per-client fairness (ISSUE 18): an optional per-client-key
        # in-flight cap UNDER the global semaphore. None = the round-20
        # globally-FIFO behavior, unchanged.
        self.max_inflight_per_client = (
            None if max_inflight_per_client is None
            else int(max_inflight_per_client)
        )
        # HTTP/1.1 adaptation (ISSUE 18): same listener, same admission
        # path, request bodies are protocol lines.
        self.http = bool(http)
        # Fleet membership (ISSUE 18): heartbeat-registered replica
        # record in <fleet_dir>/serve/replicas/.
        self.fleet_dir = fleet_dir
        self.replica_id = (
            str(replica_id) if replica_id else f"replica-{os.getpid()}"
        )
        self.fleet_heartbeat_s = float(fleet_heartbeat_s)
        # Idle-capacity tuning (ISSUE 19): with a tuning-fleet dir
        # attached, a replica that has had no open connections for
        # tune_idle_s claims ONE probe lease at a time from it —
        # serving traffic always preempts the next claim.
        self.tune_dir = tune_dir
        self.tune_idle_s = float(tune_idle_s)
        self._tune_thread: threading.Thread | None = None
        self._registration = None
        self._tel = engine._tel
        # Request tracing (ISSUE 20): with telemetry wired, this
        # frontend is a trace ingress — it honors an upstream (router)
        # wire context or mints its own, head-sampled at trace_sample
        # (default: everything when a trace dir is configured, nothing
        # otherwise — rate 0 keeps the request/answer bytes identical).
        self.trace_sample = (
            float(trace_sample) if trace_sample is not None
            else (1.0 if self._tel else 0.0)
        )
        self._tracker = engine.slo_tracker()
        self._inflight = threading.Semaphore(self.max_inflight)
        self._client_lock = threading.Lock()
        self._client_slots: dict[str, threading.Semaphore] = {}
        self._stats_lock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._conns: dict[socket.socket, threading.Thread] = {}
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._shutdown_requested = threading.Event()
        self.shed_active = False
        self.address: tuple[str, int] | None = None
        # Priced shedding (ISSUE 17): the degrade tier's query mode,
        # resolved lazily at the first shed (the cost model fit reads
        # the profile store once) and cached with its why-line.
        self._shed_mode_cached: tuple[str, str] | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeFrontend":
        if self._listener is not None:
            return self
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(128)
        self._listener = ls
        self.address = ls.getsockname()[:2]
        # Pre-register the overload instruments so every snapshot a
        # socket-serving process publishes carries them — a post-mortem
        # must distinguish "zero shedding happened" (counter at 0) from
        # "this was never a traffic front end" (counter absent).
        for name in ("pjtpu_shed_answers", "pjtpu_rejected",
                     "pjtpu_deadline_drops", "pjtpu_slo_shed_transitions",
                     "pjtpu_client_limited"):
            self.engine.metrics.counter(name)
        self._publish_open(0)
        # Store-backed engines publish the live-metrics snapshot beside
        # serve_stats.json (both atomic): a SIGKILLed frontend leaves
        # both readable, fresh to within one interval.
        if self.engine.store.ckpt is not None and self.engine.stats_interval_s:
            self.engine.metrics.start_snapshotter(
                self.engine.store.ckpt.dir / SERVE_LIVE_FILENAME,
                interval_s=self.engine.stats_interval_s,
            )
        # Fleet membership: heartbeat the bound address + live metrics
        # into the fleet dir so routers/top/slo_report see this replica.
        if self.fleet_dir is not None:
            from paralleljohnson_tpu.serve.fleet import ReplicaRegistration

            self._registration = ReplicaRegistration(
                self.fleet_dir, self.replica_id,
                host=self.address[0], port=self.address[1],
                graph_digest=self.engine.store.digest,
                interval_s=self.fleet_heartbeat_s,
                payload_fn=self._fleet_payload,
            ).start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pj-serve-accept", daemon=True
        )
        self._accept_thread.start()
        if self.tune_dir is not None:
            self._tune_thread = threading.Thread(
                target=self._tune_loop, name="pj-serve-tuner", daemon=True
            )
            self._tune_thread.start()
        self._tel.event("serve_listen", host=self.address[0],
                        port=self.address[1], protocol=PROTOCOL,
                        max_connections=self.max_connections,
                        max_inflight=self.max_inflight,
                        shed_policy=self.shed_policy)
        return self

    def _tune_loop(self) -> None:
        """Idle-capacity farm (ISSUE 19): while the replica is serving
        nothing, drain one tuning lease at a time from ``tune_dir``.
        One-lease-at-a-time keeps preemption latency at one probe
        budget; probes run in this daemon thread under their own hard
        wall-clock caps, and results only become real when the
        coordinator commit lands (the digest-guarded manifest idiom) —
        a replica killed mid-probe leaks nothing into the store."""
        from paralleljohnson_tpu.tuner import try_tuning_lease

        idle_since: float | None = None
        while not self._draining.is_set():
            with self._conn_lock:
                busy = bool(self._conns)
            if busy:
                idle_since = None
                self._draining.wait(self.tune_idle_s)
                continue
            now = time.monotonic()
            if idle_since is None:
                idle_since = now
            if now - idle_since < self.tune_idle_s:
                self._draining.wait(
                    max(0.05, self.tune_idle_s - (now - idle_since))
                )
                continue
            try:
                res = try_tuning_lease(
                    self.tune_dir, f"serve-{self.replica_id}"
                )
            except Exception:  # noqa: BLE001 — tuning must never kill serving
                res = None
            if res is None:
                self._draining.wait(self.tune_idle_s)
            else:
                self._tel.event(
                    "tuning_lease", replica=self.replica_id,
                    lease=res["lease"], probes=len(res["probes"]),
                )

    def _fleet_payload(self) -> dict:
        """Merged into every membership heartbeat: serve counters + a
        full live-metrics snapshot, so the fleet dir alone feeds the
        top/slo_report fleet view (histograms merge by construction)."""
        return {
            "protocol": PROTOCOL,
            "http": self.http,
            "shed_policy": self.shed_policy,
            "stats": self.engine.stats.as_dict(),
            "live": self.engine.metrics.snapshot(),
        }

    def run_until_shutdown(self, *, install_signal_handlers: bool = True) -> int:
        """Block until SIGTERM/SIGINT (or :meth:`request_shutdown`),
        then drain and return 0 — the CLI's foreground loop. The signal
        handler only sets an event; the drain itself runs here, on the
        main thread, under the drain deadline."""
        import signal

        self.start()
        if install_signal_handlers:
            handler = lambda signum, frame: self._shutdown_requested.set()  # noqa: E731
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        self._shutdown_requested.wait()
        self.drain()
        return 0

    def request_shutdown(self) -> None:
        self._shutdown_requested.set()

    def drain(self) -> None:
        """SIGTERM semantics: stop accepting, finish in-flight requests
        under the drain deadline, force-close stragglers, flush the
        final stats + metrics snapshots. Idempotent."""
        if self._draining.is_set():
            self._stopped.wait(self.drain_timeout_s + 5.0)
            return
        self._draining.set()
        # Leave the fleet first: removing the membership record stops
        # routers sending NEW traffic here while in-flight work finishes.
        if self._registration is not None:
            self._registration.stop(deregister=True)
        self._tel.event("serve_drain", open_connections=len(self._conns),
                        drain_timeout_s=self.drain_timeout_s)
        ls = self._listener
        if ls is not None:
            # shutdown() before close(): a close alone does not wake a
            # thread blocked in accept() on Linux — the shutdown does,
            # and new connects get an immediate refusal.
            try:
                ls.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                ls.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        # Half-close every connection's read side: the handler finishes
        # the request it is processing (its write side still works),
        # then sees EOF and exits — buffered-but-unread requests are
        # dropped, which is what "stop accepting work" means.
        with self._conn_lock:
            conns = dict(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        deadline = time.monotonic() + self.drain_timeout_s
        for thread in conns.values():
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
        # Past the deadline: force-close whatever is left (a wedged
        # in-flight request must not hold the drain hostage).
        with self._conn_lock:
            stragglers = dict(self._conns)
        for sock, thread in stragglers.items():
            try:
                sock.close()
            except OSError:
                pass
            thread.join(timeout=1.0)
        self.engine.metrics.stop_snapshotter(final_write=True)
        write_final_snapshot(self.engine)  # even if no snapshotter ran
        self.engine.close()  # idempotent; flushes serve_stats.json
        self._stopped.set()

    # -- accept path ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._draining.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed: drain started
            try:
                active = (self.fault_plan.fire("serve_accept")
                          if self.fault_plan is not None else None)
                if active is not None:
                    try:
                        active.wrap(lambda: None)()
                    except Exception as e:  # noqa: BLE001 — injected
                        self._send_line(sock, {
                            "error": "unavailable",
                            "detail": f"injected: {type(e).__name__}",
                            "retry_after_ms": self.retry_after_ms,
                        })
                        sock.close()
                        continue
                if self._draining.is_set():
                    sock.close()
                    return
                with self._conn_lock:
                    at_capacity = len(self._conns) >= self.max_connections
                if at_capacity:
                    self._count_rejection()
                    self._send_line(sock, {
                        "error": "overloaded",
                        "reason": "max_connections",
                        "retry_after_ms": self.retry_after_ms,
                    })
                    sock.close()
                    continue
                thread = threading.Thread(
                    target=self._handle_connection, args=(sock,),
                    name=f"pj-serve-conn-{addr[1]}", daemon=True,
                )
                with self._conn_lock:
                    self._conns[sock] = thread
                    n_open = len(self._conns)
                self._publish_open(n_open)
                thread.start()
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass

    def _publish_open(self, n_open: int) -> None:
        self.engine.stats.open_connections = n_open
        self.engine.metrics.gauge("pjtpu_open_connections", n_open)

    # -- per-connection path -------------------------------------------------

    def _send_line(self, sock: socket.socket, obj: dict) -> bool:
        try:
            sock.sendall((json.dumps(obj) + "\n").encode("utf-8"))
            return True
        except OSError:
            return False

    _HTTP_METHODS = (b"GET ", b"POST ", b"HEAD ", b"PUT ", b"DELETE ",
                     b"OPTIONS ", b"PATCH ")

    def _sniff_http(self, sock: socket.socket) -> bool:
        """Classify one accepted connection in ``--http`` mode. HTTP
        clients talk first (a method token within milliseconds);
        ``pjtpu-serve/1`` clients — the fleet router's forwards
        included — wait for the server header line. So: peek briefly,
        and anything that is not an HTTP request line (including
        silence) falls back to the line protocol. An ``--http`` replica
        therefore still serves routed fleet traffic."""
        try:
            sock.settimeout(0.25)
            first = sock.recv(8, socket.MSG_PEEK)
        except (TimeoutError, socket.timeout, OSError):
            return False
        finally:
            try:
                sock.settimeout(None)
            except OSError:
                pass
        return any(first.startswith(m[: len(first)]) and first
                   for m in self._HTTP_METHODS)

    def _handle_connection(self, sock: socket.socket) -> None:
        try:
            try:
                peer = sock.getpeername()[0]
            except OSError:
                peer = None
            if self.http and self._sniff_http(sock):
                self._serve_http(sock, peer)
                return
            self._send_line(sock, {
                "protocol": PROTOCOL,
                "graph_digest": self.engine.store.digest,
                "shed_policy": self.shed_policy,
                "max_inflight": self.max_inflight,
                "replica_id": self.replica_id,
            })
            reader = sock.makefile("r", encoding="utf-8", newline="\n")
            for line in reader:
                if not line.strip():
                    continue
                self._handle_request(sock, line, peer)
        except (OSError, ValueError):
            pass  # client went away / socket force-closed mid-drain
        finally:
            with self._conn_lock:
                self._conns.pop(sock, None)
                n_open = len(self._conns)
            self._publish_open(n_open)
            try:
                sock.close()
            except OSError:
                pass

    def _count_rejection(self, *, deadline: bool = False) -> None:
        with self._stats_lock:
            if deadline:
                self.engine.stats.deadline_drops += 1
            else:
                self.engine.stats.rejected += 1
        name = "pjtpu_deadline_drops" if deadline else "pjtpu_rejected"
        self.engine.metrics.counter(name).add(1)
        # Rejections and deadline drops spend real error budget: they
        # are the availability signal the burn-rate alert (and thus the
        # shedding trigger) keys off under overload.
        self.engine.metrics.observe_slo(self.engine.slo.name, None, ok=False)

    def _shed_now(self) -> bool:
        """Current shedding verdict + transition bookkeeping. The
        tracker's ``burning`` flips inside ``observe_slo`` (every
        answered/rejected request updates it), so this read is cheap."""
        if self.shed_policy == "off":
            return False
        burning = self._tracker.burning
        if burning and self.shed_min_events:
            # Low-traffic guard: the burn verdict must be backed by
            # real volume inside the rule's long window before the
            # front end starts degrading answers over it.
            t = self._tracker
            window = min(long_w for long_w, _, _ in t.slo.rules)
            n = t.good.count_in(window) + t.bad.count_in(window)
            if n < self.shed_min_events:
                burning = False
        if burning != self.shed_active:
            with self._stats_lock:
                flipped = burning != self.shed_active
                if flipped:
                    self.shed_active = burning
            if flipped:
                stats = self.engine.stats
                self._tel.event(
                    "slo_shed", engaged=burning, slo=self.engine.slo.name,
                    policy=self.shed_policy,
                    burn_rate=self._tracker.evaluate()["burn_rate"],
                    shed_answers=stats.shed_answers,
                    rejected=stats.rejected,
                )
                self.engine.metrics.counter("pjtpu_slo_shed_transitions").add(1)
        return self.shed_active

    def _shed_mode(self) -> str:
        """What a shed exact-miss degrades to — the chosen
        :data:`SHED_PLANS` entry's mode (``"hopset"`` / ``"approx"`` /
        ``"reject"``). Every policy goes through the same
        ``planner.select`` walk: explicit policies are forced pins,
        ``"priced"`` fits the profile store's CostModel and promotes
        the cheaper certified tier only when BOTH are priced beyond the
        planner noise band (same gate as kernel dispatch), unpriced
        falls back to declared tier order (hopset first — its composed
        interval is at least as tight as the landmark one by
        construction). Resolved once per process and cached with the
        full decision record (``health()`` reports it)."""
        if self._shed_mode_cached is not None:
            return self._shed_mode_cached[0]
        engine = self.engine
        model = None
        if self.shed_policy == "priced":
            try:
                from paralleljohnson_tpu.observe.costs import (
                    resolve_profile_dir,
                )
                from paralleljohnson_tpu.observe.store import (
                    CostModel,
                    ProfileStore,
                )

                store_dir = resolve_profile_dir(
                    getattr(engine.config, "profile_store", None)
                )
                if store_dir:
                    model = CostModel.fit(ProfileStore(store_dir))
            except Exception:  # noqa: BLE001 — pricing must never block a shed
                model = None
        try:
            from paralleljohnson_tpu.observe import current_platform

            platform = current_platform()
        except Exception:  # noqa: BLE001
            platform = "unknown"
        decision = _planner.select(
            SHED_PLANS,
            types.SimpleNamespace(engine=engine, params={}),
            model=model,
            platform=platform,
            num_edges=int(getattr(engine.graph, "num_edges", 0) or 0),
            batch=1,
            config=types.SimpleNamespace(shed_policy=self.shed_policy),
        )
        mode = _SHED_MODES[decision.chosen.plan.name]
        self._shed_mode_cached = (mode, decision.reason, decision.as_dict())
        return mode

    def health(self) -> dict:
        """The liveness document (``{"op": "health"}``): admission
        gauges, shedding state, and — when a solve heartbeat file is
        configured — its freshness verdict. A torn/partial heartbeat
        (mid-rewrite kill) degrades to ``fresh: false`` + an error tag,
        never an exception (the reader-must-degrade rule)."""
        from paralleljohnson_tpu.utils.telemetry import (
            heartbeat_fresh,
            read_heartbeat,
        )

        stats = self.engine.stats
        doc = {
            "ok": not self._draining.is_set(),
            "protocol": PROTOCOL,
            "replica_id": self.replica_id,
            "draining": self._draining.is_set(),
            "shedding": self.shed_active,
            "shed_policy": self.shed_policy,
            "shed_tier": (
                None if self._shed_mode_cached is None
                else {"mode": self._shed_mode_cached[0],
                      "reason": self._shed_mode_cached[1],
                      "plan": self._shed_mode_cached[2]}
            ),
            "open_connections": stats.open_connections,
            "max_connections": self.max_connections,
            "max_inflight": self.max_inflight,
            "batch_window": self.batch_window,
            "batch_wait_ms": self.batch_wait_ms,
            "queries_total": stats.queries_total,
            "shed_answers": stats.shed_answers,
            "rejected": stats.rejected,
            "deadline_drops": stats.deadline_drops,
        }
        if self.heartbeat_file:
            hb: dict = {
                "path": str(self.heartbeat_file),
                "fresh": heartbeat_fresh(self.heartbeat_file,
                                         self.heartbeat_stale_s),
            }
            try:
                beat = read_heartbeat(self.heartbeat_file)
                hb["ts"] = None if beat is None else beat.get("ts")
            except ValueError:
                hb["error"] = "torn or partial heartbeat file"
            doc["heartbeat"] = hb
        return doc

    def _handle_request(self, sock: socket.socket, line: str,
                        peer: str | None = None) -> None:
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("not a JSON object")
        except ValueError as e:
            self.engine.note_failed_requests(1)
            self._send_line(sock, {"error": f"bad request line: {e}"})
            return
        self._send_line(sock, self._process_request(req, peer))

    def _client_key(self, req: dict, peer: str | None) -> str:
        """Fairness identity: the request's ``client_id`` when the
        client declares one, else the peer address — so an undeclared
        hog is still one key, not anonymous."""
        cid = req.get("client_id")
        if cid is not None:
            return f"id:{cid}"
        return f"peer:{peer}" if peer else "peer:?"

    def _client_slot(self, key: str) -> threading.Semaphore:
        with self._client_lock:
            sem = self._client_slots.get(key)
            if sem is None:
                sem = threading.Semaphore(self.max_inflight_per_client)
                self._client_slots[key] = sem
            return sem

    def _count_client_limited(self) -> None:
        with self._stats_lock:
            self.engine.stats.client_limited += 1
        self.engine.metrics.counter("pjtpu_client_limited").add(1)
        # A fairness rejection spends error budget like any other
        # rejection — the hog's requests are still failed requests.
        self.engine.metrics.observe_slo(self.engine.slo.name, None, ok=False)

    def _process_request(self, req: dict, peer: str | None = None) -> dict:
        """Admission + answer for one parsed request object; always
        returns a response document, never raises. Shared by the JSONL
        socket path and the HTTP adaptation — one admission policy,
        two framings.

        Trace ingress (ISSUE 20): an upstream wire context
        (``req["trace"]``) is honored — its head-sampling decision is
        final — else one is minted at ``trace_sample``. A sampled
        request runs inside a ``serve_request`` span (``wire_parent``
        carries the router's forward-span ref for the assembler) with
        the context installed for downstream hops (convoy, engine,
        scheduled solves), and its response is stamped with
        ``trace_id``. With the rate at 0 and no wire context, this
        method IS the pre-trace code path: nothing minted, responses
        byte-identical."""
        if req.get("op") == "health":
            return {"id": req.get("id"), **self.health()}
        ctx = None
        if self.trace_sample > 0.0 or _trace.WIRE_KEY in req:
            ctx = _trace.ingress(
                req, rate=self.trace_sample if self._tel else 0.0
            )
        if ctx is None or not ctx.sampled:
            return self._admit(req, peer)
        tel = self._tel
        if not tel:
            # Untraced replica behind a traced router: echo the id so
            # the answer still joins its (router-side) timeline.
            resp = self._admit(req, peer)
            if isinstance(resp, dict):
                resp.setdefault(_trace.RESPONSE_KEY, ctx.trace_id)
            return resp
        if req.get(_trace.WIRE_KEY) is None:
            # Minted here: let the engine's per-query spans see the id.
            req[_trace.WIRE_KEY] = {"id": ctx.trace_id}
        attrs = {"trace": ctx.trace_id, "source": req.get("source")}
        if ctx.parent:
            attrs["wire_parent"] = ctx.parent
        with tel.span("serve_request", **attrs), _trace.use_trace(ctx):
            resp = self._admit(req, peer)
            if isinstance(resp, dict):
                if resp.get("error") is not None:
                    tel.event("request_error", error=str(resp["error"]))
                resp.setdefault(_trace.RESPONSE_KEY, ctx.trace_id)
            return resp

    def _admit(self, req: dict, peer: str | None = None) -> dict:
        req_id = req.get("id")
        if self._draining.is_set():
            self._count_rejection()
            return {"id": req_id, "error": "draining",
                    "retry_after_ms": self.retry_after_ms}
        arrival = time.perf_counter()
        deadline_ms = req.pop("deadline_ms", None)
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                self.engine.note_failed_requests(1)
                return {"id": req_id,
                        "error": f"bad deadline_ms {deadline_ms!r}"}

        # Per-client fairness: the hog is rejected at ITS cap with an
        # explicit client_limited flag while other clients' requests
        # keep reaching the global semaphore below — one flooding
        # client can no longer occupy every in-flight slot.
        client_sem = None
        if self.max_inflight_per_client is not None:
            client_sem = self._client_slot(self._client_key(req, peer))
            if not client_sem.acquire(blocking=False):
                self._count_client_limited()
                return {"id": req_id, "error": "overloaded",
                        "reason": "max_inflight_per_client",
                        "client_limited": True,
                        "retry_after_ms": self.retry_after_ms}
        try:
            # Admission: a free in-flight slot or an explicit answer — a
            # deadline-carrying request may wait for a slot up to its own
            # patience (the bounded queue IS the deadline), everyone else
            # is rejected immediately rather than queued.
            acquired = self._inflight.acquire(blocking=False)
            if not acquired and deadline_ms is not None:
                remaining = (deadline_ms / 1e3
                             - (time.perf_counter() - arrival))
                if remaining > 0:
                    acquired = self._inflight.acquire(timeout=remaining)
            if not acquired:
                if deadline_ms is not None:
                    self._count_rejection(deadline=True)
                    return {"id": req_id, "error": "deadline",
                            "deadline_ms": deadline_ms,
                            "waited_ms": round(
                                (time.perf_counter() - arrival) * 1e3, 3)}
                self._count_rejection()
                return {"id": req_id, "error": "overloaded",
                        "reason": "max_inflight",
                        "retry_after_ms": self.retry_after_ms}
            try:
                # The slot may have freed exactly at the deadline:
                # re-check before the engine sees the request.
                if deadline_ms is not None and (
                        (time.perf_counter() - arrival) * 1e3 > deadline_ms):
                    self._count_rejection(deadline=True)
                    return {"id": req_id, "error": "deadline",
                            "deadline_ms": deadline_ms,
                            "waited_ms": round(
                                (time.perf_counter() - arrival) * 1e3, 3)}
                return self._answer_doc(req)
            finally:
                self._inflight.release()
        finally:
            if client_sem is not None:
                client_sem.release()

    def _answer_doc(self, req: dict) -> dict:
        engine = self.engine
        req_id = req.get("id")
        shed = False
        mode = req.get("mode", engine.miss_policy)
        if mode in ("exact", "solve") and self._shed_now():
            src = req.get("source")
            is_hit = False
            try:
                is_hit = int(src) in engine.store
            except (TypeError, ValueError):
                pass  # malformed: the engine's parser owns the error
            if not is_hit:
                shed_to = self._shed_mode()
                tel = self._tel
                tid = _trace.current_trace_id() if tel else None
                if tid:
                    # The shed decision as a first-class span (ISSUE 20
                    # satellite): the chaos drill asserts a shed answer
                    # reconstructs with this decision point visible.
                    tel.finish_span(tel.begin_span(
                        "shed_decision", trace=tid,
                        policy=self.shed_policy, mode=shed_to,
                    ))
                if shed_to == "reject":
                    self._count_rejection()
                    return {"id": req_id, "error": "overloaded",
                            "reason": "shedding", "shed": True,
                            "retry_after_ms": self.retry_after_ms}
                # Certified degrade: the landmark/hopset answer is
                # flagged exact=false AND shed=true, and carries
                # max_error — never an unflagged approximation. The
                # tier is the SHED_PLANS decision's (priced under
                # "priced", forced pin otherwise).
                req = {**req, "mode": shed_to}
                shed = True
        try:
            if self.batcher is not None:
                resp = self.batcher.submit(req)
            else:
                resp = engine.query_batch([req])[0]
        except QueryError as e:
            resp = {"id": req_id, "error": str(e)}
        except Exception as e:  # noqa: BLE001 — a solve/store failure
            # must become an error RESPONSE, not a dead connection.
            engine.note_failed_requests(1)
            resp = {"id": req_id,
                    "error": f"internal: {type(e).__name__}: {e}"}
        if shed and "error" not in resp:
            resp["shed"] = True
            with self._stats_lock:
                engine.stats.shed_answers += 1
            engine.metrics.counter("pjtpu_shed_answers").add(1)
        return resp

    # -- HTTP/1.1 adaptation (ISSUE 18) --------------------------------------

    _HTTP_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                     429: "Too Many Requests",
                     500: "Internal Server Error",
                     503: "Service Unavailable", 504: "Gateway Timeout"}

    @staticmethod
    def _http_status_for(resp: dict) -> int:
        err = resp.get("error")
        if err is None:
            return 200
        if err == "overloaded":
            return 429
        if err == "draining":
            return 503
        if err == "deadline":
            return 504
        if str(err).startswith("internal"):
            return 500
        return 400

    def _send_http(self, sock: socket.socket, status: int, doc: dict,
                   *, extra_headers: tuple = ()) -> None:
        body = (json.dumps(doc) + "\n").encode("utf-8")
        head = [f"HTTP/1.1 {status} {self._HTTP_REASONS.get(status, 'OK')}",
                "Content-Type: application/json",
                f"Content-Length: {len(body)}"]
        head.extend(extra_headers)
        sock.sendall(("\r\n".join(head) + "\r\n\r\n").encode("ascii") + body)

    def _serve_http(self, sock: socket.socket, peer: str | None) -> None:
        """Minimal HTTP/1.1 framing over the same admission path, for
        commodity load balancers: ``POST /query`` carries one protocol
        line as its JSON body and returns the same answer document;
        ``GET /healthz`` maps the health op to 200/503 by the solve
        heartbeat's freshness. Overload answers 429 + ``Retry-After``.
        Stdlib request-line + header parsing; keep-alive until the
        client closes or sends ``Connection: close``."""
        reader = sock.makefile("rb")
        while True:
            reqline = reader.readline(8192)
            if not reqline or not reqline.strip():
                return
            try:
                method, path, _version = (
                    reqline.decode("ascii").split(None, 2))
            except (UnicodeDecodeError, ValueError):
                self._send_http(sock, 400, {"error": "bad request line"})
                return
            headers: dict[str, str] = {}
            while True:
                hline = reader.readline(8192)
                if not hline or hline in (b"\r\n", b"\n"):
                    break
                if b":" in hline:
                    k, v = hline.split(b":", 1)
                    headers[k.strip().lower().decode("latin-1")] = (
                        v.strip().decode("latin-1"))
            try:
                length = int(headers.get("content-length", "0"))
            except ValueError:
                self._send_http(sock, 400, {"error": "bad content-length"})
                return
            body = reader.read(length) if length > 0 else b""
            if len(body) < length:
                return  # truncated body: client went away mid-request
            method = method.upper()
            if method == "GET" and path in ("/healthz", "/health"):
                doc = self.health()
                hb = doc.get("heartbeat")
                ok = doc["ok"] and (hb is None or hb.get("fresh", False))
                self._send_http(sock, 200 if ok else 503, doc)
            elif method == "POST" and path == "/query":
                try:
                    req = json.loads(body.decode("utf-8"))
                    if not isinstance(req, dict):
                        raise ValueError("body must be a JSON object")
                except (UnicodeDecodeError, ValueError) as e:
                    self.engine.note_failed_requests(1)
                    self._send_http(sock, 400,
                                    {"error": f"bad request line: {e}"})
                else:
                    resp = self._process_request(req, peer)
                    status = self._http_status_for(resp)
                    extra = []
                    retry_ms = resp.get("retry_after_ms")
                    if status in (429, 503) and retry_ms is not None:
                        secs = max(1, (int(retry_ms) + 999) // 1000)
                        extra.append(f"Retry-After: {secs}")
                    self._send_http(sock, status, resp,
                                    extra_headers=tuple(extra))
            else:
                self._send_http(sock, 404,
                                {"error": f"no route {method} {path}"})
            if headers.get("connection", "").lower() == "close":
                return


def write_final_snapshot(engine) -> None:
    """One last atomic serve_live.json beside the store (used by the
    CLI after a drain when the periodic snapshotter never started —
    e.g. an in-memory store that grew a checkpoint mid-serve)."""
    if engine.store.ckpt is None:
        return
    try:
        engine.metrics.write_snapshot(
            engine.store.ckpt.dir / SERVE_LIVE_FILENAME
        )
    except OSError:
        pass
