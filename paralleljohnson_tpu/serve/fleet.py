"""Serve-fleet membership and consistent-hash routing (stdlib-only).

Replicas are ordinary :class:`~paralleljohnson_tpu.serve.frontend.ServeFrontend`
processes; there is no fleet server. Membership reuses the round-15
coordinator idiom: each replica atomically rewrites a heartbeat record at
``<fleet>/serve/replicas/<id>.json`` on the heartbeat clock, and readers
eject records stale by age. The routing table (``<fleet>/serve/routing.json``)
consistent-hashes sources to replicas with virtual nodes and is published
atomically with a monotonic epoch counter, so hot tiers partition across
the fleet instead of duplicating.

Ownership is a cache-locality hint, never a correctness boundary: any
replica can answer any source (a misrouted query is only colder). Torn or
absent files degrade readers (``None`` / flagged records) — they never
raise out of this module.

This module deliberately imports nothing from the package so that
standalone tools (``scripts/slo_report.py`` loads ``observe/live.py`` the
same way) can ``importlib``-load it without jax/numpy present.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Callable

REPLICAS_DIRNAME = "serve/replicas"
ROUTING_FILENAME = "serve/routing.json"

DEFAULT_VNODES = 64
DEFAULT_HEARTBEAT_INTERVAL_S = 1.0
#: A replica whose record is older than this is ejected from the live set.
#: Chosen as several heartbeat intervals so one slow beat does not flap.
DEFAULT_REPLICA_STALE_S = 5.0


def replicas_dir(fleet_dir: str | os.PathLike) -> Path:
    return Path(fleet_dir) / REPLICAS_DIRNAME


def routing_path(fleet_dir: str | os.PathLike) -> Path:
    return Path(fleet_dir) / ROUTING_FILENAME


def _atomic_write_json(path: Path, payload: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
    tmp.write_text(json.dumps(payload, sort_keys=True), encoding="utf-8")
    os.replace(tmp, path)


def _read_json(path: Path) -> dict | None:
    """Tolerant read: absent/torn/non-dict files are ``None``, never an error."""
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _hash64(key: str) -> int:
    """Stable 64-bit hash. Python's ``hash()`` is salted per process and
    must never decide ring placement — two processes would disagree."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


# ---------------------------------------------------------------------------
# membership


class ReplicaRegistration:
    """Heartbeated membership record for one serve replica.

    Atomically rewrites ``<fleet>/serve/replicas/<id>.json`` every
    ``interval_s`` seconds from a daemon thread. ``payload_fn`` (if given)
    is called on every beat and its dict is merged into the record — the
    frontend uses it to embed live metrics + serve counters so the fleet
    dir is a self-contained observability surface. A failing payload_fn
    degrades to a bare liveness record; it never kills the heartbeat.
    """

    def __init__(
        self,
        fleet_dir: str | os.PathLike,
        replica_id: str,
        *,
        host: str,
        port: int,
        graph_digest: str | None = None,
        interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
        payload_fn: Callable[[], dict] | None = None,
    ) -> None:
        self.fleet_dir = Path(fleet_dir)
        self.replica_id = str(replica_id)
        self.host = host
        self.port = int(port)
        self.graph_digest = graph_digest
        self.interval_s = max(0.05, float(interval_s))
        self.payload_fn = payload_fn
        self.path = replicas_dir(self.fleet_dir) / f"{self.replica_id}.json"
        self.started_ts: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def record(self) -> dict:
        rec = {
            "kind": "serve_replica",
            "replica_id": self.replica_id,
            "host": self.host,
            "port": self.port,
            "pid": os.getpid(),
            "graph_digest": self.graph_digest,
            "started_ts": self.started_ts,
            "heartbeat_interval_s": self.interval_s,
            "ts": time.time(),
        }
        if self.payload_fn is not None:
            try:
                extra = self.payload_fn()
                if isinstance(extra, dict):
                    rec.update(extra)
            except Exception:
                pass  # liveness beats must outlive a broken payload
        return rec

    def beat(self) -> None:
        _atomic_write_json(self.path, self.record())

    def start(self) -> "ReplicaRegistration":
        if self._thread is not None:
            return self
        self.started_ts = time.time()
        self.beat()
        self._thread = threading.Thread(
            target=self._loop, name=f"replica-heartbeat-{self.replica_id}", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError:
                pass  # fleet dir unwritable this beat; stale-by-age handles it

    def stop(self, *, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if deregister:
            try:
                self.path.unlink()
            except OSError:
                pass


def read_replicas(
    fleet_dir: str | os.PathLike,
    *,
    stale_after_s: float = DEFAULT_REPLICA_STALE_S,
    now: float | None = None,
) -> list[dict]:
    """All membership records, each age-stamped and ``stale``-flagged.

    Torn records come back as ``{"replica_id": <stem>, "torn": True,
    "stale": True}`` so surfaces can show the corpse instead of crashing.
    """
    if now is None:
        now = time.time()
    out: list[dict] = []
    rdir = replicas_dir(fleet_dir)
    try:
        paths = sorted(p for p in rdir.iterdir() if p.suffix == ".json")
    except OSError:
        return out
    for path in paths:
        rec = _read_json(path)
        if rec is None:
            out.append({"replica_id": path.stem, "torn": True, "ts": None,
                        "age_s": None, "stale": True})
            continue
        rec.setdefault("replica_id", path.stem)
        ts = rec.get("ts")
        age = (now - ts) if isinstance(ts, (int, float)) else None
        rec["age_s"] = round(age, 3) if age is not None else None
        rec["stale"] = age is None or age > stale_after_s
        out.append(rec)
    return out


def live_replicas(
    fleet_dir: str | os.PathLike,
    *,
    stale_after_s: float = DEFAULT_REPLICA_STALE_S,
    now: float | None = None,
) -> list[dict]:
    """Fresh, addressable membership records only (stale-by-age ejected)."""
    return [
        r
        for r in read_replicas(fleet_dir, stale_after_s=stale_after_s, now=now)
        if not r["stale"] and isinstance(r.get("port"), int)
    ]


# ---------------------------------------------------------------------------
# routing


class RoutingTable:
    """Consistent-hash ring: sources -> replica ids, with virtual nodes.

    Each replica contributes ``vnodes`` points at
    ``_hash64(f"{rid}#{i}")``; a source lands on the first ring point at or
    after ``_hash64(str(source))``. Removing one of N replicas therefore
    re-homes only the sources whose successor point belonged to it
    (~1/N of them) — everything else keeps its owner.
    """

    def __init__(
        self,
        replicas: dict[str, dict],
        *,
        vnodes: int = DEFAULT_VNODES,
        epoch: int = 0,
    ) -> None:
        self.replicas = {str(k): dict(v) for k, v in replicas.items()}
        self.vnodes = int(vnodes)
        self.epoch = int(epoch)
        points: list[tuple[int, str]] = []
        for rid in self.replicas:
            for i in range(self.vnodes):
                points.append((_hash64(f"{rid}#{i}"), rid))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [r for _, r in points]

    def owner(self, source: object) -> str | None:
        if not self._points:
            return None
        h = _hash64(str(source))
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._points):
            idx = 0  # wrap around the ring
        return self._owners[idx]

    def address(self, replica_id: str) -> tuple[str, int] | None:
        rec = self.replicas.get(replica_id)
        if rec is None:
            return None
        host, port = rec.get("host"), rec.get("port")
        if not isinstance(port, int):
            return None
        return str(host), port

    def as_dict(self) -> dict:
        return {
            "kind": "serve_routing",
            "epoch": self.epoch,
            "vnodes": self.vnodes,
            "ts": time.time(),
            "replicas": self.replicas,
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "RoutingTable":
        return cls(
            doc.get("replicas") or {},
            vnodes=int(doc.get("vnodes") or DEFAULT_VNODES),
            epoch=int(doc.get("epoch") or 0),
        )


def read_routing(fleet_dir: str | os.PathLike) -> RoutingTable | None:
    """Read the published table; absent/torn files are ``None``, never raise."""
    doc = _read_json(routing_path(fleet_dir))
    if doc is None:
        return None
    try:
        return RoutingTable.from_dict(doc)
    except (TypeError, ValueError):
        return None


def publish_routing(
    fleet_dir: str | os.PathLike,
    replicas: dict[str, dict] | list[dict],
    *,
    vnodes: int = DEFAULT_VNODES,
    min_epoch: int = 0,
) -> RoutingTable:
    """Atomically publish a new table with a strictly increasing epoch.

    ``replicas`` may be membership records (as from :func:`live_replicas`)
    or an ``id -> {host, port}`` mapping. The epoch is read-increment over
    the current file; pass ``min_epoch`` to stay ahead of a table observed
    elsewhere.
    """
    if isinstance(replicas, list):
        replicas = {
            r["replica_id"]: {"host": r.get("host"), "port": r.get("port")}
            for r in replicas
            if r.get("replica_id")
        }
    prev = read_routing(fleet_dir)
    epoch = max((prev.epoch if prev is not None else 0) + 1, int(min_epoch))
    table = RoutingTable(replicas, vnodes=vnodes, epoch=epoch)
    _atomic_write_json(routing_path(fleet_dir), table.as_dict())
    return table
