"""Thin stdlib router for a replicated serve fleet (``pjtpu serve --route``).

Forwards ``pjtpu-serve/1`` lines to the replica that owns each request's
source under the published consistent-hash table (:mod:`.fleet`). Replies
are forwarded **verbatim** — the router never rewrites an answer document,
so exactness/staleness flags survive byte-for-byte.

Failure handling is the whole point: on connection-refused / broken-pipe /
EOF from a replica (a SIGKILLed process presents all three) the router
ejects the corpse, re-publishes ``routing.json`` minus it (epoch bumped),
and retries the request on the new owner — bounded attempts, then an
explicit ``{"error": "unavailable", "retry_after_ms": ...}``. Replicas
whose heartbeat goes stale-by-age are ejected by the background refresh
even with no traffic aimed at them. Because any replica can serve any
source, failover can only make an answer colder, never wrong.

Request tracing (ISSUE 20): when constructed with ``telemetry`` the
router is the fleet's first ingress — it mints a ``trace_id`` per
request (head-sampled at ``trace_sample``), wraps routing in a
``route_request`` span and each upstream attempt in a ``forward`` span,
and injects the wire context (``{"trace": {"id", "parent"}}``) into the
forwarded line with the *forward span's* global ref as the replica's
parent — so a failover retry shows up in the assembled timeline as two
``forward`` hops (the first status=error) under one ``route_request``.
Replies remain verbatim: the REPLICA stamps ``trace_id`` into the
answer document, the router never rewrites it.
"""

from __future__ import annotations

import json
import signal
import socket
import threading
import time

from paralleljohnson_tpu.observe import trace as _trace
from paralleljohnson_tpu.serve import fleet as _fleet
from paralleljohnson_tpu.utils import telemetry as _telemetry

PROTOCOL = "pjtpu-serve/1"  # same wire protocol as serve.frontend

DEFAULT_RETRY_AFTER_MS = 100
DEFAULT_MAX_ATTEMPTS = 4
DEFAULT_REFRESH_INTERVAL_S = 0.5
DEFAULT_CONNECT_TIMEOUT_S = 2.0
DEFAULT_IO_TIMEOUT_S = 30.0


class _ReplicaDown(Exception):
    """One upstream replica refused/closed — eject and re-route."""


class FleetRouter:
    def __init__(
        self,
        fleet_dir,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        stale_after_s: float = _fleet.DEFAULT_REPLICA_STALE_S,
        vnodes: int = _fleet.DEFAULT_VNODES,
        retry_after_ms: int = DEFAULT_RETRY_AFTER_MS,
        max_attempts: int = DEFAULT_MAX_ATTEMPTS,
        connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
        io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
        refresh_interval_s: float = DEFAULT_REFRESH_INTERVAL_S,
        telemetry=None,
        trace_sample: float | None = None,
    ) -> None:
        self.fleet_dir = fleet_dir
        self._tel = _telemetry.resolve(telemetry)
        # Default sample rate: trace everything when telemetry is wired
        # (a trace dir was configured), nothing otherwise — the ISSUE 20
        # contract. The untraced path never parses/mints anything.
        self.trace_sample = (
            float(trace_sample) if trace_sample is not None
            else (1.0 if self._tel else 0.0)
        )
        self.host = host
        self.port = int(port)
        self.stale_after_s = float(stale_after_s)
        self.vnodes = int(vnodes)
        self.retry_after_ms = int(retry_after_ms)
        self.max_attempts = max(1, int(max_attempts))
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.refresh_interval_s = max(0.05, float(refresh_interval_s))
        self.graph_digest: str | None = None
        self.stats = {
            "connections": 0,
            "forwarded": 0,
            "retries": 0,
            "ejected": 0,
            "republished": 0,
            "unavailable": 0,
        }
        self._lock = threading.Lock()
        self._members: dict[str, dict] = {}
        # rid -> wall-clock of our forced eject; a record must heartbeat
        # AFTER this to be re-admitted (a fresh-looking corpse stays out).
        self._dead: dict[str, float] = {}
        self._table: _fleet.RoutingTable | None = None
        self._last_refresh = 0.0
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._refresh_thread: threading.Thread | None = None
        self._draining = threading.Event()
        self._stopped = threading.Event()

    # -- membership / table -------------------------------------------------

    def _refresh(self, *, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            if not force and now - self._last_refresh < self.refresh_interval_s:
                return
            self._last_refresh = now
            dead = dict(self._dead)
        live = _fleet.live_replicas(
            self.fleet_dir, stale_after_s=self.stale_after_s, now=now
        )
        members: dict[str, dict] = {}
        for rec in live:
            rid = rec["replica_id"]
            died_at = dead.get(rid)
            if died_at is not None and not (
                isinstance(rec.get("ts"), (int, float)) and rec["ts"] > died_at
            ):
                continue  # ejected corpse with a not-yet-stale record
            members[rid] = {"host": rec.get("host"), "port": rec.get("port")}
            if self.graph_digest is None and rec.get("graph_digest"):
                self.graph_digest = rec["graph_digest"]
        with self._lock:
            for rid in members:
                self._dead.pop(rid, None)
            if set(members) != set(self._members) or self._table is None:
                self._members = members
                self._table = _fleet.publish_routing(
                    self.fleet_dir, members, vnodes=self.vnodes
                )
                self.stats["republished"] += 1

    def _eject(self, replica_id: str) -> None:
        with self._lock:
            self._dead[replica_id] = time.time()
            if replica_id not in self._members:
                return
            del self._members[replica_id]
            self.stats["ejected"] += 1
            self._table = _fleet.publish_routing(
                self.fleet_dir, self._members, vnodes=self.vnodes
            )
            self.stats["republished"] += 1

    def _refresh_loop(self) -> None:
        while not self._stopped.wait(self.refresh_interval_s):
            try:
                self._refresh(force=True)
            except OSError:
                pass

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        self._refresh(force=True)
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self.port))
        listener.listen(128)
        self.host, self.port = listener.getsockname()[:2]
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-router-accept", daemon=True
        )
        self._accept_thread.start()
        self._refresh_thread = threading.Thread(
            target=self._refresh_loop, name="fleet-router-refresh", daemon=True
        )
        self._refresh_thread.start()
        return self

    def address(self) -> tuple[str, int]:
        return self.host, self.port

    @property
    def table(self) -> "_fleet.RoutingTable | None":
        with self._lock:
            return self._table

    def drain(self) -> None:
        if self._stopped.is_set():
            return
        self._draining.set()
        self._stopped.set()
        if self._listener is not None:
            # close() alone does not wake a thread blocked in accept()
            # on Linux — poke the listener so the accept loop observes
            # the drain flag instead of riding out the join timeout.
            try:
                poke_host = ("127.0.0.1" if self.host in ("", "0.0.0.0")
                             else self.host)
                with socket.create_connection(
                    (poke_host, self.port), timeout=0.5
                ):
                    pass
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        for t in (self._accept_thread, self._refresh_thread):
            if t is not None:
                t.join(timeout=2.0)

    def run_until_shutdown(self) -> None:
        def _sig(_signum, _frame):
            threading.Thread(target=self.drain, daemon=True).start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _sig)
            except ValueError:
                pass  # not the main thread
        self._stopped.wait()
        self.drain()

    # -- serving ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._draining.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                break
            with self._lock:
                self.stats["connections"] += 1
            threading.Thread(
                target=self._handle_connection, args=(sock,), daemon=True
            ).start()

    def health(self) -> dict:
        now = time.time()
        recs = _fleet.read_replicas(
            self.fleet_dir, stale_after_s=self.stale_after_s, now=now
        )
        with self._lock:
            epoch = self._table.epoch if self._table is not None else None
            live = len(self._members)
            stats = dict(self.stats)
        return {
            "ok": live > 0,
            "router": True,
            "listening": f"{self.host}:{self.port}",
            "epoch": epoch,
            "replicas_live": live,
            "replicas": {
                r["replica_id"]: {
                    "host": r.get("host"),
                    "port": r.get("port"),
                    "age_s": r.get("age_s"),
                    "stale": r.get("stale"),
                }
                for r in recs
            },
            "stats": stats,
        }

    def _header(self) -> dict:
        with self._lock:
            epoch = self._table.epoch if self._table is not None else None
            live = len(self._members)
        return {
            "protocol": PROTOCOL,
            "router": True,
            "graph_digest": self.graph_digest,
            "epoch": epoch,
            "replicas": live,
        }

    def _handle_connection(self, sock: socket.socket) -> None:
        upstreams: dict[str, tuple[socket.socket, object]] = {}
        try:
            sock.sendall((json.dumps(self._header()) + "\n").encode("utf-8"))
            reader = sock.makefile("r", encoding="utf-8", newline="\n")
            for line in reader:
                line = line.strip()
                if not line:
                    continue
                out = self._route_line(upstreams, line)
                if isinstance(out, dict):
                    out = json.dumps(out) + "\n"
                elif not out.endswith("\n"):
                    out += "\n"
                sock.sendall(out.encode("utf-8"))
        except OSError:
            pass
        finally:
            for up_sock, _rfile in upstreams.values():
                try:
                    up_sock.close()
                except OSError:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    def _route_line(self, upstreams, line: str):
        """One request line -> forwarded reply string or local error doc."""
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            return {"error": f"bad request line: {exc}"}
        if req.get("op") == "health":
            return self.health()
        tel = self._tel
        ctx = None
        if tel and self.trace_sample > 0.0:
            ctx = _trace.ingress(req, rate=self.trace_sample)
        if ctx is None:
            # Tracing off at this router: forward the line untouched (a
            # client-supplied wire context, if any, rides through to the
            # replica — bitwise-identical requests, the PR-5 guarantee).
            return self._forward(upstreams, req, line, None, None)
        if not ctx.sampled:
            # Head sampling declined this trace: downstream must not
            # re-mint, so the verdict still travels the wire — but no
            # spans open anywhere.
            if req.get(_trace.WIRE_KEY) is None:
                line = json.dumps({**req, _trace.WIRE_KEY: ctx.to_wire()})
            return self._forward(upstreams, req, line, None, None)
        span_attrs = {"trace": ctx.trace_id, "source": str(req.get("source"))}
        if ctx.parent:
            span_attrs["wire_parent"] = ctx.parent
        with tel.span("route_request", **span_attrs):
            return self._forward(upstreams, req, line, ctx, tel)

    def _forward(self, upstreams, req: dict, line: str, ctx, tel):
        """The bounded attempt loop. With a sampled ``ctx``, every
        attempt gets its own ``forward`` span whose global ref becomes
        the replica-side parent — the retry hop after a replica death
        is a first-class span (status=error), not a lost counter."""
        source_key = str(req.get("source"))
        for attempt in range(1, self.max_attempts + 1):
            self._refresh()
            with self._lock:
                table = self._table
            rid = table.owner(source_key) if table is not None else None
            if rid is None:
                break
            if ctx is not None:
                span_id = tel.begin_span(
                    "forward", replica=rid, attempt=attempt,
                    trace=ctx.trace_id,
                )
                wire = ctx.child(tel.global_ref(span_id)).to_wire()
                line_out = json.dumps({**req, _trace.WIRE_KEY: wire})
            else:
                span_id = None
                line_out = line
            try:
                reply = self._roundtrip(upstreams, table, rid, line_out)
            except _ReplicaDown:
                if span_id is not None:
                    tel.finish_span(span_id, "error", "replica_down")
                    tel.event("route_retry", trace=ctx.trace_id,
                              replica=rid, attempt=attempt)
                self._eject(rid)
                with self._lock:
                    self.stats["retries"] += 1
                continue
            if span_id is not None:
                tel.finish_span(span_id)
            with self._lock:
                self.stats["forwarded"] += 1
            return reply
        if ctx is not None:
            tel.event("route_unavailable", trace=ctx.trace_id)
        with self._lock:
            self.stats["unavailable"] += 1
        return {"error": "unavailable", "retry_after_ms": self.retry_after_ms}

    def _roundtrip(self, upstreams, table, rid: str, line: str) -> str:
        conn = upstreams.get(rid)
        if conn is None:
            addr = table.address(rid)
            if addr is None:
                raise _ReplicaDown(rid)
            try:
                up = socket.create_connection(addr, timeout=self.connect_timeout_s)
                up.settimeout(self.io_timeout_s)
                rfile = up.makefile("r", encoding="utf-8", newline="\n")
                if not rfile.readline():  # replica header; EOF = dead
                    raise OSError("no header from replica")
            except OSError as exc:
                raise _ReplicaDown(rid) from exc
            conn = (up, rfile)
            upstreams[rid] = conn
        up, rfile = conn
        try:
            up.sendall((line + "\n").encode("utf-8"))
            reply = rfile.readline()
        except OSError as exc:
            self._drop_upstream(upstreams, rid)
            raise _ReplicaDown(rid) from exc
        if not reply:
            self._drop_upstream(upstreams, rid)
            raise _ReplicaDown(rid)
        return reply

    @staticmethod
    def _drop_upstream(upstreams, rid: str) -> None:
        conn = upstreams.pop(rid, None)
        if conn is not None:
            try:
                conn[0].close()
            except OSError:
                pass
