"""Query engine: source-batched lookups over a :class:`TileStore`.

The serving front end (ROADMAP item 6): point-to-point and one-to-many
queries from many concurrent clients are AGGREGATED — one
:meth:`QueryEngine.query_batch` call resolves each distinct source row
once (hot/warm/cold tier walk), and every source that misses the store
is solved in ONE exact batch through the ordinary resilient solver
(``ParallelJohnsonSolver.solve`` — retries, watchdog deadlines, OOM
batch degradation, and the pipelined fan-out all apply; with a
checkpoint-backed store the new rows also land on disk, growing the
cold tier for the next process). Alternatively (``miss_policy=
"landmark"``) a miss answers immediately from the landmark index with a
certified ``(estimate, max_error)`` — never an unflagged approximation.

The exact-vs-approximate contract every response carries:

- ``exact: true`` — the distance is bitwise the solver's output for
  (graph, source, dst); ``max_error`` is 0.
- ``exact: false`` — ``distance`` is the landmark upper bound and
  ``|distance - d(s, t)| <= max_error`` (``max_error`` may be +inf when
  the landmarks carry no information about the pair — the caller sees
  exactly how much the answer is worth).
- ``stale: true`` (with ``exact: true``) — the distance is bitwise the
  solver's output for the PRE-update graph; ``max_error`` is then the
  landmark interval width for the pair (ISSUE 16 satellite): an honest
  ESTIMATE of how far the served value may drift from the repaired
  graph's answer, shaped exactly like a certified-shed response (+inf
  when no landmark index is attached — the estimate is never silently
  absent, and never silently zero).

Lookup dispatch (ISSUE 16 tentpole): each aggregated batch's lookup
work — exact hot hits plus landmark bounds — goes through the priced
planner registry (``planner.LOOKUP_PLANS``). The ``device_lookup`` plan
megabatches the batch into one kernel launch over the store's device
tile (``serve/device_query.py``); ``host_lookup`` is the per-source
tier walk. Answers are bitwise-identical either way (the device path's
design invariant), so forcing either path via the engine's
``device_lookup`` tristate reproduces the other bit for bit; tiny
batches and CPU platforms keep the host path by qualification, and the
per-batch decision (with its why-line) is kept on
``engine.last_lookup_decision``.

Concurrency (ISSUE 12): the engine is thread-safe — one re-entrant
lock serializes the batch pipeline (tier walk, scheduled solve, counter
updates), so K client threads hammering :meth:`query_batch` get exact
answers, lost-increment-free counters, and still exactly ONE scheduled
solve per aggregated miss batch. Latency samples include lock wait —
queueing delay is real serving latency, not overhead to hide.

Live metrics (ISSUE 12): per-query latency streams into a log-bucketed
:class:`~paralleljohnson_tpu.observe.live.LogHistogram` (bounded
memory, exact counts, percentile error bounded by one bucket width and
reported beside the estimate) instead of the old unbounded sample
list; hit-tier / stale / error counts feed sliding-window rate
counters; an optional :class:`~paralleljohnson_tpu.observe.live.SLO`
is evaluated with multi-window burn-rate rules (``slo_burn`` flight
events + the ``pjtpu_slo_burn_rate`` gauge). With a checkpoint-backed
store, ``serve_stats.json`` is atomically REWRITTEN every
``stats_interval_s`` while the engine serves (the heartbeat idiom) —
a SIGKILLed serve process leaves usable stats, fresh to within one
interval, plus a final write at :meth:`close`.

Telemetry: every batch is a ``serve_batch`` span, every query a
``query`` span (round-10 ``Tracer``); heartbeat progress carries
``queries_done``; :meth:`write_metrics` exports ``pjtpu_queries_total``
/ ``pjtpu_query_latency_ms`` (a real Prometheus histogram — use
``histogram_quantile`` for percentiles; the deprecated round-11
p50/p99 gauges were removed after their one-release grace period)
through the same atomic ``write_prom_metrics`` writer the solver
uses.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import weakref
from pathlib import Path

import types

import numpy as np

from paralleljohnson_tpu import planner as _planner
from paralleljohnson_tpu.observe.live import (
    SLO,
    LogHistogram,
    MetricsRegistry,
)
from paralleljohnson_tpu.serve import device_query as _device_query
from paralleljohnson_tpu.serve.landmarks import (
    finish_estimates,
    widen_bounds,
)
from paralleljohnson_tpu.utils.telemetry import resolve as _resolve_telemetry
from paralleljohnson_tpu.utils.telemetry import write_prom_metrics

SERVE_STATS_FILENAME = "serve_stats.json"
SERVE_LIVE_FILENAME = "serve_live.json"

# Default periodic serve_stats.json rewrite interval; 0/None disables.
DEFAULT_STATS_INTERVAL_S = 5.0

# The default serving objective `pjtpu serve` runs under when no SLO is
# configured explicitly: 99.9% of queries good, p99 under 250 ms. The
# CLI overrides via --slo-p99-ms / --slo-availability.
DEFAULT_SLO = SLO(name="serve", latency_ms=250.0, latency_pct=99.0,
                  availability=0.999)


@dataclasses.dataclass
class ServeStats:
    """Per-engine query counters + a streaming latency histogram.

    ``hist`` replaced the round-11 bounded sample LIST (ISSUE 12): the
    histogram absorbs any query volume in bounded memory with exact
    counts; only percentile positions are bucket-rounded, and every
    estimate travels with that bound (``p50_err_ms`` / ``p99_err_ms``).
    """

    queries_total: int = 0
    exact_answers: int = 0
    approx_answers: int = 0
    # Certified approximate tier split (ISSUE 17): how many of the
    # approximate answers came from the hopset tier (composed hopset +
    # landmark bounds, tighter wins) vs the plain landmark walk.
    hopset_answers: int = 0
    errors: int = 0
    batches_scheduled: int = 0
    solved_sources: int = 0
    stale_answers: int = 0
    # Traffic-front-end counters (ISSUE 15): maintained by the socket
    # frontend (the engine never sheds or rejects by itself) but kept
    # here so serve_stats.json / pjtpu top / prom all read ONE set of
    # serving counters regardless of which loop drove the engine.
    shed_answers: int = 0
    rejected: int = 0
    deadline_drops: int = 0
    # Per-client fairness (ISSUE 18): requests rejected at a client's
    # own in-flight cap while the rest of the fleet kept flowing.
    client_limited: int = 0
    open_connections: int = 0
    # Lookup-path accounting (ISSUE 16): which dispatch served each
    # answered query — the device megabatch or the host tier walk —
    # plus the width distribution of the device megabatches (the whole
    # point of aggregating: widths near 1 mean the batching isn't
    # happening and the launch overhead is pure loss).
    device_lookups: int = 0
    host_lookups: int = 0
    hits_by_tier: dict = dataclasses.field(default_factory=dict)
    hist: LogHistogram = dataclasses.field(default_factory=LogHistogram)
    batch_hist: LogHistogram = dataclasses.field(default_factory=LogHistogram)

    def record_latency(self, ms: float,
                       exemplar: str | None = None) -> None:
        # ``exemplar`` is the request's trace_id (ISSUE 20): it rides
        # into the latency bucket so "p99 = 38 ms" links to concrete
        # assembled traces (prom exemplars, `pjtpu top`, slo_report).
        self.hist.record(float(ms), exemplar=exemplar)

    def percentiles(self) -> dict:
        """``{"p50_ms", "p50_err_ms", "p99_ms", "p99_err_ms"}`` — the
        streaming estimates with their one-bucket error bounds."""
        if self.hist.count == 0:
            return {"p50_ms": 0.0, "p50_err_ms": 0.0,
                    "p99_ms": 0.0, "p99_err_ms": 0.0}
        return self.hist.percentiles((50, 99))

    def as_dict(self) -> dict:
        return {
            "queries_total": self.queries_total,
            "exact_answers": self.exact_answers,
            "approx_answers": self.approx_answers,
            "hopset_answers": self.hopset_answers,
            "errors": self.errors,
            "batches_scheduled": self.batches_scheduled,
            "solved_sources": self.solved_sources,
            "stale_answers": self.stale_answers,
            "shed_answers": self.shed_answers,
            "rejected": self.rejected,
            "deadline_drops": self.deadline_drops,
            "client_limited": self.client_limited,
            "open_connections": self.open_connections,
            "device_lookups": self.device_lookups,
            "host_lookups": self.host_lookups,
            "hits_by_tier": dict(self.hits_by_tier),
            **{k: round(v, 4) for k, v in self.percentiles().items()},
            **({} if self.batch_hist.count == 0 else {
                k: round(v, 4) for k, v in self.batch_hist.percentiles(
                    (50, 99), key="batch_width_p{p}").items()
            }),
        }


# Prometheus table for :func:`write_prom_metrics` — the getters take the
# ENGINE (stats + store hit-rate live on different objects).
SERVE_PROM_METRICS = (
    ("pjtpu_queries_total", "counter",
     "Queries answered by the serving engine",
     lambda e: e.stats.queries_total),
    ("pjtpu_query_errors_total", "counter",
     "Malformed or out-of-range queries rejected",
     lambda e: e.stats.errors),
    ("pjtpu_query_exact_total", "counter",
     "Queries answered exactly (store row or scheduled solve)",
     lambda e: e.stats.exact_answers),
    ("pjtpu_query_approx_total", "counter",
     "Queries answered from the landmark index (with max_error)",
     lambda e: e.stats.approx_answers),
    # Certified approximate tier (ISSUE 17): every counted answer is
    # flagged exact: false and carries a certified max_error.
    ("pjtpu_approx_answers_total", "counter",
     "Queries answered by a certified approximate tier (landmark or "
     "hopset) — every one flagged exact: false with a max_error",
     lambda e: e.stats.approx_answers),
    ("pjtpu_hopset_answers_total", "counter",
     "Queries answered by the hopset tier (composed hopset + landmark "
     "bounds, tighter wins)",
     lambda e: e.stats.hopset_answers),
    ("pjtpu_hopset_edges", "gauge",
     "Edges in the attached (1+eps) hopset (0 = no hopset attached)",
     lambda e: 0 if e.hopset is None else e.hopset.num_hopset_edges),
    ("pjtpu_serve_batches_scheduled_total", "counter",
     "Exact solve batches the engine scheduled for store misses",
     lambda e: e.stats.batches_scheduled),
    ("pjtpu_stale_answers_total", "counter",
     "Answers served from a pre-update checkpoint while (or after) an "
     "incremental repair ran — every one carries stale: true",
     lambda e: e.stats.stale_answers),
    # Traffic-front-end counters (ISSUE 15): certified shedding,
    # admission rejections, deadline drops, live connection gauge.
    ("pjtpu_shed_answers_total", "counter",
     "Exact-miss queries downgraded to flagged landmark answers while "
     "the burn-rate alert fired (every one carries shed: true + a "
     "certified max_error)",
     lambda e: e.stats.shed_answers),
    ("pjtpu_rejected_total", "counter",
     "Connections/requests rejected by admission control (explicit "
     "overloaded + retry_after_ms, never an unbounded queue)",
     lambda e: e.stats.rejected),
    ("pjtpu_deadline_drops_total", "counter",
     "Requests dropped because they could not start before their "
     "deadline_ms (rejected without touching the engine)",
     lambda e: e.stats.deadline_drops),
    ("pjtpu_client_limited_total", "counter",
     "Requests rejected at their client's per-key in-flight cap "
     "(fairness: the hog is limited while other clients keep flowing)",
     lambda e: e.stats.client_limited),
    ("pjtpu_open_connections", "gauge",
     "Client connections currently open on the socket frontend",
     lambda e: e.stats.open_connections),
    ("pjtpu_query_hit_rate", "gauge",
     "Fraction of row lookups served by a store tier (hot/warm/cold)",
     lambda e: e.store.hit_rate()),
    # Lookup-path dispatch (ISSUE 16): device megabatch vs host walk,
    # plus the device megabatch width distribution.
    ("pjtpu_device_lookups_total", "counter",
     "Queries answered by the device-resident megabatch path (bitwise "
     "identical to the host walk by design)",
     lambda e: e.stats.device_lookups),
    ("pjtpu_host_lookups_total", "counter",
     "Queries answered by the per-source host tier walk",
     lambda e: e.stats.host_lookups),
    ("pjtpu_lookup_batch_width", "histogram",
     "Width (queries per launch) of device lookup megabatches",
     lambda e: e.stats.batch_hist),
    # The real latency distribution (ISSUE 12): cumulative _bucket /
    # _sum / _count lines so PromQL histogram_quantile works...
    ("pjtpu_query_latency_ms", "histogram",
     "Per-query latency distribution (log-bucketed streaming histogram; "
     "percentile error bounded by one bucket width ~19%)",
     lambda e: e.stats.hist),
    # The round-11 pjtpu_query_latency_p50_ms / _p99_ms gauges were
    # kept one release (round 17) after the histogram landed and are
    # now REMOVED (ISSUE 14 satellite): use
    # histogram_quantile(0.99, rate(pjtpu_query_latency_ms_bucket[5m])).
    ("pjtpu_slo_burn_rate", "gauge",
     "Error-budget burn rate per registered SLO (1 = spending exactly "
     "the budget; the multi-window alert fires per the SLO's rules)",
     lambda e: e.metrics.slo_burn_gauge(), "slo"),
)

_MISS_POLICIES = ("solve", "landmark", "hopset")

# Lookup-path tristate (ISSUE 16): "auto" lets the planner registry
# choose per batch, "on"/"off" pin the device megabatch / host walk
# (both answer bitwise-identically — the pin is for benchmarking and
# for platforms where auto-qualification guesses wrong).
_DEVICE_LOOKUP_MODES = ("auto", "on", "off")

# rows[] sentinel marking a source whose values arrive from the device
# megabatch rather than a host row reference.
_DEVICE_ROW = object()


class QueryError(ValueError):
    """A malformed request (bad JSON shape, out-of-range vertex)."""


class QueryEngine:
    """Answers queries over one graph from a tile store (+ optional
    landmark index). ``config`` is the :class:`SolverConfig` the
    exact-miss solver runs under; its ``checkpoint_dir`` is overridden
    to the store's backing directory so scheduled batches persist into
    the cold tier (or to None for an in-memory store).

    ``metrics``: a shared :class:`MetricsRegistry` (one is created per
    engine when None). ``slo``: the serving objective to evaluate
    (None = :data:`DEFAULT_SLO`). ``stats_interval_s``: period of the
    live ``serve_stats.json`` rewrite for checkpoint-backed stores
    (started lazily with the first served batch; 0 disables)."""

    def __init__(self, graph, store, *, landmarks=None, hopset=None,
                 config=None,
                 miss_policy: str = "solve", metrics=None, slo=None,
                 stats_interval_s: float = DEFAULT_STATS_INTERVAL_S,
                 device_lookup: str = "auto") -> None:
        import dataclasses as _dc

        from paralleljohnson_tpu.config import SolverConfig
        from paralleljohnson_tpu.solver import ParallelJohnsonSolver

        if miss_policy not in _MISS_POLICIES:
            raise ValueError(
                f"miss_policy must be one of {_MISS_POLICIES}, "
                f"got {miss_policy!r}"
            )
        if device_lookup not in _DEVICE_LOOKUP_MODES:
            raise ValueError(
                f"device_lookup must be one of {_DEVICE_LOOKUP_MODES}, "
                f"got {device_lookup!r}"
            )
        if miss_policy == "landmark" and landmarks is None:
            raise ValueError(
                "miss_policy='landmark' requires a LandmarkIndex "
                "(build one or switch to miss_policy='solve')"
            )
        if miss_policy == "hopset" and hopset is None:
            raise ValueError(
                "miss_policy='hopset' requires a Hopset (build one with "
                "ops.hopset.build_hopset or switch to miss_policy='solve')"
            )
        if (hopset is not None and getattr(store, "digest", None)
                and getattr(hopset, "digest", None)
                and hopset.digest != store.digest):
            # Same contract as Hopset.load's expect_digest: a hopset
            # built for another graph must never bound this one.
            raise ValueError(
                "hopset graph digest does not match the store's graph "
                f"({hopset.digest[:12]}... != {store.digest[:12]}...)"
            )
        self.graph = graph
        self.store = store
        self.landmarks = landmarks
        self.hopset = hopset
        self.miss_policy = miss_policy
        base = config or SolverConfig()
        self.config = _dc.replace(
            base,
            checkpoint_dir=str(store.root) if store.ckpt is not None else None,
        )
        self.solver = ParallelJohnsonSolver(self.config)
        self._tel = _resolve_telemetry(self.config.telemetry)
        self.metrics = metrics if metrics is not None else MetricsRegistry(
            label="serve", telemetry=self.config.telemetry
        )
        self.slo = slo if slo is not None else DEFAULT_SLO
        # The stats histogram IS the registry's, so snapshots and prom
        # exports read one set of counts (no drift between surfaces).
        self.stats = ServeStats(
            hist=self.metrics.histogram("pjtpu_query_latency_ms"),
            batch_hist=self.metrics.histogram("pjtpu_lookup_batch_width"),
        )
        # Device-resident lookup path (ISSUE 16): built lazily on first
        # batch so engines on jax-less hosts never pay an import probe
        # per query; the unavailability reason is cached for the
        # planner's why-line.
        self.device_lookup = device_lookup
        self._device_path = None
        self._device_unavail: str | None = None
        self.last_lookup_decision: dict | None = None
        self.metrics.slo(self.slo, histogram="pjtpu_query_latency_ms")
        # One re-entrant lock serializes the whole batch pipeline: the
        # tier walk + scheduled solve + counters are a critical section
        # (TileStore's own lock protects its dicts, but hit counters and
        # the miss->solve->put sequence span many store calls).
        self._lock = threading.RLock()
        # Closed-engine contract (ISSUE 15 satellite): the frontend's
        # drain path closes the engine while late connections may still
        # hold a reference — queries after close must fail with a
        # diagnosable QueryError, never a racy AttributeError.
        self._closed = False
        self.stats_interval_s = (
            float(stats_interval_s) if stats_interval_s else 0.0
        )
        self._stats_stop = threading.Event()
        self._stats_thread: threading.Thread | None = None
        # A dropped engine must not leave its writer thread spinning.
        self._finalizer = weakref.finalize(self, self._stats_stop.set)

    # -- request parsing -----------------------------------------------------

    def _parse(self, req: dict) -> dict:
        v = self.graph.num_nodes
        if not isinstance(req, dict):
            raise QueryError(f"query must be a JSON object, got {type(req).__name__}")
        if "source" not in req:
            raise QueryError("query is missing 'source'")
        try:
            source = int(req["source"])
        except (TypeError, ValueError):
            raise QueryError(f"bad source {req['source']!r}") from None
        if not 0 <= source < v:
            raise QueryError(f"source {source} out of range [0, {v})")
        dst = req.get("dst")
        if dst is not None:
            many = isinstance(dst, (list, tuple))
            try:
                dsts = np.asarray(
                    dst if many else [dst], np.int64
                )
            except (TypeError, ValueError):
                raise QueryError(f"bad dst {dst!r}") from None
            if dsts.ndim != 1 or (len(dsts) and (
                    dsts.min() < 0 or dsts.max() >= v)):
                raise QueryError(f"dst out of range [0, {v})")
        else:
            many = True
            dsts = None  # full row (all V destinations)
        mode = req.get("mode", self.miss_policy)
        if mode == "exact":
            mode = "solve"
        elif mode == "approx":
            # Generic "any certified tier": landmark when attached
            # (the hopset tier composes it in anyway), else hopset.
            if self.landmarks is not None:
                mode = "landmark"
            elif self.hopset is not None:
                mode = "hopset"
            else:
                raise QueryError(
                    "mode 'approx' needs a certified tier "
                    "(landmark index or hopset)"
                )
        if mode not in _MISS_POLICIES:
            raise QueryError(f"bad mode {req.get('mode')!r}")
        if mode == "landmark" and self.landmarks is None:
            raise QueryError("mode 'approx' needs a landmark index")
        if mode == "hopset" and self.hopset is None:
            raise QueryError("mode 'hopset' needs an attached hopset")
        # Trace passthrough (ISSUE 20): the wire context rides the
        # request JSON; only a SAMPLED id tags spans/exemplars (an
        # upstream ingress's head decision is final).
        t = req.get("trace")
        trace_id = None
        if isinstance(t, dict):
            if t.get("sampled", True) is not False:
                tid = t.get("id")
                trace_id = tid if isinstance(tid, str) else None
        elif isinstance(t, str):
            trace_id = t
        return {"id": req.get("id"), "source": source, "dsts": dsts,
                "many": many, "mode": mode, "trace": trace_id}

    # -- the serving loop ----------------------------------------------------

    def query(self, source: int, dst=None, *, mode: str | None = None) -> dict:
        """One request (see :meth:`query_batch`). ``dst``: vertex id for
        point-to-point, list for one-to-many, None for the full row."""
        req: dict = {"source": source, "dst": dst}
        if mode is not None:
            req["mode"] = mode
        out = self.query_batch([req])[0]
        if "error" in out:
            raise QueryError(out["error"])
        return out

    def query_batch(self, requests: list[dict]) -> list[dict]:
        """Answer many requests in one pass: each distinct source's row
        is fetched ONCE, every exact-mode miss joins one scheduled solve
        batch, responses come back in request order. Malformed requests
        yield ``{"error": ...}`` responses (the batch survives).
        Thread-safe: concurrent batches serialize on the engine lock
        (each aggregated batch still schedules at most one solve); the
        per-query latency samples include the lock wait — queueing is
        part of what a client experiences."""
        t_batch = time.perf_counter()
        tel = self._tel
        with self._lock:
            if self._closed:
                raise QueryError(
                    "query engine is closed (the serving process drained "
                    "or shut down; open a new engine over the store)"
                )
            self._ensure_stats_writer()
            responses = self._query_batch_locked(requests, t_batch, tel)
        return responses

    def _fire_fault(self, stage: str, batch=None) -> None:
        """Serving-path fault injection (ISSUE 15): fire the FaultPlan's
        scheduled fault for ``stage`` INSIDE the latency-measured
        section — an injected ``slow_ms`` inflates the very histogram
        the SLO burn rules watch (a realistic store stall), an injected
        ``error`` raises out of :meth:`query_batch` exactly like a real
        solver/store failure (the frontend converts it to per-request
        error responses; a direct caller sees the raw failure)."""
        fp = getattr(self.config, "fault_plan", None)
        if fp is None:
            return
        active = fp.fire(stage, batch=batch)
        if active is not None:
            active.wrap(lambda: None)()

    def _query_batch_locked(self, requests, t_batch, tel) -> list[dict]:
        with tel.span("serve_batch", n_queries=len(requests)):
            self._fire_fault("serve_lookup")
            parsed: list[dict | None] = []
            responses: list[dict | None] = []
            for req in requests:
                try:
                    parsed.append(self._parse(req))
                    responses.append(None)
                except QueryError as e:
                    parsed.append(None)
                    self.stats.errors += 1
                    self.metrics.counter("pjtpu_query_errors").add(1)
                    self.metrics.observe_slo(self.slo.name, None, ok=False)
                    responses.append({
                        "id": req.get("id") if isinstance(req, dict) else None,
                        "error": str(e),
                    })

            # Lookup-path dispatch (ISSUE 16): the planner registry
            # decides per batch whether lookups megabatch over the
            # device tile or walk the host tiers.
            device_slots = self._plan_lookup(parsed)
            n_valid = sum(1 for p in parsed if p is not None)
            if n_valid:
                # Aggregated lookup width — the quantity micro-batching
                # exists to raise (batch_width_p50/p99 in stats).
                self.stats.batch_hist.record(float(n_valid))

            # One row fetch per distinct source; one solve for ALL
            # exact-mode misses (the aggregation the tentpole names).
            rows: dict[int, tuple] = {}
            seen: set[int] = set()
            device_sources: list[int] = []
            for p in parsed:
                if p is None or p["source"] in seen:
                    continue
                seen.add(p["source"])
                if p["source"] in device_slots:
                    # The values come from the megabatch below; the
                    # sentinel keeps the miss/solve logic unchanged.
                    rows[p["source"]] = (_DEVICE_ROW, "hot")
                    device_sources.append(p["source"])
                    continue
                row, row_tier = self.store.get(p["source"])
                if row is not None:
                    rows[p["source"]] = (row, row_tier)
            if device_sources:
                # Device-path hits must leave the same footprint the
                # host walk would: one hot hit + an LRU refresh each.
                self.store.note_hot_hits(device_sources)
            missing_exact = sorted({
                p["source"] for p in parsed
                if p is not None and p["source"] not in rows
                and p["mode"] == "solve"
            })
            if missing_exact and self.store.refresh_cold_if_changed():
                # Live-fleet awareness (ISSUE 18): another process —
                # a solve worker or a sibling replica — committed
                # manifest increments since we attached. Re-check the
                # misses against the refreshed cold index before paying
                # for a solve; an in-flight fleet solve's batches turn
                # our misses into cold hits. The check is one stat()
                # per manifest, and only on the (already-expensive)
                # miss path — the hot path never touches the disk.
                still_missing = []
                for s in missing_exact:
                    row, row_tier = self.store.get(s)
                    if row is not None:
                        rows[s] = (row, row_tier)
                    else:
                        still_missing.append(s)
                missing_exact = still_missing
            if missing_exact:
                batch = np.asarray(missing_exact, np.int64)
                # The scheduled solve tagged with the traces it serves
                # (ISSUE 20): a store miss's solve cost shows up IN the
                # request's assembled timeline, not as anonymous work.
                miss_set = set(missing_exact)
                solve_traces = sorted({
                    p["trace"] for p in parsed
                    if p is not None and p.get("trace")
                    and p["source"] in miss_set
                })
                extra = ({"trace": solve_traces[0],
                          "traces": solve_traces[:8]}
                         if solve_traces else {})
                with tel.span("serve_solve", n_sources=len(batch),
                              **extra):
                    self._fire_fault("serve_solve",
                                     batch=self.stats.batches_scheduled)
                    res = self.solver.solve(self.graph, sources=batch)
                self.stats.batches_scheduled += 1
                self.stats.solved_sources += len(batch)
                self.metrics.counter("pjtpu_serve_batches_scheduled").add(1)
                self.store.put(res.sources, res.dist, tier="hot")
                if self.store.ckpt is not None:
                    self.store.invalidate_cold_index()
                for s, row in res.rows_by_source().items():
                    rows[s] = (row, "solved")

            # The megabatch: every device-eligible lookup in this batch
            # flattens into (at most) one launch per query class.
            pre = self._device_precompute(parsed, rows, device_slots)

            for i, p in enumerate(parsed):
                if p is None:
                    continue
                q_attrs = ({"trace": p["trace"]} if p.get("trace")
                           else {})
                with tel.span("query", source=p["source"],
                              many=p["many"], **q_attrs):
                    responses[i] = self._answer(p, rows, pre.get(i))
                self.stats.queries_total += 1
                latency_ms = (time.perf_counter() - t_batch) * 1e3
                self.stats.record_latency(latency_ms,
                                          exemplar=p.get("trace"))
                self.metrics.counter("pjtpu_queries").add(1)
                self.metrics.observe_slo(self.slo.name, latency_ms, ok=True)
            self.metrics.gauge("pjtpu_query_hit_rate",
                               self.store.hit_rate())
            tel.progress(queries_done=self.stats.queries_total,
                         batches_scheduled=self.stats.batches_scheduled)
        return responses  # type: ignore[return-value]

    # -- lookup-path dispatch (ISSUE 16 tentpole) -----------------------------

    def _device_path_maybe(self):
        """The lazily built :class:`DeviceQueryPath`, or None with the
        reason cached in ``_device_unavail``."""
        if self.device_lookup == "off":
            self._device_unavail = "disabled (device_lookup='off')"
            return None
        if self._device_path is None:
            if self._device_unavail is not None:
                return None  # probed and failed; don't re-import per batch
            ok, reason = _device_query.available()
            if not ok:
                self._device_unavail = reason
                return None
            self._device_path = _device_query.DeviceQueryPath(
                self.store, self.landmarks
            )
        return self._device_path

    def _plan_lookup(self, parsed) -> dict[int, int]:
        """Run the planner over ``LOOKUP_PLANS`` for this batch. Returns
        the source -> tile-slot map to serve from the device (empty map
        = host walk). The decision (with why-line) lands on
        ``last_lookup_decision``."""
        dpath = self._device_path_maybe()
        slots: dict[int, int] = {}
        platform = "cpu"
        if dpath is None:
            avail, reason = False, self._device_unavail or "unavailable"
        else:
            try:
                slots = dpath.refresh()
                platform = dpath.platform()
                if slots:
                    avail, reason = True, "device tile resident"
                else:
                    avail = False
                    reason = "empty device tile (nothing hot, or all stale)"
            except Exception as e:  # noqa: BLE001 — degrade, never crash a query
                slots = {}
                avail = False
                reason = f"device path failed: {type(e).__name__}: {e}"
        n_eligible = sum(
            1 for p in parsed if p is not None and p["source"] in slots
        )
        ctx = types.SimpleNamespace(
            platform=platform,
            device_available=avail,
            device_reason=reason,
            n_device_eligible=n_eligible,
            forced_on=self.device_lookup == "on",
        )
        decision = _planner.select(
            _planner.LOOKUP_PLANS, ctx,
            platform=platform, num_edges=self.graph.num_edges,
            batch=max(1, n_eligible),
            config=types.SimpleNamespace(device_lookup=self.device_lookup),
        )
        self.last_lookup_decision = decision.as_dict()
        if decision.chosen.plan.name == "device_lookup":
            return slots
        return {}

    def _device_precompute(self, parsed, rows, device_slots) -> dict:
        """Flatten this batch's device-eligible lookups and run the
        megabatch: exact (slot, dst) pairs and full rows gather over the
        tile; landmark misses compute their RAW f64 bounds on-device and
        finish through the SAME host helpers the host path uses (the
        bitwise-parity seam — see ``serve/device_query.py``). Returns
        ``{query_index: ("exact", vals_f64) | ("landmark", est, err)}``."""
        pre: dict[int, tuple] = {}
        if not device_slots:
            return pre
        dpath = self._device_path
        lm_dev = dpath.landmark_device_ok()
        pair_q: list[int] = []
        pair_seg: list[int] = []
        pair_slots: list[int] = []
        pair_dsts: list[int] = []
        row_q: list[int] = []
        row_slots: list[int] = []
        lmp_q: list[int] = []
        lmp_seg: list[int] = []
        lmp_s: list[int] = []
        lmp_t: list[int] = []
        lmr_q: list[int] = []
        lmr_s: list[int] = []
        for i, p in enumerate(parsed):
            if p is None:
                continue
            s, dsts = p["source"], p["dsts"]
            if s in device_slots:
                if dsts is None:
                    row_q.append(i)
                    row_slots.append(device_slots[s])
                else:
                    pair_q.append(i)
                    pair_seg.append(len(dsts))
                    pair_slots.extend([device_slots[s]] * len(dsts))
                    pair_dsts.extend(int(d) for d in dsts)
            elif lm_dev and s not in rows and p["mode"] == "landmark":
                # Store miss answered by landmark bounds: the f64 raw
                # part rides the same launch window (platforms without
                # real f64 — TPU — fail the probe and these stay host).
                if dsts is None:
                    lmr_q.append(i)
                    lmr_s.append(s)
                else:
                    lmp_q.append(i)
                    lmp_seg.append(len(dsts))
                    lmp_s.extend([s] * len(dsts))
                    lmp_t.extend(int(d) for d in dsts)
        nonneg = (self.landmarks.nonnegative
                  if self.landmarks is not None else True)
        if not (pair_q or row_q or lmp_q or lmr_q):
            return pre
        # The megabatch kernel launch as one span (ISSUE 20): tagged
        # with every trace riding this launch, so an assembled timeline
        # shows WHICH device launch served the request (and how wide it
        # was — convoy width reaching the accelerator).
        tel = self._tel
        mb_attrs = {}
        if tel.enabled:
            mb_traces = sorted({
                parsed[qi]["trace"]
                for qi in (pair_q + row_q + lmp_q + lmr_q)
                if parsed[qi] is not None and parsed[qi].get("trace")
            })
            if mb_traces:
                mb_attrs = {"trace": mb_traces[0],
                            "traces": mb_traces[:8]}
        with tel.span("device_megabatch", pairs=len(pair_slots),
                      rows=len(row_q), lm_pairs=len(lmp_s),
                      lm_rows=len(lmr_q), **mb_attrs):
            if pair_q:
                flat = dpath.exact_pairs(pair_slots, pair_dsts)
                off = 0
                for qi, seg in zip(pair_q, pair_seg):
                    pre[qi] = ("exact",
                               np.asarray(flat[off:off + seg],
                                          np.float64))
                    off += seg
            if row_q:
                out = dpath.exact_rows(row_slots)
                for j, qi in enumerate(row_q):
                    pre[qi] = ("exact", np.asarray(out[j], np.float64))
            if lmp_q:
                lo, up = dpath.landmark_pairs(lmp_s, lmp_t)
                lo, up = widen_bounds(lo, up, nonnegative=nonneg)
                est, err = finish_estimates(lo, up)
                off = 0
                for qi, seg in zip(lmp_q, lmp_seg):
                    pre[qi] = ("landmark", est[off:off + seg],
                               err[off:off + seg])
                    off += seg
            if lmr_q:
                lo, up = dpath.landmark_rows(lmr_s)
                for j, qi in enumerate(lmr_q):
                    wl, wu = widen_bounds(lo[j], up[j],
                                          nonnegative=nonneg)
                    est, err = finish_estimates(wl, wu)
                    pre[qi] = ("landmark", est, err)
        return pre

    def _hopset_estimate(self, s, dsts):
        """The hopset tier's ``(estimates, max_errors)``: the hopset's
        certified interval intersected with the landmark index's (when
        one is attached) — the composition rule: tighter wins PER
        ENTRY, both factors are certified, so the intersection is too.
        Finished through the same inf-aware helper as every certified
        tier (proven-inf -> (inf, 0); unknown -> (inf, inf) — an
        unreachable pair is never silently bounded)."""
        lower, upper = self.hopset.bounds_row(s, dsts)
        if self.landmarks is not None and self.landmarks.k > 0:
            lm_lo, lm_up = self.landmarks.bounds_row(s, dsts)
            lower = np.maximum(lower, lm_lo)
            upper = np.minimum(upper, lm_up)
        return finish_estimates(lower, upper)

    def _stale_error_bound(self, s, dsts, many):
        """The ISSUE 16 stale-honesty satellite: a landmark-derived
        ``max_error`` for a stale (pre-update) answer, shaped like a
        certified-shed response's. The landmark interval width is an
        honest ESTIMATE of how far the served value can drift from the
        repaired graph's answer — not a certificate (the index predates
        the repair too), which is exactly why it rides next to
        ``stale: true`` instead of replacing it. Without an index the
        bound is +inf: present, never silently zero."""
        if self.landmarks is not None and self.landmarks.k > 0:
            _, err = self.landmarks.estimate_row(s, dsts)
        else:
            n = 1 if dsts is not None and not many else (
                len(dsts) if dsts is not None else self.graph.num_nodes
            )
            err = np.full(max(n, 1), np.inf)
        return [float(e) for e in err] if many else float(err[0])

    def _answer(self, p: dict, rows: dict[int, tuple],
                pre: tuple | None = None) -> dict:
        s, dsts, many = p["source"], p["dsts"], p["many"]
        out: dict = {"id": p["id"], "source": s}
        # Staleness contract (ISSUE 11): while (or after) an incremental
        # repair runs against this store's graph, every answer whose
        # source is in the repair's affected set reflects PRE-update
        # distances — exact for the old graph, flagged here so it is
        # never served as current silently. This applies to every tier
        # AND to freshly scheduled solves / landmark bounds: they all
        # answer for the engine's (pre-update) graph. Absence of the
        # key means the answer is provably current for the updated
        # graph too (the repair dependency argument).
        if self.store.is_stale(s):
            out["stale"] = True
            self.stats.stale_answers += 1
            self.metrics.counter("pjtpu_stale_answers").add(1)
        hit = rows.get(s)
        device = pre is not None
        if device and pre[0] == "exact":
            # Megabatched gather: same f32 bits, same f64 conversion —
            # tier is "hot" exactly as the host walk would report.
            vals = pre[1]
            tier = "hot"
            self.stats.exact_answers += 1
            out.update(exact=True, max_error=0.0, tier="hot")
        elif hit is not None:
            row, tier = hit
            vals = np.asarray(row if dsts is None else row[dsts],
                              np.float64)
            self.stats.exact_answers += 1
            out.update(exact=True, max_error=0.0, tier=tier)
        elif device and pre[0] == "landmark":
            # Device-raw + host-finished bounds (bitwise the host path).
            est, err = pre[1], pre[2]
            vals = est
            self.stats.approx_answers += 1
            tier = "landmark"
            out.update(
                exact=False, tier="landmark",
                max_error=(
                    [float(e) for e in err] if many else float(err[0])
                ),
            )
        elif p["mode"] == "hopset":
            # Hopset tier (ISSUE 17): certified interval from the
            # (1+eps) hopset composed with the landmark interval when
            # an index is also attached — tighter wins per entry, and
            # the answer is flagged exactly like a landmark one.
            est, err = self._hopset_estimate(s, dsts)
            vals = est
            self.stats.approx_answers += 1
            self.stats.hopset_answers += 1
            tier = "hopset"
            out.update(
                exact=False, tier="hopset",
                max_error=(
                    [float(e) for e in err] if many else float(err[0])
                ),
            )
        else:
            # Landmark path — approximation, always flagged with its
            # certified error bound.
            est, err = self.landmarks.estimate_row(s, dsts)
            vals = est
            self.stats.approx_answers += 1
            tier = "landmark"
            out.update(
                exact=False, tier="landmark",
                max_error=(
                    [float(e) for e in err] if many else float(err[0])
                ),
            )
        if out.get("stale") and out.get("exact"):
            # Stale-honesty satellite: the pre-update answer ships with
            # its drift estimate, never a bare flag.
            out["max_error"] = self._stale_error_bound(s, dsts, many)
        if device:
            self.stats.device_lookups += 1
            self.metrics.counter("pjtpu_device_lookups").add(1)
        else:
            self.stats.host_lookups += 1
            self.metrics.counter("pjtpu_host_lookups").add(1)
        self.stats.hits_by_tier[tier] = (
            self.stats.hits_by_tier.get(tier, 0) + 1
        )
        self.metrics.counter(f"pjtpu_answers_{tier}").add(1)
        if many:
            out["dst"] = None if dsts is None else [int(d) for d in dsts]
            out["distances"] = [float(x) for x in vals]
        else:
            out["dst"] = int(dsts[0])
            out["distance"] = float(vals[0])
        return out

    # -- the front end's hooks (ISSUE 15) ------------------------------------

    def slo_tracker(self):
        """The live :class:`~paralleljohnson_tpu.observe.live.SLOTracker`
        for this engine's objective — the burn-state the frontend's
        shedding decision reads (``tracker.burning`` flips on the same
        multi-window rules that emit ``slo_burn`` events)."""
        return self.metrics.slo(self.slo)

    def note_failed_requests(self, n: int = 1) -> None:
        """File ``n`` requests that died OUTSIDE the batch pipeline (a
        solve/store exception the frontend converted to error responses)
        into the same counters + SLO stream a parse error uses — a
        failure that burned real error budget must never be invisible to
        the burn-rate alert."""
        with self._lock:
            self.stats.errors += n
        self.metrics.counter("pjtpu_query_errors").add(n)
        for _ in range(int(n)):
            self.metrics.observe_slo(self.slo.name, None, ok=False)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- warm-up and ops surface ---------------------------------------------

    def warm(self, sources) -> int:
        """Pre-solve ``sources`` into the store (one scheduled batch for
        whichever of them the store does not already hold). Returns how
        many sources were actually solved."""
        with self._lock:
            if self._closed:
                raise QueryError("query engine is closed")
            missing = [int(s) for s in np.asarray(sources, np.int64)
                       if self.store.get(int(s))[0] is None]
            if not missing:
                return 0
            batch = np.asarray(sorted(set(missing)), np.int64)
            with self._tel.span("serve_warm", n_sources=len(batch)):
                res = self.solver.solve(self.graph, sources=batch)
            self.stats.batches_scheduled += 1
            self.stats.solved_sources += len(batch)
            self.store.put(res.sources, res.dist, tier="hot")
            if self.store.ckpt is not None:
                self.store.invalidate_cold_index()
            return len(batch)

    def query_lines(self, lines) -> tuple[list[dict], int]:
        """Parse JSONL request lines and answer them as one aggregated
        batch. Returns ``(responses_in_order, n_errors)`` — a malformed
        line becomes an ``{"error": ...}`` response, never a crash (the
        request loop must survive any input)."""
        requests: list[dict] = []
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                if not isinstance(obj, dict):
                    raise ValueError("not a JSON object")
                requests.append(obj)
            except ValueError as e:
                requests.append({"_parse_error": f"line {i + 1}: {e}"})
        for r in requests:
            if "_parse_error" in r:
                r.pop("source", None)  # force the engine's error path
        responses = self.query_batch([
            r if "_parse_error" not in r else {"source": None}
            for r in requests
        ])
        for r, resp in zip(requests, responses):
            if "_parse_error" in r and "error" in resp:
                resp["error"] = r["_parse_error"]
        n_errors = sum(1 for r in responses if "error" in r)
        return responses, n_errors

    def write_metrics(self, path, *, labels: dict | None = None) -> Path:
        """Prometheus textfile export (``pjtpu_queries_total``, the
        ``pjtpu_query_latency_ms`` histogram — percentiles via
        ``histogram_quantile`` — hit rate,
        ``pjtpu_slo_burn_rate{slo=...}``, ...)."""
        return write_prom_metrics(self, path, labels=labels,
                                  metrics=SERVE_PROM_METRICS)

    def serve_summary(self) -> dict:
        if self._device_path is not None:
            device_path = self._device_path.describe()
        else:
            device_path = {
                "available": False,
                "reason": self._device_unavail or "not probed yet",
            }
        return {
            "engine": self.stats.as_dict(),
            "store": self.store.stats(),
            "landmarks": 0 if self.landmarks is None else self.landmarks.k,
            # Approximate-tier provenance (ISSUE 17): what `pjtpu top`
            # and `pjtpu info --serve-store` report about the attached
            # hopset (None = exact + landmark tiers only).
            "hopset": None if self.hopset is None else {
                "epsilon": float(self.hopset.epsilon),
                "beta": int(self.hopset.beta),
                "k": int(self.hopset.k),
                "edges": int(self.hopset.num_hopset_edges),
                "converged": bool(self.hopset.converged),
            },
            "miss_policy": self.miss_policy,
            # Lookup-path dispatch (ISSUE 16): the tristate, the device
            # path's state, and the last planner decision with its
            # why-line — what `pjtpu top` / bench detail read.
            "lookup": {
                "device_lookup": self.device_lookup,
                "device_path": device_path,
                "decision": self.last_lookup_decision,
            },
            # The live view (ISSUE 12): windowed rates, histogram with
            # its full mergeable state, and the SLO burn verdicts —
            # what `pjtpu top` and slo_report read.
            "live": self.metrics.snapshot(),
        }

    # -- periodic stats publishing (ISSUE 12 satellite) -----------------------

    def _stats_path(self) -> Path | None:
        if self.store.ckpt is None:
            return None
        return self.store.ckpt.dir / SERVE_STATS_FILENAME

    def _write_stats(self) -> None:
        """One atomic serve_stats.json publish (tmp + rename — the
        HeartbeatReporter guarantee: a reader never sees a torn file)."""
        path = self._stats_path()
        if path is None:
            return
        payload = self.serve_summary()
        payload["ts"] = time.time()
        payload["pid"] = os.getpid()
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(payload), encoding="utf-8")
        os.replace(tmp, path)

    def _ensure_stats_writer(self) -> None:
        """Start the periodic rewriter lazily with the first served
        batch (an engine that never serves never spawns a thread)."""
        if (self._stats_thread is not None or not self.stats_interval_s
                or self.store.ckpt is None):
            return

        def loop() -> None:
            while not self._stats_stop.wait(self.stats_interval_s):
                try:
                    self._write_stats()
                except Exception:  # noqa: BLE001 — stats must never kill serving
                    pass

        self._stats_stop.clear()
        self._stats_thread = threading.Thread(
            target=loop, name="pj-serve-stats", daemon=True
        )
        self._stats_thread.start()

    def close(self) -> None:
        """Stop the periodic writer and persist the final serving
        counters next to the store's batches (atomic) so ``pjtpu info
        --serve-store`` / ``pjtpu top`` can report capacity, landmark
        count, and hit rates after the loop exits. Does NOT close the
        telemetry façade — its owner (the CLI) does.

        Idempotent (ISSUE 15 satellite): the frontend's drain path and
        the CLI's finally block may both call it; the second call is a
        no-op. In-flight batches finish (close waits on the engine
        lock); queries that arrive after raise :class:`QueryError`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stats_stop.set()
        t = self._stats_thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.stats_interval_s))
            self._stats_thread = None
        if self.store.ckpt is None:
            return
        try:
            self._write_stats()
        except OSError:
            pass  # a read-only store dir still served every query
