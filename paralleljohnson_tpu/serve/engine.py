"""Query engine: source-batched lookups over a :class:`TileStore`.

The serving front end (ROADMAP item 6): point-to-point and one-to-many
queries from many concurrent clients are AGGREGATED — one
:meth:`QueryEngine.query_batch` call resolves each distinct source row
once (hot/warm/cold tier walk), and every source that misses the store
is solved in ONE exact batch through the ordinary resilient solver
(``ParallelJohnsonSolver.solve`` — retries, watchdog deadlines, OOM
batch degradation, and the pipelined fan-out all apply; with a
checkpoint-backed store the new rows also land on disk, growing the
cold tier for the next process). Alternatively (``miss_policy=
"landmark"``) a miss answers immediately from the landmark index with a
certified ``(estimate, max_error)`` — never an unflagged approximation.

The exact-vs-approximate contract every response carries:

- ``exact: true`` — the distance is bitwise the solver's output for
  (graph, source, dst); ``max_error`` is 0.
- ``exact: false`` — ``distance`` is the landmark upper bound and
  ``|distance - d(s, t)| <= max_error`` (``max_error`` may be +inf when
  the landmarks carry no information about the pair — the caller sees
  exactly how much the answer is worth).

Telemetry: every batch is a ``serve_batch`` span, every query a
``query`` span (round-10 ``Tracer``); heartbeat progress carries
``queries_done``; :meth:`write_metrics` exports ``pjtpu_queries_total``
/ ``pjtpu_query_latency_*`` Prometheus gauges through the same atomic
``write_prom_metrics`` writer the solver uses.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.utils.metrics import latency_percentiles
from paralleljohnson_tpu.utils.telemetry import resolve as _resolve_telemetry
from paralleljohnson_tpu.utils.telemetry import write_prom_metrics

SERVE_STATS_FILENAME = "serve_stats.json"

# Latency reservoir cap: percentiles over the most recent samples only —
# a long-lived server must not grow host memory linearly in queries.
_MAX_LATENCY_SAMPLES = 65536


@dataclasses.dataclass
class ServeStats:
    """Per-engine query counters + a bounded latency reservoir."""

    queries_total: int = 0
    exact_answers: int = 0
    approx_answers: int = 0
    errors: int = 0
    batches_scheduled: int = 0
    solved_sources: int = 0
    stale_answers: int = 0
    latencies_ms: list = dataclasses.field(default_factory=list)

    def record_latency(self, ms: float) -> None:
        if len(self.latencies_ms) >= _MAX_LATENCY_SAMPLES:
            del self.latencies_ms[: _MAX_LATENCY_SAMPLES // 2]
        self.latencies_ms.append(float(ms))

    def percentiles(self) -> dict:
        return latency_percentiles(self.latencies_ms)

    def as_dict(self) -> dict:
        return {
            "queries_total": self.queries_total,
            "exact_answers": self.exact_answers,
            "approx_answers": self.approx_answers,
            "errors": self.errors,
            "batches_scheduled": self.batches_scheduled,
            "solved_sources": self.solved_sources,
            "stale_answers": self.stale_answers,
            **{k: round(v, 4) for k, v in self.percentiles().items()},
        }


# Prometheus table for :func:`write_prom_metrics` — the getters take the
# ENGINE (stats + store hit-rate live on different objects).
SERVE_PROM_METRICS = (
    ("pjtpu_queries_total", "counter",
     "Queries answered by the serving engine",
     lambda e: e.stats.queries_total),
    ("pjtpu_query_errors_total", "counter",
     "Malformed or out-of-range queries rejected",
     lambda e: e.stats.errors),
    ("pjtpu_query_exact_total", "counter",
     "Queries answered exactly (store row or scheduled solve)",
     lambda e: e.stats.exact_answers),
    ("pjtpu_query_approx_total", "counter",
     "Queries answered from the landmark index (with max_error)",
     lambda e: e.stats.approx_answers),
    ("pjtpu_serve_batches_scheduled_total", "counter",
     "Exact solve batches the engine scheduled for store misses",
     lambda e: e.stats.batches_scheduled),
    ("pjtpu_stale_answers_total", "counter",
     "Answers served from a pre-update checkpoint while (or after) an "
     "incremental repair ran — every one carries stale: true",
     lambda e: e.stats.stale_answers),
    ("pjtpu_query_hit_rate", "gauge",
     "Fraction of row lookups served by a store tier (hot/warm/cold)",
     lambda e: e.store.hit_rate()),
    ("pjtpu_query_latency_p50_ms", "gauge",
     "Median per-query latency (batch-relative, most recent samples)",
     lambda e: e.stats.percentiles()["p50_ms"]),
    ("pjtpu_query_latency_p99_ms", "gauge",
     "99th-percentile per-query latency",
     lambda e: e.stats.percentiles()["p99_ms"]),
)

_MISS_POLICIES = ("solve", "landmark")


class QueryError(ValueError):
    """A malformed request (bad JSON shape, out-of-range vertex)."""


class QueryEngine:
    """Answers queries over one graph from a tile store (+ optional
    landmark index). ``config`` is the :class:`SolverConfig` the
    exact-miss solver runs under; its ``checkpoint_dir`` is overridden
    to the store's backing directory so scheduled batches persist into
    the cold tier (or to None for an in-memory store)."""

    def __init__(self, graph, store, *, landmarks=None, config=None,
                 miss_policy: str = "solve") -> None:
        import dataclasses as _dc

        from paralleljohnson_tpu.config import SolverConfig
        from paralleljohnson_tpu.solver import ParallelJohnsonSolver

        if miss_policy not in _MISS_POLICIES:
            raise ValueError(
                f"miss_policy must be one of {_MISS_POLICIES}, "
                f"got {miss_policy!r}"
            )
        if miss_policy == "landmark" and landmarks is None:
            raise ValueError(
                "miss_policy='landmark' requires a LandmarkIndex "
                "(build one or switch to miss_policy='solve')"
            )
        self.graph = graph
        self.store = store
        self.landmarks = landmarks
        self.miss_policy = miss_policy
        base = config or SolverConfig()
        self.config = _dc.replace(
            base,
            checkpoint_dir=str(store.root) if store.ckpt is not None else None,
        )
        self.solver = ParallelJohnsonSolver(self.config)
        self._tel = _resolve_telemetry(self.config.telemetry)
        self.stats = ServeStats()

    # -- request parsing -----------------------------------------------------

    def _parse(self, req: dict) -> dict:
        v = self.graph.num_nodes
        if not isinstance(req, dict):
            raise QueryError(f"query must be a JSON object, got {type(req).__name__}")
        if "source" not in req:
            raise QueryError("query is missing 'source'")
        try:
            source = int(req["source"])
        except (TypeError, ValueError):
            raise QueryError(f"bad source {req['source']!r}") from None
        if not 0 <= source < v:
            raise QueryError(f"source {source} out of range [0, {v})")
        dst = req.get("dst")
        if dst is not None:
            many = isinstance(dst, (list, tuple))
            try:
                dsts = np.asarray(
                    dst if many else [dst], np.int64
                )
            except (TypeError, ValueError):
                raise QueryError(f"bad dst {dst!r}") from None
            if dsts.ndim != 1 or (len(dsts) and (
                    dsts.min() < 0 or dsts.max() >= v)):
                raise QueryError(f"dst out of range [0, {v})")
        else:
            many = True
            dsts = None  # full row (all V destinations)
        mode = req.get("mode", self.miss_policy)
        if mode == "exact":
            mode = "solve"
        elif mode == "approx":
            mode = "landmark"
        if mode not in _MISS_POLICIES:
            raise QueryError(f"bad mode {req.get('mode')!r}")
        if mode == "landmark" and self.landmarks is None:
            raise QueryError("mode 'approx' needs a landmark index")
        return {"id": req.get("id"), "source": source, "dsts": dsts,
                "many": many, "mode": mode}

    # -- the serving loop ----------------------------------------------------

    def query(self, source: int, dst=None, *, mode: str | None = None) -> dict:
        """One request (see :meth:`query_batch`). ``dst``: vertex id for
        point-to-point, list for one-to-many, None for the full row."""
        req: dict = {"source": source, "dst": dst}
        if mode is not None:
            req["mode"] = mode
        out = self.query_batch([req])[0]
        if "error" in out:
            raise QueryError(out["error"])
        return out

    def query_batch(self, requests: list[dict]) -> list[dict]:
        """Answer many requests in one pass: each distinct source's row
        is fetched ONCE, every exact-mode miss joins one scheduled solve
        batch, responses come back in request order. Malformed requests
        yield ``{"error": ...}`` responses (the batch survives)."""
        t_batch = time.perf_counter()
        tel = self._tel
        with tel.span("serve_batch", n_queries=len(requests)):
            parsed: list[dict | None] = []
            responses: list[dict | None] = []
            for req in requests:
                try:
                    parsed.append(self._parse(req))
                    responses.append(None)
                except QueryError as e:
                    parsed.append(None)
                    self.stats.errors += 1
                    responses.append({
                        "id": req.get("id") if isinstance(req, dict) else None,
                        "error": str(e),
                    })

            # One row fetch per distinct source; one solve for ALL
            # exact-mode misses (the aggregation the tentpole names).
            rows: dict[int, tuple] = {}
            seen: set[int] = set()
            for p in parsed:
                if p is None or p["source"] in seen:
                    continue
                seen.add(p["source"])
                row, row_tier = self.store.get(p["source"])
                if row is not None:
                    rows[p["source"]] = (row, row_tier)
            missing_exact = sorted({
                p["source"] for p in parsed
                if p is not None and p["source"] not in rows
                and p["mode"] == "solve"
            })
            if missing_exact:
                batch = np.asarray(missing_exact, np.int64)
                with tel.span("serve_solve", n_sources=len(batch)):
                    res = self.solver.solve(self.graph, sources=batch)
                self.stats.batches_scheduled += 1
                self.stats.solved_sources += len(batch)
                self.store.put(res.sources, res.dist, tier="hot")
                if self.store.ckpt is not None:
                    self.store.invalidate_cold_index()
                for s, row in res.rows_by_source().items():
                    rows[s] = (row, "solved")

            for i, p in enumerate(parsed):
                if p is None:
                    continue
                with tel.span("query", source=p["source"],
                              many=p["many"]):
                    responses[i] = self._answer(p, rows)
                self.stats.queries_total += 1
                self.stats.record_latency(
                    (time.perf_counter() - t_batch) * 1e3
                )
            tel.progress(queries_done=self.stats.queries_total,
                         batches_scheduled=self.stats.batches_scheduled)
        return responses  # type: ignore[return-value]

    def _answer(self, p: dict, rows: dict[int, tuple]) -> dict:
        s, dsts, many = p["source"], p["dsts"], p["many"]
        out: dict = {"id": p["id"], "source": s}
        # Staleness contract (ISSUE 11): while (or after) an incremental
        # repair runs against this store's graph, every answer whose
        # source is in the repair's affected set reflects PRE-update
        # distances — exact for the old graph, flagged here so it is
        # never served as current silently. This applies to every tier
        # AND to freshly scheduled solves / landmark bounds: they all
        # answer for the engine's (pre-update) graph. Absence of the
        # key means the answer is provably current for the updated
        # graph too (the repair dependency argument).
        if self.store.is_stale(s):
            out["stale"] = True
            self.stats.stale_answers += 1
        hit = rows.get(s)
        if hit is not None:
            row, tier = hit
            vals = np.asarray(row if dsts is None else row[dsts],
                              np.float64)
            self.stats.exact_answers += 1
            out.update(exact=True, max_error=0.0, tier=tier)
        else:
            # Landmark path — approximation, always flagged with its
            # certified error bound.
            est, err = self.landmarks.estimate_row(s, dsts)
            vals = est
            self.stats.approx_answers += 1
            out.update(
                exact=False, tier="landmark",
                max_error=(
                    [float(e) for e in err] if many else float(err[0])
                ),
            )
        if many:
            out["dst"] = None if dsts is None else [int(d) for d in dsts]
            out["distances"] = [float(x) for x in vals]
        else:
            out["dst"] = int(dsts[0])
            out["distance"] = float(vals[0])
        return out

    # -- warm-up and ops surface ---------------------------------------------

    def warm(self, sources) -> int:
        """Pre-solve ``sources`` into the store (one scheduled batch for
        whichever of them the store does not already hold). Returns how
        many sources were actually solved."""
        missing = [int(s) for s in np.asarray(sources, np.int64)
                   if self.store.get(int(s))[0] is None]
        if not missing:
            return 0
        batch = np.asarray(sorted(set(missing)), np.int64)
        with self._tel.span("serve_warm", n_sources=len(batch)):
            res = self.solver.solve(self.graph, sources=batch)
        self.stats.batches_scheduled += 1
        self.stats.solved_sources += len(batch)
        self.store.put(res.sources, res.dist, tier="hot")
        if self.store.ckpt is not None:
            self.store.invalidate_cold_index()
        return len(batch)

    def query_lines(self, lines) -> tuple[list[dict], int]:
        """Parse JSONL request lines and answer them as one aggregated
        batch. Returns ``(responses_in_order, n_errors)`` — a malformed
        line becomes an ``{"error": ...}`` response, never a crash (the
        request loop must survive any input)."""
        requests: list[dict] = []
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                if not isinstance(obj, dict):
                    raise ValueError("not a JSON object")
                requests.append(obj)
            except ValueError as e:
                requests.append({"_parse_error": f"line {i + 1}: {e}"})
        for r in requests:
            if "_parse_error" in r:
                r.pop("source", None)  # force the engine's error path
        responses = self.query_batch([
            r if "_parse_error" not in r else {"source": None}
            for r in requests
        ])
        for r, resp in zip(requests, responses):
            if "_parse_error" in r and "error" in resp:
                resp["error"] = r["_parse_error"]
        n_errors = sum(1 for r in responses if "error" in r)
        return responses, n_errors

    def write_metrics(self, path, *, labels: dict | None = None) -> Path:
        """Prometheus textfile export (``pjtpu_queries_total``,
        ``pjtpu_query_latency_p50_ms`` / ``_p99_ms``, hit rate, ...)."""
        return write_prom_metrics(self, path, labels=labels,
                                  metrics=SERVE_PROM_METRICS)

    def serve_summary(self) -> dict:
        return {
            "engine": self.stats.as_dict(),
            "store": self.store.stats(),
            "landmarks": 0 if self.landmarks is None else self.landmarks.k,
            "miss_policy": self.miss_policy,
        }

    def close(self) -> None:
        """Persist the serving counters next to the store's batches
        (atomic) so ``pjtpu info --serve-store`` can report capacity,
        landmark count, and hit rates after the loop exits. Does NOT
        close the telemetry façade — its owner (the CLI) does."""
        if self.store.ckpt is None:
            return
        path = self.store.ckpt.dir / SERVE_STATS_FILENAME
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(self.serve_summary()), encoding="utf-8")
        os.replace(tmp, path)
