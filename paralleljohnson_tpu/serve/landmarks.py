"""Landmark (pivot-source) distance approximation with error bounds.

The fast path for queries on sources nobody solved yet (PAPERS.md
"Faster Parallel Algorithm for Approximate Shortest Path", arXiv:
1911.01626 — the hopset idea of answering through a small set of
well-connected intermediate vertices). At store build, k pivot sources
are solved EXACTLY, twice: forward rows ``fwd[L] = d(L, ·)`` on the
graph and reverse rows ``rev[L] = d(·, L)`` on the edge-reversed graph
(``CSRGraph.reverse``). A query (s, t) then gets directed
triangle-inequality bounds:

  upper = min_L  d(s, L) + d(L, t)             (a real path through L)
  lower = max_L  max(d(L, t) - d(L, s),  d(s, L) - d(t, L))
          (each from one application of d(x, z) <= d(x, y) + d(y, z);
           vacuous terms — subtrahend +inf — are skipped)

so ``lower <= d(s, t) <= upper`` always holds, with IEEE inf arithmetic
carrying unreachability: a finite ``d(L, s)`` with infinite ``d(L, t)``
PROVES ``d(s, t) = +inf`` (lower = +inf). The estimate returned is the
upper bound and ``max_error = upper - lower`` — an approximation is
never unflagged: callers get the bound, not a guess.

Non-negative graphs additionally clamp ``lower >= 0`` (and the engine
answers s == t as exactly 0 — the empty path; negative-cycle-free
graphs cannot beat it).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.utils.checkpoint import graph_digest

LANDMARKS_FILENAME = "landmarks.npz"

# Pivot pickers for :meth:`LandmarkIndex.build` (ISSUE 16 satellite;
# "boundary" added by ISSUE 17 — the partitioned route's ready-made
# high-coverage set, ROADMAP item 3).
PIVOT_PICKERS = ("uniform", "coverage", "boundary")


def widen_bounds(lower, upper, *, nonnegative: bool):
    """The f32-slack widening + non-negative clamp, split out of
    :meth:`LandmarkIndex.bounds_row` so the device-resident query path
    (``serve/device_query.py``) can compute the RAW min/max/add/sub
    bounds on-device and finish them through this exact host code —
    bitwise identity between the lookup paths is then a consequence of
    running the same instructions, not a numerical accident.

    The triangle inequality is exact for TRUE distances, but the
    solver's rows are f32 path sums — two independently rounded sums can
    violate it by a few ULP. Widen both bounds by a small relative
    tolerance (the ops/pred.py tight-edge idiom) so ``lower <= exact <=
    upper`` is a contract, not a coin flip; the widening is ~1e-5
    relative — invisible next to any real approximation gap. The clamp
    at 0 (non-negative graphs) and +inf values stay exact: no slack
    applies to them."""
    tol = 32 * float(np.finfo(np.float32).eps)
    with np.errstate(invalid="ignore"):  # inf-inf in discarded branches
        finite_lo = np.isfinite(lower)
        lower = np.where(
            finite_lo, lower - tol * (1.0 + np.abs(lower)), lower
        )
        finite_up = np.isfinite(upper)
        upper = np.where(
            finite_up, upper + tol * (1.0 + np.abs(upper)), upper
        )
    if nonnegative:
        lower = np.maximum(lower, 0.0)
    return lower, upper


def finish_estimates(lower, upper):
    """``(estimates, max_errors)`` from WIDENED bounds — the serving
    contract per entry: proven-inf pairs report ``(inf, 0)``, unknown
    ones ``(inf, inf)``, everything else ``(upper, upper - lower)``.
    Shared by the host and device lookup paths (same rationale as
    :func:`widen_bounds`)."""
    proven_inf = np.isinf(lower) & (lower > 0)
    est = np.where(proven_inf, np.inf, upper)
    with np.errstate(invalid="ignore"):
        gap = upper - lower
    err = np.where(proven_inf, 0.0,
                   np.where(np.isfinite(gap), gap, np.inf))
    return est, err


def boundary_vertices(graph, *, labels=None, seed: int = 0) -> np.ndarray:
    """The partitioned route's boundary-vertex set: endpoints of edges
    whose two ends carry different partition labels. ``labels`` is an
    ``int[V]`` partition labeling (``solver.partitioned``'s attach-time
    labels when the caller has them); None computes a fresh seeded
    ``partition_by_pivots`` labeling — deterministic for (graph, seed).
    Empty when the graph condenses to one part (no cross edges)."""
    from paralleljohnson_tpu.solver.partitioned import (
        auto_num_parts,
        partition_by_pivots,
    )

    v = graph.num_nodes
    if labels is None:
        labels = partition_by_pivots(graph, auto_num_parts(v), seed=seed)
    labels = np.asarray(labels)
    if labels.shape != (v,):
        raise ValueError(
            f"labels must be shape ({v},), got {labels.shape}"
        )
    e = graph.num_real_edges
    src = graph.src[:e]
    dst = graph.indices[:e]
    cross = labels[src] != labels[dst]
    mask = np.zeros(v, bool)
    mask[src[cross]] = True
    mask[dst[cross]] = True
    return np.flatnonzero(mask).astype(np.int64)


def pick_pivots(graph, k: int, *, seed: int = 0,
                picker: str = "uniform", labels=None) -> np.ndarray:
    """Seeded pivot draw. ``"uniform"`` (the default, unchanged) draws
    without replacement from all vertices; ``"coverage"`` weights the
    draw by total degree (in + out + 1) — on power-law graphs the
    high-degree hubs sit on far more shortest paths, so a pivot set
    biased toward them tightens the triangle-inequality interval for
    the same k (the partitioned route's boundary-vertex observation).
    ``"boundary"`` (ISSUE 17, ROADMAP item 3) draws from that
    observation's LITERAL set — the partitioned route's boundary
    vertices (:func:`boundary_vertices`, using the caller's partition
    ``labels`` when given, else a fresh seeded labeling): every
    cross-part shortest path passes through one, so they cover pairs a
    degree heuristic can miss on low-degree road-like graphs; when the
    boundary set is smaller than k (a one-part graph has none) the draw
    falls back to ``coverage``. All three are deterministic for a given
    (graph, k, seed[, labels])."""
    if picker not in PIVOT_PICKERS:
        raise ValueError(
            f"picker must be one of {PIVOT_PICKERS}, got {picker!r}"
        )
    v = graph.num_nodes
    k = max(0, min(int(k), v))
    if k == 0:
        return np.zeros(0, np.int64)
    rng = np.random.default_rng(seed)
    if picker == "boundary":
        try:
            boundary = boundary_vertices(graph, labels=labels, seed=seed)
        except ValueError:
            raise
        except Exception:  # noqa: BLE001 — labeling failure degrades, never crashes
            boundary = np.zeros(0, np.int64)
        if len(boundary) >= k:
            return np.sort(rng.choice(boundary, size=k, replace=False))
        picker = "coverage"
    if picker == "coverage":
        indptr = np.asarray(graph.indptr, np.int64)
        out_deg = np.diff(indptr)
        # Only CSR-owned edges count — the pad tail (indices past
        # indptr[-1]) belongs to no row and must not skew vertex 0.
        in_deg = np.bincount(
            np.asarray(graph.indices[:indptr[-1]], np.int64),
            minlength=v,
        )[:v]
        w = (out_deg + in_deg + 1).astype(np.float64)
        return np.sort(rng.choice(v, size=k, replace=False, p=w / w.sum()))
    return np.sort(rng.choice(v, size=k, replace=False))


@dataclasses.dataclass
class Bounds:
    """One query's certified interval. ``estimate`` is the value a caller
    should serve (the upper bound — a realizable path length, or +inf
    when no landmark connects the pair); ``max_error`` bounds
    ``|estimate - exact|`` (0 when the interval pins the answer, +inf
    when the landmarks carry no information about the pair)."""

    lower: float
    upper: float

    @property
    def estimate(self) -> float:
        # Both bounds infinite: d(s,t) is PROVEN +inf (lower <= exact).
        if np.isinf(self.lower) and self.lower > 0:
            return float("inf")
        return self.upper

    @property
    def max_error(self) -> float:
        if np.isinf(self.lower) and self.lower > 0:
            return 0.0  # proven unreachable: the estimate is exact
        err = self.upper - self.lower
        return float(err) if np.isfinite(err) else float("inf")


class LandmarkIndex:
    """k exact pivot solves answering any pair with a certified interval.

    ``fwd``/``rev`` are host ``[k, V]`` arrays (k is small — the index
    costs 2k exact SSSP rows, solved once through the ordinary resilient
    solver at build). Pivots are a deterministic seeded uniform draw:
    good enough for bound quality at this stage, and reproducible so a
    persisted index can be validated against a rebuild.
    """

    def __init__(self, sources: np.ndarray, fwd: np.ndarray,
                 rev: np.ndarray, *, nonnegative: bool,
                 digest: str | None = None) -> None:
        self.sources = np.asarray(sources, np.int64)
        # f64 working copies: the bound arithmetic must not add rounding
        # of its own on top of the solver's (k is small; 2 x k x V f64
        # is cheap next to the store's row tiers).
        self.fwd = np.asarray(fwd, np.float64)
        self.rev = np.asarray(rev, np.float64)
        self.nonnegative = bool(nonnegative)
        self.digest = digest
        if self.fwd.shape != self.rev.shape or len(self.fwd) != len(self.sources):
            raise ValueError(
                f"inconsistent landmark shapes: sources {self.sources.shape}, "
                f"fwd {self.fwd.shape}, rev {self.rev.shape}"
            )

    @property
    def k(self) -> int:
        return len(self.sources)

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, graph, k: int, *, config=None, seed: int = 0,
              solver=None, picker: str = "uniform",
              labels=None) -> "LandmarkIndex":
        """Solve ``k`` seeded pivots exactly (forward + reverse graph)
        through the resilient solver — retries, OOM degradation, and the
        pipeline all apply, exactly like any other solve. ``picker``
        selects the pivot draw (:func:`pick_pivots`): ``"uniform"``
        (default, unchanged), ``"coverage"`` (degree-weighted, for
        power-law graphs), or ``"boundary"`` (the partitioned route's
        boundary-vertex set; ``labels`` optionally supplies attach-time
        partition labels)."""
        from paralleljohnson_tpu.solver import ParallelJohnsonSolver

        v = graph.num_nodes
        pivots = pick_pivots(graph, k, seed=seed, picker=picker,
                             labels=labels)
        k = len(pivots)
        if solver is None:
            solver = ParallelJohnsonSolver(config)
        if k == 0:
            empty = np.zeros((0, v), graph.dtype)
            return cls(pivots, empty, empty,
                       nonnegative=not graph.has_negative_weights,
                       digest=graph_digest(graph))
        fwd = np.asarray(solver.solve(graph, sources=pivots).dist)
        rev = np.asarray(solver.solve(graph.reverse(), sources=pivots).dist)
        return cls(pivots, fwd, rev,
                   nonnegative=not graph.has_negative_weights,
                   digest=graph_digest(graph))

    # -- bounds --------------------------------------------------------------

    def bounds(self, s: int, t: int) -> Bounds:
        row = self.bounds_row(s, np.array([t], np.int64))
        return Bounds(lower=float(row[0][0]), upper=float(row[1][0]))

    def raw_bounds_row(self, s: int, dsts: np.ndarray | None = None):
        """The pure add/sub/min/max triangle-inequality bounds, BEFORE
        the f32-slack widening and non-negative clamp (those live in
        :func:`widen_bounds`). This split is the device-parity seam: the
        raw part is elementwise adds/subs plus order-independent min/max
        reductions over values that are never NaN, so a device kernel
        computing it in f64 is bitwise identical to this numpy code —
        the finishing always runs on host through the shared helpers."""
        d_s_L = self.rev[:, s]          # [k]  d(s, L)
        d_L_s = self.fwd[:, s]          # [k]  d(L, s)
        fwd_t = self.fwd if dsts is None else self.fwd[:, dsts]  # [k, D]
        rev_t = self.rev if dsts is None else self.rev[:, dsts]  # [k, D]
        n_dst = fwd_t.shape[1]
        if self.k == 0:
            return np.full(n_dst, -np.inf), np.full(n_dst, np.inf)
        with np.errstate(invalid="ignore"):
            upper_c = d_s_L[:, None] + fwd_t        # path s -> L -> t
            # inf + inf = inf is fine; (+inf) + (-anything) never occurs
            # (distances are never -inf on negative-cycle-free graphs).
            upper = np.min(upper_c, axis=0)
            # d(L,t) - d(L,s) valid iff d(L,s) finite; vacuous -> -inf.
            a = np.where(np.isfinite(d_L_s)[:, None], fwd_t - d_L_s[:, None],
                         -np.inf)
            # d(s,L) - d(t,L) valid iff d(t,L) finite; vacuous -> -inf.
            b = np.where(np.isfinite(rev_t), d_s_L[:, None] - rev_t, -np.inf)
        lower = np.maximum(np.max(a, axis=0), np.max(b, axis=0))
        return lower, upper

    def bounds_row(self, s: int, dsts: np.ndarray | None = None):
        """Vectorized one-to-many bounds from source ``s``: returns
        ``(lower[len(dsts)], upper[len(dsts)])`` (all V destinations when
        ``dsts`` is None)."""
        if self.k == 0:
            n_dst = self.fwd.shape[1] if dsts is None else len(dsts)
            lower = np.zeros(n_dst) if self.nonnegative else np.full(
                n_dst, -np.inf)
            return lower, np.full(n_dst, np.inf)
        lower, upper = self.raw_bounds_row(s, dsts)
        return widen_bounds(lower, upper, nonnegative=self.nonnegative)

    def estimate(self, s: int, t: int) -> tuple[float, float]:
        """``(estimate, max_error)`` for one pair — the serving contract:
        ``|estimate - d(s, t)| <= max_error`` (inf-aware: a proven-inf
        pair reports ``(inf, 0)``; an unknown one ``(inf, inf)``)."""
        b = self.bounds(s, t)
        return b.estimate, b.max_error

    def estimate_row(self, s: int, dsts: np.ndarray | None = None):
        """Vectorized :meth:`estimate` — ``(estimates, max_errors)``
        arrays for a one-to-many query, same per-entry semantics."""
        lower, upper = self.bounds_row(s, dsts)
        return finish_estimates(lower, upper)

    # -- persistence ---------------------------------------------------------

    def save(self, directory: str | Path) -> Path:
        """Persist next to the tile store's batches (one npz: pivots +
        both row blocks + the graph digest that keys validity)."""
        path = Path(directory) / LANDMARKS_FILENAME
        tmp = path.with_suffix(".tmp.npz")
        np.savez_compressed(
            tmp, sources=self.sources, fwd=self.fwd, rev=self.rev,
            nonnegative=np.array(self.nonnegative),
            digest=np.array(self.digest or ""),
        )
        tmp.rename(path)
        return path

    @classmethod
    def load(cls, directory: str | Path, *,
             expect_digest: str | None = None) -> "LandmarkIndex | None":
        """Load a persisted index; None when absent, unreadable, or built
        for a different graph (digest mismatch — a stale index must
        rebuild, never silently bound the wrong graph)."""
        path = Path(directory) / LANDMARKS_FILENAME
        if not path.exists():
            return None
        try:
            with np.load(path) as data:
                digest = str(data["digest"]) if "digest" in data.files else ""
                if expect_digest is not None and digest != expect_digest:
                    return None
                return cls(
                    data["sources"], data["fwd"], data["rev"],
                    nonnegative=bool(data["nonnegative"]),
                    digest=digest or None,
                )
        except Exception:  # noqa: BLE001 — a torn index is a rebuild, not a crash
            return None
