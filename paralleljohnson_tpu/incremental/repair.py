"""Dirty-part repair: make a batch of edge updates cheap.

Given a solved ``--checkpoint-dir`` and a batch of edge updates, this
engine produces the POST-update checkpoint without a full re-solve, by
repairing along the condensed decomposition the persisted
:class:`~paralleljohnson_tpu.incremental.state.IncrementalState`
tracks:

1. **Diagnose** — map each changed edge through the partition labels:
   a within-part change dirties that part's closure, a cross-part
   change dirties the boundary core. Everything else is clean by the
   digest-dependency argument (a part's closure depends only on its
   internal edges).
2. **Re-close** ONLY dirty parts (through the ordinary resilient
   solver — retries / watchdog / OOM degradation / fault injection all
   apply) and, when anything that feeds it changed, the boundary core.
3. **Re-expand only affected source ranges.** The affected set is
   computed from BITWISE comparisons of the recomputed factors against
   the cached ones, so "dirty" work that turned out not to change any
   distance (a reweighted edge that was never tight) shrinks the
   affected set to nothing:

   - sources in a part whose local closure changed, or whose boundary
     rows of the core changed, need FULL row re-expansion (their
     source-to-core distances moved);
   - sources in clean parts need only COLUMN patches at target parts
     whose outsider-visible block (``local[boundary_rows, :]``)
     changed — their source-to-core distances are bitwise unchanged,
     so every other column is provably identical;
   - if the boundary SET itself changed (cross edges appeared or
     vanished), everything re-expands — correct and rare.

4. **Commit** each repaired batch through the existing
   corruption-checked checkpoint writer (``checked_save``) into the NEW
   graph digest's subdirectory — batch files appear atomically
   (tmp+rename), so the repaired checkpoint swaps in per part while the
   old directory keeps serving stale-but-flagged answers
   (``incremental.status``).

**Exactness.** Repaired rows are the condensed decomposition's values;
copied rows are the old solver's values, kept only when the
decomposition proves them unchanged. On integer (exactly-representable)
weights every route agrees bitwise, so the repaired checkpoint is
bitwise-identical to a fresh full solve of the updated graph — asserted
by the property tests and the ``incremental_update`` bench. On general
f32 weights the repair agrees to the same ULP-level reassociation as
the condensed route itself. Negative-cycle detection is complete: a
new cycle must contain a changed edge, so it surfaces either closing
that edge's part or closing the recomputed core (if every recomputed
closure is bitwise unchanged and no cross edge changed, no cycle can
have appeared). Predecessor arrays are NOT repaired — a pred-bearing
checkpoint repairs distances only (re-solve with ``--predecessors``
for fresh trees).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.graphs import CSRGraph
from paralleljohnson_tpu.incremental import status as repair_status
from paralleljohnson_tpu.incremental.state import (
    IncrementalState,
    close_core,
    close_part,
    closure_solver,
    compute_core_digest,
    compute_part_digest,
    _within_selector,
)
from paralleljohnson_tpu.utils.checkpoint import (
    BatchCheckpointer,
    checked_save,
    graph_digest,
)
from paralleljohnson_tpu.observe.live import resolve_metrics as _resolve_metrics
from paralleljohnson_tpu.observe.trace import trace_attrs as _trace_attrs
from paralleljohnson_tpu.utils.telemetry import resolve as _resolve_telemetry

ROUTE_TAG = "incremental-repair"


def _np_minplus(d: np.ndarray, a: np.ndarray, *, b_block: int = 128,
                k_block: int = 128, n_block: int = 512) -> np.ndarray:
    """Blocked host-side min-plus product ``out[i, j] = min_k d[i, k] +
    a[k, j]`` — the repair expansion kernel. Host numpy, not the jitted
    ``relax.minplus``: repair's inputs (cached closures) already live
    on the host, the row workload is one-shot per update batch (a jit
    compile per padded shape bucket would dominate the repair wall the
    bench measures), and the result is bitwise-identical anyway — the
    min ranges over the exact same multiset of f32 sums regardless of
    blocking or device. Blocks bound the broadcast temp to
    ``b_block x k_block x n_block`` floats."""
    out = np.full((d.shape[0], a.shape[1]), np.inf,
                  dtype=np.result_type(d, a))
    for b0 in range(0, d.shape[0], b_block):
        db = d[b0:b0 + b_block]
        for n0 in range(0, a.shape[1], n_block):
            ab = a[:, n0:n0 + n_block]
            acc = out[b0:b0 + b_block, n0:n0 + n_block]
            for k0 in range(0, d.shape[1], k_block):
                cand = (
                    db[:, k0:k0 + k_block, None]
                    + ab[None, k0:k0 + k_block, :]
                )
                np.minimum(acc, cand.min(axis=1), out=acc)
    return out


def _np_minplus_macs(b: int, k: int, n: int) -> int:
    """Exact candidate ops of one host min-plus product (unpadded — the
    host kernel performs no pad no-ops, so none are counted)."""
    return int(b) * int(k) * int(n)


@dataclasses.dataclass
class DirtySet:
    """The diagnosis: which closures a batch of changed edges
    invalidates (digest-level reasoning over the partition — no solve
    work; what ``pjtpu update --dry-run`` and ``cli info`` print)."""

    num_parts: int
    dirty_parts: list
    within_changed: dict
    cross_changed: int
    core_dirty: bool

    def as_dict(self) -> dict:
        return {
            "num_parts": self.num_parts,
            "dirty_parts": [int(p) for p in self.dirty_parts],
            "within_changed": {
                str(k): int(v) for k, v in sorted(self.within_changed.items())
            },
            "cross_changed": self.cross_changed,
            "core_dirty": self.core_dirty,
        }


def diagnose(state: IncrementalState, changed_edges) -> DirtySet:
    """Map changed edges to the minimal dirty set through the
    partition labels (see class docstring)."""
    labels = state.labels
    within: dict[int, int] = {}
    cross = 0
    for (u, v, _old, _new) in changed_edges:
        if labels[u] == labels[v]:
            p = int(labels[u])
            within[p] = within.get(p, 0) + 1
        else:
            cross += 1
    return DirtySet(
        num_parts=state.num_parts,
        dirty_parts=sorted(within),
        within_changed=within,
        cross_changed=cross,
        core_dirty=cross > 0,
    )


@dataclasses.dataclass
class RepairResult:
    """What one repair did (``as_dict`` is the CLI/bench surface)."""

    old_digest: str
    new_digest: str
    trivial: bool
    parts_total: int
    dirty_parts_closed: int
    core_recomputed: bool
    boundary_changed: bool
    full_row_parts: list
    col_parts: list
    affected_rows: int
    rows_recomputed: int
    rows_patched: int
    rows_copied: int
    batches_rewritten: int
    expand_macs: int
    closures_s: float
    expand_s: float
    io_s: float
    wall_s: float
    diag: DirtySet | None = None
    plan: dict | None = None

    def as_dict(self) -> dict:
        out = {
            k: getattr(self, k)
            for k in (
                "old_digest", "new_digest", "trivial", "parts_total",
                "dirty_parts_closed", "core_recomputed", "boundary_changed",
                "affected_rows", "rows_recomputed", "rows_patched",
                "rows_copied", "batches_rewritten", "expand_macs",
            )
        }
        out["full_row_parts"] = [int(p) for p in self.full_row_parts]
        out["col_parts"] = [int(p) for p in self.col_parts]
        for k in ("closures_s", "expand_s", "io_s", "wall_s"):
            out[k] = round(float(getattr(self, k)), 6)
        if self.diag is not None:
            out["dirty_set"] = self.diag.as_dict()
        if self.plan is not None:
            out["plan"] = self.plan
        return out


# -- repair-vs-resolve plan registry (ISSUE 19 satellite) --------------------
#
# Whether an update batch is cheaper to REPAIR (dirty-part closures +
# affected-row re-expansion) or to RE-SOLVE outright used to be the
# caller's problem — ``pjtpu update`` always repaired. It is now the
# same priced ``select()`` walk as every dispatch decision, with each
# side priced at its HONEST work unit via ``Plan.price_batch``: repair
# at the estimated affected-row count (from the digest-level diagnosis
# — no closure work is paid before the decision), resolve at B=V. The
# ``kind:"repair"`` records every repair lands (route
# ``incremental-repair``) are the calibration that makes the repair
# side priceable. Unpriced, priority order keeps the old behavior:
# repair first, always.


REPAIR_PLANS = [
    # Imported lazily below to keep module import order stable; filled
    # at first use via _repair_plans().
]


def _repair_plans():
    if REPAIR_PLANS:
        return REPAIR_PLANS
    from paralleljohnson_tpu import planner as _planner

    REPAIR_PLANS.extend([
        _planner.Plan(
            name="repair", entry="repair", priority=10,
            qualify=lambda ctx: (
                True, "dirty-part repair is the incremental default"
            ),
            price_routes=("incremental-repair",),
            forced=lambda cfg: getattr(
                cfg, "repair_strategy", "auto") == "repair",
            force_overrides={"repair_strategy": "repair"},
            price_batch=lambda ctx: max(1, int(ctx.affected_rows)),
            tunables=("partition_parts",),
        ),
        _planner.Plan(
            name="resolve", entry="repair", priority=20,
            qualify=lambda ctx: (True, "full re-solve always qualifies"),
            price_routes=(
                "vm-blocked+dw", "vm-blocked", "gs", "dia", "vm",
                "sweep-sm",
            ),
            forced=lambda cfg: getattr(
                cfg, "repair_strategy", "auto") == "resolve",
            force_overrides={"repair_strategy": "resolve"},
            price_batch=lambda ctx: int(ctx.num_nodes),
        ),
    ])
    return REPAIR_PLANS


def estimate_affected_rows(state, diag, num_nodes: int) -> int:
    """Digest-level UPPER BOUND on the rows a repair would re-expand,
    before any closure runs: rows in dirty parts re-expand fully; a
    dirty core (cross-part change) conservatively touches everything
    (the bitwise affected-set refinement needs the closures we are
    deciding whether to pay for). No state → no decomposition to
    repair along → everything."""
    if state is None or diag is None:
        return int(num_nodes)
    if diag.core_dirty:
        return int(num_nodes)
    parts, _lids, _bl, _bc = state.indices()
    part_pos = {int(p): i for i, p in enumerate(state.part_ids)}
    rows = sum(
        int(parts[part_pos[int(p)]].size)
        for p in diag.dirty_parts if int(p) in part_pos
    )
    return min(int(num_nodes), rows)


def decide_repair_strategy(
    checkpoint_dir,
    graph: CSRGraph,
    report,
    *,
    config=None,
    state: IncrementalState | None = None,
    strategy: str = "auto",
):
    """Walk :data:`REPAIR_PLANS` for one update batch. ``report`` is
    the ``apply_edge_updates`` report (old/new digests + changed
    edges). Returns the ``PlanDecision``; unpriced it always chooses
    ``repair`` (the pre-ISSUE-19 behavior, asserted by the parity
    test). ``strategy`` pins a side ("repair"/"resolve") through the
    ordinary forced-plan mechanism."""
    import types as _types

    from paralleljohnson_tpu import planner as _planner
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.observe import current_platform

    cfg = config if config is not None else SolverConfig()
    if strategy not in ("auto", "repair", "resolve"):
        raise ValueError(
            f"repair strategy must be auto/repair/resolve, got {strategy!r}"
        )
    if state is None:
        old_ckpt = BatchCheckpointer(
            checkpoint_dir, graph_key=report.old_digest
        )
        try:
            state = IncrementalState.load(
                old_ckpt.dir, expect_digest=report.old_digest
            )
        except Exception:  # noqa: BLE001 — unreadable state = no state
            state = None
    diag = (
        diagnose(state, report.changed_edges) if state is not None else None
    )
    v = graph.num_nodes
    affected = estimate_affected_rows(state, diag, v)
    ctx = _types.SimpleNamespace(
        state=state, diag=diag, affected_rows=affected, num_nodes=v,
        config=cfg, params={},
    )
    model = None
    if getattr(cfg, "planner", True) is not False:
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir
        from paralleljohnson_tpu.observe.tuning import cached_records

        store_dir = resolve_profile_dir(
            getattr(cfg, "profile_store", None)
        )
        records = cached_records(store_dir) if store_dir else []
        if records:
            from paralleljohnson_tpu.observe.store import CostModel

            try:
                model = CostModel.fit(records)
            except Exception:  # noqa: BLE001 — unreadable = unpriced
                model = None
    decision = _planner.select(
        _repair_plans(), ctx, model=model, platform=current_platform(),
        num_edges=graph.num_real_edges, batch=max(1, affected),
        config=_types.SimpleNamespace(repair_strategy=strategy),
    )
    decision.params.update(
        affected_rows_estimate=int(affected),
        dirty_parts=len(diag.dirty_parts) if diag is not None else None,
    )
    return decision


class RepairPlan:
    """Everything between diagnosis and batch rewriting: the recomputed
    factors, the affected-set classification, and the per-row repair
    primitives the serial engine AND the repair fleet share."""

    def __init__(self, *, checkpoint_root, old_graph, new_graph, report,
                 state_old, config, telemetry) -> None:
        self.checkpoint_root = Path(checkpoint_root)
        self.old_graph = old_graph
        self.new_graph = new_graph
        self.report = report
        self.state_old = state_old
        self.state_new: IncrementalState | None = None
        self.config = config
        self.tel = telemetry
        self.diag: DirtySet | None = None
        self.trivial = report.num_changed == 0
        self.boundary_changed = False
        self.core_recomputed = False
        self.full_row_parts: set[int] = set()   # positions into part_ids
        self.col_parts: set[int] = set()        # positions into part_ids
        self.full_mask = np.zeros(old_graph.num_nodes, bool)
        self.closures_s = 0.0
        self.expand_s = 0.0
        self.expand_macs = 0
        digest = report.old_digest
        self.old_ckpt = BatchCheckpointer(checkpoint_root, graph_key=digest)
        self.new_ckpt: BatchCheckpointer | None = None

    # -- affected-set surface ------------------------------------------------

    @property
    def patch_all(self) -> bool:
        """True when every non-full row still needs column patches."""
        return bool(self.col_parts)

    def affected_sources(self):
        """``"all"`` or the sorted array of sources whose rows may
        change — the staleness set the serve layer flags."""
        if self.trivial:
            return np.array([], np.int64)
        if self.patch_all or self.full_mask.all():
            return "all"
        return np.flatnonzero(self.full_mask).astype(np.int64)

    def row_action(self, source: int) -> str:
        """``"recompute"`` / ``"patch"`` / ``"copy"`` for one row."""
        if self.full_mask[int(source)]:
            return "recompute"
        return "patch" if self.patch_all else "copy"

    # -- row repair primitives ----------------------------------------------

    def recompute_rows(self, sources) -> np.ndarray:
        """Full expansion of the given sources' rows from the NEW
        state's factors — arithmetic-identical to the condensed route's
        expansion stage (same candidate-path enumeration; the host
        min-plus takes the min over the identical sum multiset), so
        integer-weight rows land bitwise where a fresh solve would."""
        _mp, _mp_macs = _np_minplus, _np_minplus_macs
        st = self.state_new
        parts, lids, blocal, bcore = st.indices()
        sources = np.asarray(sources, np.int64)
        v = len(st.labels)
        nc = st.boundary.size
        t0 = time.perf_counter()
        dist = np.full((sources.size, v), np.inf,
                       dtype=self.new_graph.dtype)
        part_pos = {int(p): i for i, p in enumerate(st.part_ids)}
        by_part: dict[int, list[int]] = {}
        for i, s in enumerate(sources):
            by_part.setdefault(int(st.labels[s]), []).append(i)
        for p, rows in sorted(by_part.items()):
            pi = part_pos[p]
            rows = np.asarray(rows, np.int64)
            verts = parts[pi]
            ls = lids[sources[rows]]
            local_p = st.locals_closed[pi]
            dist[np.ix_(rows, verts)] = local_p[ls]
            if nc == 0 or blocal[pi].size == 0:
                continue  # no way out of this part: local rows are final
            s2core = _mp(
                local_p[np.ix_(ls, blocal[pi])], st.core_closed[bcore[pi]]
            )
            self.expand_macs += _mp_macs(rows.size, blocal[pi].size, nc)
            for qi, verts_q in enumerate(parts):
                if blocal[qi].size == 0:
                    continue  # no way into q from outside
                upd = _mp(
                    s2core[:, bcore[qi]], st.locals_closed[qi][blocal[qi]]
                )
                self.expand_macs += _mp_macs(
                    rows.size, blocal[qi].size, verts_q.size
                )
                dist[np.ix_(rows, verts_q)] = np.minimum(
                    dist[np.ix_(rows, verts_q)], upd
                )
        self.expand_s += time.perf_counter() - t0
        return dist

    def patch_rows(self, sources, rows: np.ndarray) -> np.ndarray:
        """Column patches (in place) for CLEAN-part rows: replace the
        columns of every part whose outsider-visible block changed.
        These sources' source-to-core distances are bitwise unchanged
        (that is what kept them out of the full set), so the patched
        columns are the complete decomposition value — a replace, not a
        min against stale data — and every other column is provably
        identical to the old row."""
        _mp, _mp_macs = _np_minplus, _np_minplus_macs
        if not self.col_parts:
            return rows
        st = self.state_new
        parts, lids, blocal, bcore = st.indices()
        sources = np.asarray(sources, np.int64)
        t0 = time.perf_counter()
        part_pos = {int(p): i for i, p in enumerate(st.part_ids)}
        by_part: dict[int, list[int]] = {}
        for i, s in enumerate(sources):
            if not self.full_mask[int(s)]:
                by_part.setdefault(int(st.labels[s]), []).append(i)
        for p, ridx in sorted(by_part.items()):
            qi = part_pos[p]
            if blocal[qi].size == 0:
                continue  # no escape from this part: cross columns stay inf
            ridx = np.asarray(ridx, np.int64)
            ls = lids[sources[ridx]]
            s2core = _mp(
                st.locals_closed[qi][np.ix_(ls, blocal[qi])],
                st.core_closed[bcore[qi]],
            )
            self.expand_macs += _mp_macs(
                ridx.size, blocal[qi].size, st.boundary.size
            )
            for pi in sorted(self.col_parts):
                if blocal[pi].size == 0:
                    continue
                upd = _mp(
                    s2core[:, bcore[pi]], st.locals_closed[pi][blocal[pi]]
                )
                self.expand_macs += _mp_macs(
                    ridx.size, blocal[pi].size, parts[pi].size
                )
                rows[np.ix_(ridx, parts[pi])] = upd
        self.expand_s += time.perf_counter() - t0
        return rows

    def repair_batch_rows(self, sources, old_rows: np.ndarray | None):
        """One batch's repaired rows + (recomputed, patched, copied)
        counts. ``old_rows=None`` (corrupt/unreadable old batch) falls
        back to recomputing every row — degraded, never wrong."""
        sources = np.asarray(sources, np.int64)
        if old_rows is None:
            rows = self.recompute_rows(sources)
            return rows, (sources.size, 0, 0)
        rows = np.array(old_rows, copy=True)
        full_sel = self.full_mask[sources]
        patched = 0
        if self.patch_all and (~full_sel).any():
            rows = self.patch_rows(sources, rows)
            patched = int((~full_sel).sum())
        if full_sel.any():
            rows[full_sel] = self.recompute_rows(sources[full_sel])
        n_full = int(full_sel.sum())
        copied = sources.size - n_full - patched
        return rows, (n_full, patched, copied)


def prepare_repair(
    checkpoint_dir,
    graph: CSRGraph,
    updates,
    *,
    config=None,
    state: IncrementalState | None = None,
    num_parts: int | None = None,
    seed: int = 0,
) -> RepairPlan:
    """Diagnose + re-close (steps 1-3 of the module docstring). Returns
    the plan whose row primitives the serial engine or a repair fleet
    then drives; the repair status marker is live (``repairing``) from
    the moment closures start."""
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.solver.johnson import NegativeCycleError

    cfg = config if config is not None else SolverConfig()
    tel = _resolve_telemetry(getattr(cfg, "telemetry", None))
    old_digest = graph_digest(graph)
    new_graph, report = graph.apply_edge_updates(updates)
    plan = RepairPlan(
        checkpoint_root=checkpoint_dir, old_graph=graph,
        new_graph=new_graph, report=report, state_old=None,
        config=cfg, telemetry=tel,
    )
    if not plan.old_ckpt.manifest():
        raise ValueError(
            f"{plan.old_ckpt.dir}: no completed batches for this graph "
            "(digest mismatch, or the solve never checkpointed here) — "
            "nothing to repair"
        )
    if plan.trivial:
        plan.state_new = state
        return plan

    v = graph.num_nodes
    with tel.span("repair_prepare", changed=report.num_changed,
                  **_trace_attrs()):
        # Conservative staleness from the first moment repair work runs;
        # refined to the exact affected set once closures land.
        repair_status.write_repair_status(
            plan.old_ckpt.dir, status="repairing",
            new_digest=report.new_digest, affected="all", total_sources=v,
        )
        if state is None:
            state = IncrementalState.load(
                plan.old_ckpt.dir, expect_digest=old_digest
            )
        if state is None:
            with tel.span("incremental_build"):
                state = IncrementalState.build(
                    graph, num_parts=num_parts, seed=seed, config=cfg
                )
                state.save(plan.old_ckpt.dir)
        elif state.graph_digest != old_digest:
            raise ValueError(
                f"incremental state digest {state.graph_digest} does not "
                f"match the graph being updated ({old_digest})"
            )
        plan.state_old = state
        plan.diag = diagnose(state, report.changed_edges)
        tel.event("dirty_set", **plan.diag.as_dict())
        tel.progress(op="repair", parts_total=state.num_parts,
                     dirty_parts=len(plan.diag.dirty_parts))

        parts, lids, blocal, bcore = state.indices()
        e2 = new_graph.num_real_edges
        src2 = new_graph.src[:e2]
        dst2 = new_graph.indices[:e2]
        w2 = new_graph.weights[:e2]
        labels = state.labels
        part_pos = {int(p): i for i, p in enumerate(state.part_ids)}

        t0 = time.perf_counter()
        new_locals = list(state.locals_closed)
        new_digests = list(state.part_digests)
        changed_local: dict[int, bool] = {}
        sub_solver = closure_solver(cfg)
        try:
            for p in plan.diag.dirty_parts:
                pi = part_pos[int(p)]
                sel = _within_selector(labels, src2, dst2, p)
                with tel.span("repair_close_part", part=int(p),
                              vertices=int(parts[pi].size)):
                    new_local = close_part(
                        new_graph, parts[pi], lids, sel, config=cfg,
                        solver=sub_solver,
                    )
                changed_local[pi] = not np.array_equal(
                    state.locals_closed[pi], new_local
                )
                new_locals[pi] = new_local
                new_digests[pi] = compute_part_digest(
                    parts[pi], lids, src2, dst2, w2, sel
                )

            cross2 = labels[src2] != labels[dst2]
            boundary_mask = np.zeros(v, bool)
            boundary_mask[src2[cross2]] = True
            boundary_mask[dst2[cross2]] = True
            boundary2 = np.flatnonzero(boundary_mask)
            plan.boundary_changed = not np.array_equal(
                boundary2, state.boundary
            )

            state_new = IncrementalState(
                graph_digest=report.new_digest,
                seed=state.seed,
                labels=labels,
                part_ids=state.part_ids,
                part_digests=new_digests,
                core_digest=compute_core_digest(
                    boundary2, src2, dst2, w2, cross2
                ),
                boundary=boundary2,
                locals_closed=new_locals,
                core_closed=state.core_closed,
            )
            need_core = (
                plan.diag.cross_changed > 0
                or any(changed_local.values())
                or plan.boundary_changed
            )
            if need_core:
                with tel.span("repair_close_core",
                              boundary=int(boundary2.size)):
                    state_new.core_closed = close_core(
                        state_new, new_graph, config=cfg,
                        solver=sub_solver,
                    )
                plan.core_recomputed = True
        except NegativeCycleError:
            repair_status.write_repair_status(
                plan.old_ckpt.dir, status="failed",
                new_digest=report.new_digest, affected="all",
                total_sources=v, reason="negative cycle created by update",
            )
            raise
        plan.closures_s = time.perf_counter() - t0
        plan.state_new = state_new

        # -- affected-set classification (bitwise, see module docstring)
        k = state.num_parts
        if plan.boundary_changed:
            plan.full_row_parts = set(range(k))
            plan.col_parts = set()
        else:
            core_rows_changed = [False] * k
            if plan.core_recomputed:
                for qi in range(k):
                    rows = bcore[qi]
                    core_rows_changed[qi] = not np.array_equal(
                        state.core_closed[rows],
                        state_new.core_closed[rows],
                    )
            plan.full_row_parts = {
                pi for pi, ch in changed_local.items() if ch
            } | {qi for qi in range(k) if core_rows_changed[qi]}
            plan.col_parts = {
                pi for pi, ch in changed_local.items()
                if ch and not np.array_equal(
                    state.locals_closed[pi][blocal[pi]],
                    new_locals[pi][blocal[pi]],
                )
            }
        for pi in plan.full_row_parts:
            plan.full_mask[parts[pi]] = True

        repair_status.write_repair_status(
            plan.old_ckpt.dir, status="repairing",
            new_digest=report.new_digest,
            affected=plan.affected_sources(), total_sources=v,
            dirty_parts=len(plan.diag.dirty_parts),
            parts_total=k,
        )
    plan.new_ckpt = BatchCheckpointer(
        plan.checkpoint_root, graph_key=report.new_digest
    )
    return plan


def finish_repair(plan: RepairPlan) -> None:
    """Publish the terminal artifacts: the NEW graph's incremental
    state (so the next update chains without a rebuild) and the
    ``done`` status on the old directory (its affected rows stay
    flagged forever — they can never become current there)."""
    if plan.state_new is not None and plan.new_ckpt is not None:
        plan.state_new.save(plan.new_ckpt.dir)
    repair_status.write_repair_status(
        plan.old_ckpt.dir, status="done",
        new_digest=plan.report.new_digest,
        affected=plan.affected_sources(), remaining=[],
        total_sources=plan.old_graph.num_nodes,
        dirty_parts=len(plan.diag.dirty_parts) if plan.diag else 0,
        parts_total=plan.state_old.num_parts if plan.state_old else 0,
    )


def execute_repair(plan: RepairPlan) -> RepairResult:
    """Serial batch loop over the old checkpoint's manifest: repair
    each batch's rows and commit through ``checked_save`` into the new
    digest's subdirectory (atomic per batch — the per-part swap)."""
    t_start = time.perf_counter()
    tel = plan.tel
    if plan.trivial:
        return RepairResult(
            old_digest=plan.report.old_digest,
            new_digest=plan.report.new_digest,
            trivial=True,
            parts_total=(
                plan.state_new.num_parts if plan.state_new is not None else 0
            ),
            dirty_parts_closed=0, core_recomputed=False,
            boundary_changed=False, full_row_parts=[], col_parts=[],
            affected_rows=0, rows_recomputed=0, rows_patched=0,
            rows_copied=0, batches_rewritten=0, expand_macs=0,
            closures_s=0.0, expand_s=0.0, io_s=0.0,
            wall_s=time.perf_counter() - t_start, diag=plan.diag,
        )
    live = _resolve_metrics(getattr(plan.config, "metrics", None))
    manifest = plan.old_ckpt.manifest()
    files: dict[str, int] = {}
    for _s, (batch_idx, filename) in manifest.items():
        files[filename] = int(batch_idx)
    affected = plan.affected_sources()
    remaining = (
        set() if isinstance(affected, str)
        else {int(s) for s in affected}
    )
    n_re = n_patch = n_copy = 0
    io_s = 0.0
    v = plan.old_graph.num_nodes
    with tel.span("repair_expand", batches=len(files)):
        for i, filename in enumerate(sorted(files)):
            batch_idx = files[filename]
            sources = plan.old_ckpt.batch_sources(filename)
            if sources is None:
                continue  # manifest entry vanished under us: nothing to do
            loaded = plan.old_ckpt.load(batch_idx, sources)
            old_rows = None if loaded is None else loaded[0]
            with tel.span("repair_batch", batch=batch_idx,
                          n_sources=int(sources.size)):
                rows, (re_, pa, co) = plan.repair_batch_rows(
                    sources, old_rows
                )
                t0 = time.perf_counter()
                checked_save(plan.new_ckpt, batch_idx, sources, rows)
                io_s += time.perf_counter() - t0
            n_re += re_
            n_patch += pa
            n_copy += co
            if remaining:
                remaining -= {int(s) for s in sources}
                repair_status.write_repair_status(
                    plan.old_ckpt.dir, status="repairing",
                    new_digest=plan.report.new_digest,
                    affected=affected, remaining=sorted(remaining),
                    total_sources=v,
                    dirty_parts=len(plan.diag.dirty_parts),
                    parts_total=plan.state_old.num_parts,
                )
            tel.progress(op="repair", batches_done=i + 1,
                         batches_total=len(files))
    finish_repair(plan)
    affected_rows = (
        int(plan.full_mask.sum()) if not plan.patch_all
        else v
    )
    result = RepairResult(
        old_digest=plan.report.old_digest,
        new_digest=plan.report.new_digest,
        trivial=False,
        parts_total=plan.state_new.num_parts,
        dirty_parts_closed=len(plan.diag.dirty_parts),
        core_recomputed=plan.core_recomputed,
        boundary_changed=plan.boundary_changed,
        full_row_parts=sorted(
            int(plan.state_new.part_ids[pi]) for pi in plan.full_row_parts
        ),
        col_parts=sorted(
            int(plan.state_new.part_ids[pi]) for pi in plan.col_parts
        ),
        affected_rows=affected_rows,
        rows_recomputed=n_re, rows_patched=n_patch, rows_copied=n_copy,
        batches_rewritten=len(files),
        expand_macs=int(plan.expand_macs),
        closures_s=plan.closures_s, expand_s=plan.expand_s, io_s=io_s,
        wall_s=time.perf_counter() - t_start,
        diag=plan.diag,
    )
    # Live metrics (ISSUE 12): repair wall into the streaming histogram
    # and the exact dirty-part accounting as gauges, so `pjtpu top` (and
    # a fleet worker's snapshot, when repairs run under one) shows
    # repair health alongside serve/solve health.
    live.histogram("pjtpu_repair_wall_ms").record(result.wall_s * 1e3)
    live.counter("pjtpu_repairs").add(1)
    live.counter("pjtpu_repair_rows_recomputed").add(result.rows_recomputed)
    live.gauge("pjtpu_repair_dirty_parts", result.dirty_parts_closed)
    live.gauge("pjtpu_repair_parts_total", result.parts_total)
    _append_profile_record(plan, result)
    return result


def repair_checkpoint(
    checkpoint_dir,
    graph: CSRGraph,
    updates,
    *,
    config=None,
    state: IncrementalState | None = None,
    num_parts: int | None = None,
    seed: int = 0,
    strategy: str = "auto",
) -> RepairResult:
    """Prepare + execute one repair (the ``pjtpu update`` entry).

    ``strategy`` (ISSUE 19 satellite): ``"auto"`` prices
    repair-vs-resolve through :data:`REPAIR_PLANS` from the learned
    ``kind:"repair"`` records BEFORE any closure work is paid — a
    cheaper full re-solve skips the repair machinery entirely;
    ``"repair"``/``"resolve"`` pin a side. Unpriced auto is the old
    behavior: always repair."""
    from paralleljohnson_tpu.config import SolverConfig

    cfg = config if config is not None else SolverConfig()
    decision = None
    if strategy != "repair":
        # Pre-compute the update report once for the decision; the
        # repair path re-derives it inside prepare_repair (host-side
        # CSR rebuild — linear, and correctness-critical to keep in
        # one place there).
        _, report = graph.apply_edge_updates(updates)
        if report.num_changed:
            decision = decide_repair_strategy(
                checkpoint_dir, graph, report, config=cfg, state=state,
                strategy=strategy,
            )
    if decision is not None and decision.chosen.plan.name == "resolve":
        return _resolve_checkpoint(
            checkpoint_dir, graph, updates, config=cfg, decision=decision,
        )
    plan = prepare_repair(
        checkpoint_dir, graph, updates, config=cfg, state=state,
        num_parts=num_parts, seed=seed,
    )
    # A repair driven on behalf of a traced update request joins that
    # request's timeline (ISSUE 20); {} on every untraced/offline path.
    with plan.tel.span("repair", changed=plan.report.num_changed,
                       **_trace_attrs()):
        result = execute_repair(plan)
    if decision is not None:
        result.plan = decision.as_dict(built="repair")
    return result


def _resolve_checkpoint(
    checkpoint_dir,
    graph: CSRGraph,
    updates,
    *,
    config,
    decision,
) -> RepairResult:
    """The priced re-solve side of the repair-vs-resolve walk: solve
    the updated graph through the ordinary solver straight into the
    NEW digest's checkpoint subtree (same layout a repair commits to),
    then finish like a repair — status ``done``, stale rows cleared.
    The solve itself lands the usual ``kind:"solve"`` records, so the
    decision keeps calibrating from real walls on both sides."""
    t_start = time.perf_counter()
    from paralleljohnson_tpu.solver.johnson import ParallelJohnsonSolver

    new_graph, report = graph.apply_edge_updates(updates)
    v = new_graph.num_nodes
    old_ckpt = BatchCheckpointer(checkpoint_dir, graph_key=report.old_digest)
    repair_status.write_repair_status(
        old_ckpt.dir, status="repairing", new_digest=report.new_digest,
        affected="all", total_sources=v,
    )
    cfg = dataclasses.replace(config, checkpoint_dir=str(checkpoint_dir))
    t0 = time.perf_counter()
    ParallelJohnsonSolver(cfg).solve(new_graph)
    solve_s = time.perf_counter() - t0
    new_ckpt = BatchCheckpointer(checkpoint_dir, graph_key=report.new_digest)
    repair_status.write_repair_status(
        old_ckpt.dir, status="done", new_digest=report.new_digest,
        affected="all", remaining=[], total_sources=v,
    )
    result = RepairResult(
        old_digest=report.old_digest, new_digest=report.new_digest,
        trivial=False, parts_total=0, dirty_parts_closed=0,
        core_recomputed=False, boundary_changed=False,
        full_row_parts=[], col_parts=[], affected_rows=v,
        rows_recomputed=v, rows_patched=0, rows_copied=0,
        batches_rewritten=len(new_ckpt.manifest()), expand_macs=0,
        closures_s=0.0, expand_s=solve_s, io_s=0.0,
        wall_s=time.perf_counter() - t_start,
        plan=decision.as_dict(built="resolve"),
    )
    return result


def _append_profile_record(plan: RepairPlan, result: RepairResult) -> None:
    """One ``kind: "repair"`` profile-store record per repair, so the
    cost model learns repair-vs-resolve pricing (``CostModel.fit``
    accepts the kind; route ``incremental-repair`` sits in the same
    priced table as every solve route). Observability must never fail a
    repair that already committed correct rows."""
    try:
        from paralleljohnson_tpu.observe import current_platform
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir
        from paralleljohnson_tpu.observe.store import ProfileStore

        store_dir = resolve_profile_dir(
            getattr(plan.config, "profile_store", None)
        )
        if not store_dir:
            return
        ProfileStore(store_dir).append({
            "ts": time.time(),
            "kind": "repair",
            "label": "repair",
            "route": ROUTE_TAG,
            "platform": current_platform(),
            "nodes": int(plan.new_graph.num_nodes),
            "edges": int(plan.new_graph.num_real_edges),
            "batch": max(1, int(result.affected_rows)),
            "measured": {
                "wall_s": float(result.wall_s),
                "compute_s": float(result.closures_s + result.expand_s),
                "phase_seconds": {
                    "close": float(result.closures_s),
                    "expand": float(result.expand_s),
                    "io": float(result.io_s),
                },
            },
            "edges_relaxed": int(result.expand_macs),
            "repair": result.as_dict(),
            "cost": {
                "cost_analysis_unavailable":
                    "repair composes cached closures; no single compiled "
                    "executable to harvest"
            },
        })
    except Exception:  # noqa: BLE001 — observability is never fatal
        pass
