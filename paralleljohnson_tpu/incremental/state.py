"""Dependency-tracked partition state — the incremental substrate.

The condensed partitioned route (``solver.partitioned``) already proves
the decomposition this subsystem repairs along: every shortest path is
within-part runs joined at boundary vertices, so full APSP factors into
per-part local closures, one boundary-core closure, and per-part
min-plus expansions. :class:`IncrementalState` persists exactly those
factors next to a checkpoint, with a digest HIERARCHY over them::

    graph digest  ->  per-part digests (each part's internal edges)
                  ->  boundary-core digest (boundary set + cross edges)

so a batch of edge updates maps to a minimal dirty set by digest-level
reasoning: an update inside part P invalidates P's digest (P's closure
must be re-run), a cross-part update invalidates the core digest, and
everything else is PROVABLY reusable — a part's local closure depends
only on its internal edges, never on the rest of the graph.

Closures run through the ORDINARY resilient solver
(``ParallelJohnsonSolver.solve`` on the part's relabeled subgraph), not
a private kernel: retries, watchdog deadlines, OOM degradation,
pipelining, fault injection, and telemetry spans all apply to repair
work exactly as they do to any solve, and negative cycles are detected
by the same Bellman-Ford machinery (a cycle inside a part surfaces
closing that part; a cycle across parts surfaces closing the core).

Persisted as ``incremental/state.npz`` inside the checkpoint's
per-graph subdirectory, digest-guarded like ``landmarks.npz``: a state
written for a different graph is invisible, never silently reused.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.graphs import CSRGraph

STATE_DIRNAME = "incremental"
STATE_FILENAME = "state.npz"


def _digest_arrays(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def closure_config(config=None):
    """The SolverConfig repair closures run under: the caller's knobs
    (retries, deadlines, fault plan, telemetry) with the layers that
    must not recurse or double-write stripped — no nested
    checkpointing, no oracle validation, no partitioned re-dispatch
    (the repair IS the partitioned machinery), no per-closure profile
    records (the repair appends ONE record for the whole operation).
    The source batch is pinned to the closure V-bucket quantum so every
    fan-out batch of every closure compiles to the same [128, Vp]
    shape (see :func:`close_subgraph`)."""
    from paralleljohnson_tpu.config import SolverConfig

    base = config if config is not None else SolverConfig()
    return dataclasses.replace(
        base,
        checkpoint_dir=None,
        validate=False,
        partitioned=False,
        profile_store=None,
        source_batch_size=_CLOSURE_V_BUCKET,
    )


def closure_solver(config=None):
    """One resilient solver for a whole build/repair operation: part
    closures share its backend, so the jit caches of one closure's
    shape bucket serve every later closure in the same bucket instead
    of re-tracing per part."""
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver

    return ParallelJohnsonSolver(closure_config(config))


# Closure subgraphs pad V up to this multiple with isolated vertices
# (no edges: distance rows inf off their 0 diagonal, affecting nothing)
# so parts of similar size share ONE compiled shape bucket instead of
# recompiling the whole solve pipeline per exact part size.
_CLOSURE_V_BUCKET = 128


def close_subgraph(sub: CSRGraph, config=None, *, solver=None):
    """All-pairs closure of one (small) subgraph through the ordinary
    resilient solver. Returns the dense ``[n, n]`` distance matrix
    ordered by vertex id; raises ``NegativeCycleError`` exactly where a
    blocked-FW closure would read a negative diagonal. The subgraph is
    padded to the shared V bucket (isolated pad vertices — provably
    inert) before solving, so repeated closures amortize compiles."""
    n = sub.num_nodes
    if n == 0:
        return np.zeros((0, 0), sub.dtype)
    vp = _CLOSURE_V_BUCKET * (-(-n // _CLOSURE_V_BUCKET))
    if vp > n:
        indptr = np.concatenate([
            sub.indptr,
            np.full(vp - n, sub.indptr[-1], np.int32),
        ])
        sub = CSRGraph(indptr=indptr, indices=sub.indices,
                       weights=sub.weights)
    if solver is None:
        solver = closure_solver(config)
    res = solver.solve(sub)
    return np.asarray(res.matrix, dtype=sub.dtype)[:n, :n]


def close_dense_seed(seed: np.ndarray, config=None, *, solver=None):
    """Closure of a dense seed matrix (the boundary core): finite
    off-diagonal entries become edges of a graph on the core vertices,
    closed through the same resilient solver path."""
    nc = seed.shape[0]
    if nc == 0:
        return seed.copy()
    r, c = np.nonzero(np.isfinite(seed) & ~np.eye(nc, dtype=bool))
    sub = CSRGraph.from_edges(r, c, seed[r, c], nc, dtype=seed.dtype)
    return close_subgraph(sub, config, solver=solver)


def _within_selector(labels, src, dst, p):
    return (labels[src] == p) & (labels[dst] == p)


@dataclasses.dataclass
class IncrementalState:
    """The persisted repair substrate for ONE graph (see module
    docstring). ``parts``/``locals_closed``/``part_digests`` are
    aligned with ``part_ids``; ``boundary`` is sorted."""

    graph_digest: str
    seed: int
    labels: np.ndarray            # int64[V]
    part_ids: np.ndarray          # int64[k]
    part_digests: list
    core_digest: str
    boundary: np.ndarray          # int64, sorted
    locals_closed: list
    core_closed: np.ndarray

    # -- derived indices -----------------------------------------------------

    @property
    def num_parts(self) -> int:
        return len(self.part_ids)

    def indices(self):
        """``(parts, lids, blocal, bcore)``: per-part vertex arrays,
        global->local id map, and each part's boundary vertices as
        (local ids, core ids) — recomputed on demand (cheap) instead of
        persisted."""
        cached = self.__dict__.get("_indices")
        if cached is not None:
            return cached
        v = len(self.labels)
        parts = [np.flatnonzero(self.labels == p) for p in self.part_ids]
        lids = np.full(v, -1, np.int64)
        for verts in parts:
            lids[verts] = np.arange(verts.size)
        boundary_mask = np.zeros(v, bool)
        boundary_mask[self.boundary] = True
        core_idx = np.full(v, -1, np.int64)
        core_idx[self.boundary] = np.arange(self.boundary.size)
        blocal = []
        bcore = []
        for verts in parts:
            bv = verts[boundary_mask[verts]]
            blocal.append(lids[bv])
            bcore.append(core_idx[bv])
        self.__dict__["_indices"] = (parts, lids, blocal, bcore)
        return self.__dict__["_indices"]

    # -- construction --------------------------------------------------------

    @classmethod
    def build(
        cls,
        graph: CSRGraph,
        *,
        num_parts: int | None = None,
        seed: int = 0,
        config=None,
    ) -> "IncrementalState":
        """Partition + close everything once — the amortized cost of
        attaching the incremental subsystem to an existing checkpoint.
        Partition labels come from the same seeded pivot draw the
        condensed route uses, so quality trade-offs are shared; every
        closure runs through the resilient solver (see module
        docstring)."""
        from paralleljohnson_tpu.solver.partitioned import (
            auto_num_parts,
            partition_by_pivots,
        )
        from paralleljohnson_tpu.utils.checkpoint import graph_digest

        v = graph.num_nodes
        k = int(
            num_parts
            or getattr(config, "partition_parts", None)
            or auto_num_parts(v)
        )
        labels = partition_by_pivots(graph, k, seed=seed)
        part_ids = np.unique(labels)
        e = graph.num_real_edges
        src, dst, w = graph.src[:e], graph.indices[:e], graph.weights[:e]
        cross = labels[src] != labels[dst]
        boundary_mask = np.zeros(v, bool)
        boundary_mask[src[cross]] = True
        boundary_mask[dst[cross]] = True
        boundary = np.flatnonzero(boundary_mask)

        state = cls(
            graph_digest=graph_digest(graph),
            seed=int(seed),
            labels=labels,
            part_ids=part_ids,
            part_digests=[],
            core_digest=compute_core_digest(boundary, src, dst, w, cross),
            boundary=boundary,
            locals_closed=[],
            core_closed=np.zeros((0, 0), graph.dtype),
        )
        parts, lids, blocal, bcore = state.indices()
        solver = closure_solver(config)
        for p, verts in zip(part_ids, parts):
            sel = _within_selector(labels, src, dst, p)
            state.part_digests.append(
                compute_part_digest(verts, lids, src, dst, w, sel)
            )
            state.locals_closed.append(
                close_part(graph, verts, lids, sel, config=config,
                           solver=solver)
            )
        state.core_closed = close_core(state, graph, config=config,
                                       solver=solver)
        return state

    # -- persistence ---------------------------------------------------------

    def save(self, graph_dir: str | Path) -> Path:
        """Atomic write of ``incremental/state.npz`` under the
        checkpoint's per-graph subdirectory."""
        d = Path(graph_dir) / STATE_DIRNAME
        d.mkdir(parents=True, exist_ok=True)
        path = d / STATE_FILENAME
        payload = {
            "graph_digest": np.array(self.graph_digest),
            "seed": np.array(self.seed, np.int64),
            "labels": self.labels,
            "part_ids": self.part_ids,
            "part_digests": np.array(self.part_digests),
            "core_digest": np.array(self.core_digest),
            "boundary": self.boundary,
            "core_closed": self.core_closed,
        }
        for i, local in enumerate(self.locals_closed):
            payload[f"local_{i:04d}"] = local
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        # Write through a file handle: np.savez would append ".npz" to
        # a bare tmp path and the atomic rename would miss it.
        with open(tmp, "wb") as fh:
            np.savez_compressed(fh, **payload)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(
        cls, graph_dir: str | Path, *, expect_digest: str
    ) -> "IncrementalState | None":
        """Digest-guarded load: None when absent, unreadable, or written
        for a different graph — a stale state must never be repaired
        from (the same contract as ``LandmarkIndex.load``)."""
        path = Path(graph_dir) / STATE_DIRNAME / STATE_FILENAME
        if not path.exists():
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if str(z["graph_digest"]) != expect_digest:
                    return None
                part_ids = np.asarray(z["part_ids"], np.int64)
                return cls(
                    graph_digest=str(z["graph_digest"]),
                    seed=int(z["seed"]),
                    labels=np.asarray(z["labels"], np.int64),
                    part_ids=part_ids,
                    part_digests=[str(s) for s in z["part_digests"]],
                    core_digest=str(z["core_digest"]),
                    boundary=np.asarray(z["boundary"], np.int64),
                    locals_closed=[
                        np.asarray(z[f"local_{i:04d}"])
                        for i in range(len(part_ids))
                    ],
                    core_closed=np.asarray(z["core_closed"]),
                )
        except Exception:  # noqa: BLE001 — torn/corrupt state: rebuild
            return None


# -- the digest hierarchy ----------------------------------------------------


def compute_part_digest(verts, lids, src, dst, w, sel) -> str:
    """Content digest of one part: its vertex set + internal edges in
    LOCAL ids (so the digest is invariant to everything outside the
    part — exactly the dependency set of its closure)."""
    idx = np.flatnonzero(sel)
    return _digest_arrays(
        verts, lids[src[idx]], lids[dst[idx]], w[idx]
    )


def compute_core_digest(boundary, src, dst, w, cross) -> str:
    """Content digest of the boundary core's OWN inputs: the boundary
    vertex set + the cross edges. (Core seeds also take each part's
    boundary-to-boundary closure — that dependency is tracked through
    the part digests, not duplicated here.)"""
    idx = np.flatnonzero(cross)
    return _digest_arrays(boundary, src[idx], dst[idx], w[idx])


# -- closure helpers (shared by build and repair) ----------------------------


def close_part(graph: CSRGraph, verts, lids, sel, *, config=None,
               solver=None):
    """Closure of one part: relabel its internal edges to local ids and
    solve the subgraph through the resilient solver."""
    idx = np.flatnonzero(sel)
    sub = CSRGraph.from_edges(
        lids[graph.src[idx]], lids[graph.indices[idx]], graph.weights[idx],
        int(verts.size), dtype=graph.dtype,
    )
    from paralleljohnson_tpu.solver.johnson import NegativeCycleError

    try:
        return close_subgraph(sub, config, solver=solver)
    except NegativeCycleError as e:
        raise NegativeCycleError(
            "negative-weight cycle inside a partition "
            f"(part of {verts.size} vertices): {e}"
        ) from e


def close_core(state: IncrementalState, graph: CSRGraph, *, config=None,
               solver=None):
    """Seed + close the boundary core from the state's CURRENT local
    closures and the graph's cross edges (the condensed route's exact
    construction: per-part boundary-to-boundary closures min'd with raw
    cross edges, then closed)."""
    from paralleljohnson_tpu.solver.johnson import NegativeCycleError

    parts, lids, blocal, bcore = state.indices()
    nc = state.boundary.size
    core = np.full((nc, nc), np.inf, dtype=graph.dtype)
    if nc == 0:
        return core
    np.fill_diagonal(core, 0.0)
    for closed, bl, bc in zip(state.locals_closed, blocal, bcore):
        if bl.size:
            core[np.ix_(bc, bc)] = np.minimum(
                core[np.ix_(bc, bc)], closed[np.ix_(bl, bl)]
            )
    e = graph.num_real_edges
    src, dst, w = graph.src[:e], graph.indices[:e], graph.weights[:e]
    cross = state.labels[src] != state.labels[dst]
    core_idx = np.full(len(state.labels), -1, np.int64)
    core_idx[state.boundary] = np.arange(nc)
    np.minimum.at(
        core, (core_idx[src[cross]], core_idx[dst[cross]]), w[cross]
    )
    try:
        return close_dense_seed(core, config, solver=solver)
    except NegativeCycleError as e:
        raise NegativeCycleError(
            f"negative-weight cycle across partitions (core of {nc} "
            f"boundary vertices): {e}"
        ) from e
