"""The repair-status marker — the stale-but-servable contract's wire.

The repair engine atomically rewrites ``repair_status.json`` inside the
OLD graph's checkpoint subdirectory (``graph_<old_digest>/``) while it
runs. The serving layer (``serve.store.TileStore``) reads it (mtime-
cached) and flags every answer whose source is in the affected set as
``stale: true`` — the old rows are still EXACT for the pre-update
graph, and every source OUTSIDE the affected set is provably bitwise
identical on the post-update graph too (the dependency argument in
``incremental.repair``), so only genuinely outdated answers carry the
flag.

Lifecycle: ``repairing`` (repair in flight; ``remaining`` shrinks as
parts land in the new digest's subdirectory — the per-part atomic
swap) -> ``done`` (the affected set stays stale forever in the OLD
directory: those rows can never become current there; serve the new
graph digest instead) or ``failed`` (e.g. the update created a
negative cycle: the new graph has no servable distances, the old
answers stay flagged).

``affected`` is ``"all"`` or a sorted source list; lists longer than
``_AFFECTED_LIST_CAP`` collapse to ``"all"`` (a JSON status file must
stay cheap to rewrite per repaired part).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

REPAIR_STATUS_FILENAME = "repair_status.json"

_AFFECTED_LIST_CAP = 200_000


def _encode_sources(sources) -> "str | list[int]":
    if isinstance(sources, str):
        return "all"
    sources = sorted(int(s) for s in sources)
    if len(sources) > _AFFECTED_LIST_CAP:
        return "all"
    return sources


def write_repair_status(
    graph_dir: str | Path,
    *,
    status: str,
    new_digest: str,
    affected,
    total_sources: int,
    remaining=None,
    dirty_parts: int = 0,
    parts_total: int = 0,
    reason: str | None = None,
) -> Path:
    """Atomically (tmp + rename) publish one repair-status snapshot."""
    if status not in ("repairing", "done", "failed"):
        raise ValueError(f"bad repair status {status!r}")
    payload = {
        "version": 1,
        "status": status,
        "new_digest": new_digest,
        "affected": _encode_sources(affected),
        "remaining": (
            _encode_sources(remaining) if remaining is not None
            else _encode_sources(affected)
        ),
        "total_sources": int(total_sources),
        "dirty_parts": int(dirty_parts),
        "parts_total": int(parts_total),
        "ts": time.time(),
    }
    if reason is not None:
        payload["reason"] = reason
    p = Path(graph_dir) / REPAIR_STATUS_FILENAME
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(payload), encoding="utf-8")
    os.replace(tmp, p)
    return p


def read_repair_status(graph_dir: str | Path) -> dict | None:
    """The current status dict, or None when no repair ever touched this
    directory (or the marker is torn — a torn marker must read as
    "no information", never crash the serving loop)."""
    p = Path(graph_dir) / REPAIR_STATUS_FILENAME
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "status" not in data:
        return None
    return data


def stale_sources(status: dict | None) -> "set[int] | str | None":
    """The set of sources a server must flag stale given a status dict:
    ``None`` (nothing stale), ``"all"``, or a set of ints. The AFFECTED
    set — not ``remaining`` — drives staleness: a repaired part's rows
    land in the NEW digest's directory, so in the old directory they
    stay outdated forever."""
    if status is None:
        return None
    affected = status.get("affected", "all")
    if affected == "all":
        return "all"
    return {int(s) for s in affected}
