"""Incremental APSP (ISSUE 11 tentpole) — *make graph updates cheap*.

Today's checkpoints are keyed by graph content digest, so any edge
change used to invalidate the whole directory. This package repairs a
checkpoint instead of re-solving it, along the condensed partitioned
decomposition (ROADMAP item 5; RAPID-Graph's recursive-decomposition
insight):

- :mod:`~paralleljohnson_tpu.incremental.state` — the dependency-
  tracked partition state: graph digest -> per-part digests ->
  boundary-core digest, plus the cached closures repair reuses.
- :mod:`~paralleljohnson_tpu.incremental.repair` — dirty-set
  diagnosis + the repair engine: re-close only dirty parts + the core
  (through the ordinary resilient solver), re-expand only affected
  source ranges, commit through the corruption-checked checkpoint
  writer. Bitwise-identical to a fresh full solve on integer weights.
- :mod:`~paralleljohnson_tpu.incremental.status` — the
  stale-but-servable marker the serve layer reads: answers from the
  pre-update checkpoint carry ``stale: true`` while (and after) repair
  runs, never an unflagged stale value.
- :mod:`~paralleljohnson_tpu.incremental.fleet` — repair sharding
  through the round-15 lease coordinator.
- :mod:`~paralleljohnson_tpu.incremental.updates` — the
  ``pjtpu update`` edge-update file format.

CLI: ``pjtpu update <graph> --updates FILE --checkpoint-dir DIR``.
"""

from paralleljohnson_tpu.incremental.repair import (  # noqa: F401
    DirtySet,
    RepairResult,
    diagnose,
    prepare_repair,
    repair_checkpoint,
)
from paralleljohnson_tpu.incremental.state import (  # noqa: F401
    IncrementalState,
)
from paralleljohnson_tpu.incremental.status import (  # noqa: F401
    REPAIR_STATUS_FILENAME,
    read_repair_status,
    stale_sources,
    write_repair_status,
)
from paralleljohnson_tpu.incremental.updates import (  # noqa: F401
    load_updates,
    parse_update_line,
)

__all__ = [
    "DirtySet",
    "IncrementalState",
    "REPAIR_STATUS_FILENAME",
    "RepairResult",
    "diagnose",
    "load_updates",
    "parse_update_line",
    "prepare_repair",
    "read_repair_status",
    "repair_checkpoint",
    "stale_sources",
    "write_repair_status",
]
