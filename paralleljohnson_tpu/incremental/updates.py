"""Edge-update batch files — the input format of ``pjtpu update``.

Two line formats, mixable in one file (blank lines and ``#`` comments
ignored):

- JSON object per line: ``{"u": 3, "v": 7, "w": 2.5}`` — ``w`` of
  ``null`` (or the string ``"inf"``) removes the edge.
- Whitespace triples: ``3 7 2.5`` — ``w`` of ``inf`` / ``x`` / ``-``
  removes the edge.

Each line is one update; the LAST update to a given ``(u, v)`` in the
file wins (``CSRGraph.apply_edge_updates`` semantics). Malformed lines
raise ``ValueError`` naming file and 1-based line number — the same
diagnosability contract as the graph loaders' ``GraphFormatError``.
"""

from __future__ import annotations

import json
import math
from pathlib import Path

_REMOVE_TOKENS = ("inf", "x", "-", "remove", "null")


def _line_error(path, lineno: int, what: str, line: str) -> ValueError:
    return ValueError(f"{path}:{lineno}: {what} in {line!r}")


def parse_update_line(line: str):
    """One ``(u, v, w_or_None)`` triple from a line (see module
    docstring); raises bare ``ValueError`` on malformed input (the file
    loader re-raises with file:line context)."""
    line = line.strip()
    if line.startswith("{"):
        obj = json.loads(line)
        if not isinstance(obj, dict) or "u" not in obj or "v" not in obj:
            raise ValueError("JSON update needs 'u' and 'v'")
        u, v = int(obj["u"]), int(obj["v"])
        w = obj.get("w")
        if isinstance(w, str):
            if w.lower() not in _REMOVE_TOKENS:
                raise ValueError(f"bad weight {w!r}")
            w = None
        elif w is not None:
            w = float(w)
    else:
        parts = line.split()
        if len(parts) != 3:
            raise ValueError("expected 'u v w'")
        u, v = int(parts[0]), int(parts[1])
        tok = parts[2].lower()
        w = None if tok in _REMOVE_TOKENS else float(parts[2])
    if w is not None and math.isinf(w) and w > 0:
        w = None  # +inf spelled numerically: also a removal
    return u, v, w


def load_updates(path: str | Path) -> list:
    """Parse an update file into the ``(u, v, w_or_None)`` list
    ``CSRGraph.apply_edge_updates`` consumes. Range/NaN validation is
    the graph's job (it knows V); this loader only owns syntax."""
    path = Path(path)
    out = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            stripped = line.strip()
            if not stripped or stripped.startswith("#"):
                continue
            try:
                out.append(parse_update_line(stripped))
            except ValueError as e:
                raise _line_error(path, lineno, str(e) or "malformed update",
                                  stripped) from None
    if not out:
        raise ValueError(f"{path}: no updates in file")
    return out
