"""Repair sharding through the round-15 fleet coordinator.

A repair's row regeneration is embarrassingly parallel once the plan's
closures are in hand, so it shards exactly like any other solve: the
work list (every source whose row needs recomputation or patching, in
sorted order) is cut into contiguous LEASES of a
:class:`~paralleljohnson_tpu.distributed.coordinator.Coordinator` plan
(``graph_spec = "repair:<new digest>"``), workers claim leases through
the same flock'd transition log — deadline lapse, heartbeat liveness,
requeue-to-survivors, and ``pjtpu fleet status`` introspection all
apply unchanged — and each committed lease's rows land as one
atomically-published batch file in the NEW digest's checkpoint
subdirectory. Unaffected rows are copied by the driver (no compute to
shard), and ``finish_repair`` publishes the terminal state exactly as
the serial engine does.

``run_in_process_repair_fleet`` drives N workers sequentially in this
process — the tier-1 twin of a real multi-process repair fleet, same
machinery minus subprocess spawn (mirroring
``distributed.launch.run_in_process_fleet``).
"""

from __future__ import annotations

import numpy as np

from paralleljohnson_tpu.incremental.repair import (
    RepairPlan,
    RepairResult,
    execute_repair,
    finish_repair,
    prepare_repair,
)
from paralleljohnson_tpu.utils.checkpoint import checked_save

# Lease-written batch files use indices in this range so they can never
# shadow a copied original batch index in diagnostics (filenames are
# unique either way — the sources digest is in the name).
REPAIR_LEASE_BATCH_BASE = 100_000


def _work_sources(plan: RepairPlan) -> tuple[np.ndarray, np.ndarray]:
    """``(work, copy)``: manifest-covered sources that need compute
    (recompute or patch) vs bitwise copies, both sorted."""
    manifest_sources = np.array(sorted(plan.old_ckpt.manifest()), np.int64)
    if manifest_sources.size == 0:
        return manifest_sources, manifest_sources
    needs = np.array(
        [plan.row_action(int(s)) != "copy" for s in manifest_sources], bool
    )
    return manifest_sources[needs], manifest_sources[~needs]


def _rows_for(plan: RepairPlan, sources: np.ndarray) -> np.ndarray:
    """Repaired rows for an arbitrary source subset: old rows fetched
    batch-wise through the manifest (corruption-checked), then repaired
    through the plan's primitives. A source whose old batch is corrupt
    falls back to full recomputation."""
    manifest = plan.old_ckpt.manifest()
    v = plan.old_graph.num_nodes
    old_rows = np.full((sources.size, v), np.nan, plan.new_graph.dtype)
    missing = np.ones(sources.size, bool)
    by_file: dict[str, list[int]] = {}
    for i, s in enumerate(sources):
        entry = manifest.get(int(s))
        if entry is not None:
            by_file.setdefault(entry[1], []).append(i)
    for filename, idxs in by_file.items():
        batch_sources = plan.old_ckpt.batch_sources(filename)
        if batch_sources is None:
            continue
        loaded = plan.old_ckpt.load(
            int(manifest[int(sources[idxs[0]])][0]), batch_sources
        )
        if loaded is None:
            continue
        rows, _ = loaded
        pos = {int(s): j for j, s in enumerate(batch_sources)}
        for i in idxs:
            old_rows[i] = rows[pos[int(sources[i])]]
            missing[i] = False
    out = np.array(old_rows, copy=True)
    if (~missing).any():
        sel = ~missing
        patched = plan.patch_rows(sources[sel], out[sel])
        out[sel] = patched
    full_sel = plan.full_mask[sources] | missing
    if full_sel.any():
        out[full_sel] = plan.recompute_rows(sources[full_sel])
    return out


def run_in_process_repair_fleet(
    checkpoint_dir,
    graph,
    updates,
    *,
    coordinator_dir,
    workers: int = 2,
    lease_rows: int | None = None,
    config=None,
    state=None,
    num_parts: int | None = None,
    seed: int = 0,
) -> RepairResult:
    """Shard one repair across ``workers`` in-process claim loops (see
    module docstring). Returns the same :class:`RepairResult` surface
    as the serial engine; the coordinator directory remains inspectable
    (``pjtpu fleet status --coordinator-dir ...``) afterwards."""
    import time

    from paralleljohnson_tpu.distributed import Coordinator

    t0 = time.perf_counter()
    plan = prepare_repair(
        checkpoint_dir, graph, updates, config=config, state=state,
        num_parts=num_parts, seed=seed,
    )
    if plan.trivial:
        return execute_repair(plan)
    work, copy = _work_sources(plan)
    manifest = plan.old_ckpt.manifest()
    files: dict[str, int] = {}
    for _s, (batch_idx, filename) in manifest.items():
        files[filename] = int(batch_idx)

    n_re = n_patch = 0
    batches_written = 0
    if work.size:
        coord = Coordinator.create(
            coordinator_dir,
            graph_spec=f"repair:{plan.report.new_digest}",
            graph_digest=plan.report.new_digest,
            num_sources=int(work.size),
            lease_sources=int(
                lease_rows
                or max(1, -(-int(work.size) // max(1, workers * 2)))
            ),
            lease_deadline_s=300.0,
        )
        # Round-robin claim loop: one lease per worker per round, so the
        # in-process twin exercises the same interleaved claim pattern a
        # real multi-process fleet produces.
        active = True
        while active:
            active = False
            for w in range(max(1, int(workers))):
                worker_id = f"rw{w}"
                lease = coord.claim(worker_id)
                if lease is None:
                    continue
                active = True
                sl = work[lease.start:lease.stop]
                rows = _rows_for(plan, sl)
                checked_save(
                    plan.new_ckpt,
                    REPAIR_LEASE_BATCH_BASE + lease.lease_id, sl, rows,
                )
                coord.commit(lease.lease_id, worker_id)
                batches_written += 1
                full = int(plan.full_mask[sl].sum())
                n_re += full
                n_patch += sl.size - full
        if not coord.done():
            raise RuntimeError(
                f"repair fleet incomplete: {coord.status()['leases']}"
            )

    # Driver copies the bitwise-unchanged remainder of each old batch.
    n_copy = 0
    copy_set = {int(s) for s in copy}
    for filename in sorted(files):
        batch_idx = files[filename]
        sources = plan.old_ckpt.batch_sources(filename)
        if sources is None:
            continue
        keep = np.array([int(s) in copy_set for s in sources], bool)
        if not keep.any():
            continue
        loaded = plan.old_ckpt.load(batch_idx, sources)
        if loaded is None:
            # Corrupt old batch: its "copy" rows must be recomputed too.
            sub = np.asarray(sources, np.int64)[keep]
            checked_save(
                plan.new_ckpt, batch_idx, sub, plan.recompute_rows(sub)
            )
            n_re += int(keep.sum())
        else:
            rows, _ = loaded
            checked_save(
                plan.new_ckpt, batch_idx,
                np.asarray(sources, np.int64)[keep], rows[keep],
            )
            n_copy += int(keep.sum())
        batches_written += 1
    finish_repair(plan)
    affected = plan.affected_sources()
    return RepairResult(
        old_digest=plan.report.old_digest,
        new_digest=plan.report.new_digest,
        trivial=False,
        parts_total=plan.state_new.num_parts,
        dirty_parts_closed=len(plan.diag.dirty_parts),
        core_recomputed=plan.core_recomputed,
        boundary_changed=plan.boundary_changed,
        full_row_parts=sorted(
            int(plan.state_new.part_ids[pi]) for pi in plan.full_row_parts
        ),
        col_parts=sorted(
            int(plan.state_new.part_ids[pi]) for pi in plan.col_parts
        ),
        affected_rows=(
            plan.old_graph.num_nodes if plan.patch_all
            else int(plan.full_mask.sum())
        ),
        rows_recomputed=n_re, rows_patched=n_patch, rows_copied=n_copy,
        batches_rewritten=batches_written,
        expand_macs=int(plan.expand_macs),
        closures_s=plan.closures_s, expand_s=plan.expand_s, io_s=0.0,
        wall_s=time.perf_counter() - t0,
        diag=plan.diag,
    )
