"""``JaxBackend`` — the TPU execution engine (SURVEY.md §7 step 4).

The attested goal (BASELINE.json:5): Bellman-Ford as a vmapped
edge-relaxation scan over CSR, the N-source phase as batched min-plus
frontier relaxation, source batches sharded across the TPU mesh, and an ICI
all-gather of distance rows. This backend owns the HBM-resident CSR buffers
and the jitted kernels; sharding lives in ``paralleljohnson_tpu.parallel``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from paralleljohnson_tpu import planner
from paralleljohnson_tpu.backends.base import Backend, KernelResult, register_backend
from paralleljohnson_tpu.graphs import CSRGraph
from paralleljohnson_tpu.ops import relax
from paralleljohnson_tpu.utils import resilience

# Default inner-fixpoint cap of the blocked Gauss-Seidel kernels
# (SolverConfig.gs_inner_cap overrides): bounds extra per-block
# propagation per visit (never correctness — see ops/gauss_seidel).
GS_INNER_CAP = 64

# Edge count above which the dst-blocked layout is built on DEVICE
# (sort + scatter) instead of host numpy + upload (see vm_blocked_layout).
VMB_DEVICE_BUILD_MIN_EDGES = 1 << 22

# Dst-block size of the blocked vertex-major fan-out; graphs with V above
# this route to the blocked sweep (below it, plain full-V segments are
# already this small). [VM_BLOCK, B] update slices are 32 MB at B=128.
VM_BLOCK = 1 << 16


@dataclasses.dataclass(frozen=True)
class JaxDeviceGraph:
    """HBM-resident COO/CSR buffers (padded edges are (0, 0, +inf) no-ops).

    The ``*_by_dst`` triple is the same edge list re-sorted by destination,
    for the vertex-major sweep (sorted segment reduction instead of
    scatter); built lazily at first use and cached on the instance.
    """

    src: jax.Array      # int32[E_pad]
    dst: jax.Array      # int32[E_pad]
    weights: jax.Array  # f32[E_pad]
    indptr: np.ndarray  # host-side int32[V+1] (row structure, rarely needed)
    num_nodes: int
    num_real_edges: int
    # Reference to the uploaded host CSR (no copy — the caller's arrays).
    # Consumed by host preprocessing (Gauss-Seidel RCM layout, dst-blocked
    # fan-out layout). After reweight() the STRUCTURE stays valid but the
    # host weights are stale (the reweighted weights exist only on
    # device) — host_weights_stale gates the consumers that read them.
    host_graph: CSRGraph | None = dataclasses.field(
        default=None, compare=False, repr=False
    )
    host_weights_stale: bool = False
    _by_dst_cache: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False
    )
    # Weight-INDEPENDENT preprocessing (dst-blocked chunk structure),
    # keyed by layout params. reweight() carries this dict object over,
    # so the host-side sort/bucketing runs once per graph structure.
    _struct_cache: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False
    )

    def by_dst(self) -> tuple[jax.Array, jax.Array, jax.Array]:
        """(src, dst, weights) sorted by dst (stable), device-resident."""
        cached = self._by_dst_cache.get("v")
        if cached is None:
            order = jnp.argsort(self.dst, stable=True)
            cached = (
                self.src[order], self.dst[order], self.weights[order]
            )
            self._by_dst_cache["v"] = cached
        return cached

    def indptr_dev(self) -> jax.Array:
        """Device-resident CSR indptr (int32[V+1]), cached."""
        cached = self._by_dst_cache.get("indptr")
        if cached is None:
            cached = jnp.asarray(self.indptr, jnp.int32)
            self._by_dst_cache["indptr"] = cached
        return cached

    @property
    def max_degree(self) -> int:
        """Max out-degree (host int, cached) — static arg of the frontier
        kernel's out-edge gather tile."""
        cached = self._by_dst_cache.get("max_deg")
        if cached is None:
            deg = np.diff(self.indptr)
            cached = int(deg.max()) if deg.size else 0
            self._by_dst_cache["max_deg"] = cached
        return cached

    def _gather_weights_with_holes(self, edge_ids) -> jax.Array:
        """CURRENT device weights at ``edge_ids`` (any shape), with
        negative ids (layout holes / padding) as +inf no-ops — the one
        idiom every weight-independent layout uses to re-derive its
        weights after reweighting."""
        return jnp.where(
            edge_ids >= 0,
            self.weights[jnp.maximum(edge_ids, 0)],
            jnp.inf,
        ).astype(self.weights.dtype)

    def vm_blocked_layout(self, vb: int, ec: int) -> dict | None:
        """Device-resident dst-blocked fan-out layout
        (``ops.relax.build_vm_blocked_layout``): weight-independent chunk
        structure cached across reweight in ``_struct_cache``; the chunk
        weights are gathered from the CURRENT device weights (so the
        layout serves the reweighted graph too) and cached per instance.
        None when no host structure is available."""
        if self.host_graph is None:
            return None
        key = ("vmb", vb, ec)
        v_pad = vb * max(1, -(-self.num_nodes // vb))
        e = self.num_real_edges
        struct = self._struct_cache.get(key)
        if struct is None:
            g = self.host_graph
            if e >= VMB_DEVICE_BUILD_MIN_EDGES:
                # Large edge lists: sort + padded-slot scatter ON DEVICE
                # — the host lexsort and the ~16E-byte layout upload
                # through the device tunnel dominate at RMAT-22 scale.
                # Only the per-block counts cross from the host.
                nb = max(1, -(-self.num_nodes // vb))
                # g.indices may carry a pad tail (re-uploaded pad_edges
                # graph); counts must cover real edges only to match the
                # device slices below.
                counts = np.bincount(
                    g.indices[:e] // vb, minlength=nb
                ).astype(np.int64)
                dev = relax.build_vm_blocked_layout_device(
                    self.src[:e], self.dst[:e], self.weights[:e],
                    counts, vb=vb, ec=ec,
                )
                struct = {
                    "src_ck": dev["src_ck"],
                    "dstl_ck": dev["dstl_ck"],
                    "base_ck": dev["base_ck"],
                    "order": dev["order"],
                    "slots": dev["slots"],
                    "vb": vb,
                    "v_pad": v_pad,
                }
                self._struct_cache[key] = struct
                self._by_dst_cache[key] = dev["w_ck"]
            else:
                host = relax.build_vm_blocked_layout(
                    g.indptr, g.indices, g.num_nodes, vb=vb, ec=ec
                )
                struct = {
                    "src_ck": jnp.asarray(host["src_ck"], jnp.int32),
                    "dstl_ck": jnp.asarray(host["dstl_ck"], jnp.int32),
                    "base_ck": jnp.asarray(host["base_ck"], jnp.int32),
                    "edge_order": jnp.asarray(host["edge_order"], jnp.int32),
                    "vb": vb,
                    "v_pad": v_pad,
                }
                self._struct_cache[key] = struct
        w_ck = self._by_dst_cache.get(key)
        if w_ck is None:
            if "order" in struct:
                w_ck = relax.regather_vm_blocked_weights(
                    self.weights, struct["order"], struct["slots"],
                    struct["src_ck"].size, struct["src_ck"].shape,
                )
            else:
                w_ck = self._gather_weights_with_holes(
                    struct["edge_order"]
                )
            self._by_dst_cache[key] = w_ck
        return {**struct, "w_ck": w_ck}

    def pallas_sweep_layout(self, vb: int, ec: int) -> dict | None:
        """Device-resident (db, sb)-bucketed layout for the Pallas
        VMEM-resident fan-out sweep (``ops.pallas_sweep``): structure
        cached across reweight in ``_struct_cache``; chunk weights
        gathered from the CURRENT device weights. None without host CSR."""
        if self.host_graph is None:
            return None
        key = ("pallas", vb, ec)
        struct = self._struct_cache.get(key)
        if struct == "refused":
            return None
        if struct is None:
            from paralleljohnson_tpu.ops.pallas_sweep import (
                build_pallas_sweep_layout, pallas_traffic_model,
            )

            g = self.host_graph
            # Traffic gate (round-4 verdict weak #4): the kernel's own
            # model says its bucket-grid block DMAs exceed the plain
            # sweep's amplified gather traffic at large V — refuse to
            # build the layout so the caller falls through to the XLA
            # routes, instead of happily moving tens of GB per sweep.
            # Only gated past the blocked-sweep threshold: below it the
            # grid is small and the model's constants don't matter.
            # When the gate passes, its (db, sb) bucket counts feed the
            # builder so the O(E) host binning runs once (ADVICE r5).
            counts = None
            if g.num_nodes > VM_BLOCK:
                ratio, nc, counts = pallas_traffic_model(
                    g.indptr, g.indices, g.num_nodes, vb=vb, ec=ec
                )
                if ratio > 1.0:
                    import warnings

                    warnings.warn(
                        "pallas sweep refused by its traffic model: "
                        f"{nc} chunks x [{vb}, B] block DMAs are "
                        f"{ratio:.1f}x the plain sweep's gather traffic "
                        f"at V={g.num_nodes}; falling back to the XLA "
                        "sweep routes",
                        RuntimeWarning,
                        stacklevel=3,
                    )
                    self._struct_cache[key] = "refused"
                    return None
            host = build_pallas_sweep_layout(
                g.indptr, g.indices, g.num_nodes, vb=vb, ec=ec,
                counts=counts,
            )
            struct = {
                "srcl_ck": jnp.asarray(host["srcl_ck"], jnp.int32),
                "dstl_ck": jnp.asarray(host["dstl_ck"], jnp.int32),
                "edge_order": jnp.asarray(host["edge_order"], jnp.int32),
                "runend_ck": jnp.asarray(host["runend_ck"], jnp.int32),
                "sb_ids": jnp.asarray(host["sb_ids"], jnp.int32),
                "db_ids": jnp.asarray(host["db_ids"], jnp.int32),
                "first_ck": jnp.asarray(host["first_ck"], jnp.int32),
                "vb": host["vb"],
                "v_pad": host["v_pad"],
            }
            self._struct_cache[key] = struct
        w_ck = self._by_dst_cache.get(key)
        if w_ck is None:
            w_ck = self._gather_weights_with_holes(struct["edge_order"])
            self._by_dst_cache[key] = w_ck
        return {**struct, "w_ck": w_ck}

    def dia_layout(self, max_offsets: int) -> dict | None:
        """Device-resident DIA (diagonal) layout for the gather-free B=1
        relaxation sweep (``ops.dia``): weight-independent structure
        (offsets + per-slot edge ids) cached across reweight in
        ``_struct_cache``; the [K, V] diagonal weights are gathered from
        the CURRENT device weights (same pattern as ``gs_layout``).
        None when no host CSR is available or the given labeling is not
        diagonal (``build_dia_layout`` contract)."""
        if self.host_graph is None:
            return None
        key = ("dia", max_offsets)
        struct = self._struct_cache.get(key)
        if struct == "none":
            return None
        if struct is None:
            from paralleljohnson_tpu.ops.dia import build_dia_layout

            g = self.host_graph
            host = build_dia_layout(
                g.indptr, g.indices, g.num_nodes, max_offsets=max_offsets
            )
            if host is None:
                self._struct_cache[key] = "none"
                return None
            struct = {
                "offsets": host["offsets"],
                "diag_edge": jnp.asarray(host["diag_edge"], jnp.int32),
                "num_entries": host["num_entries"],
            }
            self._struct_cache[key] = struct
        w_diag = self._by_dst_cache.get(key)
        if w_diag is None:
            w_diag = self._gather_weights_with_holes(struct["diag_edge"])
            self._by_dst_cache[key] = w_diag
        return {**struct, "w_diag": w_diag}

    def dw_layout(self, vb: int) -> dict | None:
        """Device-resident dirty-window layout
        (``ops.relax.build_dw_layout``): per-source-block padded
        out-edge tiles, weight-independent structure cached across
        reweight in ``_struct_cache``; the tile weights are gathered
        from the CURRENT device weights (the shared layout idiom).
        Also carries the dst-sorted COO triple the kernel's overflow
        full-sweep fallback consumes. None when V is 0."""
        if self.num_nodes == 0:
            return None
        key = ("dw", vb)
        struct = self._struct_cache.get(key)
        if struct is None:
            indices = (
                self.host_graph.indices if self.host_graph is not None
                else np.asarray(self.dst)
            )
            host = relax.build_dw_layout(
                self.indptr, indices, self.num_nodes, vb=vb
            )
            struct = {
                "e_src": jnp.asarray(host["e_src"], jnp.int32),
                "e_dst": jnp.asarray(host["e_dst"], jnp.int32),
                "edge_order": jnp.asarray(host["edge_order"], jnp.int32),
                "blk_of_v": jnp.asarray(host["blk_of_v"], jnp.int32),
                "real_ck_host": host["real_ck"],
                "vb": host["vb"],
                "nb": host["nb"],
                "em": host["em"],
            }
            self._struct_cache[key] = struct
        w_tile = self._by_dst_cache.get(key)
        if w_tile is None:
            w_tile = self._gather_weights_with_holes(struct["edge_order"])
            self._by_dst_cache[key] = w_tile
        return {**struct, "w_tile": w_tile}

    def gs_layout(self, vb: int) -> dict | None:
        """Device-resident blocked Gauss-Seidel layout (RCM relabeling +
        dst-block edge buckets — ``ops.gauss_seidel.build_gs_layout``).
        The weight-INDEPENDENT structure is built once per graph and
        cached across reweight in ``_struct_cache``; the chunk weights
        are gathered from the CURRENT device weights (exactly like
        ``vm_blocked_layout``), so Johnson's phase-2 fan-out on the
        reweighted graph gets the GS route too (round-3 verdict weak #4).
        None when no host structure is available."""
        if self.host_graph is None:
            return None
        key = ("gs", vb)
        struct = self._struct_cache.get(key)
        if struct is None:
            from paralleljohnson_tpu.ops.gauss_seidel import build_gs_layout

            g = self.host_graph
            host = build_gs_layout(
                g.indptr, g.indices, None, g.num_nodes, vb=vb
            )
            struct = {
                "rank_host": host["rank"],
                "rank": jnp.asarray(host["rank"], jnp.int32),
                "src_blk": jnp.asarray(host["src_blk"], jnp.int32),
                "dstl_blk": jnp.asarray(host["dstl_blk"], jnp.int32),
                "edge_order": jnp.asarray(host["edge_order"], jnp.int32),
                # Host int64 per-block real-edge counts, for the exact
                # Python-int work accounting (never uploaded).
                "real_edges_host": host["real_edges_blk"],
                "vb": host["vb"],
                "v_pad": host["v_pad"],
                "halo": host["halo"],
                "in_adj": jnp.asarray(host["in_adj"]),
            }
            self._struct_cache[key] = struct
        w_blk = self._by_dst_cache.get(key)
        if w_blk is None:
            w_blk = self._gather_weights_with_holes(struct["edge_order"])
            self._by_dst_cache[key] = w_blk
        return {**struct, "w_blk": w_blk}


def _edge_chunk_for(batch: int, num_edges: int, budget_elems: int = 1 << 26) -> int:
    """Bound the [B, chunk] relaxation intermediate to ~``budget_elems``
    floats (256 MB at f32) regardless of graph size."""
    chunk = max(1, budget_elems // max(batch, 1))
    return int(min(max(chunk, 1 << 12), max(num_edges, 1)))


@functools.partial(jax.jit, static_argnames=("max_iter", "edge_chunk"))
def _bf_kernel(dist0, src, dst, w, *, max_iter: int, edge_chunk: int):
    return relax.bellman_ford_sweeps(
        dist0, src, dst, w, max_iter=max_iter, edge_chunk=edge_chunk
    )


# -- convergence-observatory kernel twins (ISSUE 9, observe.convergence) -----
#
# Each instrumented route gets a SEPARATE jitted twin of its fixpoint
# that carries the [traj_cap, 2] int32 + [traj_cap] f32 trajectory
# buffers through the while_loop (zero per-iteration host syncs; one
# D2H after convergence). Twins — not flags inside the original
# kernels — so the disabled path dispatches the exact pre-observatory
# executables and its jaxpr cannot drift (tests/test_trajectory.py
# asserts this). Dispatch picks the twin via JaxBackend._traj_cap().


@functools.partial(
    jax.jit, static_argnames=("max_iter", "edge_chunk", "traj_cap")
)
def _bf_kernel_traj(
    dist0, src, dst, w, *, max_iter: int, edge_chunk: int, traj_cap: int
):
    from paralleljohnson_tpu.observe.convergence import instrumented_fixpoint

    return instrumented_fixpoint(
        lambda d: relax.relax_sweep(d, src, dst, w, edge_chunk=edge_chunk),
        dist0, max_iter=max_iter, cap=traj_cap,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "max_iter", "edge_chunk", "traj_cap"),
)
def _fanout_kernel_traj(
    sources, src, dst, w, *, num_nodes: int, max_iter: int,
    edge_chunk: int, traj_cap: int,
):
    """Trajectory twin of ``_fanout_kernel`` (sweep-sm, dist [B, V])."""
    from paralleljohnson_tpu.observe.convergence import instrumented_fixpoint

    dist0 = relax.multi_source_init(sources, num_nodes, dtype=w.dtype)
    return instrumented_fixpoint(
        lambda d: relax.relax_sweep(d, src, dst, w, edge_chunk=edge_chunk),
        dist0, max_iter=max_iter, cap=traj_cap, batch_axis=0,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "max_iter", "edge_chunk", "traj_cap"),
)
def _fanout_vm_kernel_traj(
    sources, src_bd, dst_bd, w_bd, *, num_nodes: int, max_iter: int,
    edge_chunk: int, traj_cap: int,
):
    """Trajectory twin of ``_fanout_vm_kernel`` (dist [V, B])."""
    from paralleljohnson_tpu.observe.convergence import instrumented_fixpoint

    dist0 = relax.multi_source_init(sources, num_nodes, dtype=w_bd.dtype).T
    dist, iters, improving, counts, resid = instrumented_fixpoint(
        lambda d: relax.relax_sweep_vm(
            d, src_bd, dst_bd, w_bd, edge_chunk=edge_chunk
        ),
        dist0, max_iter=max_iter, cap=traj_cap, batch_axis=1,
    )
    return dist.T, iters, improving, counts, resid


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "v_pad", "vb", "max_iter", "traj_cap"),
)
def _fanout_vm_blocked_kernel_traj(
    sources, src_ck, dstl_ck, w_ck, base_ck, *,
    num_nodes: int, v_pad: int, vb: int, max_iter: int, traj_cap: int,
):
    """Trajectory twin of ``_fanout_vm_blocked_kernel`` (pad rows are
    +inf and never improve, so the frontier counts stay exact)."""
    from paralleljohnson_tpu.observe.convergence import instrumented_fixpoint

    b = sources.shape[0]
    dist0 = jnp.full((v_pad, b), jnp.inf, w_ck.dtype)
    dist0 = dist0.at[sources, jnp.arange(b)].set(0.0)
    dist, iters, improving, counts, resid = instrumented_fixpoint(
        lambda d: relax.relax_sweep_vm_blocked(
            d, src_ck, dstl_ck, w_ck, base_ck, vb=vb
        ),
        dist0, max_iter=max_iter, cap=traj_cap, batch_axis=1,
    )
    return dist[:num_nodes].T, iters, improving, counts, resid


@functools.partial(
    jax.jit, static_argnames=("offsets", "max_iter", "traj_cap")
)
def _dia_fixpoint_traj(dist0, w_diag, *, offsets: tuple, max_iter: int,
                       traj_cap: int):
    """Trajectory twin of ``ops.dia.dia_fixpoint`` ([V] or [B, V])."""
    from paralleljohnson_tpu.observe.convergence import instrumented_fixpoint
    from paralleljohnson_tpu.ops.dia import dia_sweep

    return instrumented_fixpoint(
        lambda d: dia_sweep(d, w_diag, offsets=offsets),
        dist0, max_iter=max_iter, cap=traj_cap,
        batch_axis=0 if dist0.ndim == 2 else None,
    )



@functools.partial(
    jax.jit,
    static_argnames=(
        "max_iter", "capacity", "max_degree", "num_real_edges", "edge_chunk"
    ),
)
def _bf_frontier_kernel(
    dist0, src, dst, w, indptr, *, max_iter: int, capacity: int,
    max_degree: int, num_real_edges: int, edge_chunk: int,
):
    return relax.bellman_ford_frontier(
        dist0, src, dst, w, indptr, max_iter=max_iter, capacity=capacity,
        max_degree=max_degree, num_real_edges=num_real_edges,
        edge_chunk=edge_chunk,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_steps", "capacity", "max_degree", "num_real_edges",
        "edge_chunk", "traj_cap",
    ),
)
def _bucket_kernel(
    dist0, src, dst, w, indptr, delta, *, max_steps: int, capacity: int,
    max_degree: int, num_real_edges: int, edge_chunk: int,
    traj_cap: int | None = None,
):
    """Bucketed (delta-stepping-style) B=1 relaxation (ops.bucket):
    settles the lowest distance bucket with light-edge steps before its
    heavy edges relax once, so irregular high-diameter graphs whose
    labeling disqualifies DIA stop paying GS's ~340M re-examined
    candidates against the XLA row-gather floor. ``delta`` is traced
    (one compile per graph shape, any width). ``traj_cap`` appends the
    convergence-trajectory buffers (None = the uninstrumented loop —
    the kernel python-branches, so the disabled jaxpr is unchanged)."""
    from paralleljohnson_tpu.ops.bucket import bellman_ford_bucketed

    return bellman_ford_bucketed(
        dist0, src, dst, w, indptr, delta, max_steps=max_steps,
        capacity=capacity, max_degree=max_degree,
        num_real_edges=num_real_edges, edge_chunk=edge_chunk,
        traj_cap=traj_cap,
    )


@functools.partial(
    jax.jit,
    static_argnames=("vb", "halo", "max_outer", "inner_cap", "traj_cap"),
)
def _gs_kernel(
    dist0, src_blk, dstl_blk, w_blk, rank, in_adj=None, *,
    vb: int, halo: int, max_outer: int, inner_cap: int,
    traj_cap: int | None = None,
):
    """Blocked Gauss-Seidel SSSP in relabeled ids; returns dist already
    mapped back to ORIGINAL vertex labels. ``traj_cap`` appends the
    outer-round convergence-trajectory buffers (ops.gauss_seidel)."""
    from paralleljohnson_tpu.ops.gauss_seidel import sssp_gs_blocks

    out = sssp_gs_blocks(
        dist0, src_blk, dstl_blk, w_blk,
        vb=vb, halo=halo, max_outer=max_outer, inner_cap=inner_cap,
        traj_cap=traj_cap, in_adj=in_adj,
    )
    dist, rounds, improving, iters_blk = out[:4]
    return (dist[rank], rounds, improving, iters_blk, *out[4:])


@functools.partial(
    jax.jit,
    static_argnames=(
        "v_pad", "vb", "halo", "max_outer", "inner_cap", "traj_cap"
    ),
)
def _gs_fanout_kernel(
    sources, src_blk, dstl_blk, w_blk, rank, in_adj=None, *,
    v_pad: int, vb: int, halo: int, max_outer: int, inner_cap: int,
    traj_cap: int | None = None,
):
    """Blocked Gauss-Seidel fan-out (vertex-major, relabeled ids);
    returns dist [B, V-original-labels] (+ trajectory buffers when
    ``traj_cap`` is set)."""
    from paralleljohnson_tpu.ops.gauss_seidel import fanout_gs_body

    return fanout_gs_body(
        sources, src_blk, dstl_blk, w_blk, rank,
        v_pad=v_pad, vb=vb, halo=halo, max_outer=max_outer,
        inner_cap=inner_cap, traj_cap=traj_cap, in_adj=in_adj,
    )


def _gs_examined_exact(
    iters_blk, real_edges_host: np.ndarray, b: int,
    *, rounds: int | None = None, inner_cap: int | None = None,
) -> int:
    """Exact candidate-relaxation count of a GS solve, in Python ints:
    sum over blocks of (inner iterations x real edges) x batch width —
    the same overflow-free host-side accounting standard as
    ``parallel.mesh._row_sweeps_exact`` (round-3 verdict weak #7).

    When ``rounds``/``inner_cap`` are given, the int32 exactness domain
    of ``iters_blk`` (ops.gauss_seidel._gs_engine docstring) is checked
    against the ACHIEVABLE bound 2 x rounds x inner_cap via the shared
    ``utils.metrics.warn_if_counter_wrapped`` guard (ADVICE round 4;
    the sharded path runs the same guard — round-5 verdict weak #5)."""
    if rounds is not None and inner_cap is not None:
        from paralleljohnson_tpu.utils.metrics import warn_if_counter_wrapped

        warn_if_counter_wrapped(rounds, inner_cap, where="gs")
    iters = np.asarray(iters_blk, np.int64)
    return int(np.dot(iters, real_edges_host.astype(np.int64))) * int(b)


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "max_iter", "edge_chunk")
)
def _fanout_kernel(
    sources, src, dst, w, *, num_nodes: int, max_iter: int, edge_chunk: int
):
    dist0 = relax.multi_source_init(sources, num_nodes, dtype=w.dtype)
    return relax.bellman_ford_sweeps(
        dist0, src, dst, w, max_iter=max_iter, edge_chunk=edge_chunk
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "v_pad", "vb", "max_iter"),
)
def _fanout_vm_blocked_kernel(
    sources, src_ck, dstl_ck, w_ck, base_ck, *,
    num_nodes: int, v_pad: int, vb: int, max_iter: int,
):
    """Dst-blocked vertex-major fan-out (ops.relax dst-blocked sweep):
    avoids the full-V per-chunk segment writes of the plain vm kernel at
    large V. Returns dist [B, V] (pad rows trimmed)."""
    b = sources.shape[0]
    dist0 = jnp.full((v_pad, b), jnp.inf, w_ck.dtype)
    dist0 = dist0.at[sources, jnp.arange(b)].set(0.0)
    dist, iters, improving = relax.bellman_ford_sweeps_vm_blocked(
        dist0, src_ck, dstl_ck, w_ck, base_ck, vb=vb, max_iter=max_iter
    )
    return dist[:num_nodes].T, iters, improving


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "v_pad", "vb", "max_iter", "interpret"),
)
def _fanout_pallas_kernel(
    sources, srcl_ck, dstl_ck, w_ck, runend_ck, sb_ids, db_ids, first_ck, *,
    num_nodes: int, v_pad: int, vb: int, max_iter: int, interpret: bool,
):
    """VMEM-resident Pallas fan-out (ops.pallas_sweep): both distance
    blocks live in VMEM, so the per-row HBM gather floor of the XLA
    sweeps (~10 cycles/row measured) does not apply. Opt-in via
    use_pallas=True until on-chip measurement promotes it (round-3
    verdict weak #6)."""
    from paralleljohnson_tpu.ops.pallas_sweep import pallas_fanout

    b = sources.shape[0]
    dist0 = jnp.full((v_pad, b), jnp.inf, w_ck.dtype)
    dist0 = dist0.at[sources, jnp.arange(b)].set(0.0)
    dist, iters, improving = pallas_fanout(
        dist0, srcl_ck, dstl_ck, w_ck, runend_ck, sb_ids, db_ids, first_ck,
        vb=vb, max_iter=max_iter, interpret=interpret,
    )
    return dist[:num_nodes].T, iters, improving


# Pallas fan-out tile parameters: chunk length, and the dst/src block
# height — two [vb, B] f32 blocks at B=128 must fit VMEM (~16 MB/core)
# with headroom, so vb caps at 8192 (4 MB per block).
PALLAS_EC = 2048
# The kernel's VMEM block specs are sized for this batch width; wider
# fan-outs run as slices of it (tests shrink it to cover the slicing).
PALLAS_BATCH_SLICE = 128


def _pallas_vb(v: int) -> int:
    return 8192 if v > (1 << 19) else 4096


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "max_iter", "edge_chunk")
)
def _fanout_vm_kernel(
    sources, src_bd, dst_bd, w_bd, *, num_nodes: int, max_iter: int,
    edge_chunk: int,
):
    """Vertex-major fan-out: dist [V, B], dst-sorted edges, sorted segment
    reduction (no scatter). Returns dist already transposed to [B, V]."""
    dist0 = relax.multi_source_init(sources, num_nodes, dtype=w_bd.dtype).T
    dist, iters, improving = relax.bellman_ford_sweeps_vm(
        dist0, src_bd, dst_bd, w_bd, max_iter=max_iter, edge_chunk=edge_chunk
    )
    return dist.T, iters, improving


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_nodes", "vb", "capacity", "max_iter", "num_real_edges",
        "edge_chunk", "traj_cap",
    ),
)
def _dw_fanout_kernel(
    sources, e_src, e_dst, w_tile, blk_of_v, src_bd, dst_bd, w_bd, *,
    num_nodes: int, vb: int, capacity: int, max_iter: int,
    num_real_edges: int, edge_chunk: int, traj_cap: int | None = None,
):
    """Dirty-window compacted fan-out (ISSUE 13 tentpole, route
    ``vm-blocked+dw``): per-destination-block activity bitmaps in the
    while_loop carry, compacted dirty-block out-edge tiles per round,
    full-sweep overflow fallback — ``ops.relax.bellman_ford_sweeps_dw``.
    Returns (dist [B, V], rounds, still_improving, ex_hi, ex_lo,
    full_rounds[, traj buffers]); the split examined counter is in edge
    SLOTS (multiply by B host-side)."""
    b = sources.shape[0]
    dist0 = jnp.full((num_nodes, b), jnp.inf, w_bd.dtype)
    dist0 = dist0.at[sources, jnp.arange(b)].set(0.0)
    out = relax.bellman_ford_sweeps_dw(
        dist0, e_src, e_dst, w_tile, blk_of_v, src_bd, dst_bd, w_bd,
        vb=vb, capacity=capacity, max_iter=max_iter,
        num_real_edges=num_real_edges, edge_chunk=edge_chunk,
        traj_cap=traj_cap,
    )
    return (out[0].T, *out[1:])


_reweight_kernel = jax.jit(relax.reweight_weights)


@functools.partial(jax.jit, static_argnames=("max_iter", "edge_chunk"))
def _bf_pred_kernel(dist0, src, dst, w, *, max_iter: int, edge_chunk: int):
    return relax.bellman_ford_sweeps_pred(
        dist0, src, dst, w, max_iter=max_iter, edge_chunk=edge_chunk
    )


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "max_iter", "edge_chunk")
)
def _fanout_pred_kernel(
    sources, src, dst, w, *, num_nodes: int, max_iter: int, edge_chunk: int
):
    dist0 = relax.multi_source_init(sources, num_nodes, dtype=w.dtype)
    return relax.bellman_ford_sweeps_pred(
        dist0, src, dst, w, max_iter=max_iter, edge_chunk=edge_chunk
    )


@functools.partial(jax.jit, static_argnames=("edge_chunk",))
def _extract_pred_kernel(dist, sources, src, dst, w, *, edge_chunk: int):
    """Post-fixpoint tight-edge predecessor extraction (ops.pred): one
    vectorized O(E x B / chunk) pass over the COO edges after ANY route
    converged, plus the pointer-doubling tree check. Returns
    (pred[B, V] int32, ok bool) — ok=False routes the solve to the
    legacy argmin-sweep fallback (zero-weight tight cycle)."""
    from paralleljohnson_tpu.ops.pred import extract_pred

    return extract_pred(dist, sources, src, dst, w, edge_chunk=edge_chunk)


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "tile", "k_block")
)
def _fw_apsp_kernel(sources, src, dst, w, *, num_nodes: int, tile: int,
                    k_block: int):
    """Blocked min-plus Floyd-Warshall APSP (ops.fw, ROADMAP item 3):
    dense adjacency padded to a tile multiple, R-Kleene closure
    (diagonal-tile Kleene, row/column panels, min-plus trailing
    "matmul"), then a row gather of the requested sources. O(V^3)
    tropical MACs — the log2(V)-factor win over min-plus squaring.
    Returns (dist[B, V], negative_cycle)."""
    from paralleljohnson_tpu.ops import fw

    a = relax.dense_adjacency(src, dst, w, num_nodes, dtype=w.dtype)
    closed, neg = fw.fw_apsp_blocked(
        fw.pad_dense(a, tile), tile=tile, k_block=k_block
    )
    return closed[sources, :num_nodes], neg


def _minplus_impl(use_pallas: bool, interpret: bool):
    """The min-plus product impl for dense kernels: the Pallas/Mosaic tile
    kernel (SURVEY.md §7 step 6) or None (the XLA blocked fallback)."""
    if not use_pallas:
        return None
    from paralleljohnson_tpu.ops.pallas_kernels import minplus_pallas

    return functools.partial(minplus_pallas, interpret=interpret)


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "max_iter", "use_pallas", "interpret"),
)
def _dense_fanout_kernel(
    sources, src, dst, w, *, num_nodes: int, max_iter: int,
    use_pallas: bool = False, interpret: bool = False,
):
    a = relax.dense_adjacency(src, dst, w, num_nodes, dtype=w.dtype)
    return relax.dense_fanout(
        a, sources, max_iter=max_iter, mp=_minplus_impl(use_pallas, interpret)
    )


@functools.partial(jax.jit, static_argnames=("num_nodes", "graph_chunk"))
def _batch_johnson_kernel(src, dst, w, *, num_nodes: int, graph_chunk: int):
    """Johnson APSP vmapped over a padded batch of graphs
    (BASELINE.json:11). Per graph: virtual-source BF (one no-op sweep on
    non-negative graphs), reweight, V-source sweeps, un-reweight. Graphs
    are streamed in ``graph_chunk`` slabs via lax.map to bound HBM."""
    v = num_nodes
    eye0 = jnp.where(jnp.eye(v, dtype=bool), 0.0, jnp.inf).astype(w.dtype)

    def per_graph(args):
        s, t, wt = args
        # One dst-sort per graph, then BOTH phases run vertex-major: the
        # sorted segment reduction replaces the unsorted scatter-min that
        # dominated this kernel (measured on the mini preset: 37.4 s ->
        # see BASELINE.md batch_small rows).
        order = jnp.argsort(t)
        s2, t2, w2 = s[order], t[order], wt[order]
        h_vm, _, neg = relax.bellman_ford_sweeps_vm(
            jnp.zeros((v, 1), wt.dtype), s2, t2, w2, max_iter=v
        )
        h = h_vm[:, 0]
        wp2 = relax.reweight_weights(w2, s2, t2, h)
        dist_vm, iters, _ = relax.bellman_ford_sweeps_vm(
            eye0, s2, t2, wp2, max_iter=v
        )
        # dist_vm[v_idx, b] = d'(source b -> v_idx); un-reweight on the
        # [B, V] orientation.
        dist = dist_vm.T - h[:, None] + h[None, :]
        return dist, iters, neg

    g = src.shape[0]
    chunk = min(graph_chunk, g)
    nb = -(-g // chunk)
    pad = nb * chunk - g

    def pad_g(x):
        if not pad:
            return x
        fill = jnp.full((pad, x.shape[1]), jnp.inf, x.dtype) if jnp.issubdtype(
            x.dtype, jnp.floating
        ) else jnp.zeros((pad, x.shape[1]), x.dtype)
        return jnp.concatenate([x, fill])

    src, dst, w = pad_g(src), pad_g(dst), pad_g(w)
    reshape = lambda x: x.reshape(nb, chunk, x.shape[1])
    dist, iters, neg = jax.lax.map(
        jax.vmap(per_graph), (reshape(src), reshape(dst), reshape(w))
    )
    unchunk = lambda x: x.reshape(nb * chunk, *x.shape[2:])[:g]
    return unchunk(dist), unchunk(iters), unchunk(neg)


class JaxBackend(Backend):
    """XLA/TPU backend: jitted frontier sweeps, device-resident buffers."""

    name = "jax"

    def __init__(self, config=None) -> None:
        super().__init__(config)
        # Persistent XLA/Mosaic compile cache (ROADMAP item 1): opt-in
        # via SolverConfig.compilation_cache_dir / PJ_COMPILE_CACHE, so
        # the 3x-retry TPU passes stop re-paying compiles per attempt.
        from paralleljohnson_tpu.utils.platform import (
            enable_compilation_cache,
        )

        enable_compilation_cache(self.config.compilation_cache_dir)
        # Compiled-cost capture (observe.costs): enabled only when a
        # profile store is configured (SolverConfig.profile_store /
        # PJ_PROFILE_DIR) — capture pays one AOT lower+compile per
        # (route, platform, shape-bucket) key, so plain solves opt out.
        from paralleljohnson_tpu.observe.costs import (
            CostCapture,
            resolve_profile_dir,
        )

        self.cost_capture = CostCapture(
            enabled=resolve_profile_dir(self.config.profile_store)
            is not None
        )

    def _observe_cost(self, route, jitfn, args, kwargs, dgraph, batch=1):
        """Harvest XLA cost/memory analysis for ``route``'s executable at
        these shapes (once per key; see observe.costs). Returns the
        analytic-cost dict for ``KernelResult.cost``, or None when
        capture is off. Never raises — an unlowerable call degrades to
        the explicit ``cost_analysis_unavailable`` marker inside."""
        cap = self.cost_capture
        if not cap.enabled:
            return None
        return cap.capture(
            route, jitfn, args, kwargs,
            num_nodes=dgraph.num_nodes,
            num_edges=dgraph.num_real_edges, batch=batch,
        )

    def _observe_analytic(self, route, cost, dgraph, batch=1):
        """Model-priced cost record (``observe.costs.CostCapture
        .analytic``) for the semiring routes XLA's per-op cost table
        misprices (the blocked-FW tile model — see ``ops.fw``)."""
        cap = self.cost_capture
        if not cap.enabled:
            return None
        return cap.analytic(
            route, cost,
            num_nodes=dgraph.num_nodes,
            num_edges=dgraph.num_real_edges, batch=batch,
        )

    def _observe_unavailable(self, route, reason, dgraph, batch=1):
        """Explicit capture marker for routes with no single
        AOT-lowerable executable (sharded collectives, Pallas slices)."""
        cap = self.cost_capture
        if not cap.enabled:
            return None
        return cap.unavailable(
            route, reason,
            num_nodes=dgraph.num_nodes,
            num_edges=dgraph.num_real_edges, batch=batch,
        )

    def _traj_cap(self) -> int | None:
        """Static trajectory-buffer length for this solve, or None when
        the convergence observatory is off (ISSUE 9). ``"auto"`` enables
        it exactly when something can consume the trajectory — a
        telemetry sink or a profile store — so a plain solve compiles
        the original, uninstrumented kernels (disabled-path purity).
        True forces (tests / ad-hoc introspection); False disables."""
        flag = getattr(self.config, "convergence", "auto")
        if flag is False:
            return None
        if flag is not True and not (
            getattr(self.config, "telemetry", None) is not None
            or self.cost_capture.enabled
        ):
            return None
        from paralleljohnson_tpu.observe.convergence import DEFAULT_TRAJ_CAP

        return DEFAULT_TRAJ_CAP

    def _attach_trajectory(
        self, res: KernelResult, counts, resid, dgraph, batch: int = 1,
        iterations: int | None = None,
    ) -> KernelResult:
        """Decode one kernel call's device trajectory buffers onto the
        KernelResult (the single post-convergence D2H) and summarize.
        Runs the shared int32 addend wrap guard first — shapes whose
        per-iteration relaxations bound (batch x V) reaches 2^31 get a
        warned lower bound, never a silent lie (the ops/bucket split-
        counter standard). Never fatal: a decode failure drops the
        trajectory, not the solve."""
        try:
            from paralleljohnson_tpu.observe import convergence as conv
            from paralleljohnson_tpu.utils.metrics import (
                warn_if_traj_counter_wrapped,
            )

            warn_if_traj_counter_wrapped(
                batch, dgraph.num_nodes, where=res.route or "trajectory"
            )
            iters = res.iterations if iterations is None else iterations
            traj = conv.decode_trajectory(counts, resid, iters)
            res.trajectory = traj
            # Size-biased mean degree (cached per structure): corrects
            # the JFR-skippable estimator's uniform-degree skew on
            # power-law graphs (ISSUE 13 satellite).
            bias = dgraph._by_dst_cache.get("degree_bias", "unset")
            if bias == "unset":
                bias = conv.degree_bias_from_degrees(
                    np.diff(dgraph.indptr)
                )
                dgraph._by_dst_cache["degree_bias"] = bias
            res.convergence = conv.summarize_trajectory(
                traj,
                num_nodes=dgraph.num_nodes,
                batch=batch,
                num_edges=dgraph.num_real_edges,
                iterations=iters,
                degree_bias=bias,
            )
        except Exception:  # noqa: BLE001 — observability is never fatal
            pass
        return res

    @property
    def _dtype(self):
        if self.config.precision == "f64" and not jax.config.jax_enable_x64:
            raise ValueError(
                "precision=f64 on the jax backend requires jax_enable_x64"
            )
        return jnp.float64 if self.config.precision == "f64" else jnp.float32

    def upload(self, graph: CSRGraph) -> JaxDeviceGraph:
        g = graph.pad_edges(self.config.edge_pad_multiple)
        return JaxDeviceGraph(
            src=jnp.asarray(g.src, jnp.int32),
            dst=jnp.asarray(g.indices, jnp.int32),
            weights=jnp.asarray(g.weights, self._dtype),
            indptr=graph.indptr,
            num_nodes=graph.num_nodes,
            num_real_edges=graph.num_real_edges,
            host_graph=graph,
        )

    def download_graph(self, dgraph: JaxDeviceGraph) -> CSRGraph:
        e = dgraph.num_real_edges
        g = CSRGraph(
            indptr=dgraph.indptr,
            indices=np.asarray(dgraph.dst)[:e],
            weights=np.asarray(dgraph.weights)[:e],
        )
        g.__dict__["_src"] = np.asarray(dgraph.src)[:e]
        return g

    def clear_caches(self, dgraph: JaxDeviceGraph) -> None:
        """Drop every rebuildable layout cache held by ``dgraph`` —
        the HBM-hygiene step for large row downloads (the s22 worker
        crash happened under HBM pressure DURING a row download while
        the fan-out layouts were still resident; VERDICT missing #3).
        ``_struct_cache`` can hold device-built chunk structures
        (``build_vm_blocked_layout_device``: ~16E bytes at rmat-22) and
        ``_by_dst_cache`` the dst-sorted edge triple + per-layout chunk
        weights; all of it is re-derivable, so the solver frees it
        before multi-batch downloads and the next kernel call rebuilds
        on demand."""
        dgraph._struct_cache.clear()
        dgraph._by_dst_cache.clear()

    def stage_rows_async(self, *arrays) -> None:
        """Kick off the D2H copies early (``jax.Array.copy_to_host_async``)
        so the pipelined fan-out's row download DMA runs under the next
        batch's compute; the later ``np.asarray`` then collects a mostly
        finished transfer instead of starting one. Purely a scheduling
        hint — failures are swallowed (the synchronous download still
        happens and is the correctness path)."""
        for a in arrays:
            start = getattr(a, "copy_to_host_async", None)
            if start is None:
                continue
            try:
                start()
            except Exception:  # noqa: BLE001 — hint only, never correctness
                pass

    def _memory_budget_bytes(self) -> int:
        """Usable accelerator memory for one fan-out call. Prefers the
        device's own bytes_limit (TPU HBM); CPU hosts get a conservative
        constant so the simulated mesh never balloons."""
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0))
            if limit > 0:
                return limit // 2  # leave headroom for XLA temporaries
        except Exception:
            pass
        return 4 << 30

    def suggested_source_batch(
        self, dgraph: JaxDeviceGraph, with_pred: bool = False
    ) -> int | None:
        """Cap the [B, V] distance block to the device budget
        (SolverConfig.source_batch_size=None contract). The edge-chunk
        intermediate is bounded separately by ``_edge_chunk_for``, so the
        [B, V] blocks dominate: ~6 of them live across the while_loop
        carry, the update, and XLA temporaries. ``with_pred`` adds ~3
        more (the int32 pred block itself plus the extraction pass's
        (best_du, best_u) scan carries — ops.pred), so a pred solve no
        longer silently overshoots the budget the plain sizing promised.
        The pipelined fan-out (``config.pipeline_depth`` > 1) additionally
        holds one computed-but-unmaterialized [B, V] block per extra
        in-flight slot (plus its pred block on pred solves) while the
        next batch computes — budgeted here so double-buffering cannot
        OOM a batch the serial sizing promised would fit."""
        v = max(dgraph.num_nodes, 1)
        itemsize = jnp.dtype(self._dtype).itemsize
        blocks = 9 if with_pred else 6
        carry_slots = max(0, self._pipeline_depth(dgraph) - 1)
        blocks += carry_slots * (2 if with_pred else 1)
        # Per-DEVICE budget: row blocks shard over the "sources" axis only
        # (on a 2-D mesh they replicate over "edges"), so the global B is
        # n_sources x what one device can hold.
        n = self._sources_axis_size()
        b = (self._memory_budget_bytes() // (blocks * v * itemsize)) * n
        b = int(max(1, min(b, 1 << 16)))
        if b > n:
            b -= b % n  # keep shards even on the mesh
        return b

    def _use_dense(self, dgraph: JaxDeviceGraph) -> bool:
        """Dense min-plus pays only when the graph is actually dense:
        per sweep it does B x V^2 work vs the sparse path's B x E, so at
        E = dense_min_density x V^2 (default 1/16) the regularity
        advantage of the dense formulation (contiguous VPU tiles vs
        gather/segment) breaks even. Measured (1-core CPU, rmat10 B=64,
        E/V^2 = 1.6%): dense 323 ms vs sparse vertex-major 3 ms for
        identical results — a pure V <= threshold gate put every
        small-but-sparse graph on the slow path."""
        v = dgraph.num_nodes
        if v > self.config.dense_threshold or v == 0:
            return False
        return dgraph.num_real_edges >= self.config.dense_min_density * v * v

    def _use_fw(self, dgraph: JaxDeviceGraph, batch: int) -> bool:
        """Blocked min-plus Floyd-Warshall (ops.fw) for the squaring
        regime of the dense family — APSP over the tropical semiring as
        a blocked matrix multiply (ROADMAP item 3). "auto" engages when
        (a) most rows are wanted anyway (the same 2B >= V test that
        picks the squaring regime), (b) the graph is actually dense
        (the ``dense_min_density`` gate the dense path uses — FW does
        V^2-shaped work regardless of E), (c) V is within
        ``fw_threshold``, and (d) the exact analytic MAC counters say
        the blocked closure beats squaring — both counts are host ints
        from the same padded scale (``relax.dense_fanout_regime`` /
        ``ops.fw.fw_mac_count``), so the regime pick and its work
        accounting can never drift apart. True forces (negative edges
        are handled natively); False disables."""
        flag = self.config.fw
        if flag is False or getattr(self, "_fw_disabled", False):
            return False
        v = dgraph.num_nodes
        if v == 0:
            return False
        if flag is True:
            return True
        if v > self.config.fw_threshold:
            return False
        regime, per_iter = relax.dense_fanout_regime(v, batch)
        if regime != "squaring":
            return False
        if dgraph.num_real_edges < self.config.dense_min_density * v * v:
            return False
        from paralleljohnson_tpu.ops import fw as fw_ops

        tile = fw_ops.effective_tile(v, self._fw_tile(dgraph)[0])
        fw_macs = fw_ops.fw_mac_count(fw_ops.pad_tiles(v, tile), tile)
        return fw_macs < relax.squaring_steps(v) * per_iter

    @staticmethod
    def _low_degree_family(dgraph: JaxDeviceGraph) -> bool:
        """The road/grid graph family both the frontier and Gauss-Seidel
        paths target: non-tiny, low max out-degree (hub-heavy graphs
        would pad every gather tile to the hub degree). One definition so
        the two routes can never drift apart."""
        return dgraph.num_nodes >= 512 and 0 < dgraph.max_degree <= 32

    def _use_frontier(self, dgraph: JaxDeviceGraph) -> bool:
        """Frontier compaction pays when the out-edge gather tile
        (capacity x max_degree) is small next to E — the low-degree
        family (road networks, grids)."""
        flag = self.config.frontier
        if flag != "auto":
            return bool(flag)
        # Near the int32 edge-index ceiling the frontier kernel's split
        # examined counter cannot take a full-sweep addend (it would
        # raise — ops.relax.FRONTIER_ADDEND_MAX); auto routes such
        # graphs to the sweep family instead of crashing the solve.
        if dgraph.num_real_edges >= relax.FRONTIER_ADDEND_MAX:
            return False
        return self._low_degree_family(dgraph)

    def _frontier_capacity(self, dgraph: JaxDeviceGraph) -> int:
        """Static frontier-id buffer size: big enough that road/grid
        frontiers (~sqrt(V)-ish) rarely overflow into full sweeps, small
        enough that one frontier round is far cheaper than a sweep —
        Measured on the 515x515 grid (neg=0.2, CPU): capacity V/8 (33k)
        leaves ~zero overflow fallbacks and the least total edge work
        (4.4e7 examined vs 1.2e9 for full sweeps); smaller capacities
        trade cheaper rounds for O(E) fallback sweeps and lose on total
        work. Every per-round op scales with capacity, so a TPU mesh
        (cheap wide ops, expensive sweeps) wants the overflow-free
        setting."""
        if self.config.frontier_capacity is not None:
            return int(self.config.frontier_capacity)
        v = dgraph.num_nodes
        return int(min(v, max(1024, v // 8)))

    def _edge_mesh(self):
        """Mesh over the ``"edges"`` axis (same devices as the fan-out
        mesh), for edge-sharded single-source Bellman-Ford."""
        from paralleljohnson_tpu.parallel import make_edge_mesh

        cached = getattr(self, "_edge_mesh_cache", None)
        if cached is None:
            cached = make_edge_mesh(self.config.mesh_shape)
            self._edge_mesh_cache = cached
        return cached

    def _use_gs(self, dgraph: JaxDeviceGraph) -> bool:
        """Blocked Gauss-Seidel targets the same low-max-degree graph
        family as the frontier path (road/grid); "auto" picks it on TPU,
        where the frontier's per-round fixed cost (~15 ms of scatter +
        nonzero, BASELINE.md round-3 notes) makes round COUNT the only
        lever — on CPU the frontier's compacted work measures faster.
        Requires the host CSR STRUCTURE only (the layout is
        weight-independent; current device weights are gathered in)."""
        flag = self.config.gauss_seidel
        if (
            flag is False
            or dgraph.host_graph is None
            or getattr(self, "_gs_disabled", False)
        ):
            return False
        if flag is True:
            return True
        if self.config.frontier is True or self.config.bucket is True:
            # An explicitly forced frontier/bucket path wins over
            # gauss_seidel "auto" — "True forces" must hold everywhere.
            return False
        return (
            jax.default_backend() == "tpu"
            and self._low_degree_family(dgraph)
        )

    def _use_dia(self, dgraph: JaxDeviceGraph) -> bool:
        """Gather-free DIA stencil route for B=1 solves (ops.dia): on
        TPU it sidesteps the XLA row-gather floor that lower-bounds
        every gather-based sweep (the round-5 off-chip analysis,
        bench_artifacts/gs_offchip_validation.md), so "auto" prefers it
        whenever the graph's labeling is diagonal. An explicitly forced
        frontier/gauss_seidel route wins over "auto" (the "True forces"
        contract); on CPU the frontier's compacted work stays the
        measured winner, so auto is TPU-only."""
        flag = self.config.dia
        if (
            flag is False
            or dgraph.host_graph is None
            or getattr(self, "_dia_disabled", False)
        ):
            return False
        if flag is True:
            return self.dia_bundle(dgraph) is not None
        if (
            self.config.frontier is True
            or self.config.gauss_seidel is True
            or self.config.bucket is True
        ):
            return False
        return (
            jax.default_backend() == "tpu"
            and self.dia_bundle(dgraph) is not None
        )

    def dia_bundle(self, dgraph: JaxDeviceGraph) -> dict | None:
        return dgraph.dia_layout(self.config.dia_max_offsets)

    def _use_bucket(self, dgraph: JaxDeviceGraph) -> bool:
        """Bucketed delta-stepping route for B=1 solves (ops.bucket):
        the road-family mitigation for graphs whose LABELING is not
        diagonal — exactly where DIA declines and GS's validated model
        still prices 4.5-8 s at full dimacs scale (the examined x
        gather-floor term). "auto" prefers it on TPU for the low-degree
        family whenever DIA disqualifies; an explicitly forced
        frontier/gauss_seidel/dia route wins over "auto" (the "True
        forces" contract), and near the int32 edge-index ceiling the
        split examined counter rules the route out exactly like the
        frontier kernel's."""
        flag = self.config.bucket
        if flag is False or getattr(self, "_bucket_disabled", False):
            return False
        if flag is True:
            return True
        if (
            self.config.frontier is True
            or self.config.gauss_seidel is True
            or self.config.dia is True
        ):
            return False
        if dgraph.num_real_edges >= relax.FRONTIER_ADDEND_MAX:
            return False
        return (
            jax.default_backend() == "tpu"
            and self._low_degree_family(dgraph)
            and self.dia_bundle(dgraph) is None
        )

    def _use_dw(self, dgraph: JaxDeviceGraph, batch: int) -> bool:
        """Dirty-window compacted fan-out route (ISSUE 13, route
        ``vm-blocked+dw``). True forces; False disables; ``"auto"``
        NEVER engages blindly: it requires a profile store whose
        trajectory records for this graph's shape bucket show a
        collapsing frontier worth the schedule
        (``observe.convergence.dw_decision`` — the first concrete step
        of the priced dispatch registry, ROADMAP item 2), refined by
        the CostModel when it has calibrations for both the dw and the
        plain batched route. A graph with no recorded collapse (or a
        flat trajectory) stays on plain vm / vm-blocked."""
        flag = getattr(self.config, "dirty_window", "auto")
        if flag is False or getattr(self, "_dw_disabled", False):
            return False
        if dgraph.num_nodes == 0:
            return False
        if flag is True:
            return True
        if dgraph.num_real_edges >= relax.FRONTIER_ADDEND_MAX:
            # The split examined counter's full-sweep addend would wrap.
            return False
        decision = self._dw_decision(dgraph, batch)
        return bool(decision.get("engage"))

    def _dw_decision(self, dgraph: JaxDeviceGraph, batch: int) -> dict:
        """The trajectory-record dispatch decision for this graph
        (cached per dgraph + pow2 batch bucket): read the configured
        profile store's ``kind: "trajectory"`` records, match this
        graph's shape bucket, and apply the collapse thresholds; when
        the store's CostModel prices BOTH ``vm-blocked+dw`` and the
        plain batched route for this platform, the cheaper prediction
        wins (priced dispatch, never blind)."""
        from paralleljohnson_tpu.observe.convergence import dw_decision

        bucket = max(1, int(batch) - 1).bit_length()
        key = ("dw_decision", bucket)
        cached = dgraph._by_dst_cache.get(key)
        if cached is not None:
            return cached
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir

        store_dir = resolve_profile_dir(self.config.profile_store)
        if store_dir is None:
            decision = {
                "engage": False,
                "reason": "no profile store configured (auto engages "
                          "only from recorded trajectory evidence)",
            }
        else:
            try:
                from paralleljohnson_tpu.observe.store import (
                    CostModel,
                    ProfileStore,
                )

                records = ProfileStore(store_dir).records()
                decision = dw_decision(
                    records,
                    num_nodes=dgraph.num_nodes,
                    num_edges=dgraph.num_real_edges,
                    platform=jax.default_backend(),
                )
                if decision.get("engage"):
                    # Priced refinement: only veto when the model can
                    # price BOTH routes — an unpriced route must read
                    # as unpriced, not as free or as infinite.
                    model = CostModel.fit(records)
                    platform = jax.default_backend()
                    dw_p = model.predict(
                        "vm-blocked+dw", num_edges=dgraph.num_real_edges,
                        batch=batch, platform=platform,
                    )
                    plain = None
                    for route in ("vm-blocked", "vm", "sweep-sm"):
                        plain = model.predict(
                            route, num_edges=dgraph.num_real_edges,
                            batch=batch, platform=platform,
                        )
                        if plain is not None:
                            break
                    if (
                        dw_p is not None and plain is not None
                        and dw_p["predicted_s"] > plain["predicted_s"]
                    ):
                        decision = {
                            "engage": False,
                            "reason": (
                                "cost model prices dw at "
                                f"{dw_p['predicted_s']:.4g}s vs plain "
                                f"{plain['predicted_s']:.4g}s"
                            ),
                        }
            except Exception as e:  # noqa: BLE001 — a torn store must not crash dispatch
                decision = {
                    "engage": False,
                    "reason": f"profile store unreadable: "
                              f"{type(e).__name__}: {e}",
                }
        dgraph._by_dst_cache[key] = decision
        return decision

    def _dw_capacity(self, nb: int, em: int, batch: int) -> int:
        """Tier-2 dirty-buffer capacity: nb/4 floored at 1024 — measured
        on the scrambled 96x96 grid (two-tier kernel, CPU): nb/8 costs
        overflow full-sweeps at batch width while nb/2 bills quiet
        rounds at flood-tile cost; nb/4 held 2.3-3.1x plain at B=1..8.
        ``dw_capacity_clamp`` applies the counter/memory bounds."""
        if self.config.frontier_capacity is not None:
            cap = int(self.config.frontier_capacity)
        else:
            cap = max(1024, nb // 4)
        return relax.dw_capacity_clamp(cap, nb, em, batch)

    def _bucket_delta(self, dgraph: JaxDeviceGraph) -> float:
        """Resolved bucket width: SolverConfig.delta, or the auto-tune
        (mean |weight| x degree heuristic — ops.bucket.auto_delta) from
        the CURRENT device weights via two scalar reductions (no O(E)
        host download; cached per weight generation — _by_dst_cache is
        cleared on reweight, so the reweighted graph re-tunes)."""
        if self.config.delta is not None:
            return float(self.config.delta)
        cached = dgraph._by_dst_cache.get("bucket_delta")
        if cached is None:
            # Profile-tuned width first (ISSUE 14 auto-tuning): a
            # recorded plan whose explicit delta measured faster on
            # this (platform, shape bucket) becomes the auto value;
            # the mean-weight heuristic stays the no-profile fallback.
            from paralleljohnson_tpu.observe.tuning import resolve_param

            tuned, source = resolve_param(
                "delta", None, None,
                config=self.config, platform=jax.default_backend(),
                num_nodes=dgraph.num_nodes,
                num_edges=dgraph.num_real_edges,
                validate=lambda d: isinstance(d, (int, float)) and d > 0,
            )
            if source == "profile-tuned":
                cached = float(tuned)
                dgraph._by_dst_cache["bucket_delta"] = cached
                return cached
            from paralleljohnson_tpu.ops.bucket import auto_delta

            finite = jnp.isfinite(dgraph.weights)
            mean_w = float(
                jnp.sum(jnp.where(finite, jnp.abs(dgraph.weights), 0.0))
                / jnp.maximum(jnp.sum(finite), 1)
            )
            cached = auto_delta(
                mean_w, dgraph.num_nodes, dgraph.num_real_edges
            )
            dgraph._by_dst_cache["bucket_delta"] = cached
        return cached

    def _auto_route_failed(
        self, flag_attr: str, message: str, *, forced: bool
    ) -> None:
        """An auto-selected kernel route raised (call from an active
        ``except`` block) — typically an XLA/Mosaic rejection or runtime
        failure on a platform CI cannot cover (the round-3 verdict's
        'TPU-gated default that never ran on TPU' risk). ``forced``:
        propagate — the user asked for exactly this kernel. Otherwise:
        warn once, set ``flag_attr`` on this backend instance so the
        route is not retried, and let the caller fall through — an auto
        default must degrade, not crash the solve."""
        if forced:
            raise
        if not getattr(self, flag_attr, False):
            setattr(self, flag_attr, True)
            import sys
            import traceback
            import warnings

            warnings.warn(message, RuntimeWarning, stacklevel=3)
            traceback.print_exc(file=sys.stderr)

    def _gs_auto_failed(self, dgraph: JaxDeviceGraph) -> None:
        self._auto_route_failed(
            "_gs_disabled",
            "gauss_seidel='auto' kernel failed on this platform; "
            "falling back to sweep routes for this backend instance",
            forced=self.config.gauss_seidel is True,
        )

    @property
    def _telemetry(self):
        """The solve's ``utils.telemetry.Telemetry`` (or None) — handed to
        the ``parallel.mesh`` sharded entry points so each collective
        dispatch lands as a span on the flight record."""
        return getattr(self.config, "telemetry", None)

    def _shard_fault_hook(self):
        """Fault-injection hook handed to the ``parallel.mesh`` sharded
        entry points (``config.fault_plan`` stage ``"sharded_fanout"``):
        fires inside the sharded path, so a simulated collective/tunnel
        failure surfaces exactly where the real one would. None when no
        plan is configured."""
        plan = self.config.fault_plan
        if plan is None:
            return None

        def hook():
            active = plan.fire("sharded_fanout")
            if active is not None:
                active.wrap(lambda: None)()

        return hook

    def _sharded_fallback(
        self, exc: BaseException, dgraph: JaxDeviceGraph, sources, *,
        pred_sweep: bool = False,
    ) -> KernelResult:
        """A sharded fan-out raised (collective failure / tunnel drop):
        degrade to single-device instead of dying — warn once, pin this
        backend instance to a 1-device mesh, and re-dispatch the SAME
        batch through the single-chip routes. OOM is NOT handled here:
        the solver's OOMDegrader owns that recovery (shrink the batch,
        keep the mesh), so RESOURCE_EXHAUSTED re-raises untouched."""
        if resilience.is_oom_error(exc):
            raise exc
        self._auto_route_failed(
            "_sharded_disabled",
            "sharded fan-out failed (collective/tunnel failure); "
            "falling back to single-device solves for this backend "
            "instance",
            forced=False,
        )
        self._mesh_cache = None  # _mesh() rebuilds as a 1-device mesh
        if pred_sweep:
            res = self._multi_source_pred_sweep(dgraph, sources)
        else:
            res = self.multi_source(dgraph, sources)
        res.route = f"{res.route or 'sweep'}+1dev-fallback"
        return res

    def _use_edge_shard(self, dgraph: JaxDeviceGraph) -> bool:
        """Edge sharding is the only way a multi-device mesh helps a B=1
        solve. Precedence: an explicit ``edge_shard=True`` wins (the
        documented scale-out escape hatch for edge lists beyond one
        chip's HBM); ``"auto"`` defers to the frontier/Gauss-Seidel
        paths on low-degree graphs where they are work-optimal."""
        flag = self.config.edge_shard
        if flag is False or self._mesh().devices.size <= 1:
            return False
        if getattr(self, "_edge_shard_disabled", False):
            return False
        if flag is True:
            return True
        return not (
            self._use_frontier(dgraph)
            or self._use_gs(dgraph)
            or self._use_dia(dgraph)
            or self._use_bucket(dgraph)
        )

    def bellman_ford(self, dgraph: JaxDeviceGraph, source: int | None) -> KernelResult:
        """B=1 (SSSP / virtual-source) dispatch through the priced
        planner registry (ISSUE 17 satellite; the route ladder that
        survived the round-19 ``multi_source`` conversion is gone):
        ``planner.select`` over ``SSSP_PLANS`` evaluates the same
        ``_use_*`` gates the ladder consulted, so with nothing priced
        the ranking IS the old ladder order and dispatch (therefore
        distances) is bit-for-bit what the ladder produced. The walk
        degrades don't-crash exactly like ``multi_source``: an auto
        plan that raises warns once + disables itself for this backend
        instance and the next qualified plan serves the solve; a
        forced plan propagates."""
        from paralleljohnson_tpu import planner as _planner

        v = dgraph.num_nodes
        if source is None:
            dist0 = jnp.zeros(v, self._dtype)
        else:
            dist0 = jnp.full(v, jnp.inf, self._dtype).at[source].set(0.0)
        ctx = _SsspCtx(
            backend=self,
            dgraph=dgraph,
            source=source,
            dist0=dist0,
            max_iter=self.config.max_iterations or v,
            chunk=_edge_chunk_for(1, dgraph.src.shape[0]),
        )
        decision = _planner.select(
            SSSP_PLANS, ctx,
            model=self._planner_model(),
            platform=jax.default_backend(),
            num_edges=dgraph.num_real_edges,
            batch=1,
            config=self.config,
        )
        self.last_plan_decision = decision
        for cand in decision.ranking:
            try:
                res = cand.plan.build(ctx)
            except Exception:
                if cand.plan.failure is None:
                    raise
                # Called from this active except block so a forced
                # flag's bare ``raise`` propagates the original error.
                cand.plan.failure(self, ctx)
                continue
            if res is None:
                continue
            decision.params.update(ctx.params)
            res.plan = decision.as_dict(built=cand.plan.name)
            return res
        raise RuntimeError(
            "planner: every qualified SSSP plan failed (the sweep plan "
            "is unconditional — this is a bug)"
        )

    # -- B=1 plan builds (the registry's build hooks; each is the body
    #    its ladder branch used to hold, verbatim kernels) -------------

    def _sssp_build_edge_sharded(self, ctx) -> KernelResult:
        from paralleljohnson_tpu.parallel import edge_sharded_bellman_ford

        dgraph, dist0, max_iter = ctx.dgraph, ctx.dist0, ctx.max_iter
        v = dgraph.num_nodes
        emesh = self._edge_mesh()
        dist, iters, improving = edge_sharded_bellman_ford(
            emesh, dist0, dgraph.src, dgraph.dst, dgraph.weights,
            max_iter=max_iter,
            edge_chunk=_edge_chunk_for(
                1, -(-dgraph.src.shape[0] // emesh.devices.size)
            ),
            fault_hook=self._shard_fault_hook(),
            telemetry=self._telemetry,
        )
        iters = int(iters)
        improving = bool(improving)
        return KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=iters,
            # Each round relaxes the full edge list (across shards).
            edges_relaxed=iters * dgraph.num_real_edges,
            route="edge-sharded",
            cost=self._observe_unavailable(
                "edge-sharded",
                "sharded collective executables are not "
                "cost-instrumented", dgraph,
            ),
        )

    def _sssp_build_dia(self, ctx) -> KernelResult:
        from paralleljohnson_tpu.ops.dia import dia_fixpoint

        dgraph, dist0, max_iter = ctx.dgraph, ctx.dist0, ctx.max_iter
        v = dgraph.num_nodes
        lay = self.dia_bundle(dgraph)
        cap = self._traj_cap()
        traj_bufs = None
        if cap is not None:
            dist, iters, improving, *traj_bufs = _dia_fixpoint_traj(
                dist0, lay["w_diag"],
                offsets=lay["offsets"], max_iter=max_iter,
                traj_cap=cap,
            )
            dia_fn, dia_kwargs = _dia_fixpoint_traj, dict(
                offsets=lay["offsets"], max_iter=max_iter,
                traj_cap=cap,
            )
        else:
            dist, iters, improving = dia_fixpoint(
                dist0, lay["w_diag"],
                offsets=lay["offsets"], max_iter=max_iter,
            )
            dia_fn, dia_kwargs = dia_fixpoint, dict(
                offsets=lay["offsets"], max_iter=max_iter,
            )
        iters = int(iters)
        improving = bool(improving)
        res = KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=iters,
            # Each chained sweep examines every stored diagonal
            # entry once (= E: the layout stores all real edges).
            edges_relaxed=iters * lay["num_entries"],
            route="dia",
            cost=self._observe_cost(
                "dia", dia_fn, (dist0, lay["w_diag"]),
                dia_kwargs, dgraph,
            ),
        )
        if traj_bufs is not None:
            self._attach_trajectory(res, *traj_bufs, dgraph)
        return res

    def _sssp_build_bucket(self, ctx) -> KernelResult:
        from paralleljohnson_tpu.ops.bucket import auto_capacity

        dgraph, dist0 = ctx.dgraph, ctx.dist0
        max_iter, chunk = ctx.max_iter, ctx.chunk
        v = dgraph.num_nodes
        delta = self._bucket_delta(dgraph)
        # The resolved bucket width rides on the decision params so
        # kind:"plan" records carry the sample the delta auto-tuner
        # compares (observe.tuning).
        ctx.params["delta"] = float(delta)
        # Generous step budget: converging solves use ~hop-
        # diameter steps << V; the bucket schedule does NOT
        # subsume Jacobi rounds, so exhausting it is handed to
        # the sweep kernel below, which finishes from the
        # (valid upper bound) distances AND owns the negative-
        # cycle certificate.
        max_steps = 2 * max_iter + 64
        cap = self._traj_cap()
        bucket_kwargs = dict(
            max_steps=max_steps,
            capacity=auto_capacity(v, dgraph.max_degree),
            max_degree=dgraph.max_degree,
            num_real_edges=dgraph.num_real_edges,
            edge_chunk=chunk,
            traj_cap=cap,
        )
        # traj_cap=None compiles the exact uninstrumented loop
        # (ops.bucket python-branches); the splat is empty then.
        dist_b, steps, still, ex_hi, ex_lo, *traj_bufs = (
            _bucket_kernel(
                dist0, dgraph.src, dgraph.dst, dgraph.weights,
                dgraph.indptr_dev(),
                jnp.asarray(delta, self._dtype),
                **bucket_kwargs,
            )
        )
        steps = int(steps)
        examined = relax.examined_exact(ex_hi, ex_lo)
        bucket_cost = self._observe_cost(
            "bucket", _bucket_kernel,
            (dist0, dgraph.src, dgraph.dst, dgraph.weights,
             dgraph.indptr_dev(),
             jnp.asarray(delta, self._dtype)),
            bucket_kwargs,
            dgraph,
        )
        if bool(still):
            dist_b, it2, improving = _bf_kernel(
                dist_b, dgraph.src, dgraph.dst, dgraph.weights,
                max_iter=max_iter, edge_chunk=chunk,
            )
            it2 = int(it2)
            improving = bool(improving)
            res = KernelResult(
                dist=dist_b,
                negative_cycle=improving and max_iter >= v,
                converged=not improving,
                iterations=steps + it2,
                edges_relaxed=examined
                + it2 * dgraph.num_real_edges,
                route="bucket+sweep",
                cost=bucket_cost,
            )
            if traj_bufs:
                # The trajectory covers the bucketed steps only
                # (the finishing sweep is the uninstrumented
                # certifier) — decode at the bucket step count.
                self._attach_trajectory(
                    res, *traj_bufs, dgraph, iterations=steps
                )
            return res
        res = KernelResult(
            dist=dist_b,
            # Empty active+pending masks certify the global
            # fixpoint (ops.bucket invariant), so a reachable
            # negative cycle is impossible here.
            negative_cycle=False,
            converged=True,
            iterations=steps,
            edges_relaxed=examined,
            route="bucket",
            cost=bucket_cost,
        )
        if traj_bufs:
            self._attach_trajectory(res, *traj_bufs, dgraph)
        return res

    def _sssp_build_gs(self, ctx) -> KernelResult:
        dgraph, dist0, max_iter = ctx.dgraph, ctx.dist0, ctx.max_iter
        source = ctx.source
        v = dgraph.num_nodes
        bundle = dgraph.gs_layout(self.config.gs_block_size)
        dist0_gs = jnp.full(bundle["v_pad"], jnp.inf, self._dtype)
        if source is None:
            # Virtual source: 0 at every REAL vertex, +inf pads.
            dist0_gs = dist0_gs.at[: v].set(0.0)
        else:
            dist0_gs = dist0_gs.at[
                int(bundle["rank_host"][source])
            ].set(0.0)
        gs_kwargs = dict(
            vb=bundle["vb"], halo=bundle["halo"],
            max_outer=max_iter,
            inner_cap=self.config.gs_inner_cap,
            traj_cap=self._traj_cap(),
        )
        # Dirty-window extension (ISSUE 13): exact block
        # in-adjacency gating instead of the halo window —
        # value-exact either way, tighter skips; route "gs+dw".
        gs_in_adj = (
            bundle["in_adj"] if self._use_dw(dgraph, 1) else None
        )
        gs_route = "gs+dw" if gs_in_adj is not None else "gs"
        dist, rounds, improving, iters_blk, *traj_bufs = (
            _gs_kernel(
                dist0_gs, bundle["src_blk"], bundle["dstl_blk"],
                bundle["w_blk"], bundle["rank"], gs_in_adj,
                **gs_kwargs,
            )
        )
        iters = int(rounds)
        improving = bool(improving)
        res = KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=iters,
            edges_relaxed=_gs_examined_exact(
                iters_blk, bundle["real_edges_host"], 1,
                rounds=iters, inner_cap=self.config.gs_inner_cap,
            ),
            route=gs_route,
            cost=self._observe_cost(
                gs_route, _gs_kernel,
                (dist0_gs, bundle["src_blk"], bundle["dstl_blk"],
                 bundle["w_blk"], bundle["rank"], gs_in_adj),
                gs_kwargs,
                dgraph,
            ),
        )
        if traj_bufs:
            self._attach_trajectory(res, *traj_bufs, dgraph)
        return res

    def _sssp_build_frontier(self, ctx) -> KernelResult:
        dgraph, dist0 = ctx.dgraph, ctx.dist0
        max_iter, chunk = ctx.max_iter, ctx.chunk
        dist, iters, improving, ex_hi, ex_lo = _bf_frontier_kernel(
            dist0, dgraph.src, dgraph.dst, dgraph.weights,
            dgraph.indptr_dev(),
            max_iter=max_iter,
            capacity=self._frontier_capacity(dgraph),
            max_degree=dgraph.max_degree,
            num_real_edges=dgraph.num_real_edges,
            edge_chunk=chunk,
        )
        iters = int(iters)
        improving = bool(improving)
        return KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= dgraph.num_nodes,
            converged=not improving,
            iterations=iters,
            edges_relaxed=relax.examined_exact(ex_hi, ex_lo),
            route="frontier",
            cost=self._observe_cost(
                "frontier", _bf_frontier_kernel,
                (dist0, dgraph.src, dgraph.dst, dgraph.weights,
                 dgraph.indptr_dev()),
                dict(max_iter=max_iter,
                     capacity=self._frontier_capacity(dgraph),
                     max_degree=dgraph.max_degree,
                     num_real_edges=dgraph.num_real_edges,
                     edge_chunk=chunk),
                dgraph,
            ),
        )

    def _sssp_build_sweep(self, ctx) -> KernelResult:
        # Stays source-major even under fanout_layout="vertex_major":
        # a [V, 1] vm block wastes 127/128 lanes of the sorted segment
        # reduction and measures 2-3x SLOWER than the scatter sweep
        # (CPU, rmat16: 57 ms vm vs 20 ms sm) — the vm layout needs a
        # wide batch dimension to pay off.
        dgraph, dist0 = ctx.dgraph, ctx.dist0
        max_iter, chunk = ctx.max_iter, ctx.chunk
        traj_bufs = None
        cap = self._traj_cap()
        if cap is not None:
            dist, iters, improving, *traj_bufs = _bf_kernel_traj(
                dist0, dgraph.src, dgraph.dst, dgraph.weights,
                max_iter=max_iter, edge_chunk=chunk, traj_cap=cap,
            )
            sweep_fn, sweep_kwargs = _bf_kernel_traj, dict(
                max_iter=max_iter, edge_chunk=chunk, traj_cap=cap
            )
        else:
            dist, iters, improving = _bf_kernel(
                dist0, dgraph.src, dgraph.dst, dgraph.weights,
                max_iter=max_iter, edge_chunk=chunk,
            )
            sweep_fn, sweep_kwargs = _bf_kernel, dict(
                max_iter=max_iter, edge_chunk=chunk
            )
        iters = int(iters)
        improving = bool(improving)
        res = KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= dgraph.num_nodes,
            converged=not improving,
            iterations=iters,
            edges_relaxed=iters * dgraph.num_real_edges,
            route="sweep",
            cost=self._observe_cost(
                "sweep", sweep_fn,
                (dist0, dgraph.src, dgraph.dst, dgraph.weights),
                sweep_kwargs,
                dgraph,
            ),
        )
        if traj_bufs:
            self._attach_trajectory(res, *traj_bufs, dgraph)
        return res

    def _use_pred_extraction(self) -> bool:
        """Post-fixpoint tight-edge extraction (ops.pred) serves pred
        solves unless explicitly disabled or a prior auto attempt failed
        on this platform (degrade-don't-crash, like every auto route)."""
        return self.config.pred_extraction is not False and not getattr(
            self, "_pred_extract_disabled", False
        )

    def _pred_fallback(self, why: str):
        """Route a pred solve to the legacy argmin sweep — unless the
        user FORCED extraction, in which case fail loud (the "True
        forces" contract: extraction genuinely cannot represent this
        solve, silence would lie)."""
        if self.config.pred_extraction is True:
            raise RuntimeError(
                f"pred_extraction=True but {why}; the legacy argmin "
                "sweep (pred_extraction=False) handles this case"
            )
        import warnings

        warnings.warn(
            f"tight-edge predecessor extraction fell back to the legacy "
            f"argmin sweep: {why}",
            RuntimeWarning,
            stacklevel=3,
        )

    def bellman_ford_pred(self, dgraph: JaxDeviceGraph, source: int | None) -> KernelResult:
        if source is None:
            # Same contract as the numpy backend: the virtual-source pass
            # computes potentials, not paths — there is no tree to report.
            raise NotImplementedError(
                "virtual-source Bellman-Ford has no predecessor tree"
            )
        if self._use_pred_extraction():
            # Fast path (the round-7 tentpole): let the AUTO route family
            # (dia / bucket / gs / frontier / edge-sharded / sweep) run
            # the distance fixpoint, then extract the tree in one
            # tight-edge pass — instead of pinning the solve to the
            # argmin-tracking sweep below.
            res = self.bellman_ford(dgraph, source)
            if res.negative_cycle or not res.converged:
                return res  # no tree to extract (cpp backend contract)
            ok = False
            try:
                chunk = _edge_chunk_for(1, dgraph.src.shape[0])
                pred, ok = _extract_pred_kernel(
                    res.dist, jnp.asarray([source], jnp.int32),
                    dgraph.src, dgraph.dst, dgraph.weights,
                    edge_chunk=chunk,
                )
                ok = bool(ok)
            except Exception:
                self._auto_route_failed(
                    "_pred_extract_disabled",
                    "tight-edge pred extraction failed on this platform; "
                    "falling back to the argmin sweep for this backend "
                    "instance",
                    forced=self.config.pred_extraction is True,
                )
            if ok:
                res.pred = pred
                res.route = f"{res.route or 'sweep'}+pred"
                # One extraction pass examines every edge once — the
                # honest O(E) addend vs the sweep's iterations x E.
                res.edges_relaxed += dgraph.num_real_edges
                return res
            self._pred_fallback(
                "the tree check rejected the one-pass extraction "
                "(zero-weight tight cycle on a shortest path)"
            )
        return self._bellman_ford_pred_sweep(dgraph, source)

    def _bellman_ford_pred_sweep(
        self, dgraph: JaxDeviceGraph, source: int
    ) -> KernelResult:
        """Legacy argmin-tracking sweep (pred carried through every
        relaxation) — the explicit fallback route of the tight-edge
        extraction (pred_extraction=False, or a zero-weight tight cycle
        defeats the one-pass rule)."""
        v = dgraph.num_nodes
        dist0 = jnp.full(v, jnp.inf, self._dtype).at[source].set(0.0)
        max_iter = self.config.max_iterations or v
        chunk = _edge_chunk_for(1, dgraph.src.shape[0])
        dist, pred, iters, improving = _bf_pred_kernel(
            dist0, dgraph.src, dgraph.dst, dgraph.weights,
            max_iter=max_iter, edge_chunk=chunk,
        )
        iters = int(iters)
        improving = bool(improving)
        return KernelResult(
            dist=dist,
            pred=pred,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=iters,
            edges_relaxed=iters * dgraph.num_real_edges,
            route="pred-sweep",
            cost=self._observe_cost(
                "pred-sweep", _bf_pred_kernel,
                (dist0, dgraph.src, dgraph.dst, dgraph.weights),
                dict(max_iter=max_iter, edge_chunk=chunk),
                dgraph,
            ),
        )

    def multi_source_pred(self, dgraph: JaxDeviceGraph, sources: np.ndarray) -> KernelResult:
        """Fan-out with predecessor trees. Dispatches exactly like
        :meth:`multi_source` (auto route: vm-blocked / gs / dia / bucket
        / dense / sharded) and appends one post-fixpoint tight-edge
        extraction pass (ops.pred); the legacy argmin sweep
        (:meth:`_multi_source_pred_sweep`) remains as the explicit
        fallback (pred_extraction=False, or a zero-weight tight cycle
        rejected by the on-device tree check)."""
        if self._use_pred_extraction():
            res = self.multi_source(dgraph, sources)
            if not res.converged:
                return res  # the solver raises ConvergenceError; no tree
            sources_d = jnp.asarray(sources, jnp.int32)
            b = int(sources_d.shape[0])
            ok = False
            try:
                mesh = self._mesh()
                if mesh.devices.size > 1:
                    # Sharded extraction over the sources axis: rows are
                    # independent, edges replicated — the same layout as
                    # the sharded fan-out, zero collectives. Valid on
                    # 1-D and 2-D meshes alike (parallel.mesh).
                    from paralleljohnson_tpu.parallel import (
                        sharded_tight_pred,
                    )

                    ns = int(mesh.shape.get(
                        "sources", mesh.devices.size
                    ))
                    chunk = _edge_chunk_for(
                        -(-b // ns), dgraph.src.shape[0]
                    )
                    pred, ok = sharded_tight_pred(
                        mesh, res.dist, sources_d,
                        dgraph.src, dgraph.dst, dgraph.weights,
                        num_nodes=dgraph.num_nodes, edge_chunk=chunk,
                        telemetry=self._telemetry,
                    )
                else:
                    chunk = _edge_chunk_for(b, dgraph.src.shape[0])
                    pred, ok = _extract_pred_kernel(
                        res.dist, sources_d,
                        dgraph.src, dgraph.dst, dgraph.weights,
                        edge_chunk=chunk,
                    )
                    ok = bool(ok)
            except Exception:
                self._auto_route_failed(
                    "_pred_extract_disabled",
                    "tight-edge pred extraction failed on this platform; "
                    "falling back to the argmin sweep for this backend "
                    "instance",
                    forced=self.config.pred_extraction is True,
                )
            if ok:
                res.pred = pred
                res.route = f"{res.route or 'sweep'}+pred"
                # One extraction pass: E candidate examinations per row.
                res.edges_relaxed += b * dgraph.num_real_edges
                return res
            self._pred_fallback(
                "the tree check rejected the one-pass extraction "
                "(zero-weight tight cycle on a shortest path)"
            )
        return self._multi_source_pred_sweep(dgraph, sources)

    def _multi_source_pred_sweep(
        self, dgraph: JaxDeviceGraph, sources: np.ndarray
    ) -> KernelResult:
        """Legacy fan-out with argmin tracking through every sweep —
        the explicit fallback of the tight-edge extraction route;
        sources are sharded across the mesh exactly as in
        :meth:`multi_source`."""
        v = dgraph.num_nodes
        sources = jnp.asarray(sources, jnp.int32)
        max_iter = self.config.max_iterations or v
        mesh = self._mesh()
        if "edges" in mesh.axis_names:
            # Predecessor tracking needs the source-major argmin sweep,
            # which has no edges-sharded merge; run the pred fan-out on a
            # 1-D "sources" mesh over the SAME devices instead of
            # crashing (the 2-D accounting expects a sources-only vec).
            from paralleljohnson_tpu.parallel import make_mesh

            mesh = make_mesh((mesh.devices.size,))
        if mesh.devices.size > 1:
            from paralleljohnson_tpu.parallel import sharded_fanout

            chunk = _edge_chunk_for(
                -(-sources.shape[0] // mesh.devices.size),
                dgraph.src.shape[0],
            )
            try:
                dist, iters, improving, pred, row_sweeps = sharded_fanout(
                    mesh, sources, dgraph.src, dgraph.dst, dgraph.weights,
                    num_nodes=v, max_iter=max_iter, edge_chunk=chunk,
                    with_pred=True, with_row_sweeps=True,
                    fault_hook=self._shard_fault_hook(),
                    telemetry=self._telemetry,
                )
            except Exception as e:
                return self._sharded_fallback(
                    e, dgraph, sources, pred_sweep=True
                )
            cost = self._observe_unavailable(
                "pred-sweep-sharded",
                "sharded collective executables are not "
                "cost-instrumented", dgraph, batch=int(sources.shape[0]),
            )
        else:
            chunk = _edge_chunk_for(sources.shape[0], dgraph.src.shape[0])
            dist, pred, iters, improving = _fanout_pred_kernel(
                sources, dgraph.src, dgraph.dst, dgraph.weights,
                num_nodes=v, max_iter=max_iter, edge_chunk=chunk,
            )
            row_sweeps = int(iters) * int(sources.shape[0])
            cost = self._observe_cost(
                "pred-sweep", _fanout_pred_kernel,
                (sources, dgraph.src, dgraph.dst, dgraph.weights),
                dict(num_nodes=v, max_iter=max_iter, edge_chunk=chunk),
                dgraph, batch=int(sources.shape[0]),
            )
        iters = int(iters)
        return KernelResult(
            dist=dist,
            pred=pred,
            converged=not bool(improving),
            iterations=iters,
            edges_relaxed=int(row_sweeps) * dgraph.num_real_edges,
            route="pred-sweep",
            cost=cost,
        )

    def _pallas_mode(self) -> tuple[bool, bool]:
        """(use_pallas, interpret): "auto" = the measured winner, which on
        the real chip is the XLA blocked min-plus — the Pallas tile kernel
        measured 88.3 ms vs XLA's 77.3 ms at V=2048 (transpose-bound; see
        ops/pallas_kernels.py notes and BASELINE.md round-2 rows), so
        shipping it as the TPU default contradicted measure-then-decide.
        Pallas stays an explicit opt-in: use_pallas=True forces it
        anywhere (compiled on TPU, interpret-mode off-TPU for CI)."""
        flag = self.config.use_pallas
        on_tpu = jax.default_backend() == "tpu"
        if flag == "auto":
            return False, False
        return bool(flag), bool(flag) and not on_tpu

    def _mesh(self):
        """The fan-out mesh. mesh_shape=(n,) or None: 1-D over "sources";
        mesh_shape=(n_s, n_e): 2-D ("sources", "edges") — rows AND edge
        slices sharded simultaneously (sharded_fanout_2d)."""
        from paralleljohnson_tpu.parallel import make_mesh, make_mesh_2d

        cached = getattr(self, "_mesh_cache", None)
        if cached is None:
            if getattr(self, "_sharded_disabled", False):
                # A sharded solve already failed on this instance
                # (collective/tunnel failure) — stay on one device.
                cached = make_mesh((1,))
            else:
                shape = self.config.mesh_shape
                if shape is not None and len(shape) == 2:
                    cached = make_mesh_2d(shape)
                else:
                    cached = make_mesh(shape)
            self._mesh_cache = cached
        return cached

    def _sources_axis_size(self) -> int:
        """Devices along the "sources" axis (the axis [B, V] row blocks
        shard over; on a 2-D mesh rows replicate over "edges")."""
        mesh = self._mesh()
        return int(mesh.shape.get("sources", mesh.devices.size))

    def _resolve_layout(self) -> str:
        """``fanout_layout`` with ``"auto"`` resolved to the measured winner.

        Measured 2026-07-29 (see BASELINE.md "fan-out layout" rows):
        vertex-major's sorted segment reduction beats the source-major
        scatter-min ~3x on the CPU mesh (rmat14 B=64: 163 ms vs 542 ms;
        96x96 grid B=32: 284 ms vs 917 ms) and is the scatter-free
        formulation TPU Mosaic tiles well — "auto" = vertex_major.
        """
        layout = self.config.fanout_layout
        return "vertex_major" if layout == "auto" else layout

    def _planner_model(self):
        """The fitted ``CostModel`` priced dispatch consults, or None
        (pure declared-priority ladder — identical to the pre-registry
        dispatch). Enabled when ``config.planner`` is not False and a
        profile store is configured; the fit is cached against the
        store file's identity (the tuning module's mtime-keyed record
        cache), so a multi-batch fan-out re-reads the store at most
        once per solve."""
        if getattr(self.config, "planner", "auto") is False:
            return None
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir
        from paralleljohnson_tpu.observe.tuning import cached_records

        store_dir = resolve_profile_dir(self.config.profile_store)
        if store_dir is None:
            return None
        try:
            records = cached_records(store_dir)
        except Exception:  # noqa: BLE001 — a torn store must not crash dispatch
            return None
        if not records:
            return None
        cached = getattr(self, "_planner_model_cache", None)
        if cached is not None and cached[0] is records:
            return cached[1]
        from paralleljohnson_tpu.observe.store import CostModel

        model = CostModel.fit(records)
        self._planner_model_cache = (records, model)
        return model

    def _pipeline_depth(self, dgraph: JaxDeviceGraph) -> int:
        """The resolved fan-out pipeline depth for memory budgeting:
        explicit ``config.pipeline_depth`` wins, else the profile-tuned
        value for this (platform, shape bucket), else the hand-tuned
        double-buffering default of 2 (``observe.tuning``). The solver
        resolves the SAME function for its in-flight window, so the
        budget and the window can never disagree."""
        from paralleljohnson_tpu.observe.tuning import (
            DEFAULT_PIPELINE_DEPTH,
            resolve_param,
        )

        value, _ = resolve_param(
            "pipeline_depth", self.config.pipeline_depth,
            DEFAULT_PIPELINE_DEPTH,
            config=self.config, platform=jax.default_backend(),
            num_nodes=dgraph.num_nodes,
            num_edges=dgraph.num_real_edges,
            validate=lambda d: isinstance(d, int) and d >= 1,
        )
        return max(1, int(value))

    def _fw_tile(self, dgraph: JaxDeviceGraph) -> tuple[int, str]:
        """The resolved FW tile ``(value, source)``: an explicit
        ``config.fw_tile`` wins, else the profile-tuned value for this
        (platform, shape bucket), else the hand-tuned 512 default
        (``observe.tuning`` — ISSUE 14 auto-tuning). Cached per device
        graph so `_use_fw` and the build agree."""
        cached = dgraph._by_dst_cache.get("fw_tile_resolved")
        if cached is None:
            from paralleljohnson_tpu.observe.tuning import (
                DEFAULT_FW_TILE,
                resolve_param,
            )

            value, source = resolve_param(
                "fw_tile", self.config.fw_tile, DEFAULT_FW_TILE,
                config=self.config, platform=jax.default_backend(),
                num_nodes=dgraph.num_nodes,
                num_edges=dgraph.num_real_edges,
                validate=lambda t: (
                    isinstance(t, int) and t >= 128 and t % 128 == 0
                ),
            )
            cached = (int(value), source)
            dgraph._by_dst_cache["fw_tile_resolved"] = cached
        return cached

    def plan_preview(self, dgraph: JaxDeviceGraph, batch: int) -> dict:
        """The planner decision for a prospective fan-out at ``batch``
        width, WITHOUT building anything — what ``cli info --graph``
        prints (chosen plan + why-line + candidate table with explicit
        ``unpriced`` markers)."""
        from paralleljohnson_tpu import planner as _planner

        ctx = _FanoutCtx(
            backend=self,
            dgraph=dgraph,
            sources=jnp.zeros((max(1, batch),), jnp.int32),
            batch=max(1, int(batch)),
            max_iter=self.config.max_iterations or dgraph.num_nodes,
            mesh=self._mesh(),
            layout=self._resolve_layout(),
        )
        decision = _planner.select(
            FANOUT_PLANS, ctx,
            model=self._planner_model(),
            platform=jax.default_backend(),
            num_edges=dgraph.num_real_edges,
            batch=ctx.batch,
            config=self.config,
        )
        decision.params.update(ctx.params)
        decision.params.setdefault("fw_tile", self._fw_tile(dgraph)[0])
        return decision.as_dict()

    def multi_source(self, dgraph: JaxDeviceGraph, sources: np.ndarray) -> KernelResult:
        """Batched fan-out dispatch through the priced planner registry
        (ISSUE 14 tentpole; the pre-registry if/else ladder is gone):
        ``planner.select`` evaluates every plan's contract (the loud
        forced-flag NotImplementedErrors), qualification, and — when
        the profile store prices both the priority incumbent and a
        challenger — promotes the cheaper plan. With nothing priced the
        ranking IS the old ladder order, so dispatch (and therefore
        distances) is bit-for-bit what the ladder produced. The loop
        then walks the ranking degrade-don't-crash: an auto plan that
        raises warns once + disables itself for this backend instance
        and the next qualified plan serves the batch; a forced plan
        propagates. The decision (chosen plan, why-line, candidates
        with explicit ``unpriced`` markers, resolved tuned parameters)
        rides on ``KernelResult.plan`` into ``SolverStats.plan`` and
        the profile store's ``kind: "plan"`` records."""
        from paralleljohnson_tpu import planner as _planner

        sources = jnp.asarray(sources, jnp.int32)
        ctx = _FanoutCtx(
            backend=self,
            dgraph=dgraph,
            sources=sources,
            batch=int(sources.shape[0]),
            max_iter=self.config.max_iterations or dgraph.num_nodes,
            mesh=self._mesh(),
            layout=self._resolve_layout(),
        )
        decision = _planner.select(
            FANOUT_PLANS, ctx,
            model=self._planner_model(),
            platform=jax.default_backend(),
            num_edges=dgraph.num_real_edges,
            batch=ctx.batch,
            config=self.config,
        )
        self.last_plan_decision = decision
        for cand in decision.ranking:
            try:
                res = cand.plan.build(ctx)
            except Exception:
                if cand.plan.failure is None:
                    raise
                # Called from this active except block so a forced
                # flag's bare ``raise`` propagates the original error.
                cand.plan.failure(self, ctx)
                continue
            if res is None:
                continue  # required layout unavailable — degrade
            decision.params.update(ctx.params)
            res.plan = decision.as_dict(built=cand.plan.name)
            return res
        raise RuntimeError(
            "planner: every qualified fan-out plan failed (the sweep "
            "plans are unconditional — this is a bug)"
        )

    # -- fan-out plan builds (the registry's build hooks; each is the
    #    body its ladder branch used to hold, verbatim kernels) --------

    def _plan_build_dia(self, ctx) -> KernelResult:
        """DIA stencil fan-out: on a lattice labeling each sweep is K
        contiguous [B, V] roll+add+min passes — pure bandwidth, no
        per-row gather. Rows are independent, so a >1-device sources
        mesh composes with the replicated [K, V] diagonal weights and
        zero per-round collectives; an "edges" axis does not (the
        qualification gate)."""
        dgraph, sources, max_iter = ctx.dgraph, ctx.sources, ctx.max_iter
        v = dgraph.num_nodes
        lay = self.dia_bundle(dgraph)
        traj_bufs = None
        if ctx.mesh.devices.size > 1:
            from paralleljohnson_tpu.parallel import sharded_dia_fanout

            dist, iters, improving, examined = sharded_dia_fanout(
                ctx.mesh, sources, lay["w_diag"], num_nodes=v,
                offsets=lay["offsets"], max_iter=max_iter,
                num_entries=lay["num_entries"],
                fault_hook=self._shard_fault_hook(),
                telemetry=self._telemetry,
            )
            dia_route = "dia-sharded"
            dia_cost = self._observe_unavailable(
                "dia-sharded",
                "sharded collective executables are not "
                "cost-instrumented", dgraph,
                batch=ctx.batch,
            )
        else:
            from paralleljohnson_tpu.ops.dia import dia_fixpoint

            dist0_bv = jnp.full((sources.shape[0], v), jnp.inf,
                                self._dtype)
            dist0_bv = dist0_bv.at[
                jnp.arange(sources.shape[0]), sources
            ].set(0.0)
            cap = self._traj_cap()
            if cap is not None:
                dist, iters, improving, *traj_bufs = (
                    _dia_fixpoint_traj(
                        dist0_bv, lay["w_diag"],
                        offsets=lay["offsets"], max_iter=max_iter,
                        traj_cap=cap,
                    )
                )
                dia_fn, dia_kwargs = _dia_fixpoint_traj, dict(
                    offsets=lay["offsets"], max_iter=max_iter,
                    traj_cap=cap,
                )
            else:
                dist, iters, improving = dia_fixpoint(
                    dist0_bv, lay["w_diag"],
                    offsets=lay["offsets"], max_iter=max_iter,
                )
                dia_fn, dia_kwargs = dia_fixpoint, dict(
                    offsets=lay["offsets"], max_iter=max_iter,
                )
            examined = (
                int(iters) * lay["num_entries"] * int(sources.shape[0])
            )
            dia_route = "dia"
            dia_cost = self._observe_cost(
                "dia", dia_fn, (dist0_bv, lay["w_diag"]),
                dia_kwargs,
                dgraph, batch=ctx.batch,
            )
        res = KernelResult(
            dist=dist,
            converged=not bool(improving),
            iterations=int(iters),
            edges_relaxed=examined,
            route=dia_route,
            cost=dia_cost,
        )
        if traj_bufs:
            self._attach_trajectory(
                res, *traj_bufs, dgraph, batch=ctx.batch
            )
        return res

    def _plan_build_gs(self, ctx) -> KernelResult:
        """Blocked Gauss-Seidel fan-out: single-device blocked GS, or
        GS composed with source sharding (layout replicated, batch
        split, sequential block schedule per device, no per-round
        collectives)."""
        dgraph, sources, max_iter = ctx.dgraph, ctx.sources, ctx.max_iter
        bundle = dgraph.gs_layout(self.config.gs_block_size)
        traj_bufs = None
        if ctx.mesh.devices.size > 1:
            from paralleljohnson_tpu.parallel import sharded_gs_fanout

            dist, rounds, improving, examined = sharded_gs_fanout(
                ctx.mesh, sources, bundle["src_blk"],
                bundle["dstl_blk"], bundle["w_blk"],
                bundle["rank"], v_pad=bundle["v_pad"],
                vb=bundle["vb"], halo=bundle["halo"],
                max_outer=max_iter, inner_cap=self.config.gs_inner_cap,
                real_edges_host=bundle["real_edges_host"],
                fault_hook=self._shard_fault_hook(),
                telemetry=self._telemetry,
            )
            gs_route = "gs-sharded"
            gs_cost = self._observe_unavailable(
                "gs-sharded",
                "sharded collective executables are not "
                "cost-instrumented", dgraph,
                batch=ctx.batch,
            )
        else:
            gs_kwargs = dict(
                v_pad=bundle["v_pad"], vb=bundle["vb"],
                halo=bundle["halo"], max_outer=max_iter,
                inner_cap=self.config.gs_inner_cap,
                traj_cap=self._traj_cap(),
            )
            gs_in_adj = (
                bundle["in_adj"]
                if self._use_dw(dgraph, ctx.batch)
                else None
            )
            gs_route = "gs+dw" if gs_in_adj is not None else "gs"
            dist, rounds, improving, iters_blk, *traj_bufs = (
                _gs_fanout_kernel(
                    sources, bundle["src_blk"],
                    bundle["dstl_blk"], bundle["w_blk"],
                    bundle["rank"], gs_in_adj, **gs_kwargs,
                )
            )
            examined = _gs_examined_exact(
                iters_blk, bundle["real_edges_host"],
                ctx.batch,
                rounds=int(rounds),
                inner_cap=self.config.gs_inner_cap,
            )
            gs_cost = self._observe_cost(
                gs_route, _gs_fanout_kernel,
                (sources, bundle["src_blk"], bundle["dstl_blk"],
                 bundle["w_blk"], bundle["rank"], gs_in_adj),
                gs_kwargs,
                dgraph, batch=ctx.batch,
            )
        res = KernelResult(
            dist=dist,
            converged=not bool(improving),
            iterations=int(rounds),
            edges_relaxed=examined,
            route=gs_route,
            cost=gs_cost,
        )
        if traj_bufs:
            self._attach_trajectory(
                res, *traj_bufs, dgraph, batch=ctx.batch
            )
        return res

    def _plan_build_fw(self, ctx) -> KernelResult:
        """Blocked min-plus Floyd-Warshall (ops.fw, ROADMAP item 3):
        the B=V dense route — the O(V^3) closure wherever the exact MAC
        counters say it beats O(V^3 log V) squaring. Single-chip (the
        qualification gate); the tile is the ISSUE 14 auto-tuned
        parameter (explicit config > profile-tuned > 512)."""
        from paralleljohnson_tpu.ops import fw as fw_ops

        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        tile, tile_source = self._fw_tile(dgraph)
        tile = fw_ops.effective_tile(v, tile)
        ctx.params["fw_tile"] = tile
        ctx.params["fw_tile_source"] = tile_source
        vp = fw_ops.pad_tiles(v, tile)
        dist, neg = _fw_apsp_kernel(
            sources, dgraph.src, dgraph.dst, dgraph.weights,
            num_nodes=v, tile=tile, k_block=fw_ops.FW_KBLOCK,
        )
        neg = bool(neg)
        fw_route = "fw" if vp == tile else "fw-tile"
        return KernelResult(
            dist=dist,
            negative_cycle=neg,
            converged=not neg,
            iterations=vp // tile,
            # Exact tropical MACs of the closure (host int) —
            # ~squaring/log2(V) on the same padded scale.
            edges_relaxed=fw_ops.fw_mac_count(vp, tile),
            route=fw_route,
            cost=self._observe_analytic(
                fw_route,
                fw_ops.fw_analytic_cost(
                    vp, tile, jnp.dtype(self._dtype).itemsize
                ),
                dgraph, batch=ctx.batch,
            ),
        )

    def _plan_build_dw(self, ctx) -> KernelResult | None:
        """Dirty-window compacted fan-out (ISSUE 13): examined work
        tracks the measured collapsing frontier instead of rounds x E.
        Returns None when the layout is unavailable (degrade to the
        sweep chain)."""
        return self._dw_multi_source(ctx.dgraph, ctx.sources, ctx.max_iter)

    def _plan_build_sharded_2d(self, ctx) -> KernelResult:
        """2-D ("sources", "edges") mesh: rows AND edge slices sharded.
        A collective failure degrades to single-device inside
        ``_sharded_fallback`` (re-dispatching through the planner on a
        1-device mesh) — OOM re-raises for the solver's degrader."""
        from paralleljohnson_tpu.parallel import sharded_fanout_2d

        dgraph, sources, mesh = ctx.dgraph, ctx.sources, ctx.mesh
        v = dgraph.num_nodes
        ns = int(mesh.shape["sources"])
        ne = int(mesh.shape["edges"])
        chunk = _edge_chunk_for(
            -(-sources.shape[0] // ns),
            -(-dgraph.src.shape[0] // ne),
        )
        edges = (
            dgraph.by_dst() if ctx.layout == "vertex_major"
            else (dgraph.src, dgraph.dst, dgraph.weights)
        )
        try:
            dist, iters, improving, row_sweeps = sharded_fanout_2d(
                mesh, sources, *edges,
                num_nodes=v, max_iter=ctx.max_iter, edge_chunk=chunk,
                layout=ctx.layout, with_row_sweeps=True,
                fault_hook=self._shard_fault_hook(),
                telemetry=self._telemetry,
            )
        except Exception as e:
            return self._sharded_fallback(e, dgraph, sources)
        cost = self._observe_unavailable(
            "sharded-2d",
            "sharded collective executables are not "
            "cost-instrumented", dgraph, batch=ctx.batch,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, row_sweeps, "sharded-2d", cost,
            None, dgraph, ctx.batch,
        )

    def _plan_build_sharded_1d(self, ctx) -> KernelResult:
        """1-D sources mesh: fan-out rows sharded, CSR replicated."""
        from paralleljohnson_tpu.parallel import sharded_fanout

        dgraph, sources, mesh = ctx.dgraph, ctx.sources, ctx.mesh
        # Ceil: sharded_fanout pads the batch up to a mesh multiple, so
        # each shard solves ceil(B / n) rows — floor would undersize the
        # memory budget by up to 2x.
        chunk = _edge_chunk_for(
            -(-sources.shape[0] // mesh.devices.size),
            dgraph.src.shape[0],
        )
        edges = (
            dgraph.by_dst() if ctx.layout == "vertex_major"
            else (dgraph.src, dgraph.dst, dgraph.weights)
        )
        try:
            dist, iters, improving, row_sweeps = sharded_fanout(
                mesh, sources, *edges,
                num_nodes=dgraph.num_nodes, max_iter=ctx.max_iter,
                edge_chunk=chunk,
                layout=ctx.layout, with_row_sweeps=True,
                fault_hook=self._shard_fault_hook(),
                telemetry=self._telemetry,
            )
        except Exception as e:
            return self._sharded_fallback(e, dgraph, sources)
        cost = self._observe_unavailable(
            "sharded-1d",
            "sharded collective executables are not "
            "cost-instrumented", dgraph, batch=ctx.batch,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, row_sweeps, "sharded-1d", cost,
            None, dgraph, ctx.batch,
        )

    def _plan_build_dense(self, ctx) -> KernelResult:
        """Dense min-plus fan-out (B x V^2 per sweep — the regularity
        win on actually-dense small graphs)."""
        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        use_pallas, interpret = self._pallas_mode()
        dist, iters, improving = _dense_fanout_kernel(
            sources, dgraph.src, dgraph.dst, dgraph.weights,
            num_nodes=v, max_iter=ctx.max_iter,
            use_pallas=use_pallas, interpret=interpret,
        )
        # Honest work accounting for the dense regimes (BASELINE.md
        # convention note): candidate min-plus operations, NOT E edge
        # scans — per-iteration cost from the kernel's own regime
        # decision so the two can never drift.
        regime, work_per_iter = relax.dense_fanout_regime(v, ctx.batch)
        dense_route = (
            f"dense-{regime}" + ("-pallas" if use_pallas else "")
        )
        return KernelResult(
            dist=dist,
            converged=not bool(improving),
            iterations=int(iters),
            edges_relaxed=int(iters) * work_per_iter,
            route=dense_route,
            cost=self._observe_cost(
                dense_route, _dense_fanout_kernel,
                (sources, dgraph.src, dgraph.dst, dgraph.weights),
                dict(num_nodes=v, max_iter=ctx.max_iter,
                     use_pallas=use_pallas, interpret=interpret),
                dgraph, batch=ctx.batch,
            ),
        )

    def _plan_build_pallas_vm(self, ctx) -> KernelResult | None:
        """VMEM-resident Pallas fan-out sweep (explicit opt-in via
        use_pallas=True). The kernel's VMEM block specs are sized for
        B=128 (three [vb, B] f32 blocks must fit ~16 MB/core), so
        wider batches run as 128-wide slices; the last slice pads to a
        128 multiple with duplicate sources[0] rows (trimmed).
        Interpret-mode CI keeps tiny batches. None when the traffic
        model refused the layout (degrade to the XLA sweeps)."""
        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        use_pallas, interpret = self._pallas_mode()
        play = (
            dgraph.pallas_sweep_layout(_pallas_vb(v), PALLAS_EC)
            if use_pallas else None
        )
        if play is None:
            return None
        b_real = ctx.batch
        bk = PALLAS_BATCH_SLICE
        dists, iters_list, improving = [], [], False
        row_sweeps = 0
        for lo in range(0, b_real, bk):
            sl = sources[lo: lo + bk]
            b_sl = int(sl.shape[0])
            pad = 0 if interpret else (-b_sl) % bk
            if pad:
                sl = jnp.concatenate(
                    [sl, jnp.full(pad, sl[0], jnp.int32)]
                )
            d, it, imp = _fanout_pallas_kernel(
                sl, play["srcl_ck"], play["dstl_ck"],
                play["w_ck"], play["runend_ck"], play["sb_ids"],
                play["db_ids"], play["first_ck"], num_nodes=v,
                v_pad=play["v_pad"], vb=play["vb"],
                max_iter=ctx.max_iter, interpret=interpret,
            )
            dists.append(d[:b_sl])
            iters_list.append(int(it))
            improving = improving or bool(imp)
            row_sweeps += int(it) * b_sl
        dist = dists[0] if len(dists) == 1 else jnp.concatenate(dists)
        iters = max(iters_list)
        cost = self._observe_unavailable(
            "pallas-vm",
            "the sliced Pallas sweep has no single "
            "cost-instrumented executable", dgraph, batch=b_real,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, row_sweeps, "pallas-vm", cost,
            None, dgraph, ctx.batch,
        )

    def _vm_lay_chunk(self, ctx) -> int:
        # The layout's chunk size is derived from the batch size
        # ROUNDED UP to a power of two, so ragged final batches
        # (e.g. 104 of 128) reuse the canonical layout instead of
        # triggering an O(E) host rebuild + duplicate device upload.
        return _edge_chunk_for(
            1 << max(0, ctx.batch - 1).bit_length(),
            ctx.dgraph.src.shape[0],
        )

    def _plan_build_vm_blocked(self, ctx) -> KernelResult | None:
        """Dst-blocked vertex-major sweep for large graphs: per-chunk
        segment writes are [vb, B], not [V, B]. None when no host
        structure is available (degrade to the plain vm sweep)."""
        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        lay = dgraph.vm_blocked_layout(VM_BLOCK, self._vm_lay_chunk(ctx))
        if lay is None:
            return None
        cap = self._traj_cap()
        traj_bufs = None
        if cap is not None:
            dist, iters, improving, *traj_bufs = (
                _fanout_vm_blocked_kernel_traj(
                    sources, lay["src_ck"],
                    lay["dstl_ck"], lay["w_ck"],
                    lay["base_ck"], num_nodes=v,
                    v_pad=lay["v_pad"], vb=lay["vb"],
                    max_iter=ctx.max_iter, traj_cap=cap,
                )
            )
            vmb_fn = _fanout_vm_blocked_kernel_traj
            vmb_kwargs = dict(
                num_nodes=v, v_pad=lay["v_pad"],
                vb=lay["vb"], max_iter=ctx.max_iter,
                traj_cap=cap,
            )
        else:
            dist, iters, improving = (
                _fanout_vm_blocked_kernel(
                    sources, lay["src_ck"],
                    lay["dstl_ck"], lay["w_ck"],
                    lay["base_ck"], num_nodes=v,
                    v_pad=lay["v_pad"], vb=lay["vb"],
                    max_iter=ctx.max_iter,
                )
            )
            vmb_fn = _fanout_vm_blocked_kernel
            vmb_kwargs = dict(
                num_nodes=v, v_pad=lay["v_pad"],
                vb=lay["vb"], max_iter=ctx.max_iter,
            )
        iters = int(iters)
        cost = self._observe_cost(
            "vm-blocked", vmb_fn,
            (sources, lay["src_ck"], lay["dstl_ck"],
             lay["w_ck"], lay["base_ck"]),
            vmb_kwargs,
            dgraph, batch=ctx.batch,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, iters * ctx.batch, "vm-blocked",
            cost, traj_bufs, dgraph, ctx.batch,
        )

    def _plan_build_vm(self, ctx) -> KernelResult:
        """Plain vertex-major fan-out sweep: dst-sorted edges, sorted
        segment reduction (no scatter)."""
        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        chunk = _edge_chunk_for(ctx.batch, dgraph.src.shape[0])
        src_bd, dst_bd, w_bd = dgraph.by_dst()
        cap = self._traj_cap()
        traj_bufs = None
        if cap is not None:
            dist, iters, improving, *traj_bufs = (
                _fanout_vm_kernel_traj(
                    sources, src_bd, dst_bd, w_bd,
                    num_nodes=v, max_iter=ctx.max_iter,
                    edge_chunk=chunk, traj_cap=cap,
                )
            )
            vm_fn, vm_kwargs = _fanout_vm_kernel_traj, dict(
                num_nodes=v, max_iter=ctx.max_iter,
                edge_chunk=chunk, traj_cap=cap,
            )
        else:
            dist, iters, improving = _fanout_vm_kernel(
                sources, src_bd, dst_bd, w_bd,
                num_nodes=v, max_iter=ctx.max_iter,
                edge_chunk=chunk,
            )
            vm_fn, vm_kwargs = _fanout_vm_kernel, dict(
                num_nodes=v, max_iter=ctx.max_iter,
                edge_chunk=chunk,
            )
        iters = int(iters)
        cost = self._observe_cost(
            "vm", vm_fn,
            (sources, src_bd, dst_bd, w_bd),
            vm_kwargs,
            dgraph, batch=ctx.batch,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, iters * ctx.batch, "vm", cost,
            traj_bufs, dgraph, ctx.batch,
        )

    def _plan_build_sweep_sm(self, ctx) -> KernelResult:
        """Source-major fan-out sweep (flattened-id scatter-min)."""
        dgraph, sources = ctx.dgraph, ctx.sources
        v = dgraph.num_nodes
        chunk = _edge_chunk_for(ctx.batch, dgraph.src.shape[0])
        cap = self._traj_cap()
        traj_bufs = None
        if cap is not None:
            dist, iters, improving, *traj_bufs = _fanout_kernel_traj(
                sources, dgraph.src, dgraph.dst, dgraph.weights,
                num_nodes=v, max_iter=ctx.max_iter, edge_chunk=chunk,
                traj_cap=cap,
            )
            sm_fn, sm_kwargs = _fanout_kernel_traj, dict(
                num_nodes=v, max_iter=ctx.max_iter, edge_chunk=chunk,
                traj_cap=cap,
            )
        else:
            dist, iters, improving = _fanout_kernel(
                sources, dgraph.src, dgraph.dst, dgraph.weights,
                num_nodes=v, max_iter=ctx.max_iter, edge_chunk=chunk,
            )
            sm_fn, sm_kwargs = _fanout_kernel, dict(
                num_nodes=v, max_iter=ctx.max_iter, edge_chunk=chunk,
            )
        iters = int(iters)
        cost = self._observe_cost(
            "sweep-sm", sm_fn,
            (sources, dgraph.src, dgraph.dst, dgraph.weights),
            sm_kwargs,
            dgraph, batch=ctx.batch,
        )
        return self._sweep_kernel_result(
            dist, iters, improving, iters * ctx.batch, "sweep-sm",
            cost, traj_bufs, dgraph, ctx.batch,
        )

    def _sweep_kernel_result(
        self, dist, iters, improving, row_sweeps, route, cost,
        traj_bufs, dgraph, batch,
    ) -> KernelResult:
        """Shared result assembly of the sweep-family plans.
        Single-chip kernels iterate every row together, so iters x B is
        exact; the sharded paths pass the psum'd per-shard total."""
        res = KernelResult(
            dist=dist,
            converged=not bool(improving),
            iterations=int(iters),
            edges_relaxed=int(row_sweeps) * dgraph.num_real_edges,
            route=route,
            cost=cost,
        )
        if traj_bufs:
            self._attach_trajectory(res, *traj_bufs, dgraph, batch=batch)
        return res

    def _dw_multi_source(
        self, dgraph: JaxDeviceGraph, sources, max_iter: int
    ) -> KernelResult | None:
        """One dirty-window fan-out call (route ``vm-blocked+dw``).
        Returns None when the layout is unavailable so the caller falls
        through to the sweep chain."""
        v = dgraph.num_nodes
        b = int(sources.shape[0])
        vb = max(1, int(getattr(self.config, "dw_block", None) or
                        relax.DW_BLOCK))
        lay = dgraph.dw_layout(vb)
        if lay is None:
            return None
        capacity = self._dw_capacity(lay["nb"], lay["em"], b)
        src_bd, dst_bd, w_bd = dgraph.by_dst()
        chunk = _edge_chunk_for(b, dgraph.src.shape[0])
        cap = self._traj_cap()
        dw_args = (
            sources, lay["e_src"], lay["e_dst"], lay["w_tile"],
            lay["blk_of_v"], src_bd, dst_bd, w_bd,
        )
        dw_kwargs = dict(
            num_nodes=v, vb=lay["vb"], capacity=capacity,
            max_iter=max_iter, num_real_edges=dgraph.num_real_edges,
            edge_chunk=chunk, traj_cap=cap,
        )
        dist, rounds, improving, ex_hi, ex_lo, fulls, *traj_bufs = (
            _dw_fanout_kernel(*dw_args, **dw_kwargs)
        )
        rounds = int(rounds)
        # Exact counters (Python ints): the split device counter is in
        # edge SLOTS — scale by the batch width host-side, and form the
        # skipped complement against what the plain batched schedule
        # would have examined over the same rounds. The per-round-curve
        # resolution (trajectory) is int32 wrap-guarded below.
        examined_slots = relax.examined_exact(ex_hi, ex_lo)
        examined = examined_slots * b
        from paralleljohnson_tpu.utils.metrics import (
            warn_if_counter_wrapped,
        )

        warn_if_counter_wrapped(
            max(1, rounds - int(self._traj_cap() or rounds) + 1),
            capacity * lay["em"], where="dw",
        )
        res = KernelResult(
            dist=dist,
            converged=not bool(improving),
            iterations=rounds,
            edges_relaxed=examined,
            route="vm-blocked+dw",
            cost=self._observe_analytic(
                "vm-blocked+dw",
                relax.dw_analytic_cost(
                    examined_slots, b, jnp.dtype(self._dtype).itemsize
                ),
                dgraph, batch=b,
            ),
        )
        if traj_bufs:
            counts, resid, dirty_ct = traj_bufs
            self._attach_trajectory(res, counts, resid, dgraph, batch=b)
            # The dirty-block trajectory (the dw-specific curve the
            # convergence observatory records): per-round dirty-block
            # counts, downsampled the same way as the frontier curve.
            try:
                from paralleljohnson_tpu.observe.convergence import (
                    frontier_curve,
                )

                curve = np.asarray(dirty_ct)[: min(
                    rounds, dirty_ct.shape[0]
                )].astype(np.int64)
                if res.convergence is not None:
                    res.convergence["dirty_blocks_total"] = int(
                        curve.sum()
                    )
                    res.convergence["dirty_block_curve"] = frontier_curve(
                        np.stack([curve, curve, curve], axis=1)
                    )
                    res.convergence["num_blocks"] = int(lay["nb"])
                    res.convergence["full_sweep_rounds"] = int(fulls)
                    res.convergence["examined_edge_slots"] = int(
                        examined_slots
                    )
                    res.convergence["skipped_edge_slots"] = int(
                        rounds * dgraph.num_real_edges - examined_slots
                    )
            except Exception:  # noqa: BLE001 — observability never fatal
                pass
        return res

    def reweight(self, dgraph: JaxDeviceGraph, potentials) -> JaxDeviceGraph:
        h = jnp.asarray(potentials, self._dtype)
        return dataclasses.replace(
            dgraph,
            weights=_reweight_kernel(dgraph.weights, dgraph.src, dgraph.dst, h),
            # dataclasses.replace would carry the old cache over — the
            # dst-sorted / chunk weights must be re-derived from the new
            # weights. _struct_cache (weight-independent) is deliberately
            # carried: replace() keeps the same dict object.
            _by_dst_cache={},
            # The host CSR still holds PRE-reweight weights; consumers
            # that read them (GS layout) are gated off by this flag while
            # structure-only consumers keep working.
            host_weights_stale=True,
        )

    def batch_apsp(self, batch: dict[str, np.ndarray]) -> KernelResult:
        src = jnp.asarray(batch["src"], jnp.int32)
        dst = jnp.asarray(batch["dst"], jnp.int32)
        w = jnp.asarray(batch["weights"], self._dtype)
        v = int(batch["v_max"])
        g, e = src.shape
        # Bound the per-slab [chunk, V, E] relaxation intermediate.
        slab = max(1, (1 << 26) // max(v * e, 1))
        dist, iters, neg = _batch_johnson_kernel(
            src, dst, w, num_nodes=v, graph_chunk=slab
        )
        total_iters = int(jnp.sum(iters))
        cost = None
        if self.cost_capture.enabled:
            cost = self.cost_capture.capture(
                "batch-vmapped", _batch_johnson_kernel, (src, dst, w),
                dict(num_nodes=v, graph_chunk=slab),
                num_nodes=v, num_edges=e, batch=g,
            )
        return KernelResult(
            dist=dist,
            negative_cycle=bool(jnp.any(neg)),
            iterations=int(jnp.max(iters)),
            edges_relaxed=total_iters * e * v,
            route="batch-vmapped",
            cost=cost,
        )



# -- the fan-out planner registry (ISSUE 14 tentpole) ------------------------
#
# Each kernel family declares a ``planner.Plan``: contract (the loud
# forced-flag NotImplementedErrors), qualification (the same ``_use_*``
# predicates the old ladder consulted, now data instead of branch
# order), cost hook (the CostModel route tags), build, and failure
# policy (warn-once-and-disable on auto, propagate when forced). The
# declared priorities ARE the old ladder order, so with nothing priced
# dispatch is bit-for-bit the pre-registry behavior; adding a route is
# now one Plan entry, not another elif.


@dataclasses.dataclass
class _FanoutCtx:
    """One fan-out dispatch's context (what plan hooks see)."""

    backend: "JaxBackend"
    dgraph: JaxDeviceGraph
    sources: jax.Array
    batch: int
    max_iter: int
    mesh: object
    layout: str
    params: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _SsspCtx:
    """One B=1 (SSSP / virtual-source) dispatch's context."""

    backend: "JaxBackend"
    dgraph: JaxDeviceGraph
    source: int | None
    dist0: jax.Array
    max_iter: int
    chunk: int
    params: dict = dataclasses.field(default_factory=dict)


def _no_edges_axis(ctx) -> bool:
    return "edges" not in ctx.mesh.axis_names


def _single_device(ctx) -> bool:
    return _no_edges_axis(ctx) and ctx.mesh.devices.size == 1


def _contract_gs(ctx) -> None:
    if "edges" in ctx.mesh.axis_names and ctx.backend.config.gauss_seidel is True:
        # The GS layout is not edge-sharded: its sequential block
        # schedule needs the whole edge list per device. Sources-only
        # sharding composes; an edges axis does not.
        raise NotImplementedError(
            "gauss_seidel=True fan-out shards sources only; use a "
            "1-D mesh_shape=(n,) (or leave gauss_seidel='auto' to "
            "use the 2-D sharded sweep path on this mesh)"
        )


def _contract_dia(ctx) -> None:
    if "edges" in ctx.mesh.axis_names and ctx.backend.config.dia is True:
        # Same contract as gauss_seidel=True: the stencil needs
        # every diagonal per device, so an edges axis cannot carry
        # it — "True forces" must fail loud, not silently route a
        # gather kernel.
        raise NotImplementedError(
            "dia=True fan-out shards sources only; use a 1-D "
            "mesh_shape=(n,) (or leave dia='auto' to use the 2-D "
            "sharded sweep path on this mesh)"
        )


def _contract_fw(ctx) -> None:
    if ctx.backend.config.fw is True and (
        "edges" in ctx.mesh.axis_names or ctx.mesh.devices.size > 1
    ):
        # The FW closure holds the whole [Vp, Vp] matrix on one chip;
        # "True forces" must fail rather than silently route a
        # sharded sweep.
        raise NotImplementedError(
            "fw=True is a single-chip dense route; use mesh_shape=(1,)"
        )


def _qual_dia(ctx):
    if not _no_edges_axis(ctx):
        return False, "mesh has an edges axis (stencil needs every diagonal per device)"
    if ctx.backend._use_dia(ctx.dgraph):
        return True, "diagonal labeling qualifies (gather-free stencil)"
    return False, "dia gate declined (flag / platform / labeling)"


def _qual_gs(ctx):
    if not _no_edges_axis(ctx):
        return False, "mesh has an edges axis (GS needs the whole edge list per device)"
    if ctx.backend._use_gs(ctx.dgraph):
        return True, "low-degree family on the GS platform gate"
    return False, "gs gate declined (flag / platform / degree family)"


def _qual_fw(ctx):
    if not _single_device(ctx):
        return False, "fw is a single-chip dense route"
    if ctx.backend._use_fw(ctx.dgraph, ctx.batch):
        return True, "squaring regime + density gate + exact-MAC win over squaring"
    return False, "fw gate declined (regime / density / V threshold / MAC count)"


def _qual_dw(ctx):
    be = ctx.backend
    if not _single_device(ctx):
        return False, "dirty-window is a single-device route"
    if be._use_dense(ctx.dgraph):
        return False, "dense regime (dw targets the sparse batched sweep)"
    if be._use_dw(ctx.dgraph, ctx.batch):
        if be.config.dirty_window is True:
            return True, "dirty_window=True forces (no evidence required)"
        return True, be._dw_decision(ctx.dgraph, ctx.batch).get(
            "reason", "trajectory evidence clears the dw thresholds"
        )
    flag = getattr(be.config, "dirty_window", "auto")
    if flag is False or getattr(be, "_dw_disabled", False):
        return False, "dirty_window disabled"
    if ctx.dgraph.num_nodes == 0:
        return False, "empty graph"
    if ctx.dgraph.num_real_edges >= relax.FRONTIER_ADDEND_MAX:
        return False, "split examined counter's full-sweep addend would wrap"
    return False, be._dw_decision(ctx.dgraph, ctx.batch).get(
        "reason", "no trajectory evidence"
    )


def _qual_sharded_2d(ctx):
    if "edges" in ctx.mesh.axis_names:
        return True, "2-D (sources, edges) mesh configured"
    return False, "no edges mesh axis"


def _qual_sharded_1d(ctx):
    if _no_edges_axis(ctx) and ctx.mesh.devices.size > 1:
        return True, f"{ctx.mesh.devices.size}-device sources mesh"
    return False, "single device (or edges axis owns the mesh)"


def _qual_dense(ctx):
    if not _single_device(ctx):
        return False, "dense min-plus is single-chip"
    if ctx.backend._use_dense(ctx.dgraph):
        return True, "graph clears the dense density + size gates"
    return False, "not dense enough (or above dense_threshold)"


def _qual_pallas_vm(ctx):
    if not _single_device(ctx) or ctx.backend._use_dense(ctx.dgraph):
        return False, "pallas sweep serves the single-chip sparse fan-out only"
    if ctx.layout != "vertex_major":
        return False, "pallas sweep needs the vertex-major layout"
    if ctx.backend._pallas_mode()[0]:
        return True, "use_pallas=True opt-in"
    return False, "use_pallas is not forced (XLA routes are the measured winner)"


def _qual_vm_blocked(ctx):
    if not _single_device(ctx) or ctx.backend._use_dense(ctx.dgraph):
        return False, "blocked vm serves the single-chip sparse fan-out only"
    if ctx.layout != "vertex_major":
        return False, "source-major layout configured"
    if ctx.dgraph.num_nodes <= VM_BLOCK:
        return False, f"V <= {VM_BLOCK} (plain full-V segments are already this small)"
    if getattr(ctx.backend, "_vmb_disabled", False):
        return False, "disabled after a prior failure on this backend instance"
    return True, f"V > {VM_BLOCK}: [vb, B] segment writes beat [V, B]"


def _qual_vm(ctx):
    if not _single_device(ctx) or ctx.backend._use_dense(ctx.dgraph):
        return False, "plain vm serves the single-chip sparse fan-out only"
    if ctx.layout != "vertex_major":
        return False, "source-major layout configured"
    return True, "vertex-major sorted segment reduction (the measured default)"


def _qual_sweep_sm(ctx):
    if not _single_device(ctx) or ctx.backend._use_dense(ctx.dgraph):
        return False, "source-major sweep serves the single-chip sparse fan-out only"
    if ctx.layout != "vertex_major":
        return True, "source-major layout configured"
    if ctx.backend.config.fanout_layout == "auto":
        # Under layout "auto" the scatter sweep stays QUALIFIED behind
        # the vertex-major plans: priority preserves the measured
        # default (vm wins ~3x on the CPU mesh), but a calibration
        # that prices the scatter sweep cheaper for a shape can
        # promote it — the layout choice is a planner decision, not a
        # hard gate (ISSUE 14).
        return True, (
            "layout 'auto': behind vm by priority; promotable when "
            "priced cheaper"
        )
    return False, "vertex-major layout forced by config"


def _fail_dia(be, ctx) -> None:
    be._auto_route_failed(
        "_dia_disabled",
        "dia stencil fan-out failed on this platform; "
        "falling back to the gather routes for this "
        "backend instance",
        forced=be.config.dia is True,
    )


def _fail_gs(be, ctx) -> None:
    be._gs_auto_failed(ctx.dgraph)  # re-raises when forced


def _fail_fw(be, ctx) -> None:
    be._auto_route_failed(
        "_fw_disabled",
        "blocked Floyd-Warshall route failed on this "
        "platform; falling back to the dense/sparse routes "
        "for this backend instance",
        forced=be.config.fw is True,
    )


def _fail_dw(be, ctx) -> None:
    be._auto_route_failed(
        "_dw_disabled",
        "dirty-window fan-out failed on this platform; "
        "falling back to the sweep routes for this backend "
        "instance",
        forced=be.config.dirty_window is True,
    )


def _fail_vm_blocked(be, ctx) -> None:
    be._auto_route_failed(
        "_vmb_disabled",
        "dst-blocked vm fan-out failed on this "
        "platform; falling back to the plain vm "
        "sweep for this backend instance",
        forced=False,
    )


FANOUT_PLANS = [
    planner.Plan(
        name="dia", entry="fanout", priority=10,
        qualify=_qual_dia, contract=_contract_dia,
        build=lambda ctx: ctx.backend._plan_build_dia(ctx),
        price_routes=("dia",),
        forced=lambda cfg: cfg.dia is True,
        failure=_fail_dia,
        force_overrides={"dia": True},
    ),
    planner.Plan(
        name="gs", entry="fanout", priority=20,
        qualify=_qual_gs, contract=_contract_gs,
        build=lambda ctx: ctx.backend._plan_build_gs(ctx),
        price_routes=("gs", "gs+dw"),
        forced=lambda cfg: cfg.gauss_seidel is True,
        failure=_fail_gs,
        force_overrides={"gauss_seidel": True},
    ),
    planner.Plan(
        name="fw", entry="fanout", priority=30,
        qualify=_qual_fw, contract=_contract_fw,
        build=lambda ctx: ctx.backend._plan_build_fw(ctx),
        price_routes=("fw", "fw-tile"),
        forced=lambda cfg: cfg.fw is True,
        failure=_fail_fw,
        force_overrides={"fw": True, "mesh_shape": (1,)},
        tunables=("fw_tile",),
    ),
    planner.Plan(
        name="vm-blocked+dw", entry="fanout", priority=40,
        qualify=_qual_dw,
        build=lambda ctx: ctx.backend._plan_build_dw(ctx),
        price_routes=("vm-blocked+dw",),
        forced=lambda cfg: cfg.dirty_window is True,
        failure=_fail_dw,
        force_overrides={"dirty_window": True},
    ),
    planner.Plan(
        name="sharded-2d", entry="fanout", priority=50,
        qualify=_qual_sharded_2d,
        build=lambda ctx: ctx.backend._plan_build_sharded_2d(ctx),
    ),
    planner.Plan(
        name="sharded-1d", entry="fanout", priority=60,
        qualify=_qual_sharded_1d,
        build=lambda ctx: ctx.backend._plan_build_sharded_1d(ctx),
    ),
    planner.Plan(
        name="dense", entry="fanout", priority=70,
        qualify=_qual_dense,
        build=lambda ctx: ctx.backend._plan_build_dense(ctx),
        price_routes=("dense-squaring", "dense-iterate"),
        # fw=False keeps the higher-priority FW plan out of the way so
        # "force dense" measures the iterate/squaring kernel itself.
        force_overrides={"fw": False, "mesh_shape": (1,)},
    ),
    planner.Plan(
        name="pallas-vm", entry="fanout", priority=80,
        qualify=_qual_pallas_vm,
        build=lambda ctx: ctx.backend._plan_build_pallas_vm(ctx),
        price_routes=("pallas-vm",),
        force_overrides={"use_pallas": True, "fanout_layout": "vertex_major"},
    ),
    planner.Plan(
        name="vm-blocked", entry="fanout", priority=90,
        qualify=_qual_vm_blocked,
        build=lambda ctx: ctx.backend._plan_build_vm_blocked(ctx),
        price_routes=("vm-blocked",),
        failure=_fail_vm_blocked,
        force_overrides={"fanout_layout": "vertex_major",
                         "dirty_window": False},
    ),
    planner.Plan(
        name="vm", entry="fanout", priority=100,
        qualify=_qual_vm,
        build=lambda ctx: ctx.backend._plan_build_vm(ctx),
        price_routes=("vm",),
        force_overrides={"fanout_layout": "vertex_major",
                         "dirty_window": False},
    ),
    planner.Plan(
        name="sweep-sm", entry="fanout", priority=110,
        qualify=_qual_sweep_sm,
        build=lambda ctx: ctx.backend._plan_build_sweep_sm(ctx),
        price_routes=("sweep-sm",),
        force_overrides={"fanout_layout": "source_major",
                         "dirty_window": False},
    ),
]

def _qual_sssp_bucket(ctx) -> tuple[bool, str]:
    if not ctx.backend._use_bucket(ctx.dgraph):
        return (False, "bucket gate declined")
    if ctx.source is None and ctx.backend.config.bucket is not True:
        # "auto" skips the virtual-source pass: dist0 = all-zeros
        # starts every vertex active, so bucketing degrades to full
        # sweeps — GS handles that pass in ~direction-change rounds.
        # A forced bucket=True runs it anyway (overflow fallback).
        return (False, "virtual-source pass (every vertex starts active)")
    return (True, "irregular low-degree family where DIA declines")


def _fail_sssp_edge_sharded(be, ctx) -> None:
    # Degrade-don't-crash like the fan-out's sharded branches: a
    # collective failure disables edge sharding for this backend
    # instance and the next qualified plan serves the solve. OOM
    # re-raises (the solver's retry path owns that recovery).
    import sys

    exc = sys.exc_info()[1]
    if exc is not None and resilience.is_oom_error(exc):
        raise
    be._auto_route_failed(
        "_edge_shard_disabled",
        "edge-sharded Bellman-Ford failed (collective/tunnel "
        "failure); falling back to single-chip sweeps for "
        "this backend instance",
        forced=be.config.edge_shard is True,
    )


def _fail_sssp_dia(be, ctx) -> None:
    be._auto_route_failed(
        "_dia_disabled",
        "dia stencil route failed on this platform; falling "
        "back to the gather routes for this backend instance",
        forced=be.config.dia is True,
    )


def _fail_sssp_bucket(be, ctx) -> None:
    be._auto_route_failed(
        "_bucket_disabled",
        "bucketed delta-stepping route failed on this "
        "platform; falling back to the gather routes for "
        "this backend instance",
        forced=be.config.bucket is True,
    )


def _fail_sssp_gs(be, ctx) -> None:
    be._gs_auto_failed(ctx.dgraph)  # re-raises when forced


# The B=1 (SSSP) family, declared for the same registry as the fan-out
# plans so pricing, `cli info`, and the bench harness speak one plan
# vocabulary. ``bellman_ford`` dispatches through ``select()`` over
# this list (ISSUE 17 satellite — the ROADMAP item 6 leftover): the
# qualifications wrap the SAME ``_use_*`` gates the old ladder
# consulted in the same priority order, so an unpriced ranking is the
# ladder, bit-for-bit.
SSSP_PLANS = [
    planner.Plan(
        name="edge-sharded", entry="sssp", priority=10,
        qualify=lambda ctx: (
            (True, "edge list sharded over the mesh")
            if ctx.backend._use_edge_shard(ctx.dgraph)
            else (False, "single device or frontier-family graph")
        ),
        build=lambda ctx: ctx.backend._sssp_build_edge_sharded(ctx),
        failure=_fail_sssp_edge_sharded,
        forced=lambda cfg: cfg.edge_shard is True,
        force_overrides={"edge_shard": True},
    ),
    planner.Plan(
        name="dia", entry="sssp", priority=20,
        qualify=lambda ctx: (
            (True, "diagonal labeling qualifies")
            if ctx.backend._use_dia(ctx.dgraph)
            else (False, "dia gate declined")
        ),
        build=lambda ctx: ctx.backend._sssp_build_dia(ctx),
        failure=_fail_sssp_dia,
        price_routes=("dia",),
        forced=lambda cfg: cfg.dia is True,
        force_overrides={"dia": True},
    ),
    planner.Plan(
        name="bucket", entry="sssp", priority=30,
        qualify=_qual_sssp_bucket,
        build=lambda ctx: ctx.backend._sssp_build_bucket(ctx),
        failure=_fail_sssp_bucket,
        price_routes=("bucket", "bucket+sweep"),
        forced=lambda cfg: cfg.bucket is True,
        force_overrides={"bucket": True},
        tunables=("delta",),
    ),
    planner.Plan(
        name="gs", entry="sssp", priority=40,
        qualify=lambda ctx: (
            (True, "low-degree family on the GS platform gate")
            if ctx.backend._use_gs(ctx.dgraph)
            else (False, "gs gate declined")
        ),
        build=lambda ctx: ctx.backend._sssp_build_gs(ctx),
        failure=_fail_sssp_gs,
        price_routes=("gs", "gs+dw"),
        forced=lambda cfg: cfg.gauss_seidel is True,
        force_overrides={"gauss_seidel": True},
    ),
    planner.Plan(
        name="frontier", entry="sssp", priority=50,
        qualify=lambda ctx: (
            (True, "low-degree family (compacted frontier)")
            if ctx.backend._use_frontier(ctx.dgraph)
            else (False, "frontier gate declined")
        ),
        build=lambda ctx: ctx.backend._sssp_build_frontier(ctx),
        price_routes=("frontier",),
        forced=lambda cfg: cfg.frontier is True,
        force_overrides={"frontier": True},
    ),
    planner.Plan(
        name="sweep", entry="sssp", priority=60,
        qualify=lambda ctx: (True, "unconditional full-sweep fallback"),
        build=lambda ctx: ctx.backend._sssp_build_sweep(ctx),
        price_routes=("sweep",),
    ),
]


register_backend("jax", JaxBackend)
