"""Backend plugin registry (SURVEY.md §2 #4).

Importing this package registers the built-in backends:
``numpy`` (scipy-backed reference), ``jax`` (TPU/XLA), and — when the
native library is buildable — ``cpp`` (C++/OpenMP).
"""

from paralleljohnson_tpu.backends.base import (
    Backend,
    KernelResult,
    available_backends,
    get_backend,
    register_backend,
)
import paralleljohnson_tpu.backends.numpy_backend  # noqa: F401  (registers)
import paralleljohnson_tpu.backends.jax_backend  # noqa: F401  (registers)

try:  # native backend is optional: needs a working g++ at first use
    import paralleljohnson_tpu.backends.cpp_backend  # noqa: F401
except Exception:  # pragma: no cover
    pass

__all__ = [
    "Backend",
    "KernelResult",
    "available_backends",
    "get_backend",
    "register_backend",
]
