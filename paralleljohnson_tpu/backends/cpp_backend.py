"""``CppBackend`` — the rebuilt native CPU/OpenMP execution engine.

The reference's attested native component is its C/C++ + OpenMP path
(SURVEY.md §2 #6, BASELINE.json:5); this backend is its equivalent in the
rebuild and the comparison baseline for the TPU backend's >=10x target:
Bellman-Ford as a lock-free atomic-min edge sweep (parallel over edges) and
the fan-out as heap Dijkstra (parallel over sources), implemented in
``native/pj_native.cpp`` and called through ctypes (no pybind11 in this
environment).
"""

from __future__ import annotations

import ctypes

import numpy as np

from paralleljohnson_tpu.backends.base import Backend, KernelResult, register_backend
from paralleljohnson_tpu.graphs import CSRGraph
from paralleljohnson_tpu.native import load_library

# Build/load at import: backends/__init__ wraps this import in try/except,
# so an environment without a working g++ simply lacks the "cpp" backend.
_LIB = load_library()


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class CppBackend(Backend):
    """Native C++/OpenMP backend (host shared-memory parallelism)."""

    name = "cpp"

    @property
    def _dtype(self):
        return np.float64 if self.config.precision == "f64" else np.float32

    @property
    def _suffix(self) -> str:
        return "f64" if self.config.precision == "f64" else "f32"

    @property
    def _ctype(self):
        return ctypes.c_double if self.config.precision == "f64" else ctypes.c_float

    def upload(self, graph: CSRGraph) -> CSRGraph:
        # Host backend: "upload" = ensure contiguous arrays of the configured
        # dtype (and materialize the COO src column once, outside the timed
        # kernels). Padding is unnecessary on CPU — use real edges only.
        g = graph.astype(self._dtype)
        g.src  # noqa: B018 — warm the cached COO source column
        return g

    def download_graph(self, dgraph: CSRGraph) -> CSRGraph:
        return dgraph

    def num_threads(self) -> int:
        return int(_LIB.pj_num_threads())

    def bellman_ford(self, dgraph: CSRGraph, source: int | None) -> KernelResult:
        g = dgraph
        v, e = g.num_nodes, g.num_real_edges
        if source is None:
            dist = np.zeros(v, self._dtype)
        else:
            dist = np.full(v, np.inf, self._dtype)
            dist[source] = 0.0
        max_iter = self.config.max_iterations or v
        iters = ctypes.c_int32(0)
        relaxed = ctypes.c_int64(0)
        fn = getattr(_LIB, f"pj_bellman_ford_{self._suffix}")
        improving = fn(
            np.int32(v),
            np.int64(e),
            _ptr(g.src[:e], ctypes.c_int32),
            _ptr(g.indices[:e], ctypes.c_int32),
            _ptr(g.weights[:e], self._ctype),
            _ptr(dist, self._ctype),
            np.int32(max_iter),
            ctypes.byref(iters),
            ctypes.byref(relaxed),
        )
        improving = bool(improving)
        return KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=int(iters.value),
            edges_relaxed=int(relaxed.value),
        )

    def multi_source(self, dgraph: CSRGraph, sources: np.ndarray) -> KernelResult:
        return self._multi_source(dgraph, sources, with_pred=False)

    def multi_source_pred(self, dgraph: CSRGraph, sources: np.ndarray) -> KernelResult:
        return self._multi_source(dgraph, sources, with_pred=True)

    def _multi_source(
        self, dgraph: CSRGraph, sources: np.ndarray, *, with_pred: bool
    ) -> KernelResult:
        g = dgraph
        if g.has_negative_weights:
            raise ValueError("multi_source requires non-negative weights")
        v = g.num_nodes
        srcs = np.ascontiguousarray(sources, np.int32)
        b = len(srcs)
        dist = np.empty((b, v), self._dtype)
        relaxed = ctypes.c_int64(0)
        if with_pred:
            pred = np.empty((b, v), np.int32)
            fn = getattr(_LIB, f"pj_dijkstra_fanout_pred_{self._suffix}")
            fn(
                np.int32(v),
                _ptr(g.indptr, ctypes.c_int32),
                _ptr(g.indices, ctypes.c_int32),
                _ptr(g.weights, self._ctype),
                np.int32(b),
                _ptr(srcs, ctypes.c_int32),
                _ptr(dist, self._ctype),
                _ptr(pred, ctypes.c_int32),
                ctypes.byref(relaxed),
            )
            return KernelResult(dist=dist, pred=pred,
                                edges_relaxed=int(relaxed.value))
        fn = getattr(_LIB, f"pj_dijkstra_fanout_{self._suffix}")
        fn(
            np.int32(v),
            _ptr(g.indptr, ctypes.c_int32),
            _ptr(g.indices, ctypes.c_int32),
            _ptr(g.weights, self._ctype),
            np.int32(b),
            _ptr(srcs, ctypes.c_int32),
            _ptr(dist, self._ctype),
            ctypes.byref(relaxed),
        )
        return KernelResult(dist=dist, edges_relaxed=int(relaxed.value))

    def batch_apsp(self, batch: dict[str, np.ndarray]) -> KernelResult:
        """Native many-small-graphs Johnson (BASELINE.json:11): OpenMP
        parallel over graphs, serial Johnson per graph (the shared-memory
        thread-pool decomposition — graphs are independent)."""
        src = np.ascontiguousarray(batch["src"], np.int32)
        dst = np.ascontiguousarray(batch["dst"], np.int32)
        w = np.ascontiguousarray(batch["weights"], self._dtype)
        sizes = np.ascontiguousarray(batch["num_nodes"], np.int32)
        g, e_pad = src.shape
        v_max = int(batch["v_max"])
        dist = np.empty((g, v_max, v_max), self._dtype)
        neg = np.zeros(g, np.int32)
        fn = getattr(_LIB, f"pj_batch_johnson_{self._suffix}")
        relaxed = fn(
            np.int32(g),
            np.int64(e_pad),
            _ptr(sizes, ctypes.c_int32),
            np.int32(v_max),
            _ptr(src, ctypes.c_int32),
            _ptr(dst, ctypes.c_int32),
            _ptr(w, self._ctype),
            _ptr(dist, self._ctype),
            _ptr(neg, ctypes.c_int32),
        )
        return KernelResult(
            dist=dist,
            negative_cycle=bool(neg.any()),
            edges_relaxed=int(relaxed),
        )

    def bellman_ford_pred(self, dgraph: CSRGraph, source: int | None) -> KernelResult:
        """SSSP with the shortest-path tree: the converged Bellman-Ford
        distances plus a native tight-edge BFS extraction pass."""
        if source is None:
            raise NotImplementedError(
                "virtual-source Bellman-Ford has no predecessor tree"
            )
        res = self.bellman_ford(dgraph, source)
        if res.negative_cycle or not res.converged:
            return res
        g = dgraph
        pred = np.empty(g.num_nodes, np.int32)
        dist = np.ascontiguousarray(res.dist, self._dtype)
        fn = getattr(_LIB, f"pj_extract_predecessors_{self._suffix}")
        fn(
            np.int32(g.num_nodes),
            _ptr(g.indptr, ctypes.c_int32),
            _ptr(g.indices, ctypes.c_int32),
            _ptr(g.weights, self._ctype),
            _ptr(dist, self._ctype),
            np.int32(source),
            _ptr(pred, ctypes.c_int32),
        )
        res.pred = pred
        return res


register_backend("cpp", CppBackend)
