"""Trivially-correct host backend: vectorized-numpy Bellman-Ford + scipy
Dijkstra fan-out.

This pins the plugin boundary before any performance work (SURVEY.md §7
step 2) and doubles as the equivalence anchor: every other backend must
match it (which itself is tested against scipy/networkx oracles).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph

from paralleljohnson_tpu.backends.base import Backend, KernelResult, register_backend
from paralleljohnson_tpu.graphs import CSRGraph


class NumpyBackend(Backend):
    """Host-memory reference backend (no device upload)."""

    name = "numpy"

    def upload(self, graph: CSRGraph) -> CSRGraph:
        return graph.astype(self.config.np_dtype)

    def download_graph(self, dgraph: CSRGraph) -> CSRGraph:
        return dgraph

    def bellman_ford(self, dgraph: CSRGraph, source: int | None) -> KernelResult:
        """Vectorized Bellman-Ford sweep with np.minimum.at scatter-min.

        A full-sweep (Bellman-Ford-Moore) loop: each sweep relaxes every
        edge; fixpoint in <= V-1 sweeps unless a negative cycle is
        reachable, detected by a still-improving V-th sweep.
        """
        g = dgraph
        v, e = g.num_nodes, g.num_edges
        dist = np.zeros(v, g.dtype) if source is None else np.full(v, np.inf, g.dtype)
        if source is not None:
            dist[source] = 0.0
        src, dst, w = g.src, g.indices, g.weights
        max_iter = self.config.max_iterations or v
        iterations = 0
        improving = False
        for _ in range(max_iter + 1):
            cand = dist[src] + w
            new = dist.copy()
            np.minimum.at(new, dst, cand)
            iterations += 1
            if np.array_equal(new, dist):
                improving = False
                break
            dist = new
            improving = True
        # Still improving after the V-sweep Bellman-Ford bound proves a
        # negative cycle; with a user cap below V it only proves non-
        # convergence (the solver raises ConvergenceError, not a cycle).
        return KernelResult(
            dist=dist,
            negative_cycle=improving and max_iter >= v,
            converged=not improving,
            iterations=iterations,
            edges_relaxed=iterations * e,
        )

    def multi_source(self, dgraph: CSRGraph, sources: np.ndarray) -> KernelResult:
        return self._multi_source(dgraph, sources, with_pred=False)

    def multi_source_pred(self, dgraph: CSRGraph, sources: np.ndarray) -> KernelResult:
        return self._multi_source(dgraph, sources, with_pred=True)

    def _multi_source(
        self, dgraph: CSRGraph, sources: np.ndarray, *, with_pred: bool
    ) -> KernelResult:
        g = dgraph
        if g.has_negative_weights:
            raise ValueError("multi_source requires non-negative weights")
        mat = sp.csr_matrix(
            (g.weights, g.indices, g.indptr), shape=(g.num_nodes, g.num_nodes)
        )
        sources = np.asarray(sources, np.int64)
        # Explicitly-stored zeros in a sparse csgraph input are true
        # zero-weight edges (reweighted tree edges are exactly 0).
        pred = None
        if with_pred:
            dist, pred = csgraph.dijkstra(
                mat, directed=True, indices=sources, return_predecessors=True
            )
            # scipy's "no predecessor" sentinel is -9999; normalize to -1.
            pred = np.where(pred < 0, -1, pred).astype(np.int32)
        else:
            dist = csgraph.dijkstra(mat, directed=True, indices=sources)
        # Heap Dijkstra scans each settled vertex's out-edges once: <= E per
        # source (the conventional count for this kernel).
        return KernelResult(
            dist=dist.astype(g.dtype),
            pred=pred,
            edges_relaxed=int(len(sources)) * g.num_edges,
        )

    def bellman_ford_pred(self, dgraph: CSRGraph, source: int | None) -> KernelResult:
        """Predecessor-tracking SSSP via the scipy Bellman-Ford (real
        sources only; the virtual-source variant has no tree to report)."""
        if source is None:
            raise NotImplementedError(
                "virtual-source Bellman-Ford has no predecessor tree"
            )
        g = dgraph
        mat = sp.csr_matrix(
            (g.weights, g.indices, g.indptr), shape=(g.num_nodes, g.num_nodes)
        )
        try:
            dist, pred = csgraph.bellman_ford(
                mat, directed=True, indices=source, return_predecessors=True
            )
        except csgraph.NegativeCycleError:
            return KernelResult(
                dist=np.full(g.num_nodes, np.nan, g.dtype),
                negative_cycle=True, converged=False,
                iterations=g.num_nodes, edges_relaxed=g.num_nodes * g.num_edges,
            )
        pred = np.where(pred < 0, -1, pred).astype(np.int32)
        return KernelResult(
            dist=dist.astype(g.dtype),
            pred=pred,
            iterations=g.num_nodes,
            edges_relaxed=g.num_nodes * g.num_edges,
        )


register_backend("numpy", NumpyBackend)
