"""The ``Backend`` plugin boundary (SURVEY.md §2 #4, BASELINE.json:5).

A backend owns device-resident graph buffers and the two numeric kernels of
Johnson's algorithm: the Bellman-Ford edge-relaxation pass and the N-source
non-negative shortest-path fan-out. The solver orchestrates phases through
this interface, so CPU/OpenMP <-> TPU substitution happens exactly here —
the architectural seam the reference attests ("The existing `Backend` /
`GraphLoader` plugin boundary gains a `JaxBackend`").

Kernel results carry a ``negative_cycle`` flag instead of raising, so
device backends can stay jit-compatible; the solver raises host-side.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any

import numpy as np

from paralleljohnson_tpu.config import SolverConfig
from paralleljohnson_tpu.graphs import CSRGraph


@dataclasses.dataclass
class KernelResult:
    """Output of one backend kernel invocation.

    dist: [V] (single-source) or [B, V] (multi-source) distances, +inf for
      unreachable. Device backends return their native device array (jax)
      so results can stay resident in HBM — RMAT-22 rows must never be
      forced to host wholesale; call ``np.asarray`` to materialize.
    negative_cycle: True iff a negative cycle is reachable (Bellman-Ford
      only; always False for the non-negative fan-out). Only claimed when
      the kernel ran the full |V|-sweep Bellman-Ford bound — a user-capped
      ``max_iterations`` below |V| yields converged=False instead, never a
      spurious cycle report.
    converged: False iff the kernel hit its iteration cap while distances
      were still improving (the solver raises ConvergenceError host-side).
    iterations: relaxation sweeps (sweep backends) or 0 (heap Dijkstra).
    edges_relaxed: edge relaxations performed — the attested instrumentation
      metric (BASELINE.json:2 "edges-relaxed/sec/chip"). Convention: a sweep
      counts every edge it scans; heap Dijkstra counts edges scanned from
      settled vertices; the dense min-plus regimes count candidate min-plus
      operations (B x V^2 per iteration, V^3 per squaring) since their work
      is independent of E. See the BASELINE.md convention note before
      comparing across backends/regimes.
    route: the kernel route the backend resolved to (e.g. "gs",
      "frontier", "vm-blocked", "dense-squaring", "sharded-1d") — flows
      into SolverStats and benchmark rows so before/after kernel
      comparisons stay reconstructable across measurement rounds.
    cost: compiled-cost capture for this invocation's executable
      (``observe.costs``: flops / bytes_accessed / transcendentals +
      memory analysis, or an explicit ``cost_analysis_unavailable``
      marker), keyed per (route, platform, shape-bucket). None when
      capture is disabled (no profile store configured) or the backend
      is not cost-instrumented; folds into ``SolverStats.analytic_cost``.
    trajectory: decoded per-iteration convergence trajectory (ISSUE 9,
      ``observe.convergence``): float64 ``[n, 3]`` host array with
      columns (frontier_size, relaxations_applied, residual_mass), one
      row per while_loop iteration. None when the convergence
      observatory is off or the resolved route is not instrumented
      (frontier / dense / fw / sharded / pallas routes keep their own
      exact counters instead). Folds into ``SolverStats.trajectories``.
    convergence: the trajectory's summary
      (``observe.convergence.summarize_trajectory``) — iterations,
      frontier half-life, tail fraction, JFR-skippable estimate; folds
      into ``SolverStats.convergence``.
    """

    dist: Any  # np.ndarray or a device array (see docstring)
    # Planner decision record (ISSUE 14, ``paralleljohnson_tpu.planner``):
    # {chosen, reason, candidates (with explicit ``unpriced`` markers),
    # built/degraded, params (resolved auto-tuned values)} for dispatch
    # sites that route through the registry. None for ladder-coded or
    # third-party backends; folds into ``SolverStats.plan`` and the
    # profile store's ``kind: "plan"`` records.
    plan: dict | None = None
    negative_cycle: bool = False
    iterations: int = 0
    edges_relaxed: int = 0
    converged: bool = True
    pred: np.ndarray | None = None  # predecessor vertices, -1 = none
    route: str | None = None  # resolved kernel route (see docstring)
    cost: dict | None = None  # compiled-cost capture (see docstring)
    trajectory: Any | None = None  # [n, 3] convergence curve (docstring)
    convergence: dict | None = None  # trajectory summary (docstring)


class Backend(abc.ABC):
    """Execution engine behind the solver. Subclass + register to plug in."""

    name: str = "abstract"

    def __init__(self, config: SolverConfig | None = None) -> None:
        self.config = config or SolverConfig()

    # -- device residency ---------------------------------------------------

    @abc.abstractmethod
    def upload(self, graph: CSRGraph) -> Any:
        """Move CSR buffers to execution memory (HBM on TPU; no-op on CPU).

        Returns an opaque device-graph handle accepted by the kernels below.
        """

    # -- kernels ------------------------------------------------------------

    @abc.abstractmethod
    def bellman_ford(self, dgraph: Any, source: int | None) -> KernelResult:
        """SSSP with negative weights from ``source``.

        ``source=None`` runs the virtual-source variant used for Johnson
        potentials: dist starts at 0 for every vertex (equivalent to a
        virtual vertex q with 0-weight edges to all, SURVEY.md §3.1, without
        materializing it).
        """

    @abc.abstractmethod
    def multi_source(self, dgraph: Any, sources: np.ndarray) -> KernelResult:
        """N-source shortest paths on a non-negative graph ("Dijkstra
        fan-out"). Returns dist[B, V] in the order of ``sources``."""

    # -- optional capabilities ----------------------------------------------

    def bellman_ford_pred(self, dgraph: Any, source: int | None) -> KernelResult:
        """Like :meth:`bellman_ford` but fills ``KernelResult.pred`` with the
        shortest-path tree (−1 at the source / unreached). Optional."""
        raise NotImplementedError(f"{self.name} does not track predecessors")

    def multi_source_pred(self, dgraph: Any, sources: np.ndarray) -> KernelResult:
        """Like :meth:`multi_source` but fills ``KernelResult.pred`` [B, V].
        Optional."""
        raise NotImplementedError(f"{self.name} does not track predecessors")

    def suggested_source_batch(
        self, dgraph: Any, with_pred: bool = False
    ) -> int | None:
        """Largest source batch one fan-out kernel call should take when
        ``config.source_batch_size`` is None (the promised fits-memory
        heuristic); ``None`` = no cap, solve all sources in one call.
        ``with_pred=True`` must also budget the extra int32 [B, V] pred
        block (and any extraction intermediates) a ``--predecessors``
        solve carries. Host-memory backends have no hard cap."""
        return None

    def clear_caches(self, dgraph: Any) -> None:
        """Drop rebuildable device-side caches attached to ``dgraph``
        (layout structures, re-sorted edge copies) so a large host
        download has the memory they held. No-op for host backends;
        device backends override (HBM hygiene before multi-batch row
        downloads — the RMAT-22 crash mitigation)."""

    def stage_rows_async(self, *arrays: Any) -> None:
        """Start device-to-host transfers of ``arrays`` WITHOUT blocking
        (a scheduling hint, never correctness): the pipelined fan-out
        calls this the moment a batch's rows pass the sanity guard, so
        the D2H DMA runs under the next batch's compute and the later
        ``np.asarray`` mostly just collects an already-finished copy.
        No-op for host backends (rows are already host memory); device
        backends override (``jax.Array.copy_to_host_async``)."""

    # -- optional fast paths (defaults compose the kernels host-side) -------

    def reweight(self, dgraph: Any, potentials: np.ndarray) -> Any:
        """Return a device graph with w'(u,v) = w + h(u) - h(v) (>= 0)."""
        graph = self.download_graph(dgraph)
        h = np.asarray(potentials, graph.dtype)
        wp = graph.weights + h[graph.src] - h[graph.indices]
        # Guard tiny negative float residue so the fan-out's non-negativity
        # precondition holds exactly.
        return self.upload(graph.with_weights(np.maximum(wp, 0.0)))

    def batch_apsp(self, batch: dict[str, np.ndarray]) -> KernelResult:
        """Many-small-graphs mode (BASELINE.json:11): APSP for a padded
        batch (see ``stack_graphs``). Returns dist[B, V, V]. Backends with a
        vectorized path override this; the default loops host-side."""
        raise NotImplementedError(f"{self.name} has no batch_apsp")

    def download_graph(self, dgraph: Any) -> CSRGraph:
        """Inverse of upload, for host-side composition/debug."""
        raise NotImplementedError(f"{self.name} cannot download graphs")

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__} name={self.name!r}>"


_BACKENDS: dict[str, type[Backend]] = {}


def register_backend(name: str, cls: type[Backend]) -> None:
    _BACKENDS[name] = cls


def available_backends() -> list[str]:
    return sorted(_BACKENDS)


def get_backend(name: str, config: SolverConfig | None = None) -> Backend:
    """Instantiate a registered backend — the attested ``backend=`` switch."""
    try:
        cls = _BACKENDS[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r}; available: {available_backends()}"
        ) from None
    return cls(config)
