"""Deterministic fault injection for the resilience layer.

Every recovery path in ``utils.resilience`` — retry, OOM batch
degradation, watchdog abandon, checkpoint resume, sharded→single-device
fallback, the distance-sanity guard — must be exercisable in tier-1 CPU
tests without a TPU or a real OOM. A :class:`FaultPlan` says exactly
which attempt of which stage fails and how:

    plan = FaultPlan([
        Fault(stage="fanout", kind="oom", attempt=1, batch=1),
        Fault(stage="sharded_fanout", kind="error"),
        Fault(stage="fanout", kind="timeout", sleep_s=0.5),
        Fault(stage="fanout", kind="nan", batch=0),
    ])
    SolverConfig(..., fault_plan=plan)

Attempt counting is per (stage, batch) key and lives on the plan, so the
schedule is a pure function of the call sequence — replaying the same
solve replays the same failures (no wall-clock randomness anywhere).

Stages with injection points: ``"fanout"`` / ``"bellman_ford"`` /
``"batch_apsp"`` (compute, via ``resilience.run_stage``),
``"sharded_fanout"`` (inside the collective path), and — round-9
pipeline — ``"download"`` (the staged D2H materialization of a batch's
rows, also via ``run_stage``) and ``"ckpt_write"`` (fired on the
checkpoint writer thread mid-commit, surfacing as
``SolveCorruptionError``; a killed commit leaves only an uncommitted
``.tmp.npz``, so resume recomputes exactly that batch).

Round-20 serving path: the socket frontend and the query engine fire
``"serve_accept"`` (per accepted connection), ``"serve_lookup"`` (per
query batch, before the tier walk) and ``"serve_solve"`` (around each
scheduled exact-miss solve) — the injection points
``scripts/serve_chaos_drill.py`` drives.

Kinds:
- ``"oom"``     raises :class:`InjectedOOMError` (a ``MemoryError``
                subclass — classified by ``resilience.is_oom_error``
                exactly like a real ``RESOURCE_EXHAUSTED``).
- ``"timeout"`` makes the attempt sleep ``sleep_s`` before running, so a
                watchdog deadline shorter than that abandons the stage.
- ``"slow_ms"`` makes the attempt sleep ``slow_ms`` MILLISECONDS before
                running — injected latency, not failure: the attempt
                still succeeds, just late. The chaos-drill primitive for
                realistic tail-latency storms (a store stall inflates
                p99 and burns the SLO budget without erroring anything).
- ``"error"``   raises :class:`InjectedFaultError` (a generic runtime
                failure — e.g. a collective/tunnel drop on the sharded
                path).
- ``"nan"``     leaves the call alone; the call site poisons the result
                rows via :meth:`FaultPlan.poison` so the sanity guard
                has something real to catch.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import numpy as np

_KINDS = ("oom", "timeout", "error", "nan", "slow_ms")


class InjectedOOMError(MemoryError):
    """Simulated RESOURCE_EXHAUSTED (see resilience.is_oom_error)."""


class InjectedFaultError(RuntimeError):
    """Simulated generic stage failure (collective drop, tunnel cut)."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """Fail attempt ``attempt`` of stage ``stage`` (optionally only for
    one batch index) with ``kind``. ``times``: how many consecutive
    attempts starting at ``attempt`` fail (so ``times >= max_attempts``
    models a permanent failure)."""

    stage: str
    kind: str
    attempt: int = 1
    batch: int | None = None
    times: int = 1
    sleep_s: float = 30.0
    slow_ms: float = 50.0  # "slow_ms" kind: injected latency per attempt
    rows: int = 1  # "nan" kind: poison the first ``rows`` rows

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {self.kind!r}")
        if self.attempt < 1 or self.times < 1:
            raise ValueError("attempt and times must be >= 1")
        if self.slow_ms < 0:
            raise ValueError(f"slow_ms must be >= 0, got {self.slow_ms}")


class _ActiveFault:
    """What ``FaultPlan.fire`` hands back to ``resilience.run_stage``:
    wraps the stage callable so the injected failure happens INSIDE the
    attempt (under the watchdog, like the real thing)."""

    def __init__(self, fault: Fault, sleep: Callable[[float], None]):
        self.fault = fault
        self._sleep = sleep

    def wrap(self, fn: Callable) -> Callable:
        fault = self.fault
        if fault.kind == "oom":
            def oom_call():
                raise InjectedOOMError(
                    f"injected RESOURCE_EXHAUSTED at stage {fault.stage!r}"
                )
            return oom_call
        if fault.kind == "error":
            def err_call():
                raise InjectedFaultError(
                    f"injected failure at stage {fault.stage!r}"
                )
            return err_call
        if fault.kind == "timeout":
            def slow_call():
                self._sleep(fault.sleep_s)
                return fn()
            return slow_call
        if fault.kind == "slow_ms":
            def late_call():
                self._sleep(fault.slow_ms / 1e3)
                return fn()
            return late_call
        return fn  # "nan": poisoning happens at the call site


class FaultPlan:
    """Deterministic schedule of injected faults (see module docstring).

    ``sleep``: injected-timeout sleeper, patchable in tests that want a
    wedge without real wall-clock cost.
    """

    def __init__(
        self, faults: list[Fault] | tuple[Fault, ...] = (),
        *, sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.faults = list(faults)
        self._sleep = sleep
        self._attempts: dict[tuple[str, int | None], int] = {}
        self._active: dict[tuple[str, int | None], _ActiveFault] = {}
        self.fired: list[tuple[str, int | None, int, str]] = []

    def attempts(self, stage: str, batch: int | None = None) -> int:
        """How many attempts of (stage, batch) have started so far."""
        return self._attempts.get((stage, batch), 0)

    def _match(self, stage: str, batch: int | None, attempt: int) -> Fault | None:
        for f in self.faults:
            if f.stage != stage:
                continue
            if f.batch is not None and f.batch != batch:
                continue
            if f.attempt <= attempt < f.attempt + f.times:
                return f
        return None

    def fire(self, stage: str, batch: int | None = None) -> _ActiveFault | None:
        """Record the start of one attempt; return the fault scheduled
        for it (or None). Called once per attempt by
        ``resilience.run_stage`` (or directly by non-retried call sites
        like the sharded dispatch)."""
        key = (stage, batch)
        attempt = self._attempts.get(key, 0) + 1
        self._attempts[key] = attempt
        fault = self._match(stage, batch, attempt)
        if fault is not None:
            self.fired.append((stage, batch, attempt, fault.kind))
            active = _ActiveFault(fault, self._sleep)
            self._active[key] = active
            return active
        self._active.pop(key, None)
        return None

    def poison_rows(self, stage: str, rows, batch: int | None = None):
        """Apply the ``"nan"`` fault (if any) scheduled for the attempt
        of (stage, batch) that just ran — the call-site hook for
        poisoning a stage's OUTPUT after ``fire`` armed the attempt."""
        return self.poison(self._active.get((stage, batch)), rows)

    def poison(self, active: _ActiveFault | None, rows):
        """Apply a pending ``"nan"`` fault to freshly computed distance
        rows (numpy or jax array); other kinds / no fault return rows
        unchanged. The poisoned rows are exactly what a corrupted kernel
        would hand the solver — upstream of the sanity guard AND of any
        checkpoint write."""
        if active is None or active.fault.kind != "nan":
            return rows
        k = max(1, int(active.fault.rows))
        if isinstance(rows, np.ndarray):
            rows = rows.copy()
            rows[:k] = np.nan
            return rows
        import jax.numpy as jnp

        return rows.at[:k].set(jnp.nan)
