"""Tracing / profiling (SURVEY.md §5).

Two mechanisms:
  - :func:`device_trace` — a ``jax.profiler`` trace (Perfetto/XProf
    protobufs under ``<dir>/plugins/profile``) around any region; each
    solver phase is already wrapped in ``jax.named_scope`` by
    ``utils.metrics.phase_timer``, so kernels inside the trace are
    attributable to bellman_ford / fanout / reweight / upload.
  - structured phase logs — :func:`log_stats` emits one JSON line per
    solve with per-phase wall-clock, iterations-to-fixpoint, edges-relaxed
    (the attested counter, BASELINE.json:2), and negative-cycle flags, to
    stderr or a file (observability without a trace viewer).
"""

from __future__ import annotations

import contextlib
import json
import sys
import time


@contextlib.contextmanager
def device_trace(log_dir: str | None, telemetry=None):
    """Profile the enclosed region with ``jax.profiler.trace`` when
    ``log_dir`` is set; no-op otherwise (so call sites need no branching).

    When a flight-recorder ``telemetry`` is also active, the trace dir
    is recorded as a ``device_trace`` event on it — the Chrome trace
    (host story) and the XLA device trace (kernel story) of one run can
    then be correlated offline without guessing which directories
    belong together."""
    if not log_dir:
        yield
        return
    if telemetry is not None:
        try:
            telemetry.event("device_trace", dir=str(log_dir))
        except Exception:  # noqa: BLE001 — telemetry must never block a trace
            pass
    import jax

    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named trace scope for ad-hoc regions (phases already get one via
    ``phase_timer``)."""
    import jax

    return jax.named_scope(name)


def log_stats(stats, *, label: str = "solve", stream=None, extra=None) -> dict:
    """Emit one structured JSON log line for a completed solve.

    Returns the payload dict (tests assert on it; callers may ship it to
    any log sink). ``stream=None`` writes to stderr.
    """
    payload = {
        "event": "pjtpu." + label,
        "ts": time.time(),
        **stats.as_dict(),
    }
    # Quick-read cost-observatory field: the full roofline/analytic_cost
    # dicts ride in via as_dict; the bound alone is the line a human
    # greps a log stream for.
    roof = getattr(stats, "roofline", None)
    if roof and roof.get("bound"):
        payload.setdefault("roofline_bound", roof["bound"])
    if extra:
        payload.update(extra)
    out = stream if stream is not None else sys.stderr
    print(json.dumps(payload), file=out, flush=True)
    return payload
