"""Flight-recorder telemetry (ROADMAP items 1-2: see into the failures).

The round-3 s22 attempt killed the TPU worker mid-download and left NO
record of how far it got; every wedged tunnel window since has forced
``tpu_watch_and_run.sh`` to guess whether a stage is hung or slowly
progressing. The resilience/pipeline machinery (PR 3/4) recovers from
failures but the only artifact of a solve is a single end-of-run
``log_stats`` line — if the process dies, the story dies with it.
Cluster-scale APSP systems (PAPERS.md: the Spark APSP system) treat
per-stage telemetry as a prerequisite for running large jobs at all.
This module is that subsystem, three mechanisms sharing one façade:

- :class:`Tracer` — thread-safe nested ``span(name, **attrs)`` contexts
  (contextvar parent tracking, monotonic clocks) and ``event()``
  markers. With a ``flight_path`` every record is appended to a JSONL
  **flight recorder** and flushed at once, so a SIGKILLed worker leaves
  a readable record up to the instant of death (open spans mark where
  it died). ``to_chrome_trace()`` exports Perfetto-loadable trace-event
  JSON with each OS thread (main solve loop, pipeline finalize worker,
  checkpoint writer) on its own track.
- :class:`HeartbeatReporter` — a daemon thread atomically rewriting a
  small progress JSON every ``interval_s``: current stage/batch/attempt,
  batches done, retries, current batch size, pipeline depth, host RSS,
  and the device's ``memory_stats()`` bytes-in-use when available (the
  HBM trajectory that would have explained the s22 crash). Atomic
  tmp+rename per write — a reader never sees a torn file; a STALE
  mtime means the process is hung, a fresh one means it is progressing
  (what the TPU watcher scripts key off).
- :func:`write_prom_metrics` — Prometheus textfile-collector export of
  a completed solve's :class:`~paralleljohnson_tpu.utils.metrics.SolverStats`
  for scrape-based monitoring of long production runs.

Telemetry is OFF by default (``SolverConfig.telemetry=None``) and the
disabled path is near-free: every instrumented call site goes through
:data:`NULL_TELEMETRY`, whose ``span`` returns one shared reusable
null context and whose ``event``/``progress`` are empty methods.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import itertools
import json
import os
import threading
import time
from pathlib import Path
from typing import Any

# Current span id for the CALLING thread's context. Threads start with
# the default (None), so background workers (pipeline finalize, the
# checkpoint writer) do not silently inherit the main thread's span —
# cross-thread nesting is explicit via ``span(..., parent=<id>)``,
# captured at submit time by the call sites that hop threads.
_CURRENT_SPAN: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "pj_current_span", default=None
)

_EVENT_NAMES_OF_INTEREST = (
    "retry", "abandon", "oom_degrade", "window_collapse", "batch_resumed",
    # Fleet lease lifecycle (ISSUE 10, ``paralleljohnson_tpu/distributed``)
    # — a worker's heartbeat carries its last lease transition, so
    # `fleet status` can show what each worker last did even between
    # coordinator log events.
    "lease_claimed", "lease_committed", "lease_requeued",
    "lease_stale_commit",
)


def _thread_label() -> tuple[int, str]:
    t = threading.current_thread()
    return t.ident or 0, t.name


class _SpanHandle:
    """Context manager for one span. Close status is ``"ok"`` unless the
    body raised — then ``"error"`` with the exception recorded, so a
    crashed solve's flight record shows WHICH attempt died and why."""

    __slots__ = ("_tracer", "id", "_token")

    def __init__(self, tracer: "Tracer", span_id: int):
        self._tracer = tracer
        self.id = span_id
        self._token = None

    def __enter__(self) -> "_SpanHandle":
        self._token = _CURRENT_SPAN.set(self.id)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _CURRENT_SPAN.reset(self._token)
        if exc is None:
            self._tracer._end_span(self.id, "ok", None)
        else:
            self._tracer._end_span(
                self.id, "error", f"{exc_type.__name__}: {exc}"
            )


class Tracer:
    """Thread-safe span/event recorder with an optional JSONL flight file.

    Records (one JSON object per line / list entry):
      ``{"type": "meta", "pid", "proc", ["label"], "start_ts",
         "t": 0.0}``                                              (first)
      ``{"type": "span_begin", "id", "parent", "name", "t", "tid",
         "thread", "attrs"}``
      ``{"type": "span_end", "id", "t", "status", ["error"]}``
      ``{"type": "event", "name", "t", "span", "tid", "thread", "attrs"}``

    ``t`` is monotonic seconds since tracer creation (``perf_counter``
    based — wall-clock steps cannot reorder the story); ``start_ts`` in
    the meta line anchors it to the epoch. ``proc`` is a unique
    per-tracer id (pid + random suffix): span ids are only locally
    unique, so the cross-process trace assembler
    (``observe/trace.py``) addresses spans by the *global ref*
    ``"<proc>:<span_id>"`` — :meth:`global_ref` — which is what rides
    the serve wire as the downstream hop's ``parent``. Every line
    appended to the flight file is flushed immediately: a killed
    process leaves batches 0..k-1 closed and batch k OPEN, which is
    exactly the diagnosis.
    """

    def __init__(self, flight_path: str | Path | None = None,
                 label: str | None = None) -> None:
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._t0 = time.perf_counter()
        self._records: list[dict] = []
        self._open: dict[int, dict] = {}
        self._file = None
        self.flight_path: Path | None = None
        self.proc = f"{os.getpid():x}-{os.urandom(3).hex()}"
        if flight_path is not None:
            self.flight_path = Path(flight_path)
            self.flight_path.parent.mkdir(parents=True, exist_ok=True)
            self._file = open(self.flight_path, "a", encoding="utf-8")
        meta = {"type": "meta", "pid": os.getpid(), "proc": self.proc,
                "start_ts": time.time(), "t": 0.0}
        if label is not None:
            meta["label"] = label
        self._emit(meta)

    # -- recording --------------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def _emit(self, rec: dict) -> None:
        with self._lock:
            self._records.append(rec)
            if self._file is not None:
                self._file.write(json.dumps(rec) + "\n")
                # Flush per record: the flight recorder's whole point is
                # surviving a kill at an arbitrary instant.
                self._file.flush()

    def span(self, name: str, *, parent: int | None = None, **attrs):
        """Open a nested span; use as a context manager. ``parent=None``
        nests under the calling thread's current span (contextvar);
        pass an explicit id when the span logically belongs to work
        submitted from another thread."""
        span_id = next(self._ids)
        if parent is None:
            parent = _CURRENT_SPAN.get()
        tid, tname = _thread_label()
        rec = {
            "type": "span_begin", "id": span_id, "parent": parent,
            "name": name, "t": self._now(), "tid": tid, "thread": tname,
            "attrs": attrs,
        }
        with self._lock:
            self._open[span_id] = rec
        self._emit(rec)
        return _SpanHandle(self, span_id)

    def _end_span(self, span_id: int, status: str, error: str | None) -> None:
        rec = {"type": "span_end", "id": span_id, "t": self._now(),
               "status": status}
        if error is not None:
            rec["error"] = error
        with self._lock:
            self._open.pop(span_id, None)
        self._emit(rec)

    def event(self, name: str, **attrs) -> None:
        """Point-in-time marker (retry / oom_degrade / window_collapse /
        abandon / batch_resumed ...), attached to the current span."""
        tid, tname = _thread_label()
        self._emit({
            "type": "event", "name": name, "t": self._now(),
            "span": _CURRENT_SPAN.get(), "tid": tid, "thread": tname,
            "attrs": attrs,
        })

    def current_span_id(self) -> int | None:
        return _CURRENT_SPAN.get()

    def global_ref(self, span_id: int | None = None) -> str | None:
        """The process-unique address of a span (``"<proc>:<id>"``) —
        what a forwarding hop puts on the wire as the downstream
        process's ``parent``. Defaults to the current span; None when
        there is none."""
        if span_id is None:
            span_id = _CURRENT_SPAN.get()
        if span_id is None:
            return None
        return f"{self.proc}:{span_id}"

    def begin_span(self, name: str, *, parent: int | None = None,
                   **attrs) -> int:
        """Open a span WITHOUT entering it on the calling thread's
        contextvar stack — for work tracked on behalf of another thread
        (the MicroBatcher leader opening one ``convoy_member`` span per
        follower slot). Close with :meth:`finish_span`."""
        span_id = next(self._ids)
        if parent is None:
            parent = _CURRENT_SPAN.get()
        tid, tname = _thread_label()
        rec = {
            "type": "span_begin", "id": span_id, "parent": parent,
            "name": name, "t": self._now(), "tid": tid, "thread": tname,
            "attrs": attrs,
        }
        with self._lock:
            self._open[span_id] = rec
        self._emit(rec)
        return span_id

    def finish_span(self, span_id: int, status: str = "ok",
                    error: str | None = None) -> None:
        """Close a span opened with :meth:`begin_span`."""
        self._end_span(span_id, status, error)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                    self._file.close()
                finally:
                    self._file = None

    # -- exports ----------------------------------------------------------

    def to_chrome_trace(self) -> dict:
        """Perfetto/chrome://tracing-loadable trace-event JSON. Host
        spans land on per-OS-thread tracks (main loop vs pipeline
        finalize vs checkpoint writer), events become instants, and
        spans still open (a killed run) are emitted as begin-only
        events so the death point is visible in the viewer."""
        return chrome_trace_from_records(self.records())

    def summary(self) -> dict:
        """Compact roll-up for bench row detail / log lines."""
        spans = 0
        open_spans = 0
        events: dict[str, int] = {}
        by_name: dict[str, float] = {}
        begins: dict[int, dict] = {}
        for r in self.records():
            kind = r.get("type")
            if kind == "span_begin":
                begins[r["id"]] = r
                spans += 1
                open_spans += 1
            elif kind == "span_end":
                open_spans -= 1
                b = begins.get(r["id"])
                if b is not None:
                    name = b["name"]
                    by_name[name] = by_name.get(name, 0.0) + (r["t"] - b["t"])
            elif kind == "event":
                events[r["name"]] = events.get(r["name"], 0) + 1
        out = {
            "spans": spans,
            "open_spans": open_spans,
            "events": events,
            "span_seconds_by_name": {
                k: round(v, 6) for k, v in sorted(by_name.items())
            },
        }
        if self.flight_path is not None:
            out["flight_recorder"] = str(self.flight_path)
        return out


def chrome_trace_from_records(records: list[dict]) -> dict:
    """Convert flight-recorder records (a :meth:`Tracer.records` list or
    a parsed JSONL) to trace-event JSON. Offline twin of
    :meth:`Tracer.to_chrome_trace` — ``scripts/trace_summary.py --chrome``
    runs it on a dead run's flight file."""
    pid = None
    tids: dict[int, int] = {}
    names: dict[int, str] = {}
    events: list[dict] = []

    def tid_of(rec) -> int:
        raw = rec.get("tid", 0)
        if raw not in tids:
            tids[raw] = len(tids)
            names[tids[raw]] = rec.get("thread", f"thread-{raw}")
        return tids[raw]

    begins: dict[int, dict] = {}
    ends: dict[int, dict] = {}
    for r in records:
        kind = r.get("type")
        if kind == "meta":
            pid = int(r.get("pid", 0))
        elif kind == "span_begin":
            begins[r["id"]] = r
        elif kind == "span_end":
            ends[r["id"]] = r
    pid = pid if pid is not None else os.getpid()
    for span_id, b in begins.items():
        args = dict(b.get("attrs") or {})
        args["span_id"] = span_id
        if b.get("parent") is not None:
            args["parent_span"] = b["parent"]
        e = ends.get(span_id)
        if e is not None:
            ev = {"name": b["name"], "ph": "X", "pid": pid,
                  "tid": tid_of(b), "ts": b["t"] * 1e6,
                  "dur": max(0.0, (e["t"] - b["t"]) * 1e6), "args": args}
            if e.get("status") == "error":
                ev["args"]["error"] = e.get("error", "")
        else:
            # Open at death: begin-only so the viewer shows WHERE it died.
            ev = {"name": b["name"], "ph": "B", "pid": pid,
                  "tid": tid_of(b), "ts": b["t"] * 1e6, "args": args}
        events.append(ev)
    for r in records:
        if r.get("type") == "event":
            events.append({
                "name": r["name"], "ph": "i", "s": "t", "pid": pid,
                "tid": tid_of(r), "ts": r["t"] * 1e6,
                "args": dict(r.get("attrs") or {}),
            })
    events.sort(key=lambda e: e["ts"])
    meta = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": name}}
        for tid, name in sorted(names.items())
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


_PHASES = {"B", "E", "X", "i", "I", "M", "b", "e", "n", "C"}


def validate_chrome_trace(trace: Any) -> None:
    """Raise ``ValueError`` unless ``trace`` conforms to the trace-event
    schema subset this exporter emits (and Perfetto accepts): JSON-object
    format with a ``traceEvents`` list whose entries carry ``ph``/``pid``
    /``tid``/``name``, ``ts`` (+ ``dur`` for "X") numbers, and
    JSON-serializable ``args``. The telemetry tests run every export
    through this before anything is allowed to claim Perfetto-loadable."""
    if not isinstance(trace, dict):
        raise ValueError(f"trace must be a dict, got {type(trace).__name__}")
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError("trace['traceEvents'] must be a list")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if ph not in _PHASES:
            raise ValueError(f"traceEvents[{i}]: bad ph {ph!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                raise ValueError(f"traceEvents[{i}]: {key} must be an int")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"traceEvents[{i}]: missing name")
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                raise ValueError(f"traceEvents[{i}]: ts must be a number")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            raise ValueError(f"traceEvents[{i}]: X event needs dur")
        if ph == "i" and ev.get("s") not in ("t", "p", "g", None):
            raise ValueError(f"traceEvents[{i}]: bad instant scope {ev.get('s')!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            raise ValueError(f"traceEvents[{i}]: args must be an object")
        try:
            json.dumps(ev)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"traceEvents[{i}] is not JSON-serializable: {e}"
            ) from None


# -- heartbeat ---------------------------------------------------------------


def _host_rss_bytes() -> int | None:
    """Resident set size without psutil: /proc on Linux, ru_maxrss (a
    high-water mark, close enough for a trajectory) elsewhere."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 — telemetry must never crash a solve
        return None


def _device_memory_stats() -> dict | None:
    """Per-device ``memory_stats()`` bytes (HBM in-use / peak / limit) when
    jax is ALREADY imported and the backend reports them (TPU does; CPU
    returns None). Never imports jax itself — the heartbeat thread must
    not initialize a device client behind the solve's back."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    out = {}
    try:
        for d in jax.devices():
            stats = d.memory_stats()
            if not stats:
                continue
            out[str(d.id)] = {
                k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                         "largest_alloc_size")
            }
    except Exception:  # noqa: BLE001 — a dead device must not kill telemetry
        return out or None
    return out or None


class HeartbeatReporter:
    """Atomically rewrites a small progress JSON every ``interval_s``.

    ``update(**fields)`` merges progress fields (stage/batch/attempt/
    batches_done/...) into the state from any thread; the writer thread
    serializes state + liveness (seq, ts, uptime, RSS, device memory)
    and publishes via tmp-write + ``os.replace`` so a concurrent reader
    NEVER sees a torn file. Consumers decide hung-vs-progressing from
    the file's freshness (:func:`heartbeat_age_s` or plain mtime — what
    ``scripts/tpu_round3_run.sh`` uses to extend stage deadlines)."""

    def __init__(self, path: str | Path, interval_s: float = 5.0) -> None:
        if not interval_s > 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.interval_s = float(interval_s)
        self._state: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = 0
        self._t0 = time.perf_counter()
        self._thread: threading.Thread | None = None
        self.write_errors = 0

    def update(self, **fields) -> None:
        with self._lock:
            self._state.update(fields)

    def note(self, **fields) -> None:
        """Merge live-progress fields from the SOLVE thread(s) — the
        convergence observatory's channel for ``iter`` /
        ``frontier_size`` / ``eta_s`` (ISSUE 9). Same lock-protected
        dict merge as :meth:`update` (the writer thread serializes a
        copy under the same lock, so a half-merged batch of fields can
        never be published — the atomicity the telemetry tests pin);
        a distinct name so call sites read as "push a fact", not
        "rewrite the file"."""
        self.update(**fields)

    def payload(self) -> dict:
        with self._lock:
            state = dict(self._state)
            self._seq += 1
            seq = self._seq
        return {
            "ts": time.time(),
            "uptime_s": round(time.perf_counter() - self._t0, 3),
            "seq": seq,
            "pid": os.getpid(),
            "interval_s": self.interval_s,
            "host_rss_bytes": _host_rss_bytes(),
            "device_memory": _device_memory_stats(),
            **state,
        }

    def write_now(self) -> None:
        """One atomic publish (also called by tests for determinism)."""
        try:
            payload = self.payload()
            tmp = self.path.with_name(self.path.name + f".tmp{os.getpid()}")
            tmp.write_text(json.dumps(payload), encoding="utf-8")
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 — heartbeat must never kill a solve
            self.write_errors += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_now()

    def start(self) -> "HeartbeatReporter":
        if self._thread is None:
            self._stop.clear()
            self.write_now()  # liveness visible before the first interval
            self._thread = threading.Thread(
                target=self._loop, name="pj-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, *, final_write: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.interval_s))
            self._thread = None
        if final_write:
            self.write_now()


def read_heartbeat(path: str | Path) -> dict | None:
    """Parse a heartbeat file; None when absent. Parse errors are raised:
    atomicity guarantees a reader never legitimately sees a torn file."""
    p = Path(path)
    if not p.exists():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def heartbeat_age_s(path: str | Path, now: float | None = None) -> float | None:
    """Seconds since the heartbeat's last publish (its ``ts`` field), or
    None when the file is absent. The staleness clock: fresh = the solve
    is progressing (extend its deadline), stale = hung (retry now)."""
    hb = read_heartbeat(path)
    if hb is None:
        return None
    return (time.time() if now is None else now) - float(hb["ts"])


def heartbeat_fresh(
    path: str | Path, stale_s: float, now: float | None = None
) -> bool:
    """Liveness verdict from one heartbeat file: True iff it exists, is
    readable, and its last publish is younger than ``stale_s``. The
    slow-but-alive vs dead distinction the fleet coordinator keys lease
    requeues off (ISSUE 10) — an unreadable or absent beat never
    vouches for anyone."""
    try:
        age = heartbeat_age_s(path, now=now)
    except ValueError:
        return False
    return age is not None and age < stale_s


# -- prometheus textfile export ----------------------------------------------


def _measured_compute_s(s: Any) -> float:
    compute = getattr(s, "compute_seconds", None)
    if compute is not None:
        return float(compute)
    return float(getattr(s, "total_seconds", 0.0) or 0.0)


def _roofline_kind_values(s: Any) -> dict:
    """Per-kind samples of the labeled ``pjtpu_roofline_bound`` gauge:
    1 on the solve's classified bound, 0 on the others; empty (no
    samples emitted) when the solve was never attributed."""
    roof = getattr(s, "roofline", None)
    if not roof:
        return {}
    from paralleljohnson_tpu.observe.roofline import BOUND_KINDS

    bound = roof.get("bound", "unknown")
    return {kind: 1.0 if kind == bound else 0.0 for kind in BOUND_KINDS}


_PROM_METRICS = (
    ("pjtpu_edges_relaxed_total", "counter",
     "Total edge relaxations performed by the solve",
     lambda s: s.edges_relaxed),
    ("pjtpu_solve_seconds", "gauge",
     "Wall-clock seconds across all solve phases",
     lambda s: s.total_seconds),
    ("pjtpu_retries_total", "counter",
     "Stage attempts re-run after a transient failure",
     lambda s: s.retries),
    ("pjtpu_oom_degradations_total", "counter",
     "Times the fan-out source batch was halved after a device OOM",
     lambda s: s.oom_degradations),
    ("pjtpu_ckpt_wait_seconds", "gauge",
     "Seconds the solve thread spent blocked on the checkpoint pipeline",
     lambda s: s.ckpt_wait_s),
    # Cost-observatory gauges (ISSUE 7): the calibrated prediction vs
    # the measurement it is graded against, and the labeled roofline
    # bound classification.
    ("pjtpu_route_predicted_s", "gauge",
     "Cost-model predicted compute seconds for this solve's route "
     "(0 = no calibration available)",
     lambda s: float(getattr(s, "predicted_s", None) or 0.0)),
    ("pjtpu_route_measured_s", "gauge",
     "Measured compute seconds (bellman_ford + fanout + batch_apsp)",
     _measured_compute_s),
    ("pjtpu_roofline_bound", "gauge",
     "Roofline classification of the solve: 1 on the active bound's "
     "kind label (hbm / mxu / host-io / unknown)",
     _roofline_kind_values, "kind"),
)


def write_prom_metrics(stats: Any, path: str | Path, *,
                       labels: dict | None = None,
                       metrics: tuple | None = None,
                       exemplars: bool = False) -> Path:
    """Write one stats object in Prometheus textfile-collector format
    (atomic tmp+rename — node_exporter may scrape mid-write). ``labels``
    adds constant labels to every sample (e.g. ``{"config": "rmat_apsp"}``).
    ``metrics`` is the ``(name, type, help, getter)`` table to emit —
    default the solve-stats table above; the serving layer passes its own
    (``serve.engine.SERVE_PROM_METRICS``: pjtpu_queries_total,
    pjtpu_query_latency_*, ...) so every subsystem exports through this
    one atomic writer. A 5-tuple entry ``(name, type, help, getter,
    label_name)`` is a LABELED metric: its getter returns
    ``{label_value: sample}`` and one line is emitted per label value
    (e.g. ``pjtpu_roofline_bound{kind="hbm"} 1.0``); an empty dict
    emits no samples (the metric has nothing to report).

    A 4-tuple entry whose type is ``"histogram"`` (ISSUE 12) expects
    its getter to return an ``observe.live.LogHistogram`` (anything
    with ``cumulative_buckets()`` / ``count`` / ``sum``) and emits the
    real Prometheus histogram series: ``<name>_bucket{le="..."}`` lines
    with CUMULATIVE counts and strictly increasing ``le`` edges (one
    per occupied log bucket, closing with ``le="+Inf"``), plus
    ``<name>_sum`` and ``<name>_count`` — so percentile queries work in
    PromQL (``histogram_quantile``) instead of only via the exported
    p50/p99 gauges. Run :func:`validate_prom_text` over the output in
    tests — the cumulative-bucket invariants are checked, not assumed.

    ``exemplars=True`` (ISSUE 20) appends an OpenMetrics-style exemplar
    to each histogram bucket line whose ``LogHistogram`` bucket
    recorded one — ``<bucket sample> # {trace_id="<id>"} <value>`` —
    so a scrape can jump from "the p99 bucket" to a concrete request
    trace. Off by default: plain Prometheus text-format parsers reject
    the suffix; only enable for OpenMetrics-aware collectors.
    """

    def fmt_labels(extra: dict | None = None) -> str:
        merged = dict(labels or {})
        if extra:
            merged.update(extra)
        if not merged:
            return ""
        inner = ",".join(
            f'{k}="{str(v)}"' for k, v in sorted(merged.items())
        )
        return "{" + inner + "}"

    def fmt_le(edge: float) -> str:
        if edge == float("inf"):
            return "+Inf"
        return repr(float(edge))

    label_str = fmt_labels()
    lines = []
    for entry in (metrics or _PROM_METRICS):
        if len(entry) == 5:
            name, mtype, help_text, get, label_name = entry
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for value, sample in sorted((get(stats) or {}).items()):
                lines.append(
                    f"{name}{fmt_labels({label_name: value})} "
                    f"{float(sample)}"
                )
            continue
        name, mtype, help_text, get = entry
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        if mtype == "histogram":
            hist = get(stats)
            ex_by_edge = {}
            if exemplars and hasattr(hist, "bucket_exemplars"):
                ex_by_edge = hist.bucket_exemplars() or {}
            for edge, cum in hist.cumulative_buckets():
                line = (
                    f"{name}_bucket{fmt_labels({'le': fmt_le(edge)})} "
                    f"{float(cum)}"
                )
                ex = ex_by_edge.get(edge)
                if ex is not None:
                    trace_id, ex_value = ex
                    line += (f' # {{trace_id="{trace_id}"}} '
                             f"{float(ex_value)}")
                lines.append(line)
            lines.append(f"{name}_sum{label_str} {float(hist.sum)}")
            lines.append(f"{name}_count{label_str} {float(hist.count)}")
            continue
        lines.append(f"{name}{label_str} {float(get(stats))}")
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + f".tmp{os.getpid()}")
    tmp.write_text("\n".join(lines) + "\n", encoding="utf-8")
    os.replace(tmp, p)
    return p


_PROM_SAMPLE_RE = None  # compiled lazily (keep import time free of re work)


def validate_prom_text(text: str) -> None:
    """Raise ``ValueError`` unless ``text`` conforms to the Prometheus
    text-exposition subset this writer emits: every sample line parses
    as ``name{labels} value``, every series is preceded by its HELP and
    TYPE lines, and histogram series satisfy the cumulative-bucket
    contract — ``le`` edges strictly increasing, bucket counts
    non-decreasing, a closing ``le="+Inf"`` bucket whose count equals
    ``<name>_count``, and ``_sum``/``_count`` present. An
    OpenMetrics-style exemplar suffix (``# {trace_id="..."} <value>``,
    ISSUE 20) is accepted ONLY on histogram ``_bucket`` lines — one
    anywhere else raises. The telemetry tests run every export through
    this before anything may claim scrape-ready (the
    ``validate_chrome_trace`` pattern)."""
    import re

    global _PROM_SAMPLE_RE
    if _PROM_SAMPLE_RE is None:
        _PROM_SAMPLE_RE = re.compile(
            r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
            r"(?:\{(?P<labels>[^}]*)\})?"
            r" (?P<value>[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|inf|nan))"
            r"(?P<exemplar> # \{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"\}"
            r" [-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|inf|nan))?$"
        )
    typed: dict[str, str] = {}
    helped: set[str] = set()
    # histogram name -> list of (le, count); plus captured _sum/_count.
    buckets: dict[str, list[tuple[float, float]]] = {}
    sums: dict[str, float] = {}
    counts: dict[str, float] = {}
    for n, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 4 or not parts[3].strip():
                raise ValueError(f"line {n}: HELP without text: {line!r}")
            helped.add(parts[2])
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                raise ValueError(f"line {n}: bad TYPE line: {line!r}")
            if parts[2] not in helped:
                raise ValueError(
                    f"line {n}: TYPE for {parts[2]} before its HELP"
                )
            typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            raise ValueError(f"line {n}: unknown comment: {line!r}")
        m = _PROM_SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {n}: unparseable sample: {line!r}")
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed \
                    and typed[name[: -len(suffix)]] == "histogram":
                base = name[: -len(suffix)]
                break
        if base not in typed:
            raise ValueError(
                f"line {n}: sample {name} has no preceding TYPE"
            )
        value = float(m.group("value"))
        if m.group("exemplar") and not (
            typed[base] == "histogram" and name == base + "_bucket"
        ):
            raise ValueError(
                f"line {n}: exemplar on a non-histogram-bucket "
                f"sample: {line!r}"
            )
        if typed[base] == "histogram":
            if name == base + "_bucket":
                labels = m.group("labels") or ""
                le_m = re.search(r'le="([^"]+)"', labels)
                if le_m is None:
                    raise ValueError(
                        f"line {n}: histogram bucket without le label"
                    )
                raw = le_m.group(1)
                le = float("inf") if raw == "+Inf" else float(raw)
                buckets.setdefault(base, []).append((le, value))
            elif name == base + "_sum":
                sums[base] = value
            elif name == base + "_count":
                counts[base] = value
            else:
                raise ValueError(
                    f"line {n}: bare sample {name} for histogram {base}"
                )
    for base, series in buckets.items():
        les = [le for le, _ in series]
        cums = [c for _, c in series]
        if les != sorted(les) or len(set(les)) != len(les):
            raise ValueError(
                f"{base}: bucket le edges not strictly increasing: {les}"
            )
        if cums != sorted(cums):
            raise ValueError(
                f"{base}: bucket counts not cumulative: {cums}"
            )
        if les[-1] != float("inf"):
            raise ValueError(f"{base}: missing le=\"+Inf\" bucket")
        if base not in counts or base not in sums:
            raise ValueError(f"{base}: histogram missing _sum/_count")
        if cums[-1] != counts[base]:
            raise ValueError(
                f"{base}: +Inf bucket {cums[-1]} != _count {counts[base]}"
            )
    for base, mtype in typed.items():
        if mtype == "histogram" and base not in buckets:
            raise ValueError(f"{base}: histogram TYPE with no buckets")


# -- the façade the engine is wired through ----------------------------------


class Telemetry:
    """Bundle of tracer + heartbeat that the solve engine threads through
    (``SolverConfig.telemetry``). Either part is optional; ``close()``
    stops the heartbeat, exports the Chrome trace (when a trace dir was
    given), and closes the flight file."""

    enabled = True

    def __init__(self, tracer: Tracer | None = None,
                 heartbeat: HeartbeatReporter | None = None,
                 trace_dir: str | Path | None = None,
                 label: str = "solve") -> None:
        self.tracer = tracer or Tracer()
        self.heartbeat = heartbeat
        self.trace_dir = Path(trace_dir) if trace_dir else None
        self.label = label
        self._closed = False

    @classmethod
    def create(cls, *, trace_dir: str | Path | None = None,
               heartbeat_file: str | Path | None = None,
               heartbeat_interval_s: float = 5.0,
               label: str = "solve") -> "Telemetry | None":
        """Build from CLI/env knobs; None when nothing was requested (so
        callers pass it straight to ``SolverConfig.telemetry``)."""
        if trace_dir is None and heartbeat_file is None:
            return None
        tracer = Tracer(
            flight_path=(Path(trace_dir) / f"flight-{label}.jsonl")
            if trace_dir else None,
            label=label,
        )
        hb = None
        if heartbeat_file is not None:
            hb = HeartbeatReporter(
                heartbeat_file, interval_s=heartbeat_interval_s
            ).start()
        return cls(tracer=tracer, heartbeat=hb, trace_dir=trace_dir,
                   label=label)

    def span(self, name: str, *, parent: int | None = None, **attrs):
        return self.tracer.span(name, parent=parent, **attrs)

    def event(self, name: str, **attrs) -> None:
        self.tracer.event(name, **attrs)
        if name in _EVENT_NAMES_OF_INTEREST and self.heartbeat is not None:
            self.heartbeat.update(last_event=name)

    def progress(self, **fields) -> None:
        """Merge live-progress fields into the heartbeat (no-op without
        one). Cheap: a dict update under a lock; the writer thread does
        the serialization on its own clock."""
        if self.heartbeat is not None:
            self.heartbeat.update(**fields)

    def note(self, **fields) -> None:
        """The solver-side push channel for convergence facts (``iter``
        / ``frontier_size`` / ``eta_s`` — ISSUE 9): a lock-protected
        merge into the heartbeat state (``HeartbeatReporter.note``),
        safe against the sampler thread. No-op without a heartbeat."""
        if self.heartbeat is not None:
            self.heartbeat.note(**fields)

    def current_span_id(self) -> int | None:
        return self.tracer.current_span_id()

    def global_ref(self, span_id: int | None = None) -> str | None:
        return self.tracer.global_ref(span_id)

    def begin_span(self, name: str, *, parent: int | None = None,
                   **attrs) -> int:
        return self.tracer.begin_span(name, parent=parent, **attrs)

    def finish_span(self, span_id: int, status: str = "ok",
                    error: str | None = None) -> None:
        self.tracer.finish_span(span_id, status, error)

    def summary(self) -> dict:
        return self.tracer.summary()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.trace_dir is not None:
            try:
                trace = self.tracer.to_chrome_trace()
                out = self.trace_dir / f"trace-{self.label}.json"
                out.write_text(json.dumps(trace), encoding="utf-8")
            except Exception:  # noqa: BLE001 — teardown must not mask errors
                pass
        self.tracer.close()


class _NullSpan:
    """Reusable, reentrant, thread-safe no-op context manager (one shared
    instance — the disabled path allocates nothing per span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_SPAN = _NullSpan()


class _NullTelemetry:
    """The disabled path. All call sites are wired unconditionally; this
    object makes ``telemetry=None`` (the default) near-free — no
    allocation, no locking, no IO."""

    __slots__ = ()
    enabled = False

    def __bool__(self) -> bool:
        # Falsy so call sites can gate optional extra work with a plain
        # ``if telemetry:`` while still calling the no-op methods
        # unconditionally where that is simpler.
        return False

    def span(self, name, *, parent=None, **attrs):
        return _NULL_SPAN

    def event(self, name, **attrs):
        return None

    def progress(self, **fields):
        return None

    def note(self, **fields):
        return None

    def current_span_id(self):
        return None

    def global_ref(self, span_id=None):
        return None

    def begin_span(self, name, *, parent=None, **attrs):
        return None

    def finish_span(self, span_id, status="ok", error=None):
        return None

    def summary(self):
        return {}

    def close(self):
        return None


NULL_TELEMETRY = _NullTelemetry()


def resolve(telemetry) -> Any:
    """``config.telemetry`` (or None) -> the object call sites use."""
    return telemetry if telemetry is not None else NULL_TELEMETRY


def traced(name: str, **span_attrs):
    """Decorator giving a function an optional keyword-only ``telemetry``
    argument that wraps the call in a span (used by the sharded entry
    points in ``parallel/mesh.py``). ``telemetry=None`` adds one ``is
    None`` check — the disabled path stays free."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, telemetry=None, **kwargs):
            if telemetry is None:
                return fn(*args, **kwargs)
            with telemetry.span(name, **span_attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def maybe_span(telemetry, name: str, **attrs):
    """Span context that tolerates ``telemetry=None`` (for call sites not
    on the solver's resolved path)."""
    if telemetry is None:
        yield None
        return
    with telemetry.span(name, **attrs) as s:
        yield s
