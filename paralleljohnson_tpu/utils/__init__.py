"""Instrumentation, checkpointing, and misc utilities (SURVEY.md §5)."""

from paralleljohnson_tpu.utils.checkpoint import BatchCheckpointer
from paralleljohnson_tpu.utils.metrics import SolverStats, phase_timer
from paralleljohnson_tpu.utils.telemetry import (
    HeartbeatReporter,
    Telemetry,
    Tracer,
    write_prom_metrics,
)

__all__ = [
    "BatchCheckpointer",
    "HeartbeatReporter",
    "SolverStats",
    "Telemetry",
    "Tracer",
    "phase_timer",
    "write_prom_metrics",
]
