"""Instrumentation, checkpointing, and misc utilities (SURVEY.md §5)."""

from paralleljohnson_tpu.utils.checkpoint import BatchCheckpointer
from paralleljohnson_tpu.utils.metrics import SolverStats, phase_timer

__all__ = ["BatchCheckpointer", "SolverStats", "phase_timer"]
