"""Shortest-path reconstruction from predecessor arrays.

Predecessor convention across the framework: ``pred[b, v]`` is the vertex
preceding ``v`` on a shortest path from ``sources[b]``; ``-1`` means "no
predecessor" (the source itself, or ``v`` unreachable).
"""

from __future__ import annotations

import numpy as np

NO_PRED = -1


def reconstruct_path(pred_row: np.ndarray, source: int, target: int) -> list[int]:
    """Walk ``pred_row`` back from ``target`` to ``source``.

    Returns the vertex sequence ``[source, ..., target]``; an empty list if
    ``target`` is unreachable. Raises ValueError on a malformed array (walk
    longer than |V| — a cycle, which a correct shortest-path tree cannot
    contain).
    """
    if target == source:
        return [source]
    if pred_row[target] == NO_PRED:
        return []
    path = [int(target)]
    v = int(target)
    for _ in range(len(pred_row)):
        v = int(pred_row[v])
        path.append(v)
        if v == source:
            return path[::-1]
        if pred_row[v] == NO_PRED:
            break
    raise ValueError(
        f"predecessor array does not trace back from {target} to {source}"
    )


def _min_weight_edge_map(graph):
    """(sorted int64 keys u*V+v, min weight per key) for O(log E) edge
    lookups; parallel edges resolve to their minimum weight (the only one
    a shortest path can use)."""
    v = graph.num_nodes
    keys = graph.src.astype(np.int64) * v + graph.indices.astype(np.int64)
    order = np.argsort(keys, kind="stable")
    keys, w = keys[order], graph.weights[order]
    first = np.concatenate(([True], keys[1:] != keys[:-1]))
    starts = np.flatnonzero(first)
    wmin = np.minimum.reduceat(w, starts) if keys.size else w
    return keys[first], wmin


def validate_pred_tree(
    graph, dist, pred, sources, *, rtol: float = 1e-4, atol: float = 1e-4
) -> None:
    """Validate predecessor rows against their OWN distance rows — the
    shared invariant checker for every backend's ``--predecessors``
    output (trees need not be identical across backends, only valid).

    Checks, per row b (raises ValueError on the first violation):
      - root convention: ``pred[b, sources[b]] == NO_PRED``;
      - unreachable convention: ``dist[b, v] = +inf  ->  pred = NO_PRED``;
      - coverage: finite non-source v has a predecessor;
      - tightness: ``(pred[v], v)`` is a real edge with
        ``dist[pred[v]] + w == dist[v]`` within rtol/atol (the same
        tolerance family as ``ops.pred``'s extraction rule);
      - acyclicity: every finite vertex walks back to a root within |V|
        hops (pointer doubling — a predecessor cycle never terminates).

    ``dist``/``pred``: [B, V] (or [V] with a scalar source). Host numpy —
    this module stays JAX-free by design.
    """
    dist = np.atleast_2d(np.asarray(dist))
    pred = np.atleast_2d(np.asarray(pred))
    sources = np.atleast_1d(np.asarray(sources, np.int64))
    b, v = dist.shape
    if pred.shape != dist.shape:
        raise ValueError(f"pred shape {pred.shape} != dist shape {dist.shape}")
    keys, wmin = _min_weight_edge_map(graph)
    rows = np.arange(b)
    if not (pred[rows, sources] == NO_PRED).all():
        raise ValueError("pred[source] must be NO_PRED for every row")
    finite = np.isfinite(dist)
    if (pred[~finite] != NO_PRED).any():
        raise ValueError("unreachable vertices must have pred == NO_PRED")
    src_mask = np.zeros((b, v), bool)
    src_mask[rows, sources] = True
    missing = finite & ~src_mask & (pred == NO_PRED)
    if missing.any():
        bi, vi = np.argwhere(missing)[0]
        raise ValueError(
            f"reachable vertex {vi} (row {bi}) has no predecessor"
        )
    has = pred != NO_PRED
    bi, vi = np.nonzero(has)
    ui = pred[bi, vi].astype(np.int64)
    k = ui * v + vi
    pos = np.searchsorted(keys, k)
    edge_ok = (pos < keys.size) & (keys[np.minimum(pos, keys.size - 1)] == k)
    if not edge_ok.all():
        j = np.flatnonzero(~edge_ok)[0]
        raise ValueError(
            f"pred edge ({ui[j]} -> {vi[j]}) (row {bi[j]}) is not in the graph"
        )
    lhs = dist[bi, ui] + wmin[pos]
    rhs = dist[bi, vi]
    bad = ~np.isclose(lhs, rhs, rtol=rtol, atol=atol)
    if bad.any():
        j = np.flatnonzero(bad)[0]
        raise ValueError(
            f"pred edge ({ui[j]} -> {vi[j]}) (row {bi[j]}) is not tight: "
            f"dist[u] + w = {lhs[j]:g} != dist[v] = {rhs[j]:g}"
        )
    # Acyclicity via pointer doubling (NO_PRED absorbing).
    q = pred.astype(np.int64)
    for _ in range(max(1, int(np.ceil(np.log2(max(v, 2)))))):
        hop = np.take_along_axis(q, np.maximum(q, 0), axis=1)
        q = np.where(q >= 0, hop, q)
    if (q != NO_PRED).any():
        bi, vi = np.argwhere(q != NO_PRED)[0]
        raise ValueError(
            f"predecessor cycle reachable from vertex {vi} (row {bi})"
        )


def path_weight(graph, path: list[int]) -> float:
    """Total weight of ``path`` in ``graph`` (CSRGraph); +inf if any hop is
    not an edge. Parallel edges contribute their minimum weight."""
    total = 0.0
    for u, v in zip(path, path[1:]):
        row = slice(graph.indptr[u], graph.indptr[u + 1])
        hits = graph.indices[row] == v
        if not hits.any():
            return float("inf")
        total += float(graph.weights[row][hits].min())
    return total
