"""Shortest-path reconstruction from predecessor arrays.

Predecessor convention across the framework: ``pred[b, v]`` is the vertex
preceding ``v`` on a shortest path from ``sources[b]``; ``-1`` means "no
predecessor" (the source itself, or ``v`` unreachable).
"""

from __future__ import annotations

import numpy as np

NO_PRED = -1


def reconstruct_path(pred_row: np.ndarray, source: int, target: int) -> list[int]:
    """Walk ``pred_row`` back from ``target`` to ``source``.

    Returns the vertex sequence ``[source, ..., target]``; an empty list if
    ``target`` is unreachable. Raises ValueError on a malformed array (walk
    longer than |V| — a cycle, which a correct shortest-path tree cannot
    contain).
    """
    if target == source:
        return [source]
    if pred_row[target] == NO_PRED:
        return []
    path = [int(target)]
    v = int(target)
    for _ in range(len(pred_row)):
        v = int(pred_row[v])
        path.append(v)
        if v == source:
            return path[::-1]
        if pred_row[v] == NO_PRED:
            break
    raise ValueError(
        f"predecessor array does not trace back from {target} to {source}"
    )


def path_weight(graph, path: list[int]) -> float:
    """Total weight of ``path`` in ``graph`` (CSRGraph); +inf if any hop is
    not an edge. Parallel edges contribute their minimum weight."""
    total = 0.0
    for u, v in zip(path, path[1:]):
        row = slice(graph.indptr[u], graph.indptr[u + 1])
        hits = graph.indices[row] == v
        if not hits.any():
            return float("inf")
        total += float(graph.weights[row][hits].min())
    return total
