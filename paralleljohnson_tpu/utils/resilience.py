"""Fault-tolerant solve engine (ROADMAP open items 1-2).

Large runs must DEGRADE instead of dying: the observed failure modes are
HBM ``RESOURCE_EXHAUSTED`` during the RMAT-22 fan-out (worker crash) and
device calls that wedge forever when the TPU tunnel drops mid-stage.
Distributed APSP systems survive exactly these by making the batch the
unit of recovery and retrying with degraded resources (PAPERS.md: the
Spark APSP system's per-partition recomputation; RAPID-Graph's recursion
to smaller subproblems when a tier doesn't fit). This module supplies the
three mechanisms the solver composes:

- :class:`RetryPolicy` — bounded attempts with exponential backoff +
  deterministic jitter, and a per-attempt wall-clock deadline enforced by
  a watchdog thread. Python cannot kill a wedged XLA call, so the
  watchdog LOGS-AND-ABANDONS it: the hung call keeps its daemon thread,
  the solve records the abandoned stage and moves on (retry or raise).
- :class:`OOMDegrader` — classifies an exception as device/host OOM
  (``XlaRuntimeError``/``RESOURCE_EXHAUSTED``, the cpp backend's
  ``MemoryError``), clears the backend's rebuildable device caches, and
  halves the source batch (floor ``SolverConfig.min_source_batch``,
  re-consulting ``suggested_source_batch``) so the failed batch is
  re-solved smaller instead of crashing the run.
- :func:`check_rows_sane` — the distance-sanity guard: after any route
  converges, a cheap NaN / negative-at-source reduction that raises a
  diagnosable :class:`SolveCorruptionError` (route tag + iteration)
  instead of silently writing poisoned rows to checkpoints.

Deterministic fault injection (``utils.faults``) threads through
``run_stage`` so every retry / degrade / checkpoint-resume path is
exercised in tier-1 CPU tests without a TPU.

The round-9 pipelined fan-out composes with all of it: the staged D2H
download runs through ``run_stage`` too (stage ``"download"`` — same
retry policy, same watchdog deadline, same fault plan as compute), the
checkpoint writer's failures surface as :class:`SolveCorruptionError`
(``utils.checkpoint.AsyncCheckpointWriter``), and an OOM first collapses
the in-flight window to 1 — giving back the extra [B, V] carry — before
:class:`OOMDegrader` halves the batch.
"""

from __future__ import annotations

import dataclasses
import hashlib
import queue
import threading
import time
import warnings
from typing import Any, Callable

from paralleljohnson_tpu.utils.telemetry import NULL_TELEMETRY


class StageAbandonedError(RuntimeError):
    """A stage exceeded its per-attempt wall-clock deadline on every
    allowed attempt; the watchdog abandoned the hung device call(s)."""


class SolveCorruptionError(RuntimeError):
    """A converged route produced NaN rows or a negative/nonzero distance
    at a row's own source — corrupted results must never reach
    checkpoints or callers. Carries the route tag and iteration count so
    the failing kernel is diagnosable from the message alone."""


def is_oom_error(exc: BaseException) -> bool:
    """True iff ``exc`` is a device/host out-of-memory failure.

    Covers jaxlib's ``XlaRuntimeError`` with ``RESOURCE_EXHAUSTED`` (TPU
    HBM; matched by type name + message so no jaxlib import is needed
    here) and plain ``MemoryError`` (the cpp/numpy backends' equivalent,
    and the base class of ``faults.InjectedOOMError``).
    """
    if isinstance(exc, MemoryError):
        return True
    name = type(exc).__name__
    msg = str(exc)
    if name in ("XlaRuntimeError", "JaxRuntimeError", "RuntimeError"):
        return (
            "RESOURCE_EXHAUSTED" in msg
            or "Out of memory" in msg
            or "out of memory" in msg
            or "OOM" in msg
        )
    return False


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry schedule for one solve stage.

    max_attempts: total tries per stage (1 = no retry).
    backoff_s: sleep before attempt k is ``backoff_s * factor**(k-2)``
      (no sleep before the first attempt), plus jitter.
    factor: exponential backoff multiplier.
    jitter_frac: +/- fraction of the backoff added deterministically —
      derived from (stage, attempt) via sha256, NOT wall-clock random, so
      a replayed failing run schedules identically (the same property the
      fault-injection harness relies on).
    deadline_s: per-attempt wall-clock cap enforced by the watchdog
      thread; None disables the watchdog and runs calls inline.
    """

    max_attempts: int = 3
    backoff_s: float = 0.05
    factor: float = 2.0
    jitter_frac: float = 0.1
    deadline_s: float | None = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.deadline_s is not None and not self.deadline_s > 0:
            raise ValueError(
                f"deadline_s must be > 0 (or None), got {self.deadline_s}"
            )

    def backoff(self, stage: str, attempt: int) -> float:
        """Seconds to sleep before ``attempt`` (1-based; 0.0 for the
        first). Jitter is a deterministic function of (stage, attempt)."""
        if attempt <= 1:
            return 0.0
        base = self.backoff_s * self.factor ** (attempt - 2)
        digest = hashlib.sha256(f"{stage}#{attempt}".encode()).digest()
        unit = digest[0] / 255.0  # [0, 1]
        return base * (1.0 + self.jitter_frac * (2.0 * unit - 1.0))


def _run_with_watchdog(
    fn: Callable[[], Any], deadline_s: float, stage: str
) -> Any:
    """Run ``fn`` on a watchdog-supervised daemon thread; if it does not
    finish within ``deadline_s``, log and abandon it (the thread keeps
    running — a wedged XLA call is not interruptible from Python — but
    the solve regains control) and raise :class:`StageAbandonedError`."""
    out: queue.Queue = queue.Queue(maxsize=1)

    def target() -> None:
        try:
            out.put(("ok", fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            out.put(("err", e))

    worker = threading.Thread(
        target=target, name=f"pj-stage-{stage}", daemon=True
    )
    worker.start()
    try:
        kind, payload = out.get(timeout=deadline_s)
    except queue.Empty:
        warnings.warn(
            f"stage {stage!r} exceeded its {deadline_s:g}s deadline; "
            "abandoning the hung device call (its thread is left to die "
            "with the process)",
            RuntimeWarning,
            stacklevel=3,
        )
        raise StageAbandonedError(
            f"stage {stage!r} still running after {deadline_s:g}s"
        ) from None
    if kind == "err":
        raise payload
    return payload


class OOMDegrader:
    """Drives batch degradation when a fan-out batch OOMs.

    Owns the current source-batch size for one solve. On OOM it clears
    the backend's rebuildable device caches, halves the batch (floor
    ``min_batch``), and re-consults ``suggested_source_batch`` — after
    ``clear_caches`` the budget may admit a different cap (HBM pressure
    from layout caches is exactly what crashed the s22 worker). Raises
    the original error when the batch cannot shrink further.
    """

    def __init__(
        self,
        backend: Any,
        dgraph: Any,
        batch_size: int,
        *,
        min_batch: int = 8,
        with_pred: bool = False,
    ) -> None:
        self.backend = backend
        self.dgraph = dgraph
        self.batch_size = max(1, int(batch_size))
        self.min_batch = max(1, int(min_batch))
        self.with_pred = with_pred
        self.degradations = 0

    def degrade(self, exc: BaseException) -> int:
        """Shrink after an OOM; returns the new batch size or re-raises
        ``exc`` when already at the floor (or a single-row batch)."""
        if self.batch_size <= max(self.min_batch, 1):
            raise exc
        try:
            self.backend.clear_caches(self.dgraph)
        except Exception:  # noqa: BLE001 — hygiene must not mask the OOM
            pass
        new = max(self.min_batch, self.batch_size // 2)
        try:
            suggested = self.backend.suggested_source_batch(
                self.dgraph, with_pred=self.with_pred
            )
        except Exception:  # noqa: BLE001
            suggested = None
        if suggested:
            new = min(new, max(self.min_batch, int(suggested)))
        # suggested_source_batch can exceed the failing size (its model
        # missed the real pressure — that is why we are here); the halved
        # size always wins so the schedule is strictly decreasing.
        new = min(new, self.batch_size // 2)
        new = max(new, self.min_batch)
        self.batch_size = new
        self.degradations += 1
        return new


def run_stage(
    fn: Callable[[], Any],
    *,
    stage: str,
    policy: RetryPolicy,
    stats: Any = None,
    faults: Any = None,
    batch: int | None = None,
    retryable: Callable[[BaseException], bool] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    telemetry: Any = None,
) -> Any:
    """Run one solve stage under the retry policy.

    - ``faults``: a ``utils.faults.FaultPlan`` (or None). Fired once per
      attempt; an injected OOM/timeout/error surfaces exactly like the
      real failure it models, and an injected NaN plan poisons the
      result via ``faults.poison_rows`` at the call site (not here).
    - ``retryable``: predicate for transient errors worth a plain retry
      (default: watchdog abandons only). Deterministic solver errors
      (NegativeCycleError, ConvergenceError, ValueError) must never be
      retried — the caller's predicate keeps that contract. OOM is NOT
      retried here unless the predicate opts in: the fan-out's degrader
      owns OOM recovery (shrink the batch) at the call site.
    - ``telemetry``: a ``utils.telemetry.Telemetry`` (or None). Every
      attempt becomes a flight-recorder span named after the stage
      (attrs: batch, attempt; a failed attempt closes with its error),
      retries and watchdog abandons become events, and the heartbeat's
      stage/batch/attempt fields track the attempt that is LIVE — the
      record a killed worker leaves behind ends exactly at the attempt
      that was running.

    Every plain retry increments ``stats.retries``; every watchdog
    abandon appends ``"<stage>@a<attempt>"`` (plus ``#b<batch>``) to
    ``stats.abandoned_stages``.
    """
    tel = telemetry if telemetry is not None else NULL_TELEMETRY
    attempt = 0
    while True:
        attempt += 1
        wait = policy.backoff(stage, attempt)
        if wait > 0:
            sleep(wait)
        injected = faults.fire(stage, batch=batch) if faults is not None else None
        tel.progress(stage=stage, batch=batch, attempt=attempt)
        try:
            call = fn
            if injected is not None:
                call = injected.wrap(fn)
            with tel.span(stage, batch=batch, attempt=attempt):
                if policy.deadline_s is not None:
                    return _run_with_watchdog(call, policy.deadline_s, stage)
                return call()
        except StageAbandonedError as e:
            tag = stage + (f"#b{batch}" if batch is not None else "")
            tel.event("abandon", stage=stage, batch=batch, attempt=attempt)
            if stats is not None:
                stats.abandoned_stages.append(f"{tag}@a{attempt}")
            if attempt >= policy.max_attempts:
                raise StageAbandonedError(
                    f"stage {tag!r} abandoned on all "
                    f"{policy.max_attempts} attempts"
                ) from e
            if stats is not None:
                stats.retries += 1
            tel.event("retry", stage=stage, batch=batch, attempt=attempt,
                      error="StageAbandonedError")
        except Exception as e:  # noqa: BLE001 — classified below
            if retryable is not None and retryable(e) and attempt < policy.max_attempts:
                if stats is not None:
                    stats.retries += 1
                tel.event("retry", stage=stage, batch=batch, attempt=attempt,
                          error=type(e).__name__)
                continue
            raise


def check_rows_sane(
    rows: Any,
    batch_sources: Any = None,
    *,
    route: str | None,
    iteration: int,
    stage: str = "fanout",
) -> None:
    """Distance-sanity guard (satellite): NaN anywhere, or a nonzero /
    negative entry at a row's own source, means the kernel (or the
    hardware) corrupted the result — raise before it can reach a
    checkpoint or a caller. Runs in the array namespace of ``rows``
    (jnp reductions stay on device; only two scalars sync)."""
    from paralleljohnson_tpu.utils.reductions import xp as _xp

    xp = _xp(rows)
    if bool(xp.isnan(rows).any()):
        raise SolveCorruptionError(
            f"NaN distances out of converged stage {stage!r} "
            f"(route={route!r}, iteration={iteration})"
        )
    if batch_sources is not None and getattr(rows, "ndim", 1) == 2:
        b = rows.shape[0]
        own = rows[xp.arange(b), xp.asarray(batch_sources)]
        if bool((own != 0).any()):
            raise SolveCorruptionError(
                f"nonzero distance at a row's own source out of stage "
                f"{stage!r} (route={route!r}, iteration={iteration}): "
                "row i must have dist[i, sources[i]] == 0 on the "
                "non-negative reweighted graph"
            )
