"""Device-aware array reductions, shared by solver / CLI / benchmarks.

Rows from device backends stay resident on device (SURVEY.md §7: RMAT-22
rows must never be forced to host wholesale); every reduction here runs in
the namespace where the rows live, so reducing a device-resident [B, V]
block moves only the (small) result to the host.
"""

from __future__ import annotations

import numpy as np


def xp(rows):
    """numpy for host arrays, jax.numpy for device arrays."""
    if isinstance(rows, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


def finite_frac(rows) -> float:
    """Fraction of finite entries."""
    m = xp(rows)
    return float(m.isfinite(rows).mean())


def finite_checksum(rows) -> float:
    """Sum of finite entries (the streamed-rows reduction of the RMAT
    benchmark config).

    Accumulates per-ROW partial sums in the rows' dtype on device, then
    combines them in float64 on the host: at RMAT-22 scale (~5e8 finite
    f32 entries, totals ~1.25e9) a flat f32 accumulation is sensitive to
    reduction order — BASELINE.md shows jax-vs-cpp checksums diverging in
    the 7th digit. Per-row sums (~V terms each) keep the device reduction
    cheap while the f64 host combine removes the cross-row order
    sensitivity. (TPUs have no native f64; summing on host in f64 over
    [B] partials costs nothing.)"""
    m = xp(rows)
    row_sums = m.where(m.isfinite(rows), rows, 0.0).sum(axis=-1)
    return float(np.asarray(row_sums, dtype=np.float64).sum())
