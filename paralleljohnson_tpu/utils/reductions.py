"""Device-aware array reductions, shared by solver / CLI / benchmarks.

Rows from device backends stay resident on device (SURVEY.md §7: RMAT-22
rows must never be forced to host wholesale); every reduction here runs in
the namespace where the rows live, so reducing a device-resident [B, V]
block moves only the (small) result to the host.
"""

from __future__ import annotations

import numpy as np


def xp(rows):
    """numpy for host arrays, jax.numpy for device arrays."""
    if isinstance(rows, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


def finite_frac(rows) -> float:
    """Fraction of finite entries."""
    m = xp(rows)
    return float(m.isfinite(rows).mean())


def finite_checksum(rows) -> float:
    """Sum of finite entries (the streamed-rows reduction of the RMAT
    benchmark config)."""
    m = xp(rows)
    return float(m.where(m.isfinite(rows), rows, 0.0).sum())
