"""Platform selection workaround, shared by CLI / bench entry points.

A TPU PJRT plugin may monkeypatch jax's backend selection so that even
``JAX_PLATFORMS=cpu`` initializes the TPU client (observed with the axon
plugin: ``get_backend`` is wrapped and dials the device lease). The config
update below is what actually routes to CPU; ``tests/conftest.py`` performs
the same dance inline because it must also set ``XLA_FLAGS`` before jax's
first import.
"""

from __future__ import annotations

import os


def honor_cpu_platform_request() -> bool:
    """If the environment asks for CPU (``JAX_PLATFORMS=cpu``), force jax's
    platform config to cpu. Returns True iff the override was applied."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
