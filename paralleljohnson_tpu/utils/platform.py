"""Platform selection workaround, shared by CLI / bench entry points.

A TPU PJRT plugin may monkeypatch jax's backend selection so that even
``JAX_PLATFORMS=cpu`` initializes the TPU client (observed with the axon
plugin: ``get_backend`` is wrapped and dials the device lease). The config
update below is what actually routes to CPU; ``tests/conftest.py`` performs
the same dance inline because it must also set ``XLA_FLAGS`` before jax's
first import.
"""

from __future__ import annotations

import os


def honor_cpu_platform_request() -> bool:
    """If the environment asks for CPU (``JAX_PLATFORMS=cpu``), force jax's
    platform config to cpu. Returns True iff the override was applied."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Enable jax's persistent compilation cache (ROADMAP item 1: the
    3x-retry TPU measurement passes must stop re-paying Mosaic/XLA
    compiles inside precious tunnel windows).

    Opt-in resolution: an explicit ``cache_dir``
    (``SolverConfig.compilation_cache_dir`` / ``--compilation-cache-dir``)
    wins, else the ``PJ_COMPILE_CACHE`` env var; neither set is a no-op.
    jax also honors ``JAX_COMPILATION_CACHE_DIR`` natively — this hook
    exists so the CLI / SolverConfig path gets the cache without
    exporting jax-internal env vars, and so a broken cache dir degrades
    to a warning instead of killing the solve. Returns the resolved
    directory (created if needed) or None.
    """
    path = cache_dir or os.environ.get("PJ_COMPILE_CACHE") or None
    if not path:
        return None
    from pathlib import Path

    try:
        p = Path(path).expanduser()
        p.mkdir(parents=True, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", str(p))
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        import warnings

        warnings.warn(
            f"could not enable the jax compilation cache at {path!r}: "
            f"{type(e).__name__}: {e}; compiles will not persist",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
    return str(p)
