"""Instrumentation: per-phase wall-clock, iteration counts, and the attested
edges-relaxed counters (SURVEY.md §2 #13, BASELINE.json:2
"edges-relaxed/sec/chip")."""

from __future__ import annotations

import contextlib
import dataclasses
import time
import warnings
from collections import defaultdict


def warn_if_counter_wrapped(
    rounds: int, inner_cap: int, *, where: str
) -> None:
    """Achievable-bound wrap guard for the int32 per-block GS iteration
    counters (``ops.gauss_seidel._gs_engine`` exactness contract): the
    per-block total is bounded by 2 x outer_rounds x inner_cap, so the
    host-side Python-int accounting is exact while that bound stays
    below 2^31. One implementation shared by the single-device
    accounting (``backends.jax_backend._gs_examined_exact``) and the
    sharded path (``parallel.mesh.sharded_gs_fanout``) so the two
    routes carry the same guard (round-5 verdict weak #5). The bound is
    reachable only by a ~16.7M-round negative-cycle certification run
    at the default cap, so the warn is practically dead code — but the
    exactness claim is checked, not assumed."""
    if 2 * int(rounds) * int(inner_cap) >= 1 << 31:
        warnings.warn(
            f"{where}: GS iteration counter may have wrapped "
            f"({int(rounds)} outer rounds x inner_cap {int(inner_cap)}): "
            "edges_relaxed is a lower bound, not exact",
            RuntimeWarning,
            stacklevel=3,
        )


def warn_if_traj_counter_wrapped(
    batch: int, num_nodes: int, *, where: str
) -> None:
    """Addend wrap guard for the int32 convergence-trajectory counters
    (``observe.convergence``): one iteration's ``relaxations_applied``
    is bounded by batch x V distance labels, so the per-row int32 value
    is exact while that bound stays below 2^31 — the same no-overflow
    precondition the split examined counters of ``ops/bucket.py`` /
    ``ops/relax.bellman_ford_frontier`` enforce on their per-round
    addends. Shapes past the bound still record (the buffer write
    cannot raise inside jit), but the counts become warned lower
    bounds, never a silent lie. One implementation for every
    instrumented route (the round-6 shared-guard standard)."""
    if int(batch) * int(num_nodes) >= 1 << 31:
        warnings.warn(
            f"{where}: trajectory counter addend batch x V = "
            f"{int(batch)} x {int(num_nodes)} >= 2^31: frontier_size / "
            "relaxations_applied may have wrapped — treat the "
            "trajectory as a lower bound, not exact",
            RuntimeWarning,
            stacklevel=3,
        )


@dataclasses.dataclass
class SolverStats:
    """Accumulated per-solve instrumentation.

    phase_seconds: wall-clock per named phase (upload / bellman_ford /
      reweight / fanout / unreweight / batch_apsp).
    edges_relaxed: total edge relaxations across phases.
    edges_relaxed_by_phase / iterations_by_phase: breakdowns.
    batches_resumed: source batches skipped via checkpoint resume.
    retries: stage attempts re-run after a transient failure (watchdog
      abandon or retryable device error — utils.resilience.run_stage).
    oom_degradations: times the fan-out batch was halved after a device
      OOM (utils.resilience.OOMDegrader).
    final_batch: the source-batch size the fan-out ENDED at (None until
      a fan-out runs; equals the starting size when nothing degraded).
    abandoned_stages: "<stage>[#b<batch>]@a<attempt>" tags of every
      attempt the watchdog logged-and-abandoned past its deadline.
    download_s: total wall-clock in the fan-out's download/finalize
      stage (host materialization of device rows + checkpoint submit,
      or the streaming reducer). In serial mode (pipeline_depth=1) this
      sits on the critical path; pipelined it runs behind the next
      batch's compute.
    ckpt_wait_s: wall-clock the MAIN solve thread spent blocked on the
      pipeline — draining staged downloads and the checkpoint writer's
      flush barrier. This is the residual serial cost of the off-path
      work; near-zero means the overlap fully hid it.
    overlap_saved_s: estimated wall-clock the pipeline removed from the
      critical path (background stage busy time minus the time the main
      thread actually waited on it, floored at 0 per batch). Exactly 0
      for pipeline_depth=1 — the bench proof that an improvement came
      from overlap, not noise.
    final_pipeline_depth: the in-flight window the fan-out ENDED at
      (None until a fan-out runs): the configured pipeline_depth, or 1
      after an OOM collapsed the window (which happens BEFORE any batch
      halving).
    analytic_cost: accumulated compiled-cost capture (ISSUE 7,
      ``observe.costs``) — XLA's own flops / bytes_accessed /
      transcendentals summed over every captured kernel invocation,
      plus ``captures`` (how many landed), ``peak_memory_bytes`` (the
      largest single executable footprint), and ``unavailable`` (the
      distinct no-op markers of uninstrumented routes). None when
      capture is off (no profile store configured) or the backend
      reports no costs.
    roofline: the solve's roofline attribution
      (``observe.roofline.attribute_stats``): bound classification
      ("hbm" / "mxu" / "host-io" / "unknown"), the derived bandwidth
      and compute floors, and the arithmetic-intensity-vs-ridge
      reasoning. Set by the solver for every completed solve.
    predicted_s: the profile store's calibrated prediction for this
      solve's route/shape, made BEFORE this run's record landed (None
      without a store or calibration) — prediction vs ``compute_seconds``
      is the cost model's running accuracy check.
    convergence: per-phase trajectory summaries (ISSUE 9,
      ``observe.convergence.summarize_trajectory``): iterations,
      frontier half-life, tail-iteration fraction (frontier < 1% of V
      — the JFR opportunity number), estimated JFR-skippable edge
      fraction. None when the convergence observatory is off (no
      telemetry / profile store configured) or the resolved route is
      not trajectory-instrumented.
    trajectories: the raw decoded per-iteration arrays behind those
      summaries, keyed by phase (one ``[n, 3]`` array per kernel call;
      a multi-batch fan-out lands one per batch). Deliberately NOT in
      ``as_dict`` — the curves go to the profile store
      (``observe.finalize_solve``), not into every stats line.
    """

    phase_seconds: dict = dataclasses.field(
        default_factory=lambda: defaultdict(float)
    )
    edges_relaxed: int = 0
    edges_relaxed_by_phase: dict = dataclasses.field(
        default_factory=lambda: defaultdict(int)
    )
    iterations_by_phase: dict = dataclasses.field(
        default_factory=lambda: defaultdict(int)
    )
    routes_by_phase: dict = dataclasses.field(default_factory=dict)
    batches_resumed: int = 0
    retries: int = 0
    oom_degradations: int = 0
    final_batch: int | None = None
    abandoned_stages: list = dataclasses.field(default_factory=list)
    download_s: float = 0.0
    ckpt_wait_s: float = 0.0
    overlap_saved_s: float = 0.0
    final_pipeline_depth: int | None = None
    analytic_cost: dict | None = None
    roofline: dict | None = None
    predicted_s: float | None = None
    # Planner decision of the solve's dominant dispatch (ISSUE 14,
    # ``paralleljohnson_tpu.planner``): chosen plan, why-line,
    # candidate table (explicit ``unpriced`` markers), and the resolved
    # auto-tuned parameters; finalize_solve persists it as the
    # ``kind: "plan"`` profile record. None for ladder-coded backends.
    plan: dict | None = None
    convergence: dict | None = None
    trajectories: dict = dataclasses.field(default_factory=dict, repr=False)

    def accumulate(self, result, phase: str) -> None:
        """Fold one KernelResult into the totals."""
        self.edges_relaxed += int(result.edges_relaxed)
        self.edges_relaxed_by_phase[phase] += int(result.edges_relaxed)
        self.iterations_by_phase[phase] += int(result.iterations)
        self._accumulate_cost(getattr(result, "cost", None))
        self._accumulate_trajectory(result, phase)
        plan = getattr(result, "plan", None)
        if plan:
            # Last decision wins (a multi-batch fan-out re-plans per
            # batch with identical inputs); params already resolved by
            # the solver merge in higher layers.
            self.plan = plan
        route = getattr(result, "route", None)
        if route:
            # A phase can change route mid-solve (e.g. an auto route degrades
            # after batch k of a multi-batch fan-out).  Record every distinct
            # route in order of first appearance ("vm-blocked+vm"), not just
            # the last — last-write-wins misattributed the measured kernel in
            # bench rows (ADVICE round 4).
            prev = self.routes_by_phase.get(phase)
            if prev is None:
                self.routes_by_phase[phase] = route
            elif route not in prev.split("+"):
                self.routes_by_phase[phase] = prev + "+" + route

    def _accumulate_trajectory(self, result, phase: str) -> None:
        """Fold one KernelResult's convergence trajectory (ISSUE 9):
        the raw curve joins ``trajectories[phase]`` (the profile-store
        payload) and the backend-computed summary merges into
        ``convergence[phase]`` (batches / iterations_total accumulate
        across a multi-batch fan-out)."""
        traj = getattr(result, "trajectory", None)
        if traj is not None:
            self.trajectories.setdefault(phase, []).append(traj)
        summ = getattr(result, "convergence", None)
        if summ:
            from paralleljohnson_tpu.observe.convergence import (
                merge_summaries,
            )

            conv = self.convergence if self.convergence is not None else {}
            conv[phase] = merge_summaries(conv.get(phase), summ)
            self.convergence = conv

    def _accumulate_cost(self, cost: dict | None) -> None:
        """Fold one KernelResult's compiled-cost capture. Every CAPTURED
        invocation re-pays its analytic cost (a 4-batch fan-out moves
        the bytes 4 times); unavailable markers are recorded distinctly
        so "cheap" and "unmeasured" can never be confused."""
        if not cost:
            return
        acc = self.analytic_cost
        if acc is None:
            acc = {
                "flops": 0.0, "bytes_accessed": 0.0,
                "transcendentals": 0.0, "captures": 0, "unavailable": [],
            }
            self.analytic_cost = acc
        reason = cost.get("cost_analysis_unavailable")
        if reason is not None:
            if reason not in acc["unavailable"]:
                acc["unavailable"].append(reason)
        else:
            for k in ("flops", "bytes_accessed", "transcendentals"):
                acc[k] += float(cost.get(k, 0.0))
            acc["captures"] += 1
            # Distinct pricing sources ("analytic-model" for the
            # semiring routes XLA misprices — observe.costs.analytic)
            # ride along so a profile record always says HOW it was
            # priced, not just what the numbers are.
            src_tag = cost.get("cost_source")
            if src_tag and src_tag not in acc.setdefault(
                "cost_sources", []
            ):
                acc["cost_sources"].append(src_tag)
        mem = cost.get("memory")
        if mem and mem.get("peak_bytes"):
            acc["peak_memory_bytes"] = max(
                acc.get("peak_memory_bytes", 0), int(mem["peak_bytes"])
            )

    @property
    def total_seconds(self) -> float:
        return sum(self.phase_seconds.values())

    @property
    def compute_seconds(self) -> float:
        """Wall-clock in the numeric kernel phases — the denominator of
        the headline rate and the measurement the cost model calibrates
        seconds-per-byte/FLOP against."""
        return sum(
            s for k, s in self.phase_seconds.items()
            if k in ("bellman_ford", "fanout", "batch_apsp")
        )

    def edges_relaxed_per_second(self) -> float:
        """The headline metric (per chip: divide by mesh size at call site)."""
        compute = self.compute_seconds
        return self.edges_relaxed / compute if compute > 0 else 0.0

    def as_dict(self) -> dict:
        return {
            "phase_seconds": dict(self.phase_seconds),
            "edges_relaxed": self.edges_relaxed,
            "edges_relaxed_by_phase": dict(self.edges_relaxed_by_phase),
            "iterations_by_phase": dict(self.iterations_by_phase),
            "routes_by_phase": dict(self.routes_by_phase),
            "batches_resumed": self.batches_resumed,
            "retries": self.retries,
            "oom_degradations": self.oom_degradations,
            "final_batch": self.final_batch,
            "abandoned_stages": list(self.abandoned_stages),
            "download_s": self.download_s,
            "ckpt_wait_s": self.ckpt_wait_s,
            "overlap_saved_s": self.overlap_saved_s,
            "final_pipeline_depth": self.final_pipeline_depth,
            "analytic_cost": self.analytic_cost,
            "roofline": self.roofline,
            "predicted_s": self.predicted_s,
            "plan": self.plan,
            "convergence": self.convergence,
            "total_seconds": self.total_seconds,
            "edges_relaxed_per_sec": self.edges_relaxed_per_second(),
        }


def latency_percentiles(samples_ms, pcts=(50, 99)) -> dict:
    """``{"p50_ms": ..., "p99_ms": ...}`` over a latency sample list.

    Routed through the streaming log-bucket histogram
    (``observe.live.LogHistogram`` — ISSUE 12) so the sample-list path
    and the live serving path share ONE percentile definition: an
    estimate whose error is bounded by one bucket width (~19% relative)
    of the exact nearest-rank percentile, with the bound reported in
    the companion ``p<N>_err_ms`` keys — never an unflagged
    approximation. Accepts any iterable (generators included) and any
    sample count: empty input yields zeros (a store that served
    nothing), no pre-check required."""
    from paralleljohnson_tpu.observe.live import LogHistogram

    hist = LogHistogram()
    hist.record_many(float(s) for s in samples_ms)
    if hist.count == 0:
        out = {f"p{p}_ms": 0.0 for p in pcts}
        out.update({f"p{p}_err_ms": 0.0 for p in pcts})
        return out
    return hist.percentiles(pcts)


@contextlib.contextmanager
def phase_timer(stats: SolverStats, phase: str, telemetry=None):
    """Times a phase; also opens a ``jax.named_scope``-style profiler scope
    when JAX is importable so device traces attribute kernels to phases
    (SURVEY.md §5 tracing), and — when a telemetry object is threaded in
    (``utils.telemetry``) — a flight-recorder span plus a heartbeat
    stage update.

    The accumulation is in a ``finally``: a phase whose body RAISES still
    lands its elapsed time in ``phase_seconds``, so the flight record /
    stats of a crashed solve show where the time went (previously the
    failed phase silently vanished from the accounting)."""
    scope = contextlib.nullcontext()
    try:
        import jax

        scope = jax.named_scope(phase)
    except Exception:
        pass
    tel_span = contextlib.nullcontext()
    if telemetry:  # NULL_TELEMETRY is falsy — disabled skips entirely
        telemetry.progress(stage=phase)
        # "phase:" prefix: the fanout PHASE must not collide with the
        # per-batch "fanout" stage spans nested inside it.
        tel_span = telemetry.span(f"phase:{phase}", kind="phase")
    t0 = time.perf_counter()
    try:
        with scope, tel_span:
            yield
    finally:
        stats.phase_seconds[phase] += time.perf_counter() - t0
