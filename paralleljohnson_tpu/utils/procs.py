"""Child-process supervision shared by the driver entry points.

The single-tenant remote-compile tunnel wedges on hard-killed clients, so
every supervisor in this repo must stop children the same way: SIGTERM,
a real wait, SIGKILL only as a last resort, and tolerance for a child
that is unreapable (D-state on wedged device I/O) — the caller must get
control back to emit its own result/error, never an escaped
TimeoutExpired.
"""

from __future__ import annotations

import subprocess
import sys


def graceful_stop(
    p: subprocess.Popen, *, term_wait: float = 30, kill_wait: float = 10
) -> None:
    """Stop ``p`` gently; never raises."""
    if p.poll() is not None:
        return
    p.terminate()
    try:
        p.wait(term_wait)
    except subprocess.TimeoutExpired:
        p.kill()
        try:
            p.wait(kill_wait)
        except subprocess.TimeoutExpired:
            print(
                "WARNING: child unreapable after SIGKILL", file=sys.stderr
            )
