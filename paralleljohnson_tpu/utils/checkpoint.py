"""Checkpoint / resume for the N-source fan-out (SURVEY.md §5).

The unit of recovery is the source batch: each completed batch of distance
rows is written as an ``.npz`` keyed by batch index plus a hash of the
sources it covers; resuming skips batches whose file exists and matches.
Survives preemption mid-APSP (relevant for RMAT-22-scale runs on TPU pods).

:class:`AsyncCheckpointWriter` (the round-9 pipeline) moves the
serialization + checksumming + fsync of each commit onto a bounded
background writer thread so the solve's critical path only pays an
enqueue; the ``flush()`` barrier preserves resume semantics (the solve
does not return success until every commit landed), and a writer failure
surfaces as ``SolveCorruptionError`` on the next ``submit``/``flush`` —
never silent loss. Atomicity is unchanged: a write that dies mid-file
leaves only a ``.tmp.npz`` that ``load``/``completed_batches`` ignore.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.utils.resilience import SolveCorruptionError

MANIFEST_NAME = "manifest.json"


class ManifestOverlapError(ValueError):
    """Two shard manifests claim the same source vertex — merging them
    would make the global source -> batch-file map ambiguous. Raised
    loudly (naming both claiming files) rather than resolved silently:
    overlapping shards mean the fleet's lease table was violated."""


def read_manifest_file(directory: str | Path) -> dict | None:
    """The persisted per-shard ``manifest.json`` of one checkpoint
    (graph-level) directory, or None when absent/torn/not-a-manifest —
    the same tolerance as the checkpointer's own reader (callers fall
    back to a scan or fail loud, their choice)."""
    p = Path(directory) / MANIFEST_NAME
    if not p.exists():
        return None
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "files" not in data:
        return None
    return data


def union_manifests(
    directories: "list[str | Path]",
) -> dict[int, tuple[int, str]]:
    """Merge per-shard ``manifest.json`` files into ONE global map
    ``source -> (batch_idx, "<dir>/<filename>")`` — the multi-shard
    twin of :meth:`BatchCheckpointer.manifest` (ISSUE 10 satellite).

    Unlike the single-dir manifest (where a re-listed source is the
    same rows by construction), a source claimed by TWO DIFFERENT
    shards is rejected loudly with a :class:`ManifestOverlapError`
    naming both claiming batch files: shards are supposed to cover
    disjoint lease ranges, so an overlap is corruption (or a violated
    lease table), never something to resolve by pick-the-newest. A
    directory with no readable manifest raises ``ValueError`` with the
    path — a silent skip would turn a torn shard into serving misses.
    """
    out: dict[int, tuple[int, str]] = {}
    claimed_dir: dict[int, tuple[str, str]] = {}  # source -> (dir, file)
    for directory in directories:
        directory = Path(directory)
        data = read_manifest_file(directory)
        if data is None:
            raise ValueError(
                f"{directory / MANIFEST_NAME}: missing or unreadable shard "
                "manifest (is this a checkpoint graph directory?)"
            )
        dir_key = directory.as_posix()
        for filename in sorted(data["files"]):
            entry = data["files"][filename]
            ref = (directory / filename).as_posix()
            for s in entry["sources"]:
                s = int(s)
                prev = claimed_dir.get(s)
                if prev is not None and prev[0] != dir_key:
                    raise ManifestOverlapError(
                        f"source {s} claimed by both {prev[1]} and "
                        f"{ref} — shard manifests must cover disjoint "
                        "source ranges"
                    )
                # Within ONE shard a re-listed source is the same rows
                # by construction (checkpoints are keyed by graph
                # content) — newest listing wins, like manifest().
                claimed_dir[s] = (dir_key, ref)
                out[s] = (int(entry["batch"]), ref)
    return out


def _sources_digest(sources: np.ndarray) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(np.asarray(sources, np.int64)).tobytes()
    ).hexdigest()[:16]


def graph_digest(graph) -> str:
    """Content hash of a CSRGraph (structure + weights): checkpoints from a
    different or modified graph must never be resumed."""
    h = hashlib.sha256()
    for arr in (graph.indptr, graph.indices, graph.weights):
        a = np.ascontiguousarray(arr)
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class BatchCheckpointer:
    def __init__(self, directory: str | Path, *, graph_key=None) -> None:
        """``graph_key``: the CSRGraph (or a precomputed digest string) the
        rows belong to; rows are stored under a per-graph subdirectory."""
        self.dir = Path(directory)
        if graph_key is not None:
            digest = graph_key if isinstance(graph_key, str) else graph_digest(graph_key)
            self.dir = self.dir / f"graph_{digest}"
        self.dir.mkdir(parents=True, exist_ok=True)
        self._manifest_lock = threading.Lock()

    def _path(self, batch_idx: int, sources: np.ndarray) -> Path:
        return self.dir / f"rows_{batch_idx:06d}_{_sources_digest(sources)}.npz"

    @staticmethod
    def _sha(arr: np.ndarray) -> np.ndarray:
        return np.frombuffer(
            hashlib.sha256(np.ascontiguousarray(arr).tobytes()).digest(),
            np.uint8,
        )

    def save(
        self,
        batch_idx: int,
        sources: np.ndarray,
        rows: np.ndarray,
        *,
        pred: np.ndarray | None = None,
    ) -> Path:
        path = self._path(batch_idx, sources)
        tmp = path.with_suffix(".tmp.npz")
        payload = dict(
            sources=np.asarray(sources, np.int64),
            rows=rows,
            rows_sha=self._sha(rows),
        )
        if pred is not None:
            payload.update(pred=pred, pred_sha=self._sha(pred))
        np.savez_compressed(tmp, **payload)
        tmp.rename(path)  # atomic publish: partial writes never count as done
        # Manifest AFTER the row file is published: a crash between the
        # two leaves a valid-but-unlisted batch, which resume recomputes
        # and re-lists — never a listed-but-missing one.
        self._manifest_add(path.name, batch_idx, sources)
        return path

    # -- manifest (O(1) cold-tile lookup for the serving layer) --------------

    def _manifest_path(self) -> Path:
        return self.dir / MANIFEST_NAME

    def _read_manifest_file(self) -> dict | None:
        p = self._manifest_path()
        if not p.exists():
            return None
        try:
            data = json.loads(p.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return None  # torn/corrupt manifest -> callers fall back to scan
        if not isinstance(data, dict) or "files" not in data:
            return None
        return data

    def _write_manifest_file(self, data: dict) -> None:
        p = self._manifest_path()
        tmp = p.with_name(p.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(data), encoding="utf-8")
        os.replace(tmp, p)  # atomic: a reader never sees a torn manifest

    def _manifest_add(self, filename: str, batch_idx: int,
                      sources: np.ndarray) -> None:
        with self._manifest_lock:
            data = self._read_manifest_file() or {"version": 1, "files": {}}
            data["files"][filename] = {
                "batch": int(batch_idx),
                "sources": np.asarray(sources, np.int64).tolist(),
            }
            self._write_manifest_file(data)

    def _scan_files(self) -> list[Path]:
        # a crashed save leaves rows_*.tmp.npz — never published, not done
        return sorted(
            p for p in self.dir.glob("rows_*.npz")
            if not p.name.endswith(".tmp.npz")
        )

    def _rebuild_manifest(self) -> dict:
        """Pre-manifest directory: rescan every published batch file once,
        then persist the result so the next open is O(1) again."""
        data: dict = {"version": 1, "files": {}}
        for p in self._scan_files():
            try:
                with np.load(p) as npz:
                    sources = np.asarray(npz["sources"], np.int64)
            except Exception:  # noqa: BLE001 — corrupt batch: not listable
                continue
            data["files"][p.name] = {
                "batch": int(p.name.split("_")[1]),
                "sources": sources.tolist(),
            }
        try:
            self._write_manifest_file(data)
        except OSError:
            pass  # read-only store dir: serve from the in-memory rebuild
        return data

    def manifest(self) -> dict[int, tuple[int, str]]:
        """Source vertex -> ``(batch_idx, batch_filename)`` for every batch
        this directory holds — the O(1) cold-tile index the serving layer
        keys row lookups off (``serve.store.TileStore``). Served from the
        persisted ``manifest.json`` (written once per :meth:`save`);
        pre-manifest directories are rescanned once and the rebuilt
        manifest persisted. A source solved by several batches maps to
        the newest listing (identical rows either way: checkpoints are
        keyed by graph content)."""
        with self._manifest_lock:
            data = self._read_manifest_file()
            if data is None:
                data = self._rebuild_manifest()
        out: dict[int, tuple[int, str]] = {}
        for filename in sorted(data["files"]):
            entry = data["files"][filename]
            for s in entry["sources"]:
                out[int(s)] = (int(entry["batch"]), filename)
        return out

    def batch_sources(self, filename: str) -> np.ndarray | None:
        """The exact sources array a manifest-listed batch file covers
        (what :meth:`load` needs to re-derive the file's digest path)."""
        with self._manifest_lock:
            data = self._read_manifest_file()
        if data is None or filename not in data["files"]:
            return None
        return np.asarray(data["files"][filename]["sources"], np.int64)

    def load(
        self, batch_idx: int, sources: np.ndarray, *, with_pred: bool = False
    ) -> tuple[np.ndarray, np.ndarray | None] | None:
        """(rows, pred-or-None) for this batch, or None if absent or
        CORRUPT (recompute — fault detection per SURVEY.md §5: a
        bit-flipped or truncated batch result must be caught, not
        propagated into the APSP matrix). The unkeyed sha-256 detects
        accidental corruption only — anyone who can modify rows can
        recompute the digest, so deliberate tampering is out of scope.
        ``with_pred=True`` additionally requires a valid predecessor
        array — a rows-only checkpoint is treated as missing."""
        path = self._path(batch_idx, sources)
        if not path.exists():
            return None
        try:
            with np.load(path) as data:
                if not np.array_equal(data["sources"], np.asarray(sources, np.int64)):
                    return None
                rows = data["rows"]
                if "rows_sha" in data.files and not np.array_equal(
                    self._sha(rows), data["rows_sha"]
                ):
                    return None
                if not with_pred:
                    return rows, None
                if "pred" not in data.files:
                    return None
                pred = data["pred"]
                if not np.array_equal(self._sha(pred), data["pred_sha"]):
                    return None
                return rows, pred
        except Exception:
            pass
        return None

    def completed_batches(self) -> list[int]:
        """Batch indices with a published row file, via the persisted
        manifest (O(#batches), no directory re-hash per call); falls back
        to the glob scan for pre-manifest directories. Entries whose file
        has since been deleted are dropped — the manifest lists, the
        filesystem decides."""
        with self._manifest_lock:
            data = self._read_manifest_file()
        if data is None:
            return sorted(int(p.name.split("_")[1]) for p in self._scan_files())
        return sorted(
            int(e["batch"]) for f, e in data["files"].items()
            if (self.dir / f).exists()
        )


def checked_save(
    ckpt: BatchCheckpointer,
    batch_idx: int,
    sources: np.ndarray,
    rows: np.ndarray,
    *,
    pred: np.ndarray | None = None,
    fault_hook=None,
) -> None:
    """One checkpoint commit with the ``"ckpt_write"`` fault-injection
    point in front of it; ANY failure (injected or real — disk full,
    permission, serialization) surfaces as :class:`SolveCorruptionError`
    so a lost commit is always diagnosable, never silent. Shared by the
    serial (pipeline_depth=1) inline path and the background writer so
    both depths exercise identical failure semantics."""
    try:
        if fault_hook is not None:
            fault_hook(batch_idx)
        ckpt.save(batch_idx, sources, rows, pred=pred)
    except BaseException as e:  # noqa: BLE001 — re-raised, classified
        raise SolveCorruptionError(
            f"checkpoint write failed for batch {batch_idx}: "
            f"{type(e).__name__}: {e} (the batch is NOT committed; "
            "resume will recompute it)"
        ) from e


class AsyncCheckpointWriter:
    """Bounded background checkpoint writer (round-9 pipeline).

    ``submit`` enqueues one batch commit and returns immediately (it
    blocks only when ``max_pending`` commits are already queued — the
    backpressure that bounds host-memory carry); a single daemon worker
    drains the queue FIFO through :func:`checked_save`. ``flush`` is the
    barrier callers run before declaring the solve complete: it waits
    for the queue to drain and re-raises the first worker failure. A
    failure also re-raises on the next ``submit`` so a dead writer can
    never silently swallow later batches. ``close`` stops the worker
    after draining what is already queued (good rows still commit even
    when the solve is dying of an unrelated error — completed work stays
    resumable) and never raises.

    ``fault_hook(batch_idx)``: optional ``"ckpt_write"`` fault-injection
    point, fired on the WRITER thread so an injected death happens
    mid-commit exactly like a real one. ``busy_s`` accumulates worker
    busy time for the solver's overlap accounting.

    ``telemetry`` (``utils.telemetry.Telemetry`` or None): each commit
    becomes a ``"ckpt_write"`` flight-recorder span ON the writer thread
    (its own Chrome-trace track), parented to the span that submitted it
    — a worker killed mid-commit leaves that span open in the JSONL,
    which is the diagnosis.
    """

    def __init__(
        self,
        ckpt: BatchCheckpointer,
        *,
        max_pending: int = 2,
        fault_hook=None,
        telemetry=None,
    ) -> None:
        from paralleljohnson_tpu.utils.telemetry import NULL_TELEMETRY

        self.ckpt = ckpt
        self.fault_hook = fault_hook
        self._tel = telemetry if telemetry is not None else NULL_TELEMETRY
        self.busy_s = 0.0
        self.saved = 0
        self._exc: BaseException | None = None
        self._closed = False
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(max_pending)))
        self._worker = threading.Thread(
            target=self._loop, name="pj-ckpt-writer", daemon=True
        )
        self._worker.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                batch_idx, sources, rows, pred, parent = item
                t0 = time.perf_counter()
                try:
                    with self._tel.span(
                        "ckpt_write", batch=batch_idx, parent=parent
                    ):
                        checked_save(
                            self.ckpt, batch_idx, sources, rows, pred=pred,
                            fault_hook=self.fault_hook,
                        )
                    self.saved += 1
                except BaseException as e:  # noqa: BLE001 — relayed
                    if self._exc is None:
                        self._exc = e
                finally:
                    self.busy_s += time.perf_counter() - t0
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        e = self._exc
        if isinstance(e, SolveCorruptionError):
            raise e
        raise SolveCorruptionError(
            f"background checkpoint writer failed: {type(e).__name__}: {e}"
        ) from e

    def submit(
        self,
        batch_idx: int,
        sources: np.ndarray,
        rows: np.ndarray,
        *,
        pred: np.ndarray | None = None,
    ) -> None:
        """Enqueue one commit (blocks on backpressure; raises the stored
        writer failure instead of queueing onto a dead writer). The
        submitter's current span is captured here so the writer-thread
        ``ckpt_write`` span nests under the finalize that produced it."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        parent = self._tel.current_span_id()
        while True:
            if self._exc is not None:
                self._raise_pending()
            try:
                self._q.put(
                    (batch_idx, sources, rows, pred, parent), timeout=0.05
                )
                return
            except queue.Full:
                continue

    def flush(self) -> None:
        """Barrier: every submitted commit is on disk (or the first
        failure re-raises). Run before a checkpointed solve returns.
        After ``close`` this is a no-op — the close already drained the
        queue, and a failure it held was either surfaced on an earlier
        submit/flush or deliberately swallowed by the teardown path;
        re-raising it from a later flush would mask the original error
        (or raise out of a ``finally``)."""
        if self._closed:
            return
        self._q.join()
        if self._exc is not None:
            self._raise_pending()

    def close(self) -> None:
        """Drain what is queued, stop the worker, never raise (teardown
        path: an unrelated solve error must not be masked, and completed
        rows should still commit so resume can use them). Idempotent:
        double-close and close-after-dead-worker are no-ops."""
        if self._closed:
            return
        self._closed = True
        while True:
            try:
                self._q.put(None, timeout=0.1)
                break
            except queue.Full:
                if not self._worker.is_alive():
                    return
        self._worker.join(timeout=60.0)
