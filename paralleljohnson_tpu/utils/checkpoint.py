"""Checkpoint / resume for the N-source fan-out (SURVEY.md §5).

The unit of recovery is the source batch: each completed batch of distance
rows is written as an ``.npz`` keyed by batch index plus a hash of the
sources it covers; resuming skips batches whose file exists and matches.
Survives preemption mid-APSP (relevant for RMAT-22-scale runs on TPU pods).
"""

from __future__ import annotations

import hashlib
from pathlib import Path

import numpy as np


def _sources_digest(sources: np.ndarray) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(np.asarray(sources, np.int64)).tobytes()
    ).hexdigest()[:16]


def graph_digest(graph) -> str:
    """Content hash of a CSRGraph (structure + weights): checkpoints from a
    different or modified graph must never be resumed."""
    h = hashlib.sha256()
    for arr in (graph.indptr, graph.indices, graph.weights):
        a = np.ascontiguousarray(arr)
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class BatchCheckpointer:
    def __init__(self, directory: str | Path, *, graph_key=None) -> None:
        """``graph_key``: the CSRGraph (or a precomputed digest string) the
        rows belong to; rows are stored under a per-graph subdirectory."""
        self.dir = Path(directory)
        if graph_key is not None:
            digest = graph_key if isinstance(graph_key, str) else graph_digest(graph_key)
            self.dir = self.dir / f"graph_{digest}"
        self.dir.mkdir(parents=True, exist_ok=True)

    def _path(self, batch_idx: int, sources: np.ndarray) -> Path:
        return self.dir / f"rows_{batch_idx:06d}_{_sources_digest(sources)}.npz"

    @staticmethod
    def _sha(arr: np.ndarray) -> np.ndarray:
        return np.frombuffer(
            hashlib.sha256(np.ascontiguousarray(arr).tobytes()).digest(),
            np.uint8,
        )

    def save(
        self,
        batch_idx: int,
        sources: np.ndarray,
        rows: np.ndarray,
        *,
        pred: np.ndarray | None = None,
    ) -> Path:
        path = self._path(batch_idx, sources)
        tmp = path.with_suffix(".tmp.npz")
        payload = dict(
            sources=np.asarray(sources, np.int64),
            rows=rows,
            rows_sha=self._sha(rows),
        )
        if pred is not None:
            payload.update(pred=pred, pred_sha=self._sha(pred))
        np.savez_compressed(tmp, **payload)
        tmp.rename(path)  # atomic publish: partial writes never count as done
        return path

    def load(
        self, batch_idx: int, sources: np.ndarray, *, with_pred: bool = False
    ) -> tuple[np.ndarray, np.ndarray | None] | None:
        """(rows, pred-or-None) for this batch, or None if absent or
        CORRUPT (recompute — fault detection per SURVEY.md §5: a
        bit-flipped or truncated batch result must be caught, not
        propagated into the APSP matrix). The unkeyed sha-256 detects
        accidental corruption only — anyone who can modify rows can
        recompute the digest, so deliberate tampering is out of scope.
        ``with_pred=True`` additionally requires a valid predecessor
        array — a rows-only checkpoint is treated as missing."""
        path = self._path(batch_idx, sources)
        if not path.exists():
            return None
        try:
            with np.load(path) as data:
                if not np.array_equal(data["sources"], np.asarray(sources, np.int64)):
                    return None
                rows = data["rows"]
                if "rows_sha" in data.files and not np.array_equal(
                    self._sha(rows), data["rows_sha"]
                ):
                    return None
                if not with_pred:
                    return rows, None
                if "pred" not in data.files:
                    return None
                pred = data["pred"]
                if not np.array_equal(self._sha(pred), data["pred_sha"]):
                    return None
                return rows, pred
        except Exception:
            pass
        return None

    def completed_batches(self) -> list[int]:
        return sorted(
            int(p.name.split("_")[1])
            for p in self.dir.glob("rows_*.npz")
            # a crashed save leaves rows_*.tmp.npz — never published, not done
            if not p.name.endswith(".tmp.npz")
        )
