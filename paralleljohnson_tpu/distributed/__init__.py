"""Distributed solve fleet (ROADMAP item 5 — multi-host scale-out).

The single-host solver already survives OOMs, hung device calls, and
process kills (checkpoint/resume is the unit of recovery). This package
scales that resilience OUT: a **coordinator** partitions the source
space into leases (contiguous source ranges with an owner, a deadline,
and a ``pending -> leased -> committed`` state machine persisted as an
append-only JSONL), **workers** — one per host — claim leases and solve
their ranges through the ordinary resilient/pipelined solver into
per-worker checkpoint shard dirs, and a **shard manifest** unions the
per-worker manifests into one global source -> batch-file map that the
serving layer consumes unchanged. A worker whose lease deadline lapses
with a stale heartbeat has its range re-queued to survivors: a lost
host is a re-queued source range, not a dead run.

CPU-testable end to end with local worker subprocesses over a
filesystem coordinator dir; the TPU pod path runs the SAME coordinator
with one worker process per host (``worker --multihost`` calls
``parallel.multihost.initialize`` before solving).
"""

from paralleljohnson_tpu.distributed.coordinator import (
    Coordinator,
    CoordinatorError,
    Lease,
    StaleLeaseError,
)
from paralleljohnson_tpu.distributed.launch import (
    FleetReport,
    launch_local_fleet,
    plan_fleet,
)
from paralleljohnson_tpu.distributed.manifest import (
    FLEET_MANIFEST,
    ShardedCheckpointer,
    build_fleet_manifest,
    fleet_rows,
)
from paralleljohnson_tpu.distributed.worker import run_worker

__all__ = [
    "Coordinator",
    "CoordinatorError",
    "FLEET_MANIFEST",
    "FleetReport",
    "Lease",
    "ShardedCheckpointer",
    "StaleLeaseError",
    "build_fleet_manifest",
    "fleet_rows",
    "launch_local_fleet",
    "plan_fleet",
    "run_worker",
]
