"""Fleet coordinator — lease-based source sharding over a shared dir.

The coordination substrate is a plain directory (local disk in tests,
the pod's shared filesystem in production): no RPC server to keep alive,
so a killed coordinator PROCESS loses nothing — the state machine lives
in two files and any process that can see the directory can resume it.

  fleet.json    the immutable plan: graph spec + digest, the lease
                table (contiguous source ranges), deadlines, worker
                solver-config overrides. Written once at plan time.
  leases.jsonl  append-only transition log: ``claimed`` / ``committed``
                / ``requeued`` / ``extended`` events. Current state =
                replay(plan, log); a torn trailing line (a process
                killed mid-append) is tolerated exactly like the
                flight recorder's.

Every mutation is read-modify-append under an ``flock`` on
``<dir>/.lock``, so concurrent workers claiming over the same
filesystem serialize without a server process.

The lease state machine::

    pending --claim--> leased --commit--> committed
       ^                 |
       +---requeue-------+   (deadline lapsed + heartbeat stale,
                              worker released it on error, or the
                              owner restarted)

Deadline lapse alone does NOT requeue: a fresh heartbeat file (the
worker's :class:`~paralleljohnson_tpu.utils.telemetry.HeartbeatReporter`
writes it on its own daemon thread) proves the owner process is alive,
and the lease deadline is extended instead — slow-but-alive is not
dead. A stale or absent heartbeat at lapse requeues the range to the
survivors.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from pathlib import Path

FLEET_SPEC = "fleet.json"
LEASE_LOG = "leases.jsonl"
LOCK_FILE = ".lock"

PENDING = "pending"
LEASED = "leased"
COMMITTED = "committed"


class CoordinatorError(ValueError):
    """Malformed or inconsistent coordinator state (diagnosable: names
    the file and, for log corruption, the line)."""


class StaleLeaseError(RuntimeError):
    """A commit/release from a worker that no longer owns the lease —
    its deadline lapsed and the range was re-queued (and possibly
    re-solved) while it worked. The worker's rows stay on disk but are
    orphaned: the manifest union only references committing owners."""


@dataclasses.dataclass
class Lease:
    """One contiguous source range ``[start, stop)`` and its state."""

    lease_id: int
    start: int
    stop: int
    state: str = PENDING
    owner: str | None = None
    deadline: float | None = None
    committed_by: str | None = None
    requeues: int = 0
    extensions: int = 0

    @property
    def sources(self) -> range:
        return range(self.start, self.stop)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Coordinator:
    """Filesystem-backed lease coordinator (see module docstring).

    One instance per process; many processes may hold instances on the
    same directory — every mutation re-reads the log under the lock, so
    instances never cache state across calls.
    """

    def __init__(self, directory: str | Path) -> None:
        self.dir = Path(directory)
        spec_path = self.dir / FLEET_SPEC
        if not spec_path.exists():
            raise CoordinatorError(
                f"{spec_path}: no fleet plan here — create one with "
                "Coordinator.create (or `pjtpu fleet solve`)"
            )
        try:
            self.spec = json.loads(spec_path.read_text(encoding="utf-8"))
        except ValueError as e:
            raise CoordinatorError(f"{spec_path}: unreadable plan: {e}") from e
        for key in ("graph_spec", "graph_digest", "leases",
                    "lease_deadline_s", "heartbeat_stale_s"):
            if key not in self.spec:
                raise CoordinatorError(f"{spec_path}: plan missing {key!r}")

    # -- construction --------------------------------------------------------

    @classmethod
    def create(
        cls,
        directory: str | Path,
        *,
        graph_spec: str,
        graph_digest: str,
        num_sources: int,
        lease_sources: int,
        lease_deadline_s: float = 30.0,
        heartbeat_stale_s: float | None = None,
        heartbeat_interval_s: float | None = None,
        backend: str = "jax",
        config: dict | None = None,
        start: int = 0,
    ) -> "Coordinator":
        """Write the immutable fleet plan: the source space
        ``[start, start + num_sources)`` cut into ``lease_sources``-wide
        contiguous leases. Refuses a directory that already holds a plan
        (resume via :class:`Coordinator` / ``open`` instead — a second
        plan over live shards would orphan them silently)."""
        directory = Path(directory)
        if (directory / FLEET_SPEC).exists():
            raise CoordinatorError(
                f"{directory / FLEET_SPEC}: plan already exists — open the "
                "coordinator to resume, or point at a fresh directory"
            )
        if num_sources < 1:
            raise CoordinatorError(f"num_sources must be >= 1, got {num_sources}")
        if lease_sources < 1:
            raise CoordinatorError(
                f"lease_sources must be >= 1, got {lease_sources}"
            )
        if not lease_deadline_s > 0:
            raise CoordinatorError(
                f"lease_deadline_s must be > 0, got {lease_deadline_s}"
            )
        directory.mkdir(parents=True, exist_ok=True)
        leases = []
        lo = start
        i = 0
        while lo < start + num_sources:
            hi = min(lo + lease_sources, start + num_sources)
            leases.append([i, lo, hi])
            lo = hi
            i += 1
        spec = {
            "version": 1,
            "graph_spec": graph_spec,
            "graph_digest": graph_digest,
            "backend": backend,
            "num_sources": int(num_sources),
            "start": int(start),
            "lease_sources": int(lease_sources),
            "lease_deadline_s": float(lease_deadline_s),
            # Stale threshold defaults to 2x the deadline: one full
            # missed deadline's worth of silence past the last beat.
            "heartbeat_stale_s": float(
                heartbeat_stale_s if heartbeat_stale_s is not None
                else 2.0 * lease_deadline_s
            ),
            "heartbeat_interval_s": float(
                heartbeat_interval_s if heartbeat_interval_s is not None
                else max(0.2, min(5.0, lease_deadline_s / 5.0))
            ),
            "config": dict(config or {}),
            "leases": leases,
            "created_ts": time.time(),
        }
        tmp = directory / (FLEET_SPEC + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(spec, indent=2), encoding="utf-8")
        os.replace(tmp, directory / FLEET_SPEC)
        (directory / LEASE_LOG).touch()
        for sub in ("heartbeats", "shards", "telemetry", "workers", "logs",
                    "metrics"):
            (directory / sub).mkdir(exist_ok=True)
        return cls(directory)

    # -- paths ---------------------------------------------------------------

    def heartbeat_path(self, worker: str) -> Path:
        return self.dir / "heartbeats" / f"{worker}.json"

    def shard_dir(self, worker: str) -> Path:
        """The worker's checkpoint shard root (the ordinary solver
        ``checkpoint_dir`` — ``BatchCheckpointer`` adds its per-graph
        subdirectory underneath)."""
        return self.dir / "shards" / worker

    def telemetry_dir(self, worker: str) -> Path:
        return self.dir / "telemetry" / worker

    def worker_summary_path(self, worker: str) -> Path:
        return self.dir / "workers" / f"{worker}.summary.json"

    def metrics_path(self, worker: str) -> Path:
        """The worker's live-metrics snapshot (ISSUE 12): lease
        claim-to-commit latency histogram, solver batch walls,
        retry/OOM rates — atomically rewritten every heartbeat interval
        by the worker's ``MetricsRegistry`` snapshotter, joined
        fleet-wide by ``pjtpu top``."""
        return self.dir / "metrics" / f"{worker}.json"

    # -- log machinery -------------------------------------------------------

    @contextlib.contextmanager
    def _locked(self):
        import fcntl

        fd = os.open(self.dir / LOCK_FILE, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _append(self, event: dict) -> None:
        event.setdefault("ts", time.time())
        with open(self.dir / LEASE_LOG, "a", encoding="utf-8") as f:
            f.write(json.dumps(event) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def _replay(self) -> dict[int, Lease]:
        leases = {
            int(i): Lease(lease_id=int(i), start=int(lo), stop=int(hi))
            for i, lo, hi in self.spec["leases"]
        }
        log = self.dir / LEASE_LOG
        if not log.exists():
            return leases
        lines = log.read_text(encoding="utf-8").splitlines()
        for n, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                if n == len(lines) - 1:
                    continue  # torn trailing line: killed mid-append
                raise CoordinatorError(
                    f"{log}:{n + 1}: corrupt lease event (not the last "
                    "line — this is not kill damage)"
                ) from None
            lease = leases.get(int(ev.get("lease", -1)))
            if lease is None:
                raise CoordinatorError(
                    f"{log}:{n + 1}: event for unknown lease "
                    f"{ev.get('lease')!r}"
                )
            kind = ev.get("ev")
            if kind == "claimed" and lease.state == PENDING:
                lease.state = LEASED
                lease.owner = ev["worker"]
                lease.deadline = float(ev["deadline"])
            elif kind == "committed" and lease.state == LEASED \
                    and lease.owner == ev.get("worker"):
                lease.state = COMMITTED
                lease.committed_by = ev["worker"]
            elif kind == "requeued" and lease.state == LEASED:
                lease.state = PENDING
                lease.owner = None
                lease.deadline = None
                lease.requeues += 1
            elif kind == "extended" and lease.state == LEASED \
                    and lease.owner == ev.get("worker"):
                lease.deadline = float(ev["deadline"])
                lease.extensions += 1
            else:
                raise CoordinatorError(
                    f"{log}:{n + 1}: invalid transition {kind!r} on lease "
                    f"{lease.lease_id} in state {lease.state!r} "
                    f"(owner {lease.owner!r}, event worker "
                    f"{ev.get('worker')!r})"
                )
        return leases

    # -- heartbeat liveness --------------------------------------------------

    def _owner_alive(self, worker: str, now: float) -> bool:
        """True when the worker's heartbeat file is fresher than the
        plan's stale threshold — process-liveness, not progress (a
        worker hung inside a device call is bounded by its own stage
        watchdog, which either errors the stage or kills the process)."""
        from paralleljohnson_tpu.utils.telemetry import heartbeat_fresh

        return heartbeat_fresh(
            self.heartbeat_path(worker),
            self.spec["heartbeat_stale_s"],
            now=now,
        )

    def _reap_locked(self, leases: dict[int, Lease], now: float) -> list[dict]:
        """Deadline-lapse scan (call under the lock): stale owner ->
        requeue, fresh owner -> extend. Returns the appended events."""
        events = []
        for lease in leases.values():
            if lease.state != LEASED or lease.deadline is None:
                continue
            if now < lease.deadline:
                continue
            if self._owner_alive(lease.owner, now):
                new_deadline = now + self.spec["lease_deadline_s"]
                ev = {"ev": "extended", "lease": lease.lease_id,
                      "worker": lease.owner, "deadline": new_deadline,
                      "ts": now}
                lease.deadline = new_deadline
                lease.extensions += 1
            else:
                ev = {"ev": "requeued", "lease": lease.lease_id,
                      "worker": lease.owner, "reason": "deadline", "ts": now}
                lease.state = PENDING
                lease.owner = None
                lease.deadline = None
                lease.requeues += 1
            self._append(ev)
            events.append(ev)
        return events

    # -- the worker-facing API ----------------------------------------------

    def claim(self, worker: str, *, now: float | None = None) -> Lease | None:
        """Claim the lowest-id pending lease (after a reap pass, so an
        expired dead owner's range is claimable immediately). None when
        nothing is pending — the caller polls; outstanding leases may
        still be re-queued by a later reap."""
        now = time.time() if now is None else now
        with self._locked():
            leases = self._replay()
            self._reap_locked(leases, now)
            for lease in sorted(leases.values(), key=lambda l: l.lease_id):
                if lease.state == PENDING:
                    deadline = now + self.spec["lease_deadline_s"]
                    self._append({
                        "ev": "claimed", "lease": lease.lease_id,
                        "worker": worker, "deadline": deadline, "ts": now,
                    })
                    lease.state = LEASED
                    lease.owner = worker
                    lease.deadline = deadline
                    return lease
        return None

    def commit(self, lease_id: int, worker: str,
               *, now: float | None = None) -> Lease:
        """Mark a leased range solved-and-checkpointed. Raises
        :class:`StaleLeaseError` when ``worker`` no longer owns it (the
        deadline lapsed and the range was re-queued mid-solve) — the
        caller drops the lease and moves on; its rows stay orphaned."""
        now = time.time() if now is None else now
        with self._locked():
            leases = self._replay()
            lease = self._lease_or_die(leases, lease_id)
            if lease.state != LEASED or lease.owner != worker:
                raise StaleLeaseError(
                    f"lease {lease_id} is {lease.state} "
                    f"(owner {lease.owner!r}), not leased by {worker!r} — "
                    "its deadline lapsed and the range was re-queued"
                )
            self._append({"ev": "committed", "lease": lease_id,
                          "worker": worker, "ts": now})
            lease.state = COMMITTED
            lease.committed_by = worker
            return lease

    def release(self, lease_id: int, worker: str, *, reason: str,
                now: float | None = None) -> None:
        """Voluntarily requeue a lease the worker cannot finish (solve
        error, shutdown). Stale releases raise like stale commits."""
        now = time.time() if now is None else now
        with self._locked():
            leases = self._replay()
            lease = self._lease_or_die(leases, lease_id)
            if lease.state != LEASED or lease.owner != worker:
                raise StaleLeaseError(
                    f"lease {lease_id} is {lease.state} "
                    f"(owner {lease.owner!r}), not leased by {worker!r}"
                )
            self._append({"ev": "requeued", "lease": lease_id,
                          "worker": worker, "reason": reason, "ts": now})

    def recover_worker(self, worker: str, *, now: float | None = None) -> list[int]:
        """Requeue every lease ``worker`` holds — run at WORKER STARTUP.
        A restarted worker reusing its id would otherwise vouch (via its
        fresh heartbeat) for leases its previous incarnation died
        holding, extending them forever."""
        now = time.time() if now is None else now
        requeued = []
        with self._locked():
            leases = self._replay()
            for lease in leases.values():
                if lease.state == LEASED and lease.owner == worker:
                    self._append({
                        "ev": "requeued", "lease": lease.lease_id,
                        "worker": worker, "reason": "owner-restart",
                        "ts": now,
                    })
                    requeued.append(lease.lease_id)
        return requeued

    def reap(self, *, now: float | None = None) -> list[dict]:
        """One deadline-lapse scan (the launcher's monitor loop calls
        this; workers get the same scan for free inside :meth:`claim`).
        Returns the requeue/extend events appended."""
        now = time.time() if now is None else now
        with self._locked():
            return self._reap_locked(self._replay(), now)

    @staticmethod
    def _lease_or_die(leases: dict[int, Lease], lease_id: int) -> Lease:
        lease = leases.get(int(lease_id))
        if lease is None:
            raise CoordinatorError(f"unknown lease id {lease_id}")
        return lease

    # -- introspection -------------------------------------------------------

    def leases(self) -> list[Lease]:
        with self._locked():
            state = self._replay()
        return [state[i] for i in sorted(state)]

    def done(self) -> bool:
        return all(l.state == COMMITTED for l in self.leases())

    def status(self, *, now: float | None = None) -> dict:
        """One machine-readable snapshot (``pjtpu fleet status``):
        lease counts by state, total requeues/extensions, per-worker
        committed-lease counts, heartbeat ages, and the outstanding
        leases with owner + seconds-to-deadline."""
        now = time.time() if now is None else now
        leases = self.leases()
        by_state: dict[str, int] = {PENDING: 0, LEASED: 0, COMMITTED: 0}
        committed_by: dict[str, int] = {}
        outstanding = []
        for lease in leases:
            by_state[lease.state] += 1
            if lease.committed_by:
                committed_by[lease.committed_by] = (
                    committed_by.get(lease.committed_by, 0) + 1
                )
            if lease.state == LEASED:
                outstanding.append({
                    "lease": lease.lease_id,
                    "range": [lease.start, lease.stop],
                    "owner": lease.owner,
                    "deadline_in_s": round(lease.deadline - now, 3),
                })
        heartbeats = {}
        hb_dir = self.dir / "heartbeats"
        if hb_dir.is_dir():
            for p in sorted(hb_dir.glob("*.json")):
                worker = p.stem
                try:
                    from paralleljohnson_tpu.utils.telemetry import (
                        read_heartbeat,
                    )

                    hb = read_heartbeat(p)
                    age = None if hb is None else round(
                        now - float(hb.get("ts", 0.0)), 3
                    )
                except ValueError:
                    age = "unreadable"
                heartbeats[worker] = {
                    "age_s": age,
                    "alive": self._owner_alive(worker, now),
                }
        return {
            "dir": str(self.dir),
            "graph_spec": self.spec["graph_spec"],
            "graph_digest": self.spec["graph_digest"],
            "num_sources": self.spec["num_sources"],
            "leases_total": len(leases),
            "leases": by_state,
            "requeues": sum(l.requeues for l in leases),
            "extensions": sum(l.extensions for l in leases),
            "committed_by": committed_by,
            "outstanding": outstanding,
            "heartbeats": heartbeats,
            "done": by_state[COMMITTED] == len(leases),
        }
