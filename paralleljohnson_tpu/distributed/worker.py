"""Fleet worker — one per host; claims leases and solves them through
the ordinary resilient/pipelined solver.

A worker is deliberately thin: every hard problem it has (retries,
watchdog deadlines, OOM degradation, checkpoint/resume, pipelining,
telemetry) is the single-host solver's, unchanged. What the worker adds:

- a claim/solve/commit loop against the filesystem coordinator;
- a per-worker **checkpoint shard dir** (``<coord>/shards/<worker>``)
  — the ordinary ``SolverConfig.checkpoint_dir``, so a re-claimed lease
  on the SAME worker resumes from its own completed batches, and the
  fleet manifest unions the per-shard ``BatchCheckpointer`` manifests;
- a per-worker heartbeat file (``<coord>/heartbeats/<worker>.json``,
  the existing :class:`HeartbeatReporter`) whose freshness is how the
  coordinator distinguishes slow-but-alive (extend the lease) from
  dead (requeue the range);
- a per-worker flight-recorder dir (``<coord>/telemetry/<worker>``)
  labeled by worker id — ``scripts/trace_summary.py --merge`` joins a
  whole fleet's dirs into one post-mortem timeline.

Run as a subprocess (the local CPU fleet / tests)::

    python -m paralleljohnson_tpu.distributed.worker <coord-dir> \
        --worker-id w0

or on each host of a TPU pod slice (standard SPMD launch)::

    python -m paralleljohnson_tpu.distributed.worker <coord-dir> \
        --worker-id host$JAX_PROCESS_ID --multihost
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.distributed.coordinator import (
    Coordinator,
    CoordinatorError,
    StaleLeaseError,
)


def _write_json_atomic(path: Path, payload: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(payload), encoding="utf-8")
    os.replace(tmp, path)


def run_worker(
    coordinator_dir: str | Path,
    worker_id: str,
    *,
    config_overrides: dict | None = None,
    max_leases: int | None = None,
    poll_s: float = 0.25,
    idle_timeout_s: float = 600.0,
    self_kill_after_claims: int | None = None,
    tune_dir: str | Path | None = None,
) -> dict:
    """Claim-solve-commit until the fleet is done (or ``max_leases``).

    ``tune_dir``: optional tuning-fleet directory (ISSUE 19,
    :func:`paralleljohnson_tpu.tuner.plan_tuning_fleet`). When the solve
    coordinator has no claimable lease, the worker claims ONE tuning
    lease from ``tune_dir`` instead of sleeping — idle fleet capacity
    becomes calibration probes. Solve leases always win: tuning is only
    attempted when ``claim`` comes back empty.

    ``self_kill_after_claims=k``: after the k-th successful claim the
    worker SIGKILLs itself mid-lease — the deterministic host-loss
    injection the requeue tests and the fleet dryrun use (an abrupt
    death with a lease held and no cleanup, exactly like a crashed or
    OOM-killed host).

    Returns (and persists to ``<coord>/workers/<id>.summary.json``) a
    summary: leases committed, sources solved, edges relaxed, stale
    commits, wall seconds.
    """
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.graphs import load_graph
    from paralleljohnson_tpu.observe.live import MetricsRegistry
    from paralleljohnson_tpu.observe.trace import (
        current_trace_id,
        trace_attrs as _trace_attrs,
    )
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver
    from paralleljohnson_tpu.utils.checkpoint import graph_digest
    from paralleljohnson_tpu.utils.telemetry import Telemetry

    coord = Coordinator(coordinator_dir)
    spec = coord.spec
    t0 = time.perf_counter()

    tel = Telemetry.create(
        trace_dir=coord.telemetry_dir(worker_id),
        heartbeat_file=coord.heartbeat_path(worker_id),
        heartbeat_interval_s=float(spec["heartbeat_interval_s"]),
        label=f"worker-{worker_id}",
    )
    # Live metrics (ISSUE 12): claim-to-commit lease latency + the
    # solver's batch walls/retry rates, atomically snapshotted into
    # <coord>/metrics/<worker>.json on the heartbeat's clock — a
    # SIGKILLed worker leaves a view fresh to within one interval, and
    # `pjtpu top` joins every worker's snapshot into the fleet picture.
    metrics = MetricsRegistry(
        label=f"worker-{worker_id}", telemetry=tel
    ).start_snapshotter(
        coord.metrics_path(worker_id),
        interval_s=float(spec["heartbeat_interval_s"]),
    )
    lease_hist = metrics.histogram("pjtpu_lease_wall_ms")
    summary = {
        "worker": worker_id,
        "pid": os.getpid(),
        "leases_committed": [],
        "sources_solved": 0,
        "edges_relaxed": 0,
        "stale_commits": 0,
        "claims": 0,
        "tuning_leases": 0,
        "wall_s": 0.0,
        "rc": 0,
    }
    try:
        graph = load_graph(spec["graph_spec"])
        digest = graph_digest(graph)
        if digest != spec["graph_digest"]:
            raise CoordinatorError(
                f"{coord.dir / 'fleet.json'}: graph digest mismatch — plan "
                f"expects {spec['graph_digest']}, spec "
                f"{spec['graph_spec']!r} loads as {digest}; a fleet must "
                "never mix rows from different graphs"
            )
        # A restarted worker must not let its fresh heartbeat vouch for
        # leases its previous incarnation died holding.
        requeued = coord.recover_worker(worker_id)
        if requeued and tel:
            tel.event("lease_requeued", worker=worker_id,
                      leases=requeued, reason="owner-restart")

        cfg_kwargs = dict(spec.get("config") or {})
        cfg_kwargs.update(config_overrides or {})
        cfg_kwargs["backend"] = cfg_kwargs.get("backend", spec["backend"])
        cfg_kwargs["checkpoint_dir"] = str(coord.shard_dir(worker_id))
        cfg_kwargs["telemetry"] = tel
        cfg_kwargs["metrics"] = metrics
        solver = ParallelJohnsonSolver(SolverConfig(**cfg_kwargs))

        idle_since = None
        while True:
            if max_leases is not None and summary["claims"] >= max_leases:
                break
            lease = coord.claim(worker_id)
            if lease is None:
                if coord.done():
                    break
                if tune_dir is not None:
                    # Idle-capacity farm (ISSUE 19): no solve lease to
                    # claim, so run one calibration probe lease instead
                    # of sleeping. Probes run under their own wall-clock
                    # caps, so a solve lease freed meanwhile is picked up
                    # within one probe budget.
                    from paralleljohnson_tpu.tuner import try_tuning_lease

                    tuned = try_tuning_lease(tune_dir, worker_id)
                    if tuned is not None:
                        summary["tuning_leases"] += 1
                        if tel:
                            tel.event("tuning_lease", worker=worker_id,
                                      lease=tuned["lease"],
                                      probes=len(tuned["probes"]),
                                      **_trace_attrs())
                        idle_since = None
                        continue
                # Outstanding leases belong to other workers; they will
                # either commit or be re-queued by a reap — poll, with a
                # hard idle cap so an orphaned worker cannot spin forever.
                idle_since = idle_since or time.perf_counter()
                if time.perf_counter() - idle_since > idle_timeout_s:
                    raise TimeoutError(
                        f"worker {worker_id}: no claimable lease for "
                        f"{idle_timeout_s:.0f}s and the fleet is not done"
                    )
                time.sleep(poll_s)
                continue
            idle_since = None
            summary["claims"] += 1
            t_claim = time.perf_counter()
            metrics.counter("pjtpu_lease_claims").add(1)
            if (
                self_kill_after_claims is not None
                and summary["claims"] >= self_kill_after_claims
            ):
                # Injected host loss: die abruptly WITH the lease held.
                # flush=True then SIGKILL — no atexit, no finally, no
                # lease release: exactly what a crashed host looks like.
                print(f"FLEET-WORKER {worker_id}: self-kill holding lease "
                      f"{lease.lease_id}", flush=True)
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
            if tel:
                # ISSUE 20: leases claimed on behalf of a traced update
                # carry the originating trace id so the assembler can
                # join worker flights into the request's timeline.
                tel.event("lease_claimed", worker=worker_id,
                          lease=lease.lease_id,
                          start=lease.start, stop=lease.stop,
                          **_trace_attrs())
                tel.progress(worker=worker_id, lease=lease.lease_id,
                             lease_range=[lease.start, lease.stop])
            try:
                res = solver.solve_range(graph, lease.start, lease.stop)
            except Exception:
                # Give the range back before dying: survivors take it
                # without waiting out the deadline.
                try:
                    coord.release(lease.lease_id, worker_id, reason="error")
                    if tel:
                        tel.event("lease_requeued", worker=worker_id,
                                  lease=lease.lease_id, reason="error",
                                  **_trace_attrs())
                except StaleLeaseError:
                    pass
                raise
            try:
                coord.commit(lease.lease_id, worker_id)
            except StaleLeaseError:
                # Deadline lapsed mid-solve and someone re-queued the
                # range: drop it (the rows stay orphaned in this shard;
                # the manifest union only references committing owners).
                summary["stale_commits"] += 1
                metrics.counter("pjtpu_lease_stale_commits").add(1)
                if tel:
                    tel.event("lease_stale_commit", worker=worker_id,
                              lease=lease.lease_id, **_trace_attrs())
                continue
            # Claim-to-commit wall: what a lease actually costs this
            # worker (solve + checkpoint + coordinator round trips) —
            # the number lease sizing will be priced against.
            lease_hist.record((time.perf_counter() - t_claim) * 1e3,
                              exemplar=current_trace_id())
            metrics.counter("pjtpu_leases_committed").add(1)
            summary["leases_committed"].append(lease.lease_id)
            summary["sources_solved"] += lease.stop - lease.start
            summary["edges_relaxed"] += int(res.stats.edges_relaxed)
            if tel:
                tel.event("lease_committed", worker=worker_id,
                          lease=lease.lease_id, **_trace_attrs())
                tel.progress(leases_committed=len(summary["leases_committed"]))
    except BaseException as e:
        summary["rc"] = 1
        summary["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        summary["wall_s"] = round(time.perf_counter() - t0, 6)
        metrics.stop_snapshotter()
        try:
            _write_json_atomic(coord.worker_summary_path(worker_id), summary)
        except OSError:
            pass  # a read-only coordinator dir still solved the leases
        if tel is not None:
            tel.close()
    return summary


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="paralleljohnson_tpu.distributed.worker",
        description="fleet worker: claim leases from a coordinator dir and "
                    "solve them through the resilient solver",
    )
    ap.add_argument("coordinator_dir")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--max-leases", type=int, default=None)
    ap.add_argument("--poll-s", type=float, default=0.25)
    ap.add_argument("--idle-timeout-s", type=float, default=600.0)
    ap.add_argument("--multihost", action="store_true",
                    help="call parallel.multihost.initialize() before "
                         "building the solver (TPU pod: one worker process "
                         "per host; env-driven JAX_COORDINATOR_ADDRESS / "
                         "JAX_NUM_PROCESSES / JAX_PROCESS_ID)")
    ap.add_argument("--self-kill-after-claims", type=int, default=None,
                    help="TEST HOOK: SIGKILL self after the Nth claim, "
                         "lease held (deterministic host-loss injection)")
    ap.add_argument("--tune-dir", default=None,
                    help="idle-capacity tuning (ISSUE 19): when the solve "
                         "coordinator has no claimable lease, drain one "
                         "probe lease from this tuning-fleet dir instead "
                         "of sleeping")
    args = ap.parse_args(argv)

    from paralleljohnson_tpu.utils.platform import honor_cpu_platform_request

    honor_cpu_platform_request()
    if args.multihost:
        from paralleljohnson_tpu.parallel import multihost

        multihost.initialize()
    try:
        summary = run_worker(
            args.coordinator_dir,
            args.worker_id,
            max_leases=args.max_leases,
            poll_s=args.poll_s,
            idle_timeout_s=args.idle_timeout_s,
            self_kill_after_claims=args.self_kill_after_claims,
            tune_dir=args.tune_dir,
        )
    except (CoordinatorError, ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
