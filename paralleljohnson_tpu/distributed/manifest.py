"""Shard-manifest union — one global source -> batch-file map per fleet.

Each worker checkpoints through the ordinary ``BatchCheckpointer`` into
its own shard dir, so after a fleet run the rows of one graph are spread
over ``<coord>/shards/<worker>/graph_<digest>/`` directories, each with
its own per-shard ``manifest.json``. This module unions them into a
single ``fleet_manifest.json`` at the coordinator root, and adapts it
back to the ``BatchCheckpointer`` read protocol so downstream consumers
(``serve.store.TileStore``, ``fleet_rows``) work unchanged.

The union is **lease-aware**: only batches belonging to a COMMITTED
lease, read from the shard of the worker that committed it, are
referenced. A worker that died (or went stale) mid-lease may have left
perfectly valid batches behind — those are *orphaned*, counted but
never served, because the re-queued range was re-solved and committed
by another worker and serving both would double-claim sources. Within
the referenced set, any source claimed twice is a loud
:class:`~paralleljohnson_tpu.utils.checkpoint.ManifestOverlapError`
(it would mean the lease table itself overlapped — corruption, not a
race), and a committed lease whose shard does not fully cover its range
fails loudly too: a committed-but-unreadable range must never
silently become a serving miss.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from paralleljohnson_tpu.utils.checkpoint import (
    MANIFEST_NAME,
    BatchCheckpointer,
    ManifestOverlapError,
    read_manifest_file,
)

FLEET_MANIFEST = "fleet_manifest.json"


def build_fleet_manifest(coordinator, *, write: bool = True) -> dict:
    """Union the committed leases' shard manifests into the global map.

    Returns (and, with ``write=True``, atomically persists to
    ``<coord>/fleet_manifest.json``) a dict::

        {"version": 1, "graph_digest": ..., "num_sources": ...,
         "files": {"shards/w0/graph_<d>/rows_...npz":
                      {"batch": 3, "sources": [...], "worker": "w0",
                       "lease": 7}, ...},
         "leases_committed": N, "orphaned_files": [...]}

    Raises :class:`ManifestOverlapError` on a double-claimed source and
    ``ValueError`` when a committed lease's range is not fully covered
    by its committing shard.
    """
    digest = coordinator.spec["graph_digest"]
    leases = coordinator.leases()
    files: dict[str, dict] = {}
    claimed: dict[int, str] = {}  # source -> relpath that claimed it
    referenced: set[Path] = set()
    for lease in leases:
        if lease.state != "committed":
            continue
        worker = lease.committed_by
        shard_graph_dir = coordinator.shard_dir(worker) / f"graph_{digest}"
        manifest = read_manifest_file(shard_graph_dir)
        if manifest is None:
            raise ValueError(
                f"{shard_graph_dir / MANIFEST_NAME}: lease "
                f"{lease.lease_id} [{lease.start}, {lease.stop}) is "
                f"committed by {worker!r} but its shard has no readable "
                "manifest"
            )
        covered: set[int] = set()
        for filename in sorted(manifest["files"]):
            entry = manifest["files"][filename]
            srcs = [int(s) for s in entry["sources"]]
            inside = [s for s in srcs if lease.start <= s < lease.stop]
            if not inside:
                continue  # another lease's batch in the same shard
            if len(inside) != len(srcs):
                raise ValueError(
                    f"{shard_graph_dir / filename}: batch straddles lease "
                    f"{lease.lease_id} [{lease.start}, {lease.stop}) — "
                    f"sources {srcs[:8]}... are not all inside the range"
                )
            relpath = (
                shard_graph_dir.relative_to(coordinator.dir) / filename
            ).as_posix()
            for s in srcs:
                if s in claimed:
                    raise ManifestOverlapError(
                        f"source {s} claimed by both {claimed[s]} and "
                        f"{relpath} (under {coordinator.dir}) — committed "
                        "leases must cover disjoint ranges"
                    )
                claimed[s] = relpath
            covered.update(srcs)
            referenced.add(shard_graph_dir / filename)
            files[relpath] = {
                "batch": int(entry["batch"]),
                "sources": srcs,
                "worker": worker,
                "lease": lease.lease_id,
            }
        missing = set(range(lease.start, lease.stop)) - covered
        if missing:
            raise ValueError(
                f"{shard_graph_dir / MANIFEST_NAME}: committed lease "
                f"{lease.lease_id} [{lease.start}, {lease.stop}) is "
                f"missing {len(missing)} source row(s) (e.g. "
                f"{sorted(missing)[:8]}) — the shard's manifest does not "
                "cover the range it committed"
            )
    orphaned = []
    shards_root = coordinator.dir / "shards"
    if shards_root.is_dir():
        for p in sorted(shards_root.glob(f"*/graph_{digest}/rows_*.npz")):
            if p not in referenced and not p.name.endswith(".tmp.npz"):
                orphaned.append(p.relative_to(coordinator.dir).as_posix())
    out = {
        "version": 1,
        "graph_digest": digest,
        "num_sources": coordinator.spec["num_sources"],
        "files": files,
        "leases_committed": sum(
            1 for l in leases if l.state == "committed"
        ),
        "leases_total": len(leases),
        "orphaned_files": orphaned,
    }
    if write:
        path = coordinator.dir / FLEET_MANIFEST
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(out), encoding="utf-8")
        os.replace(tmp, path)
    return out


class ShardedCheckpointer:
    """``BatchCheckpointer`` read protocol over a fleet manifest.

    Presents the union of all shards as if it were one checkpoint
    directory: ``manifest()`` / ``batch_sources()`` / ``load()`` are
    what ``serve.store.TileStore`` calls, so a tile store attaches to a
    fleet dir exactly like to a single solve's ``--checkpoint-dir``
    (``TileStore`` detects ``fleet_manifest.json`` itself). Loads
    delegate to a per-shard ``BatchCheckpointer`` so the corruption
    checks (sources match, sha-256) are exactly the single-host ones.

    A local **growth tier** rides on top: scheduled exact-miss solves
    (the serving engine's ``checkpoint_dir = store root``) write
    ordinary batches into ``<root>/graph_<digest>/``; those entries
    overlay the fleet map on every ``manifest()`` re-read, so a fleet
    store keeps growing exactly like a single-shard one.

    ``graph_key``: the expected graph (digest string or CSRGraph). A
    manifest recorded for a DIFFERENT graph yields an empty map — rows
    of another graph are invisible, never served (the same semantics as
    the checkpointer's per-graph subdirectories).
    """

    def __init__(self, root: str | Path, *, graph_key=None) -> None:
        from paralleljohnson_tpu.utils.checkpoint import graph_digest

        self.root = Path(root)
        self.manifest_path = self.root / FLEET_MANIFEST
        digest = None
        if graph_key is not None:
            digest = (
                graph_key if isinstance(graph_key, str)
                else graph_digest(graph_key)
            )
        fleet = self._read_fleet()
        self.digest = digest or (fleet or {}).get("graph_digest")
        # The growth tier: ordinary checkpointer at the fleet root —
        # scheduled solves from the serving layer land here.
        self._growth = (
            BatchCheckpointer(self.root, graph_key=self.digest)
            if self.digest else None
        )
        # .dir is what consumers use as "where this store persists
        # things" (landmark indexes, serve stats) — the growth dir.
        self.dir = self._growth.dir if self._growth else self.root

    def _read_fleet(self) -> dict | None:
        try:
            data = json.loads(self.manifest_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return None
        if not isinstance(data, dict) or "files" not in data:
            return None
        return data

    def _entries(self) -> dict[str, dict]:
        """relpath -> entry, fleet map first, growth overlay last (a
        source re-solved locally wins — identical rows either way,
        checkpoints are keyed by graph content)."""
        out: dict[str, dict] = {}
        fleet = self._read_fleet()
        if fleet is not None and fleet.get("graph_digest") == self.digest:
            out.update(fleet["files"])
        if self._growth is not None:
            growth_rel = self._growth.dir.relative_to(self.root).as_posix()
            data = read_manifest_file(self._growth.dir)
            if data is not None:
                for filename in sorted(data["files"]):
                    e = data["files"][filename]
                    out[f"{growth_rel}/{filename}"] = {
                        "batch": int(e["batch"]),
                        "sources": [int(s) for s in e["sources"]],
                    }
        return out

    # -- the BatchCheckpointer read protocol ---------------------------------

    def manifest(self) -> dict[int, tuple[int, str]]:
        # A manifest() call re-reads (TileStore re-indexes the cold tier
        # through it after invalidate_cold_index); batch_sources/load
        # then serve from the same snapshot so one lookup sequence sees
        # one consistent view.
        self._entries_snapshot = self._entries()
        out: dict[int, tuple[int, str]] = {}
        for relpath in sorted(self._entries_snapshot):
            entry = self._entries_snapshot[relpath]
            for s in entry["sources"]:
                out[int(s)] = (int(entry["batch"]), relpath)
        return out

    def batch_sources(self, relpath: str) -> np.ndarray | None:
        entry = self._entries_cache.get(relpath)
        if entry is None:
            return None
        return np.asarray(entry["sources"], np.int64)

    def load(
        self, batch_idx: int, sources: np.ndarray, *, with_pred: bool = False
    ) -> tuple[np.ndarray, np.ndarray | None] | None:
        """Find the shard file for (batch_idx, sources) and load it
        through a per-shard ``BatchCheckpointer`` (same corruption
        checks as resume). None when absent or corrupt."""
        sources = np.asarray(sources, np.int64)
        for relpath, entry in self._entries_cache.items():
            if int(entry["batch"]) != int(batch_idx):
                continue
            if not np.array_equal(
                np.asarray(entry["sources"], np.int64), sources
            ):
                continue
            shard_dir = (self.root / relpath).parent
            ckpt = BatchCheckpointer(shard_dir)
            return ckpt.load(batch_idx, sources, with_pred=with_pred)
        return None

    @property
    def _entries_cache(self) -> dict[str, dict]:
        cache = getattr(self, "_entries_snapshot", None)
        if cache is None:
            cache = self._entries()
            self._entries_snapshot = cache
        return cache


def fleet_rows(
    coordinator_dir: str | Path, *, with_pred: bool = False
) -> dict[int, np.ndarray]:
    """Source vertex -> distance row for every source the fleet
    manifest references (each batch file decoded once, corruption-
    checked). The bitwise-equivalence checks in the bench/dryrun/tests
    read fleet results through exactly this path."""
    root = Path(coordinator_dir)
    sc = ShardedCheckpointer(root)
    rows: dict[int, np.ndarray] = {}
    for relpath, entry in sc._entries_cache.items():
        sources = np.asarray(entry["sources"], np.int64)
        loaded = sc.load(int(entry["batch"]), sources, with_pred=with_pred)
        if loaded is None:
            raise ValueError(
                f"{root / relpath}: manifest-listed batch is missing or "
                "corrupt"
            )
        batch_rows = loaded[0]
        for i, s in enumerate(sources):
            rows[int(s)] = batch_rows[i]
    return rows
