"""Fleet launcher — plan, spawn, monitor, merge.

``plan_fleet`` writes the coordinator plan (graph digest, lease table);
``launch_local_fleet`` runs N worker **subprocesses on this host**
(forced to CPU — the local fleet is the CPU-testable twin of the pod
deployment, and a stray subprocess must never dial the single-tenant
TPU tunnel), monitors them with a reap loop (a dead worker's lapsed
leases re-queue to survivors), and finishes by unioning the shard
manifests into ``fleet_manifest.json``.

The TPU pod path uses the SAME coordinator over the pod's shared
filesystem but not this launcher: each host runs one worker process
directly (``python -m paralleljohnson_tpu.distributed.worker <dir>
--worker-id host$JAX_PROCESS_ID --multihost``) under the pod's own
process manager; ``pjtpu fleet status`` and ``fleet resume`` work on
that dir unchanged. See the runbook comment in
``scripts/tpu_watch_and_run.sh``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path

from paralleljohnson_tpu.distributed.coordinator import Coordinator
from paralleljohnson_tpu.distributed.manifest import (
    FLEET_MANIFEST,
    build_fleet_manifest,
)


@dataclasses.dataclass
class FleetReport:
    """What a local fleet run produced (``pjtpu fleet solve`` prints
    this as one JSON object)."""

    coordinator_dir: str
    n_workers: int
    wall_s: float
    requeues: int
    extensions: int
    leases_committed: int
    leases_total: int
    edges_relaxed: int
    worker_rcs: dict
    manifest_path: str | None
    status: dict

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def ok(self) -> bool:
        return (
            self.leases_committed == self.leases_total
            and self.manifest_path is not None
        )


def plan_fleet(
    coordinator_dir: str | Path,
    graph_spec: str,
    *,
    n_workers: int,
    num_sources: int | None = None,
    lease_sources: int | None = None,
    lease_deadline_s: float = 30.0,
    heartbeat_stale_s: float | None = None,
    heartbeat_interval_s: float | None = None,
    backend: str = "jax",
    config: dict | None = None,
) -> Coordinator:
    """Create the coordinator plan for ``graph_spec``.

    ``num_sources`` defaults to V (full APSP). ``lease_sources``
    defaults to ~4 leases per worker — coarse enough that claim traffic
    is noise, fine enough that a lost host re-queues a fraction of its
    work, not all of it. The graph is loaded once here to record its
    content digest: every worker re-loads from the spec and refuses a
    digest mismatch, so a fleet can never mix rows of different graphs.
    """
    from paralleljohnson_tpu.graphs import load_graph
    from paralleljohnson_tpu.utils.checkpoint import graph_digest

    graph = load_graph(graph_spec)
    n = graph.num_nodes if num_sources is None else int(num_sources)
    if lease_sources is None:
        lease_sources = max(1, -(-n // max(1, 4 * n_workers)))
    return Coordinator.create(
        coordinator_dir,
        graph_spec=graph_spec,
        graph_digest=graph_digest(graph),
        num_sources=n,
        lease_sources=int(lease_sources),
        lease_deadline_s=lease_deadline_s,
        heartbeat_stale_s=heartbeat_stale_s,
        heartbeat_interval_s=heartbeat_interval_s,
        backend=backend,
        config=config,
    )


def _worker_cmd(
    coordinator_dir: Path, worker_id: str, *,
    self_kill_after_claims: int | None = None,
) -> list[str]:
    cmd = [
        sys.executable, "-m", "paralleljohnson_tpu.distributed.worker",
        str(coordinator_dir), "--worker-id", worker_id,
    ]
    if self_kill_after_claims is not None:
        cmd += ["--self-kill-after-claims", str(self_kill_after_claims)]
    return cmd


def _worker_env(env: dict | None) -> dict:
    """Subprocess environment: inherit, force CPU (single-tenant TPU
    discipline — the LOCAL fleet must never touch the device tunnel),
    and make the package importable even when run from a checkout."""
    import paralleljohnson_tpu

    out = dict(os.environ)
    out.update(env or {})
    out["JAX_PLATFORMS"] = "cpu"
    repo_root = str(Path(paralleljohnson_tpu.__file__).resolve().parent.parent)
    parts = [repo_root] + [
        p for p in out.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    out["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
    return out


def launch_local_fleet(
    coordinator: Coordinator | str | Path,
    n_workers: int,
    *,
    env: dict | None = None,
    poll_s: float = 0.5,
    timeout_s: float | None = None,
    telemetry=None,
    self_kill: dict | None = None,
) -> FleetReport:
    """Run ``n_workers`` local CPU worker subprocesses to completion.

    The monitor loop reaps lapsed leases every ``poll_s`` (a SIGKILLed
    worker's heartbeat goes stale, its range re-queues to survivors —
    each requeue lands as a ``lease_requeued`` telemetry event) and
    stops when every lease is committed, every worker died, or
    ``timeout_s`` passed. On success the shard manifests are unioned
    into ``fleet_manifest.json``; on partial completion the report says
    exactly what is missing (``fleet resume`` continues it).

    ``self_kill``: ``{worker_id: n_claims}`` fault injection — that
    worker SIGKILLs itself mid-lease after its n-th claim (the
    host-loss drill the dryrun and tests run).
    """
    from paralleljohnson_tpu.utils.procs import graceful_stop

    coord = (
        coordinator if isinstance(coordinator, Coordinator)
        else Coordinator(coordinator)
    )
    worker_ids = [f"w{i}" for i in range(n_workers)]
    wenv = _worker_env(env)
    (coord.dir / "logs").mkdir(exist_ok=True)
    t0 = time.perf_counter()
    procs: dict[str, subprocess.Popen] = {}
    logs = {}
    requeue_events = 0
    try:
        for wid in worker_ids:
            log = open(coord.dir / "logs" / f"{wid}.log", "ab")
            logs[wid] = log
            procs[wid] = subprocess.Popen(
                _worker_cmd(
                    coord.dir, wid,
                    self_kill_after_claims=(self_kill or {}).get(wid),
                ),
                env=wenv, stdout=log, stderr=subprocess.STDOUT,
            )
        while True:
            for ev in coord.reap():
                if ev["ev"] == "requeued":
                    requeue_events += 1
                    if telemetry:
                        telemetry.event(
                            "lease_requeued", lease=ev["lease"],
                            worker=ev["worker"], reason=ev["reason"],
                        )
            if coord.done():
                break
            alive = [w for w, p in procs.items() if p.poll() is None]
            if not alive:
                break  # every worker exited with leases outstanding
            if timeout_s is not None and time.perf_counter() - t0 > timeout_s:
                break
            time.sleep(poll_s)
        # Workers exit on their own once the fleet is done; give them a
        # moment, then stop stragglers gently.
        deadline = time.time() + 30.0
        for wid, p in procs.items():
            remaining = max(0.1, deadline - time.time())
            try:
                p.wait(remaining)
            except subprocess.TimeoutExpired:
                graceful_stop(p)
    finally:
        for p in procs.values():
            if p.poll() is None:
                graceful_stop(p)
        for log in logs.values():
            log.close()
    status = coord.status()
    manifest_path = None
    if status["done"]:
        build_fleet_manifest(coord)
        manifest_path = str(coord.dir / FLEET_MANIFEST)
    edges = 0
    worker_rcs = {}
    for wid, p in procs.items():
        worker_rcs[wid] = p.returncode
        try:
            summary = json.loads(
                coord.worker_summary_path(wid).read_text(encoding="utf-8")
            )
            edges += int(summary.get("edges_relaxed", 0))
        except (OSError, ValueError):
            pass  # a killed worker leaves no summary — its log remains
    return FleetReport(
        coordinator_dir=str(coord.dir),
        n_workers=n_workers,
        wall_s=round(time.perf_counter() - t0, 6),
        requeues=status["requeues"],
        extensions=status["extensions"],
        leases_committed=status["leases"]["committed"],
        leases_total=status["leases_total"],
        edges_relaxed=edges,
        worker_rcs=worker_rcs,
        manifest_path=manifest_path,
        status=status,
    )


def run_in_process_fleet(
    coordinator: Coordinator | str | Path, n_workers: int
) -> FleetReport:
    """Sequential in-process twin of :func:`launch_local_fleet` — the
    same claim/solve/commit/merge machinery with zero subprocess spawn
    cost. What the tier-1 tests and the smoke bench preset use (and a
    debugging convenience: pdb works). No concurrency, so no requeues
    can happen here."""
    from paralleljohnson_tpu.distributed.worker import run_worker

    coord = (
        coordinator if isinstance(coordinator, Coordinator)
        else Coordinator(coordinator)
    )
    t0 = time.perf_counter()
    edges = 0
    worker_rcs = {}
    for i in range(n_workers):
        wid = f"w{i}"
        summary = run_worker(
            coord.dir, wid,
            max_leases=None if i == n_workers - 1 else max(
                1, len(coord.spec["leases"]) // n_workers
            ),
        )
        edges += int(summary["edges_relaxed"])
        worker_rcs[wid] = summary["rc"]
    status = coord.status()
    manifest_path = None
    if status["done"]:
        build_fleet_manifest(coord)
        manifest_path = str(coord.dir / FLEET_MANIFEST)
    return FleetReport(
        coordinator_dir=str(coord.dir),
        n_workers=n_workers,
        wall_s=round(time.perf_counter() - t0, 6),
        requeues=status["requeues"],
        extensions=status["extensions"],
        leases_committed=status["leases"]["committed"],
        leases_total=status["leases_total"],
        edges_relaxed=edges,
        worker_rcs=worker_rcs,
        manifest_path=manifest_path,
        status=status,
    )
