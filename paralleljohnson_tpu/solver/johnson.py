"""``ParallelJohnsonSolver`` — the solver orchestration layer.

Rebuild of the reference's attested solver class (SURVEY.md §2 #1,
BASELINE.json:5): Johnson's all-pairs shortest paths as

  phase 1  Bellman-Ford from a virtual source  ->  potentials h(v)
           (negative-cycle detection lives here)
  reweight w'(u,v) = w(u,v) + h(u) - h(v)  >=  0
  phase 2  N-source fan-out on w' (batched across sources)
  phase 3  un-reweight d(u,v) = d'(u,v) - h(u) + h(v)

The solver owns phase structure, batching, checkpoint/resume, and the
edges-relaxed accounting; all numeric kernels are delegated to the
configured :class:`~paralleljohnson_tpu.backends.Backend`.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import time
import types
from typing import Any

import numpy as np

from paralleljohnson_tpu import planner as _planner
from paralleljohnson_tpu.backends import Backend, get_backend
from paralleljohnson_tpu.config import SolverConfig
from paralleljohnson_tpu.graphs import CSRGraph, stack_graphs
from paralleljohnson_tpu.observe.trace import trace_attrs as _trace_attrs
from paralleljohnson_tpu.utils import resilience
from paralleljohnson_tpu.utils.metrics import SolverStats, phase_timer
from paralleljohnson_tpu.utils.reductions import finite_checksum, xp as _xp
from paralleljohnson_tpu.utils.telemetry import resolve as _resolve_telemetry


def _transient_error(e: BaseException) -> bool:
    """Worth a plain (same-resource) retry: injected/real device runtime
    failures. Deterministic solver errors (NegativeCycleError,
    ConvergenceError, ValueError, SolveCorruptionError) are excluded —
    re-running them reproduces them."""
    return type(e).__name__ in ("XlaRuntimeError", "InjectedFaultError")


class NegativeCycleError(ValueError):
    """The graph contains a cycle of negative total weight; shortest paths
    are undefined. Raised host-side from the device-computed flag."""


class ConvergenceError(RuntimeError):
    """A relaxation kernel hit its iteration cap (``max_iterations`` set
    below the graph's convergence depth) while distances were still
    improving. Distinct from a negative cycle: raise the cap and retry."""


class ValidationError(AssertionError):
    """config.validate=True cross-check against the scipy oracle failed."""


@dataclasses.dataclass
class SolveResult:
    """APSP / fan-out result.

    dist: [N_sources, V] distance rows (+inf unreachable); for full APSP
      N_sources == V and row i is distances from vertex ``sources[i]``.
      Device backends leave single-batch rows resident on device (HBM) —
      ``np.asarray(result.dist)`` materializes host-side; multi-batch and
      checkpointed solves already return host arrays.
    sources: the source vertex of each row.
    potentials: Johnson potentials h(v) (zeros when no reweighting ran).
    stats: per-phase wall-clock, iteration counts, edges-relaxed totals.
    predecessors: [N_sources, V] shortest-path-tree rows (−1 = source /
      unreachable) when the solve ran with ``predecessors=True``, else None.
      Valid for the ORIGINAL weights: Johnson reweighting preserves
      shortest paths, so the tree computed on w' is the tree on w.
    """

    dist: Any  # np.ndarray or device array (see docstring)
    sources: np.ndarray
    potentials: Any
    stats: SolverStats
    predecessors: Any | None = None

    @property
    def matrix(self) -> np.ndarray:
        """Distance matrix ordered by source vertex id (full APSP only).

        This is the explicit HOST-materialization point: ``dist`` may be a
        device array (see its docstring), and indexing it with a host
        permutation would otherwise yield another device array. Use
        ``result.dist`` directly to stay on device."""
        order = np.argsort(self.sources)
        return np.asarray(self.dist)[order]

    def rows_by_source(self) -> dict:
        """Source vertex -> its distance row, in whatever memory ``dist``
        lives (device rows stay device-resident — no implicit download).
        The serving layer's unit of storage: ``serve.store.TileStore``
        tiers exactly these rows."""
        return {int(s): self.dist[i] for i, s in enumerate(self.sources)}

    def path(self, source: int, target: int) -> list[int]:
        """Vertex sequence of a shortest ``source -> target`` path (empty if
        unreachable). Requires a ``predecessors=True`` solve."""
        if self.predecessors is None:
            raise ValueError("solve was run without predecessors=True")
        from paralleljohnson_tpu.utils.paths import reconstruct_path

        rows = np.flatnonzero(self.sources == source)
        if rows.size == 0:
            raise ValueError(f"vertex {source} was not a solve source")
        # One host materialization of the row: reconstruct_path walks it
        # element-wise, which on a device-resident row would be one
        # blocking device round-trip per hop.
        return reconstruct_path(
            np.asarray(self.predecessors[rows[0]]), source, target
        )


@dataclasses.dataclass
class ReducedResult:
    """Result of :meth:`ParallelJohnsonSolver.solve_reduced` — per-batch
    reduction values instead of distance rows (streaming mode)."""

    values: list
    sources: np.ndarray
    potentials: Any
    stats: "SolverStats"


def _reduce_checksum(rows, batch):
    return finite_checksum(rows)


def _reduce_eccentricity(rows, batch):
    xp = _xp(rows)
    return np.asarray(xp.max(xp.where(xp.isfinite(rows), rows, -xp.inf), axis=1))


def _reduce_reach_count(rows, batch):
    xp = _xp(rows)
    return np.asarray(xp.isfinite(rows).sum(axis=1))


def _unreweight(rows, h, row_sources):
    """Phase-3 arithmetic d(u,v) = d'(u,v) - h(u) + h(v), in the namespace
    where ``rows`` live: device h against host rows (the checkpointed /
    multi-batch path) would silently promote the whole matrix back onto
    the device. +inf - h + h stays +inf by IEEE inf arithmetic (h is
    always finite: the virtual source reaches every vertex).
    Single source of truth for solve() and solve_reduced().

    """
    hh = np.asarray(h) if isinstance(rows, np.ndarray) else h
    return rows - hh[row_sources][:, None] + hh[None, :]


# Row blocks at least this large trigger Backend.clear_caches before the
# host download / reduction materializes them (the HBM-hygiene step toward
# the RMAT-22 crash fix: layout caches + the download buffer must not
# coexist at full scale). 1 GB: only genuinely large multi-batch solves
# pay the cache rebuild; tests monkeypatch this to 0.
_DOWNLOAD_CLEAR_MIN_BYTES = 1 << 30


_ROW_REDUCERS = {
    "checksum": _reduce_checksum,
    "eccentricity": _reduce_eccentricity,
    "reach_count": _reduce_reach_count,
}


# -- solver-level plan registry (ISSUE 19) -----------------------------------
#
# The condensed/standard choice used to be a hand-rolled ``if
# self._use_partitioned(...)`` branch — the last dispatch decision the
# planner registry could not see, price, or tune. It is now the same
# ``select()`` walk every kernel family goes through: ``condensed+fw``
# (priority 10, qualification = the old predicate verbatim) vs
# ``standard`` (priority 20, unconditional fallback). Unpriced, the
# walk reproduces the old branch bit-for-bit (priority order == branch
# order); priced, a calibrated store can promote either side past the
# 25% noise band, and the self-proposing tuner (``tuner.py``) can probe
# the family's declared knobs like any other plan's.


def _qual_condensed(ctx):
    config = ctx.config
    if getattr(ctx.solver, "_partitioned_disabled", False):
        return False, (
            "condensed route disabled for this solver instance "
            "(earlier auto-route failure)"
        )
    flag = getattr(config, "partitioned", False)
    if flag is False:
        return False, "partitioned=False pins the standard route"
    if flag is True:
        return True, "partitioned=True forces the condensed route"
    if config.backend != "jax":
        return False, "condensed route is jax-only"
    import jax

    if jax.default_backend() != "tpu":
        return False, (
            "auto condensed is TPU-gated (the dense core pays on MXU)"
        )
    v = ctx.graph.num_nodes
    if not 1024 <= v <= config.fw_threshold:
        return False, f"V={v} outside the blocked-FW size range"
    if 2 * len(ctx.sources) < v:
        return False, "source set below full-APSP scale (2B < V)"
    if ctx.graph.num_real_edges >= config.dense_min_density * v * v:
        return False, "dense graph: the plain fw route owns it"
    return True, (
        "TPU + sparse + full-APSP scale in the blocked-FW size range"
    )


SOLVER_PLANS = [
    _planner.Plan(
        name="condensed+fw", entry="solver", priority=10,
        qualify=_qual_condensed,
        price_routes=("condensed+fw",),
        forced=lambda cfg: getattr(cfg, "partitioned", False) is True,
        force_overrides={"partitioned": True},
        tunables=("fw_tile", "partition_parts"),
    ),
    _planner.Plan(
        name="standard", entry="solver", priority=20,
        qualify=lambda ctx: (True, "unconditional standard Johnson path"),
        # The standard path's actual fan-out route is decided one layer
        # down (FANOUT_PLANS); for solver-level pricing the first
        # calibrated tag in ladder order stands in.
        price_routes=(
            "vm-blocked+dw", "vm-blocked", "gs", "dia", "vm",
            "sweep-sm", "fw",
        ),
        forced=lambda cfg: getattr(cfg, "partitioned", True) is False,
        force_overrides={"partitioned": False},
        tunables=("source_batch", "pipeline_depth"),
    ),
]


class ParallelJohnsonSolver:
    """Orchestrates Johnson's algorithm over a pluggable backend."""

    def __init__(
        self,
        config: SolverConfig | None = None,
        backend: Backend | None = None,
    ) -> None:
        self.config = config or SolverConfig()
        self.backend = backend or get_backend(self.config.backend, self.config)
        # The flight-recorder façade every stage is wired through
        # (utils.telemetry). Defaults to the falsy NULL_TELEMETRY, whose
        # span/event/progress are allocation-free no-ops — the disabled
        # path must stay near-free.
        self._tel = _resolve_telemetry(self.config.telemetry)
        # Live-metrics registry (ISSUE 12, ``observe.live``): the batch
        # loop streams per-batch wall + retry/OOM rates into it so a
        # fleet worker's snapshot shows solver health between
        # heartbeats. Same null-object discipline as telemetry.
        from paralleljohnson_tpu.observe.live import resolve_metrics

        self._metrics = resolve_metrics(
            getattr(self.config, "metrics", None)
        )

    # -- public API ---------------------------------------------------------

    def solve(
        self,
        graph: CSRGraph,
        sources: np.ndarray | None = None,
        *,
        predecessors: bool = False,
    ) -> SolveResult:
        """Full Johnson APSP (or the given source subset).

        ``predecessors=True`` also returns shortest-path trees (see
        :attr:`SolveResult.predecessors`) at the cost of an extra scatter
        pass per sweep; requires backend support.
        """
        stats = SolverStats()
        v = graph.num_nodes
        sources = (
            np.arange(v, dtype=np.int64)
            if sources is None
            else np.asarray(sources, np.int64)
        )

        tel = self._tel
        tel.progress(op="solve", sources_total=len(sources))
        # A solve scheduled on behalf of a traced serve request carries
        # the originating trace_id (ISSUE 20) — trace_attrs() reads the
        # serving thread's current trace, {} on every untraced path.
        with tel.span("solve", op="solve", n_sources=len(sources),
                      predecessors=predecessors, **_trace_attrs()):
            decision = self._solver_decision(graph, sources)
            if decision.chosen.plan.name == "condensed+fw":
                res = self._try_condensed(
                    graph, sources, stats, predecessors, tel,
                    decision=decision,
                )
                if res is not None:
                    return res
            with phase_timer(stats, "upload", tel):
                dgraph = self.backend.upload(graph)

            h, dgraph = self._potentials(graph, dgraph, stats)

            # Phase 2 — batched fan-out over sources. Phase 3 (the
            # un-reweight d(u,v) = d'(u,v) - h(u) + h(v)) rides INSIDE
            # each batch's finalize — mirroring solve_reduced — so
            # checkpointed rows are FINAL distances keyed by the
            # ORIGINAL graph's digest: any --checkpoint-dir (and every
            # fleet shard, ISSUE 10) is directly attachable to the
            # serving layer, negative weights included.
            with phase_timer(stats, "fanout", tel):
                dist, pred = self._fanout(
                    dgraph, sources, stats, with_pred=predecessors,
                    graph=graph, h=h,
                )
            result = SolveResult(dist=dist, sources=sources, potentials=h,
                                 stats=stats, predecessors=pred)
            if self.config.validate:
                self._validate(graph, result)
            self._finish_observability(
                stats, graph, len(sources), label="solve"
            )
            return result

    def solve_range(
        self,
        graph: CSRGraph,
        start: int,
        stop: int,
        *,
        predecessors: bool = False,
    ) -> SolveResult:
        """Johnson solve restricted to the contiguous source range
        ``[start, stop)`` — the fleet's unit of work (ISSUE 10: a
        coordinator lease IS a source range; a worker solves it through
        this entry so checkpointing, resilience, and pipelining apply
        unchanged, and a re-claimed lease on the same worker resumes
        from its own shard's completed batches)."""
        v = graph.num_nodes
        if not 0 <= start < stop <= v:
            raise ValueError(
                f"source range [{start}, {stop}) is not a non-empty "
                f"subrange of [0, {v})"
            )
        return self.solve(
            graph,
            sources=np.arange(start, stop, dtype=np.int64),
            predecessors=predecessors,
        )

    def solve_reduced(
        self,
        graph: CSRGraph,
        sources: np.ndarray | None = None,
        *,
        reduce_rows,
    ) -> "ReducedResult":
        """Johnson APSP with per-batch on-device row reduction — the
        streaming mode the attested RMAT-22 config requires (SURVEY.md §7:
        a scale-22 distance matrix is ~70 TB; rows must be reduced or
        streamed, never stored).

        ``reduce_rows(dist_rows, batch_sources)`` is called once per source
        batch with the UN-REWEIGHTED distance rows exactly as ``solve``
        would return them — still resident on the backend's device for
        device backends, so reductions written with jnp run on-chip and
        only their (small) results ever reach the host. Built-in names:
        ``"checksum"`` (sum of finite entries, float), ``"eccentricity"``
        ([B] max finite distance per source), ``"reach_count"`` ([B]
        finite entries per row).

        Returns :class:`ReducedResult` with ``values`` = the per-batch
        reduction results in batch order. Negative-cycle/convergence
        semantics match :meth:`solve`; checkpointing is not applied (the
        point of this mode is that rows are never materialized), and
        ``config.validate`` is rejected for the same reason — the scipy
        oracle would need the full matrix (mirrors the CLI's
        --validate/--reduce exclusion).
        """
        if self.config.validate:
            raise ValueError(
                "config.validate is incompatible with solve_reduced: "
                "streaming mode never materializes the rows the oracle "
                "check needs"
            )
        if isinstance(reduce_rows, str):
            try:
                reduce_rows = _ROW_REDUCERS[reduce_rows]
            except KeyError:
                raise ValueError(
                    f"unknown reducer {reduce_rows!r}; expected one of "
                    f"{sorted(_ROW_REDUCERS)} or a callable"
                ) from None
        stats = SolverStats()
        v = graph.num_nodes
        sources = (
            np.arange(v, dtype=np.int64)
            if sources is None
            else np.asarray(sources, np.int64)
        )
        tel = self._tel
        tel.progress(op="solve_reduced", sources_total=len(sources))
        with tel.span("solve", op="solve_reduced", n_sources=len(sources),
                      **_trace_attrs()):
            return self._solve_reduced_body(
                graph, sources, stats, reduce_rows
            )

    def _solve_reduced_body(self, graph, sources, stats, reduce_rows):
        tel = self._tel
        with phase_timer(stats, "upload", tel):
            dgraph = self.backend.upload(graph)
        h, dgraph = self._potentials(graph, dgraph, stats)
        values = []
        n_src = len(sources)

        def finalize(batch_idx, batch, res, resumed):
            """Per-batch streaming stage: un-reweight + reduce. Runs on
            the pipeline's background worker (depth > 1), so a reducer
            that materializes rows host-side overlaps the next batch's
            device compute — the same overlap the checkpointed path gets."""
            rows = res.dist
            if graph.has_negative_weights:
                rows = _unreweight(rows, h, batch)
            # Same HBM-hygiene gate as _fanout's downloads: a reducer
            # may materialize the rows host-side, and at RMAT-22
            # scale the layout caches must not still be resident
            # when it does (the s22 crash mitigation).
            if (
                len(batch) < n_src
                and int(getattr(rows, "nbytes", 0) or 0)
                >= _DOWNLOAD_CLEAR_MIN_BYTES
            ):
                self.backend.clear_caches(dgraph)
            return reduce_rows(rows, batch)

        with phase_timer(stats, "fanout", tel):
            # Same resilience driver as solve(): retry/watchdog per batch,
            # OOM -> collapse the pipeline window, then halve-and-resume
            # (streaming mode has no checkpoint — reduced values
            # accumulate host-side in batch order as finalizes drain).
            for _, _, value, _ in self._resilient_batches(
                dgraph, sources, stats, finalize=finalize
            ):
                values.append(value)
        self._finish_observability(
            stats, graph, n_src, label="solve_reduced"
        )
        return ReducedResult(
            values=values, sources=sources, potentials=h, stats=stats
        )

    def sssp(
        self, graph: CSRGraph, source: int, *, predecessors: bool = False
    ) -> SolveResult:
        """Standalone Bellman-Ford SSSP (config BASELINE.json:8) — negative
        weights allowed, no reweighting."""
        stats = SolverStats()
        tel = self._tel
        tel.progress(op="sssp", source=int(source))
        with tel.span("solve", op="sssp", source=int(source),
                      **_trace_attrs()):
            return self._sssp_body(graph, source, predecessors, stats)

    def _sssp_body(self, graph, source, predecessors, stats):
        tel = self._tel
        with phase_timer(stats, "upload", tel):
            dgraph = self.backend.upload(graph)
        with phase_timer(stats, "bellman_ford", tel):
            bf = self._run_bf(
                dgraph, stats, source=int(source), pred=predecessors
            )
        if bf.negative_cycle:
            raise NegativeCycleError("negative-weight cycle reachable from source")
        if not bf.converged:
            raise ConvergenceError(
                "Bellman-Ford hit max_iterations while still improving"
            )
        self._finish_observability(stats, graph, 1, label="sssp")
        return SolveResult(
            dist=bf.dist[None, :],
            sources=np.array([source]),
            potentials=np.zeros(graph.num_nodes, graph.dtype),
            stats=stats,
            predecessors=None if bf.pred is None else bf.pred[None, :],
        )

    def multi_source(
        self,
        graph: CSRGraph,
        sources: np.ndarray,
        *,
        predecessors: bool = False,
    ) -> SolveResult:
        """Standalone batched N-source fan-out on a non-negative graph
        (config BASELINE.json:9)."""
        if graph.has_negative_weights:
            raise ValueError(
                "multi_source requires non-negative weights; use solve()"
            )
        stats = SolverStats()
        sources = np.asarray(sources, np.int64)
        tel = self._tel
        tel.progress(op="multi_source", sources_total=len(sources))
        with tel.span("solve", op="multi_source", n_sources=len(sources),
                      **_trace_attrs()):
            with phase_timer(stats, "upload", tel):
                dgraph = self.backend.upload(graph)
            with phase_timer(stats, "fanout", tel):
                dist, pred = self._fanout(
                    dgraph, sources, stats, with_pred=predecessors,
                    graph=graph,
                )
        self._finish_observability(
            stats, graph, len(sources), label="multi_source"
        )
        return SolveResult(
            dist=dist,
            sources=sources,
            potentials=np.zeros(graph.num_nodes, graph.dtype),
            stats=stats,
            predecessors=pred,
        )

    def solve_batch(self, graphs: list[CSRGraph]) -> list[SolveResult]:
        """Many-small-graphs mode (config BASELINE.json:11): APSP for each
        graph in one vectorized run when the backend supports it."""
        stats = SolverStats()
        try:
            with phase_timer(stats, "batch_apsp", self._tel):
                batch = stack_graphs(graphs)
                res = resilience.run_stage(
                    lambda: self.backend.batch_apsp(batch),
                    stage="batch_apsp",
                    policy=self.config.retry_policy(),
                    stats=stats,
                    faults=self.config.fault_plan,
                    retryable=_transient_error,
                    telemetry=self._tel,
                )
        except NotImplementedError:
            return [self.solve(g) for g in graphs]
        stats.accumulate(res, phase="batch_apsp")
        if res.negative_cycle:
            raise NegativeCycleError("negative cycle in at least one batch graph")
        dist = np.asarray(res.dist)
        out = []
        for i, g in enumerate(graphs):
            v = g.num_nodes
            out.append(
                SolveResult(
                    dist=dist[i, :v, :v],
                    sources=np.arange(v),
                    potentials=np.zeros(v, g.dtype),
                    stats=stats,
                )
            )
        return out

    # -- internals ----------------------------------------------------------

    def _solver_model(self):
        """Fitted CostModel for the solver-level ``select()`` walk, or
        None (unpriced — pure declared priority, i.e. the old branch).
        Cached per records-list identity like the backend's
        ``_planner_model`` so repeated solves fit once per store state."""
        config = self.config
        if getattr(config, "planner", True) is False:
            return None
        from paralleljohnson_tpu.observe.costs import resolve_profile_dir
        from paralleljohnson_tpu.observe.tuning import cached_records

        store_dir = resolve_profile_dir(
            getattr(config, "profile_store", None)
        )
        if not store_dir:
            return None
        records = cached_records(store_dir)
        if not records:
            return None
        cached = getattr(self, "_solver_model_cache", None)
        if cached is not None and cached[0] is records:
            return cached[1]
        from paralleljohnson_tpu.observe.store import CostModel

        try:
            model = CostModel.fit(records)
        except Exception:  # noqa: BLE001 — unreadable store = unpriced
            return None
        self._solver_model_cache = (records, model)
        return model

    def _solver_decision(self, graph: CSRGraph, sources: np.ndarray):
        """The solver-level plan decision: ``SOLVER_PLANS`` walked
        through the ordinary priced ``select()`` (ISSUE 19 — the last
        hand-rolled dispatch branch, now registry data)."""
        from paralleljohnson_tpu.observe import current_platform

        ctx = types.SimpleNamespace(
            solver=self, graph=graph, sources=sources,
            config=self.config, params={},
        )
        return _planner.select(
            SOLVER_PLANS, ctx, model=self._solver_model(),
            platform=current_platform(),
            num_edges=graph.num_real_edges, batch=len(sources),
            config=self.config,
        )

    def _use_partitioned(self, graph: CSRGraph, sources: np.ndarray) -> bool:
        """Condense-solve-expand route qualification
        (``solver.partitioned``, route tag ``condensed+fw``) — a view
        over the :data:`SOLVER_PLANS` ``select()`` walk: True forces,
        "auto" mirrors the TPU-gated auto routes (full-APSP-scale
        source sets on sparse graphs in the blocked-FW size range, TPU
        only — where the dense core replaces a gather-bound sweep with
        MXU work), and a calibrated store can price either side past
        the planner noise band."""
        decision = self._solver_decision(graph, sources)
        return decision.chosen.plan.name == "condensed+fw"

    def _try_condensed(
        self, graph: CSRGraph, sources: np.ndarray, stats: SolverStats,
        predecessors: bool, tel, decision=None,
    ) -> SolveResult | None:
        """One condensed solve attempt. Returns None to hand the solve
        back to the standard route (auto-route failure, or the pred tree
        check rejected the one-pass extraction) — degrade-don't-crash,
        exactly like the backend's auto kernel routes; a forced
        ``partitioned=True`` propagates errors instead."""
        from paralleljohnson_tpu.backends.base import KernelResult
        from paralleljohnson_tpu.solver.partitioned import solve_condensed

        forced = self.config.partitioned is True
        try:
            with phase_timer(stats, "fanout", tel):
                dist, pred, info = solve_condensed(
                    graph, sources, config=self.config,
                    predecessors=predecessors,
                )
        except NegativeCycleError:
            raise
        except Exception:
            if forced:
                raise
            if not getattr(self, "_partitioned_disabled", False):
                self._partitioned_disabled = True
                import sys
                import traceback
                import warnings

                warnings.warn(
                    "condensed partitioned route failed; falling back to "
                    "the standard solve path for this solver instance",
                    RuntimeWarning,
                    stacklevel=2,
                )
                traceback.print_exc(file=sys.stderr)
            return None
        if predecessors and pred is None:
            # Zero-weight tight cycle defeated the one-pass extraction:
            # the standard route owns the legacy-sweep fallback chain.
            import warnings

            warnings.warn(
                "condensed route could not extract predecessor trees "
                "(tree check rejected the one-pass rule); re-solving "
                "through the standard route",
                RuntimeWarning,
                stacklevel=2,
            )
            return None
        cost = None
        capture = getattr(self.backend, "cost_capture", None)
        if capture is not None and capture.enabled:
            from paralleljohnson_tpu.ops import fw as fw_ops

            # Analytic pricing of the dominant dense closures (the same
            # tile-triple model the fw route records — ops.fw): flops
            # from the exact MAC total, bytes from the model's
            # bytes-per-MAC at the configured tile.
            tile = fw_ops.effective_tile(
                max(info["core_size"], 1),
                (info.get("params") or {}).get("fw_tile")
                or self.config.fw_tile,
            )
            per_mac_bytes = 4.0 * np.dtype(graph.dtype).itemsize / tile
            cost = capture.analytic(
                info["route"],
                {"flops": 2.0 * info["macs"],
                 "bytes_accessed": per_mac_bytes * info["macs"]},
                num_nodes=graph.num_nodes,
                num_edges=graph.num_real_edges, batch=len(sources),
            )
        stats.accumulate(
            KernelResult(
                dist=dist,
                converged=True,
                iterations=info["k_steps"],
                edges_relaxed=info["macs"],
                route=info["route"],
                cost=cost,
                # Solver-level plan note (ISSUE 14/19): the condensed
                # family's SELECT decision + its resolved auto-tuned
                # parameters (fw_tile, partition_parts) land in the
                # kind:"plan" record like every registry plan's.
                plan=(
                    {
                        **decision.as_dict(),
                        "params": dict(info.get("params") or {}),
                    }
                    if decision is not None else
                    {
                        "chosen": "condensed+fw",
                        "reason": (
                            "solver-level qualification (forced)"
                            if self.config.partitioned is True else
                            "solver-level qualification: TPU + sparse + "
                            "full-APSP scale in the blocked-FW size range"
                        ),
                        "params": dict(info.get("params") or {}),
                    }
                ),
            ),
            phase="fanout",
        )
        tel.event("route", stage="fanout", route=info["route"])
        result = SolveResult(
            dist=dist,
            sources=sources,
            potentials=np.zeros(graph.num_nodes, graph.dtype),
            stats=stats,
            predecessors=pred,
        )
        if self.config.validate:
            self._validate(graph, result)
        self._finish_observability(
            stats, graph, len(sources), label="solve"
        )
        return result

    def _emit_trajectory(self, res, *, stage: str, batch=None) -> None:
        """One ``trajectory`` flight event + heartbeat push per
        instrumented kernel stage (ISSUE 9): the summary numbers, a
        downsampled frontier-collapse curve (enough to replay the
        shape from a dead run's JSONL — ``trace_summary.py
        --convergence``), and the live ``iter``/``frontier_size``
        heartbeat fields the TPU watchdog reads next to ``eta_s``.
        No-op when the route carried no trajectory or telemetry is
        off; never fatal."""
        summ = getattr(res, "convergence", None)
        if not summ or not self._tel:
            return
        try:
            from paralleljohnson_tpu.observe.convergence import (
                frontier_curve,
            )

            attrs = dict(
                stage=stage,
                route=res.route,
                iterations=summ.get("iterations"),
                frontier_half_life=summ.get("frontier_half_life"),
                frontier_peak=summ.get("frontier_peak"),
                frontier_last=summ.get("frontier_last"),
                tail_fraction=round(
                    float(summ.get("tail_fraction", 0.0)), 4
                ),
                jfr_skippable_edge_frac=round(
                    float(summ.get("jfr_skippable_edge_frac", 0.0)), 4
                ),
            )
            if batch is not None:
                attrs["batch"] = batch
            traj = getattr(res, "trajectory", None)
            if traj is not None:
                attrs["frontier_curve"] = frontier_curve(traj)
            self._tel.event("trajectory", **attrs)
            self._tel.note(
                iter=summ.get("iterations"),
                frontier_size=summ.get("frontier_last"),
            )
        except Exception:  # noqa: BLE001 — observability is never fatal
            pass

    def _finish_observability(
        self, stats: SolverStats, graph: CSRGraph, batch: int, *,
        label: str,
    ) -> None:
        """Post-solve cost-observatory hook (ISSUE 7,
        ``paralleljohnson_tpu/observe``): roofline-attribute ``stats``
        (HBM- / MXU- / host-IO-bound), publish the bound to the
        heartbeat, and append one profile-store record (+ the
        calibrated prediction) when ``config.profile_store`` /
        ``PJ_PROFILE_DIR`` is set. Observability must never fail a
        solve that already computed correct distances — any error here
        is swallowed."""
        try:
            from paralleljohnson_tpu import observe
            from paralleljohnson_tpu.observe.convergence import (
                degree_bias_from_degrees,
            )

            observe.finalize_solve(
                stats,
                config=self.config,
                telemetry=self._tel if self._tel else None,
                label=label,
                num_nodes=graph.num_nodes,
                num_edges=graph.num_real_edges,
                batch=batch,
                degree_bias=degree_bias_from_degrees(
                    np.diff(graph.indptr)
                ),
            )
        except Exception:  # noqa: BLE001 — observability is never fatal
            pass

    def _run_bf(
        self, dgraph: Any, stats: SolverStats, *,
        source: int | None, pred: bool = False,
    ):
        """One Bellman-Ford stage through the resilience layer: bounded
        retries with watchdog deadline; a B=1 sweep has no batch to
        shrink, so an OOM frees the rebuildable device caches and retries
        with the memory they held. Converged non-cycle distances pass the
        sanity guard before anyone consumes them as potentials/results."""

        def kernel():
            if pred:
                return self.backend.bellman_ford_pred(dgraph, source=source)
            return self.backend.bellman_ford(dgraph, source=source)

        def retryable(e):
            if resilience.is_oom_error(e):
                try:
                    self.backend.clear_caches(dgraph)
                except Exception:  # noqa: BLE001 — hygiene only
                    pass
                return True
            return _transient_error(e)

        faults = self.config.fault_plan
        bf = resilience.run_stage(
            kernel,
            stage="bellman_ford",
            policy=self.config.retry_policy(),
            stats=stats,
            faults=faults,
            retryable=retryable,
            telemetry=self._tel,
        )
        stats.accumulate(bf, phase="bellman_ford")
        # Route marker on the flight record: the stage spans above were
        # opened BEFORE dispatch resolved a route, so the tag lands as
        # an event — trace_summary --by-route joins them back, keeping
        # flight recordings and cost profiles on one route vocabulary.
        self._tel.event("route", stage="bellman_ford", route=bf.route)
        self._emit_trajectory(bf, stage="bellman_ford")
        if faults is not None:
            bf.dist = faults.poison_rows("bellman_ford", bf.dist)
        if bf.converged and not bf.negative_cycle:
            resilience.check_rows_sane(
                bf.dist, None, route=bf.route,
                iteration=bf.iterations, stage="bellman_ford",
            )
        return bf

    def _potentials(self, graph: CSRGraph, dgraph: Any, stats: SolverStats):
        """Phase 1 + reweight: returns (h, reweighted dgraph). h stays on
        the backend's device (a [V] row is 16 MB at RMAT-22); phase-3
        arithmetic consumes it in place and np.asarray materializes on
        demand. No negative weights -> h = 0 is already valid, skip."""
        if not graph.has_negative_weights:
            return np.zeros(graph.num_nodes, graph.dtype), dgraph
        with phase_timer(stats, "bellman_ford", self._tel):
            bf = self._run_bf(dgraph, stats, source=None)
        if bf.negative_cycle:
            raise NegativeCycleError(
                "negative-weight cycle detected during reweighting"
            )
        if not bf.converged:
            raise ConvergenceError(
                "Bellman-Ford hit max_iterations while still improving; "
                "raise SolverConfig.max_iterations (or leave it None)"
            )
        h = bf.dist
        with phase_timer(stats, "reweight", self._tel):
            dgraph = self.backend.reweight(dgraph, h)
        return h, dgraph

    def _pipeline_depth(self, dgraph: Any = None) -> int:
        """The resolved fan-out pipeline depth (ISSUE 14 auto-tuning):
        explicit ``config.pipeline_depth`` wins, else the profile-tuned
        value for this (platform, shape bucket), else the hand-tuned 2.
        Backends that expose their own resolution (JaxBackend, which
        budgets HBM carry slots from the same number) are deferred to
        so the window and the memory budget can never disagree."""
        resolver = getattr(self.backend, "_pipeline_depth", None)
        if resolver is not None and dgraph is not None:
            try:
                return int(resolver(dgraph))
            except Exception:  # noqa: BLE001 — tuning must not fail a solve
                pass
        from paralleljohnson_tpu import observe
        from paralleljohnson_tpu.observe.tuning import (
            DEFAULT_PIPELINE_DEPTH,
            resolve_param,
        )

        value, _ = resolve_param(
            "pipeline_depth", self.config.pipeline_depth,
            DEFAULT_PIPELINE_DEPTH,
            config=self.config, platform=observe.current_platform(),
            num_nodes=int(getattr(dgraph, "num_nodes", 0) or 0),
            num_edges=int(getattr(dgraph, "num_real_edges", 0) or 0),
            validate=lambda d: isinstance(d, int) and d >= 1,
        )
        return max(1, int(value))

    def _initial_batch_size(
        self, sources: np.ndarray, dgraph: Any = None, *,
        with_pred: bool = False,
    ) -> int:
        """Starting fan-out batch size: the explicit config value, else
        the backend's fits-memory heuristic (config.source_batch_size
        docstring): the backend sizes the [B, V] block to its device
        budget so e.g. RMAT-20 full APSP cannot OOM by default. A pred
        solve passes with_pred so the extra int32 [B, V] pred block is
        budgeted too (plain calls keep the positional-only signature
        third-party backends already implement). The OOM degrader may
        shrink it mid-solve (``_resilient_batches``)."""
        bs = self.config.source_batch_size
        if bs is None and dgraph is not None:
            if with_pred:
                bs = self.backend.suggested_source_batch(
                    dgraph, with_pred=True
                )
            else:
                bs = self.backend.suggested_source_batch(dgraph)
            # Profile-tuned batch (ISSUE 14 auto-tuning): a recorded
            # plan whose explicit batch measured faster on this
            # (platform, shape bucket) refines the heuristic — but the
            # backend's memory budget stays a HARD cap (a tuned value
            # must never re-introduce the OOMs the budget prevents).
            try:
                from paralleljohnson_tpu import observe
                from paralleljohnson_tpu.observe.tuning import (
                    resolve_param,
                )

                tuned, source = resolve_param(
                    "source_batch", None, None,
                    config=self.config,
                    platform=observe.current_platform(),
                    num_nodes=int(getattr(dgraph, "num_nodes", 0) or 0),
                    num_edges=int(
                        getattr(dgraph, "num_real_edges", 0) or 0
                    ),
                    validate=lambda b: isinstance(b, int) and b >= 1,
                )
                if source == "profile-tuned" and bs:
                    bs = min(int(tuned), int(bs))
            except Exception:  # noqa: BLE001 — tuning must not fail a solve
                pass
        return int(bs or len(sources) or 1)

    def _source_batches(
        self, sources: np.ndarray, dgraph: Any = None, *,
        with_pred: bool = False,
    ) -> list[np.ndarray]:
        bs = self._initial_batch_size(sources, dgraph, with_pred=with_pred)
        return [sources[i : i + bs] for i in range(0, len(sources), bs)]

    def _resilient_batches(
        self,
        dgraph: Any,
        sources: np.ndarray,
        stats: SolverStats,
        *,
        with_pred: bool = False,
        try_resume=None,
        finalize=None,
        stage_async=None,
    ):
        """Drive the fan-out batch loop through the resilience layer as a
        double-buffered pipeline (the round-9 tentpole).

        Yields ``(batch_idx, batch, result, resumed)`` per batch, in
        batch order. When a ``finalize`` stage is given (the download /
        checkpoint / streaming-reduce step), ``result`` is its return
        value; otherwise the raw payload — the checkpointer's cached
        ``(rows, pred)`` when ``resumed``, else the backend's
        KernelResult.

        Pipeline (``config.pipeline_depth`` = max batches in flight;
        1 = the strictly serial pre-round-9 loop, bitwise-identical
        results either way):

        - batch k's ``finalize`` runs on a single background worker
          while batch k+1's device compute proceeds on this thread, so
          multi-GB D2H row downloads and checkpoint serialization leave
          the critical path (``stage_async`` — JAX's
          ``copy_to_host_async`` — starts the DMA before the worker even
          picks the batch up);
        - at most ``pipeline_depth - 1`` finalizes sit in the window,
          each carrying one computed-but-unmaterialized [B, V] block
          (+ pred) in device memory; ``suggested_source_batch`` budgets
          exactly that carry;
        - ``finalize`` runs under the SAME retry policy / watchdog
          deadline / fault plan as compute (stage ``"download"``), so a
          hung transfer is logged-and-abandoned like a hung kernel, and
          ``FaultPlan`` can kill a run mid-download;
        - on device OOM the window COLLAPSES to 1 first — the in-flight
          carry is the cheapest memory to give back — and only a repeat
          OOM walks the PR-3 batch-halving schedule (clear caches, halve,
          floor ``min_source_batch``, resume the failed range);
        - converged rows pass the distance-sanity guard BEFORE any
          finalize can download or commit them; non-OOM background
          failures surface as ``SolveCorruptionError`` (never silent
          loss); deterministic faults exercise every path on CPU.
        """
        policy = self.config.retry_policy()
        faults = self.config.fault_plan
        tel = self._tel
        degrader = resilience.OOMDegrader(
            self.backend,
            dgraph,
            self._initial_batch_size(sources, dgraph, with_pred=with_pred),
            min_batch=self.config.min_source_batch,
            with_pred=with_pred,
        )
        depth = (
            self._pipeline_depth(dgraph)
            if finalize is not None
            else 1
        )
        stats.final_pipeline_depth = depth
        n = len(sources)
        pos = 0
        batch_idx = 0
        done = 0
        t_solve0 = time.perf_counter()
        tel.progress(
            sources_total=n, sources_done=0, batches_done=0,
            current_batch_size=degrader.batch_size, pipeline_depth=depth,
        )
        # In-flight finalize window: (batch_idx, batch, payload, future).
        pending: collections.deque = collections.deque()
        worker = None
        metrics = self._metrics
        last_done_t = t_solve0
        counted = {"retries": 0, "oom": 0}

        def mark_done() -> None:
            """Heartbeat progress after one batch fully finalizes — the
            liveness signal the TPU watcher keys stage deadlines off,
            plus the trajectory-aware completion estimate (``eta_s``)
            it extends fresh soft deadlines by (ISSUE 9)."""
            nonlocal done, last_done_t
            done += 1
            now_t = time.perf_counter()
            # Live metrics (ISSUE 12): per-batch wall into the streaming
            # histogram, retry/OOM COUNTER DELTAS into the sliding-rate
            # counters (stats carries the exact totals; the registry
            # carries the rates a live console reads).
            metrics.histogram("pjtpu_solver_batch_wall_ms").record(
                (now_t - last_done_t) * 1e3
            )
            last_done_t = now_t
            metrics.counter("pjtpu_solver_batches").add(1)
            if stats.retries > counted["retries"]:
                metrics.counter("pjtpu_solver_retries").add(
                    stats.retries - counted["retries"]
                )
                counted["retries"] = stats.retries
            if stats.oom_degradations > counted["oom"]:
                metrics.counter("pjtpu_solver_oom_degradations").add(
                    stats.oom_degradations - counted["oom"]
                )
                counted["oom"] = stats.oom_degradations
            tel.progress(
                batches_done=done, sources_done=pos,
                current_batch_size=degrader.batch_size,
                retries=stats.retries,
                oom_degradations=stats.oom_degradations,
                pipeline_depth=depth,
            )
            if tel:
                from paralleljohnson_tpu.observe.convergence import (
                    estimate_eta,
                )

                remaining = -(-(n - pos) // max(degrader.batch_size, 1))
                eta = estimate_eta(
                    time.perf_counter() - t_solve0, done, remaining
                )
                if eta is not None:
                    tel.note(eta_s=round(eta, 3))

        def run_finalize(bi, b, payload, resumed, parent=None):
            """One finalize, timed, through the resilience layer (stage
            "download": retry + watchdog + fault injection). Returns
            (result, duration) so the drain can price the overlap.
            ``parent``: span to nest under when running on the pipeline
            worker thread (captured at submit on the main thread)."""
            if finalize is None:
                return payload, 0.0
            with tel.span("finalize", batch=bi, parent=parent,
                          resumed=resumed, **_trace_attrs()):
                if resumed:
                    return finalize(bi, b, payload, True), 0.0
                t0 = time.perf_counter()
                out = resilience.run_stage(
                    lambda: finalize(bi, b, payload, False),
                    stage="download",
                    policy=policy,
                    stats=stats,
                    faults=faults,
                    batch=bi,
                    retryable=_transient_error,
                    telemetry=tel,
                )
                dur = time.perf_counter() - t0
                stats.download_s += dur
                return out, dur

        def collapse_window() -> None:
            """OOM step 0: go serial — give back the in-flight [B, V]
            carry before any batch halving (the window is the cheapest
            memory on the table)."""
            nonlocal depth
            depth = 1
            stats.final_pipeline_depth = 1
            tel.event("window_collapse")
            tel.progress(pipeline_depth=1)
            try:
                self.backend.clear_caches(dgraph)
            except Exception:  # noqa: BLE001 — hygiene must not mask
                pass

        def drain_one():
            """Wait for the oldest staged finalize; account the blocked
            time (ckpt_wait_s) and the hidden time (overlap_saved_s)."""
            nonlocal depth
            bi, b, payload, fut = pending.popleft()
            t0 = time.perf_counter()
            try:
                out, dur = fut.result()
            except Exception as e:
                stats.ckpt_wait_s += time.perf_counter() - t0
                if resilience.is_oom_error(e):
                    if depth > 1:
                        # The staged materialization itself OOMed: give
                        # back the window and retry THIS finalize
                        # serially before anything harsher.
                        collapse_window()
                        out, _ = run_finalize(bi, b, payload, False)
                        return bi, b, out, False
                    raise
                if isinstance(
                    e,
                    (
                        resilience.StageAbandonedError,
                        resilience.SolveCorruptionError,
                    ),
                ):
                    raise
                raise resilience.SolveCorruptionError(
                    f"pipelined download/checkpoint stage failed for "
                    f"batch {bi}: {type(e).__name__}: {e}"
                ) from e
            wait = time.perf_counter() - t0
            stats.ckpt_wait_s += wait
            stats.overlap_saved_s += max(0.0, dur - wait)
            return bi, b, out, False

        try:
            while pos < n:
                batch = sources[pos : pos + degrader.batch_size]
                if try_resume is not None:
                    cached = try_resume(batch_idx, batch)
                    if cached is not None:
                        while pending:  # keep yields in batch order
                            drained = drain_one()
                            mark_done()
                            yield drained
                        stats.batches_resumed += 1
                        tel.event("batch_resumed", batch=batch_idx)
                        out, _ = run_finalize(batch_idx, batch, cached, True)
                        pos += len(batch)
                        batch_idx += 1
                        mark_done()
                        yield batch_idx - 1, batch, out, True
                        continue

                def kernel(b=batch):
                    if with_pred:
                        return self.backend.multi_source_pred(dgraph, b)
                    return self.backend.multi_source(dgraph, b)

                try:
                    res = resilience.run_stage(
                        kernel,
                        stage="fanout",
                        policy=policy,
                        stats=stats,
                        faults=faults,
                        batch=batch_idx,
                        retryable=_transient_error,
                        telemetry=tel,
                    )
                except Exception as e:
                    if resilience.is_oom_error(e):
                        if depth > 1:
                            while pending:  # commit the good in-flight work
                                drained = drain_one()
                                mark_done()
                                yield drained
                            collapse_window()
                            continue  # retry THIS batch serially, same size
                        old_size = degrader.batch_size
                        degrader.degrade(e)  # re-raises at the floor
                        stats.oom_degradations += 1
                        tel.event(
                            "oom_degrade", batch=batch_idx,
                            old_batch=old_size, new_batch=degrader.batch_size,
                        )
                        tel.progress(
                            oom_degradations=stats.oom_degradations,
                            current_batch_size=degrader.batch_size,
                        )
                        continue  # re-split THIS range smaller; pos unchanged
                    raise
                stats.accumulate(res, phase="fanout")
                # Route marker for this batch's stage spans (see _run_bf).
                tel.event(
                    "route", stage="fanout", batch=batch_idx,
                    route=res.route,
                )
                self._emit_trajectory(res, stage="fanout", batch=batch_idx)
                if not res.converged:
                    raise ConvergenceError(
                        "fan-out hit max_iterations while still improving"
                    )
                if faults is not None:
                    res.dist = faults.poison_rows(
                        "fanout", res.dist, batch=batch_idx
                    )
                resilience.check_rows_sane(
                    res.dist, batch, route=res.route, iteration=res.iterations
                )
                # A batch with nothing to overlap against (the only batch
                # of the solve) stays inline — single-batch device solves
                # keep their rows resident exactly as before.
                if depth > 1 and (pending or pos + len(batch) < n):
                    if stage_async is not None:
                        stage_async(res)
                    if worker is None:
                        worker = concurrent.futures.ThreadPoolExecutor(
                            max_workers=1, thread_name_prefix="pj-pipeline"
                        )
                    fut = worker.submit(
                        run_finalize, batch_idx, batch, res, False,
                        tel.current_span_id(),
                    )
                    pending.append((batch_idx, batch, res, fut))
                    pos += len(batch)
                    batch_idx += 1
                    while len(pending) >= depth:
                        drained = drain_one()
                        mark_done()
                        yield drained
                else:
                    out, _ = run_finalize(batch_idx, batch, res, False)
                    pos += len(batch)
                    batch_idx += 1
                    mark_done()
                    yield batch_idx - 1, batch, out, False
            while pending:
                drained = drain_one()
                mark_done()
                yield drained
            stats.final_batch = degrader.batch_size
        finally:
            if worker is not None:
                worker.shutdown(wait=True, cancel_futures=True)

    def _download_rows(self, dgraph: Any, rows, pred=None):
        """Materialize one batch's device rows on the host, clearing the
        backend's rebuildable device caches first when the block is large
        (``_DOWNLOAD_CLEAR_MIN_BYTES``) — at RMAT-22 scale the layout
        caches and the download buffer must not coexist in HBM."""
        nbytes = int(getattr(rows, "nbytes", 0) or 0)
        if pred is not None:
            nbytes += int(getattr(pred, "nbytes", 0) or 0)
        if nbytes >= _DOWNLOAD_CLEAR_MIN_BYTES:
            self.backend.clear_caches(dgraph)
        return (
            np.asarray(rows),
            None if pred is None else np.asarray(pred),
        )

    def _fanout(
        self,
        dgraph: Any,
        sources: np.ndarray,
        stats: SolverStats,
        *,
        with_pred: bool = False,
        graph: CSRGraph | None = None,
        h=None,
    ) -> tuple[np.ndarray, np.ndarray | None]:
        """Run phase 2 in source batches; optionally checkpoint each batch
        (SURVEY.md §5 — the batch is the unit of recovery). Checkpoints are
        keyed by graph content so a different/modified graph never resumes
        stale rows — by the ORIGINAL graph (``graph``), not the reweighted
        device copy, and with the Johnson un-reweight (``h``) applied per
        batch BEFORE the save: what lands on disk is final distances, so a
        checkpoint dir (or a fleet shard, ISSUE 10) serves through
        ``TileStore`` for negative-weight graphs too. The loop runs
        through the pipelined resilience driver (``_resilient_batches``):
        batch k's D2H download + checkpoint serialization run behind batch
        k+1's compute (pipeline_depth > 1), a batch that OOMs first
        collapses the window and then is re-split smaller and resumed —
        everything already completed is safe on disk when checkpointing is
        on, and the solve does not return until the checkpoint writer's
        flush barrier confirms every commit. Returns (dist rows,
        predecessor rows or None)."""
        from paralleljohnson_tpu.utils.checkpoint import (
            AsyncCheckpointWriter,
            BatchCheckpointer,
            checked_save,
        )

        unreweight = (
            h is not None and graph is not None
            and graph.has_negative_weights
        )
        ckpt = None
        if self.config.checkpoint_dir:
            key_graph = (
                graph if graph is not None
                else self.backend.download_graph(dgraph)
            )
            ckpt = BatchCheckpointer(
                self.config.checkpoint_dir, graph_key=key_graph
            )
        try_resume = None
        if ckpt is not None:
            def try_resume(batch_idx, batch):
                return ckpt.load(batch_idx, batch, with_pred=with_pred)

        depth = self._pipeline_depth(dgraph)
        faults = self.config.fault_plan
        fault_hook = None
        if faults is not None:
            def fault_hook(batch_idx):
                active = faults.fire("ckpt_write", batch=batch_idx)
                if active is not None:
                    active.wrap(lambda: None)()

        writer = None
        if ckpt is not None and depth > 1:
            # Checkpoint serialization + checksumming on a bounded
            # background writer; flush() below is the commit barrier.
            writer = AsyncCheckpointWriter(
                ckpt, max_pending=depth, fault_hook=fault_hook,
                telemetry=self._tel,
            )

        n_src = len(sources)

        def finalize(batch_idx, batch, payload, resumed):
            if resumed:
                return payload  # (rows, pred) host arrays from the ckpt
            # A SINGLE-batch solve keeps device-backend rows resident
            # on device (at RMAT-22 scale rows must never be forced to
            # host wholesale). Multi-batch solves STREAM each batch to
            # host: the batching exists because all rows together
            # exceed the device budget (suggested_source_batch), so
            # accumulating device buffers across batches would defeat
            # it. Checkpointing (host .npz) forces the download either
            # way. The per-batch un-reweight runs in whatever namespace
            # the rows are in at that point (host after a download,
            # device for the resident single batch).
            row, pred = payload.dist, payload.pred
            if ckpt is not None or len(batch) < n_src:
                row, pred = self._download_rows(dgraph, row, pred)
                if unreweight:
                    row = _unreweight(row, h, batch)
                if ckpt is not None:
                    if writer is not None:
                        writer.submit(batch_idx, batch, row, pred=pred)
                    else:
                        with self._tel.span("ckpt_write", batch=batch_idx):
                            checked_save(
                                ckpt, batch_idx, batch, row, pred=pred,
                                fault_hook=fault_hook,
                            )
            elif unreweight:
                row = _unreweight(row, h, batch)
            return row, pred

        def stage_async(res):
            # Start the D2H DMA the moment the rows pass the sanity
            # guard — it then runs under the next batch's compute.
            self.backend.stage_rows_async(res.dist, res.pred)

        rows: list[np.ndarray] = []
        preds: list[np.ndarray] = []
        gen = self._resilient_batches(
            dgraph, sources, stats, with_pred=with_pred,
            try_resume=try_resume, finalize=finalize,
            stage_async=stage_async,
        )
        try:
            for batch_idx, batch, (row, pred), resumed in gen:
                rows.append(row)
                if with_pred:
                    preds.append(pred)
            if writer is not None:
                # Commit barrier: resume semantics require every batch on
                # disk before this solve can report success. Blocked time
                # here is the pipeline's residual serial cost.
                t0 = time.perf_counter()
                writer.flush()
                wait = time.perf_counter() - t0
                stats.ckpt_wait_s += wait
                stats.overlap_saved_s += max(0.0, writer.busy_s - wait)
        finally:
            gen.close()
            if writer is not None:
                # Teardown drains queued commits (completed batches stay
                # resumable even when the solve is dying) without raising
                # over the original error.
                writer.close()
        dist = rows[0] if len(rows) == 1 else np.concatenate(rows, axis=0)
        if not with_pred:
            return dist, None
        pred = preds[0] if len(preds) == 1 else np.concatenate(preds, axis=0)
        return dist, pred

    def _validate(self, graph: CSRGraph, result: SolveResult) -> None:
        """config.validate: cross-check against the scipy Johnson oracle."""
        import scipy.sparse.csgraph as csgraph

        dense = np.ma.masked_invalid(graph.to_dense().astype(np.float64))
        oracle = csgraph.johnson(dense, directed=True)[result.sources]
        if not np.allclose(result.dist, oracle, rtol=1e-4, atol=1e-4):
            bad = ~np.isclose(result.dist, oracle, rtol=1e-4, atol=1e-4)
            raise ValidationError(
                f"solver disagrees with scipy oracle at {bad.sum()} of "
                f"{bad.size} entries (max |err| = "
                f"{np.nanmax(np.abs(np.where(bad, result.dist - oracle, 0))):g})"
            )
