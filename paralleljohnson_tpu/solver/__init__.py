"""Solver orchestration (SURVEY.md §2 #1)."""

from paralleljohnson_tpu.solver.johnson import (
    ConvergenceError,
    NegativeCycleError,
    ParallelJohnsonSolver,
    ReducedResult,
    SolveResult,
    ValidationError,
)

__all__ = [
    "ConvergenceError",
    "NegativeCycleError",
    "ParallelJohnsonSolver",
    "ReducedResult",
    "SolveResult",
    "ValidationError",
]
