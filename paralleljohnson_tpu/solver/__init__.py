"""Solver orchestration (SURVEY.md §2 #1)."""

from paralleljohnson_tpu.solver.johnson import (
    ConvergenceError,
    NegativeCycleError,
    ParallelJohnsonSolver,
    ReducedResult,
    SolveResult,
    ValidationError,
)

__all__ = [
    "ConvergenceError",
    "NegativeCycleError",
    "ParallelJohnsonSolver",
    "ReducedResult",
    "SolveResult",
    "ValidationError",
]

# solver.partitioned (condense-solve-expand condensed+fw route) is
# imported lazily at its dispatch site — it builds device arrays.
