"""Solver orchestration (SURVEY.md §2 #1)."""

from paralleljohnson_tpu.solver.johnson import (
    ConvergenceError,
    NegativeCycleError,
    ParallelJohnsonSolver,
    ReducedResult,
    SolveResult,
    ValidationError,
)

__all__ = [
    "ConvergenceError",
    "NegativeCycleError",
    "ParallelJohnsonSolver",
    "ReducedResult",
    "SolveResult",
    "ValidationError",
]

# solver.partitioned (condense-solve-expand condensed+fw route) and
# solver.approx (the certified hopset+bf tier) are imported lazily at
# their dispatch sites — both build device arrays.
